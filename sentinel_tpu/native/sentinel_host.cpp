// Native host-runtime primitives for the TPU flow-control engine.
//
// The device engine consumes fixed-shape micro-batches; the host hot path
// is "many request threads append events, one tick thread drains a batch".
// In the reference this role is played by lock-free Java structures
// (LongAdder queues, COW maps — SURVEY §5 "race detection").  Here:
//
//  - sx_ring:    a bounded MPMC ring buffer of acquire/complete events
//                (atomic ticket acquisition, per-slot sequence numbers —
//                 the classic Vyukov bounded queue), drained in batch
//                 order directly into caller-provided arrays so Python
//                 receives ready-to-use int32/float32 buffers.
//  - sx_intern:  an open-addressing FNV-1a string -> dense id table with
//                a single writer lock and lock-free readers (the analog
//                of CtSph's copy-on-write chainMap, CtSph.java:207-211).
//
// Built as a plain C ABI shared library; Python binds via ctypes
// (pybind11 is not available in this image).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>

extern "C" {

// ---------------------------------------------------------------------------
// event ring
// ---------------------------------------------------------------------------

struct sx_event {
    int32_t res;
    int32_t count;
    int32_t origin_id;
    int32_t param_hash;
    int32_t flags;    // bit0 inbound, bit1 prioritized, bit2 completion
    float   rt_ms;    // completions
    int32_t error;    // completions
    int32_t user_tag; // round-trips to the drainer (e.g. future index)
    int32_t aux0;     // completions: hot-param release lane 0
    int32_t aux1;     // completions: hot-param release lane 1
    int32_t aux2;     // completions: hot-param release lane 2
    int32_t aux3;     // completions: hot-param release lane 3
};

struct sx_slot {
    std::atomic<uint64_t> seq;
    sx_event ev;
};

struct sx_ring {
    uint64_t mask;
    sx_slot* slots;
    alignas(64) std::atomic<uint64_t> head; // next write ticket
    alignas(64) std::atomic<uint64_t> tail; // next read ticket
};

sx_ring* sx_ring_new(uint64_t capacity_pow2) {
    if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1)) != 0)
        return nullptr;
    auto* r = new (std::nothrow) sx_ring();
    if (!r) return nullptr;
    r->slots = new (std::nothrow) sx_slot[capacity_pow2];
    if (!r->slots) { delete r; return nullptr; }
    r->mask = capacity_pow2 - 1;
    for (uint64_t i = 0; i <= r->mask; ++i)
        r->slots[i].seq.store(i, std::memory_order_relaxed);
    r->head.store(0, std::memory_order_relaxed);
    r->tail.store(0, std::memory_order_relaxed);
    return r;
}

void sx_ring_free(sx_ring* r) {
    if (!r) return;
    delete[] r->slots;
    delete r;
}

// push one event; returns 0 on success, -1 if the ring is full.
// aux0..aux3 carry the four hot-param release lanes (param_dims <= 4)
int32_t sx_ring_push(sx_ring* r, int32_t res, int32_t count, int32_t origin_id,
                     int32_t param_hash, int32_t flags, float rt_ms,
                     int32_t error, int32_t user_tag, int32_t aux0,
                     int32_t aux1, int32_t aux2, int32_t aux3) {
    uint64_t pos = r->head.load(std::memory_order_relaxed);
    for (;;) {
        sx_slot& s = r->slots[pos & r->mask];
        uint64_t seq = s.seq.load(std::memory_order_acquire);
        int64_t diff = (int64_t)seq - (int64_t)pos;
        if (diff == 0) {
            if (r->head.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed))
            {
                s.ev = {res, count, origin_id, param_hash, flags, rt_ms,
                        error, user_tag, aux0, aux1, aux2, aux3};
                s.seq.store(pos + 1, std::memory_order_release);
                return 0;
            }
        } else if (diff < 0) {
            return -1; // full
        } else {
            pos = r->head.load(std::memory_order_relaxed);
        }
    }
}

// drain up to max_n events into parallel arrays; returns count drained.
// Single-consumer use is expected (the tick thread), but the ticket
// scheme stays correct with several.
int64_t sx_ring_drain(sx_ring* r, int64_t max_n, int32_t* res, int32_t* count,
                      int32_t* origin_id, int32_t* param_hash, int32_t* flags,
                      float* rt_ms, int32_t* error, int32_t* user_tag,
                      int32_t* aux0, int32_t* aux1, int32_t* aux2,
                      int32_t* aux3) {
    int64_t n = 0;
    while (n < max_n) {
        uint64_t pos = r->tail.load(std::memory_order_relaxed);
        sx_slot& s = r->slots[pos & r->mask];
        uint64_t seq = s.seq.load(std::memory_order_acquire);
        int64_t diff = (int64_t)seq - (int64_t)(pos + 1);
        if (diff == 0) {
            if (!r->tail.compare_exchange_weak(pos, pos + 1,
                                               std::memory_order_relaxed))
                continue;
            const sx_event& e = s.ev;
            res[n] = e.res; count[n] = e.count; origin_id[n] = e.origin_id;
            param_hash[n] = e.param_hash; flags[n] = e.flags;
            rt_ms[n] = e.rt_ms; error[n] = e.error; user_tag[n] = e.user_tag;
            aux0[n] = e.aux0; aux1[n] = e.aux1;
            aux2[n] = e.aux2; aux3[n] = e.aux3;
            s.seq.store(pos + r->mask + 1, std::memory_order_release);
            ++n;
        } else {
            break; // empty (or producer mid-write: next drain gets it)
        }
    }
    return n;
}

int64_t sx_ring_size(sx_ring* r) {
    return (int64_t)(r->head.load(std::memory_order_relaxed) -
                     r->tail.load(std::memory_order_relaxed));
}

// ---------------------------------------------------------------------------
// string interner
// ---------------------------------------------------------------------------

struct sx_intern_entry {
    std::atomic<uint64_t> hash; // 0 = empty
    std::atomic<int32_t> id;    // valid once hash is published
    char* key;
    uint32_t len;
};

struct sx_intern {
    uint64_t mask;
    sx_intern_entry* entries;
    std::atomic<int32_t> next_id;
    int32_t max_ids;
    std::mutex write_lock;
};

static uint64_t fnv1a(const char* p, uint64_t n) {
    uint64_t h = 1469598103934665603ull;
    for (uint64_t i = 0; i < n; ++i) {
        h ^= (unsigned char)p[i];
        h *= 1099511628211ull;
    }
    return h ? h : 1; // 0 is the empty marker
}

sx_intern* sx_intern_new(uint64_t capacity_pow2, int32_t first_id,
                         int32_t max_ids) {
    if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1)) != 0)
        return nullptr;
    auto* t = new (std::nothrow) sx_intern();
    if (!t) return nullptr;
    t->entries = new (std::nothrow) sx_intern_entry[capacity_pow2]();
    if (!t->entries) { delete t; return nullptr; }
    t->mask = capacity_pow2 - 1;
    t->next_id.store(first_id, std::memory_order_relaxed);
    t->max_ids = max_ids;
    return t;
}

void sx_intern_free(sx_intern* t) {
    if (!t) return;
    for (uint64_t i = 0; i <= t->mask; ++i) delete[] t->entries[i].key;
    delete[] t->entries;
    delete t;
}

// lookup-or-insert; returns the dense id, or -1 when id space / table full.
// Readers are lock-free (acquire loads); inserts take the writer lock.
int32_t sx_intern_get(sx_intern* t, const char* key, uint32_t len) {
    uint64_t h = fnv1a(key, len);
    uint64_t idx = h & t->mask;
    // fast path: lock-free probe
    for (uint64_t probes = 0; probes <= t->mask; ++probes) {
        uint64_t eh = t->entries[idx].hash.load(std::memory_order_acquire);
        if (eh == 0) break;
        if (eh == h) {
            const sx_intern_entry& e = t->entries[idx];
            if (e.len == len && std::memcmp(e.key, key, len) == 0)
                return e.id.load(std::memory_order_acquire);
        }
        idx = (idx + 1) & t->mask;
    }
    // slow path: insert under lock (re-probe: someone may have raced us)
    std::lock_guard<std::mutex> g(t->write_lock);
    idx = h & t->mask;
    for (uint64_t probes = 0; probes <= t->mask; ++probes) {
        sx_intern_entry& e = t->entries[idx];
        uint64_t eh = e.hash.load(std::memory_order_acquire);
        if (eh == h && e.len == len && std::memcmp(e.key, key, len) == 0)
            return e.id.load(std::memory_order_acquire);
        if (eh == 0) {
            int32_t id = t->next_id.load(std::memory_order_relaxed);
            if (id >= t->max_ids) return -1;
            char* copy = new (std::nothrow) char[len];
            if (!copy) return -1;
            std::memcpy(copy, key, len);
            e.key = copy;
            e.len = len;
            e.id.store(id, std::memory_order_release);
            e.hash.store(h, std::memory_order_release); // publish last
            t->next_id.store(id + 1, std::memory_order_relaxed);
            return id;
        }
        idx = (idx + 1) & t->mask;
    }
    return -1; // table full
}

int32_t sx_intern_count(sx_intern* t, int32_t first_id) {
    return t->next_id.load(std::memory_order_relaxed) - first_id;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// native front door: epoll TCP server for the cluster token protocol's FLOW
// fast path (SURVEY §2.9 "native boundary"; the reference's analog is the
// Netty pipeline in NettyTransportServer.java:88-93).
//
// Per-request Python costs ~100-300 us on an asyncio loop; this path does
// socket -> frame parse -> flow-id map -> acquire ring in C, the Python
// tick thread drains acquires straight into engine batch columns, and
// verdicts return through a response ring that this thread writes back to
// the sockets.  Python runs per TICK, not per request.
//
// Protocol handled natively on ONE port (TokenServerHandler.java:61-75
// parity): PING (replied inline), MSG_TYPE_FLOW, MSG_TYPE_PARAM_FLOW
// (param values hashed in C — int/long/bool/string; a double falls back to
// STATUS_FAIL, matching ParamFlowRequestDataWriter's primitives+strings
// envelope), and CONCURRENT acquire/release (routed to the host manager
// via the same ring, answered through respond_ex).  Multi-param requests
// fan out to one engine item per value and JOIN in the pend slot (all
// values must pass).  SO_REUSEPORT sharding: N fronts on one port, the
// kernel load-balances accepted connections across io threads.
// ---------------------------------------------------------------------------

#include <sys/epoll.h>
#include <sys/socket.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <unistd.h>
#include <fcntl.h>
#include <time.h>
#include <algorithm>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int8_t ST_TOO_MANY = -2;
constexpr int8_t ST_BAD = -4;
constexpr int8_t ST_FAIL = -1;
constexpr int8_t ST_OK = 0;
constexpr int8_t ST_NO_RULE = 5;

struct sx_conn {
    int fd;
    uint32_t gen;
    std::vector<uint8_t> rbuf;
    std::vector<uint8_t> wbuf;
    size_t woff = 0;
    bool closing = false;
};

struct Pend {
    int fd;
    uint32_t gen;
    int32_t xid;
    uint8_t type;       // request MSG_TYPE (response framing + joins)
    int16_t remaining;  // outstanding engine items (multi-param join)
    int8_t worst;       // first non-OK status seen across joined items
};

struct FlowSlot {
    std::atomic<int64_t> key;  // (flow_id << 1) | is_param; 0 = empty
    std::atomic<int32_t> row;
    std::atomic<int32_t> lane;  // param hash lane (param mappings only)
};

}  // namespace

struct sx_front {
    int listen_fd = -1;
    int epfd = -1;
    int port = 0;
    std::atomic<bool> running{false};
    std::thread io;
    sx_ring* acq = nullptr;   // front -> tick: res=row, count, flags bit1=prio,
                              // user_tag=correlation slot
    sx_ring* resp = nullptr;  // tick -> front: res=corr, count=verdict,
                              // origin_id=wait_ms
    std::vector<Pend> pend;
    std::vector<int32_t> freelist;
    FlowSlot* fmap = nullptr;
    uint64_t fmask = 0;
    std::unordered_map<int, sx_conn*> conns;
    uint32_t gen_seq = 0;
    // optional request guard: max FLOW requests per second, -1 = off
    std::atomic<int64_t> guard_max{-1};
    int64_t guard_epoch = 0;
    int64_t guard_count = 0;
};

extern "C" {

static void sxf_set_nonblock(int fd) {
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

sx_front* sx_front_new(int port, uint64_t ring_pow2, uint64_t pending_cap,
                       uint64_t fmap_pow2, int32_t reuseport) {
    auto* f = new (std::nothrow) sx_front();
    if (!f) return nullptr;
    f->acq = sx_ring_new(ring_pow2);
    f->resp = sx_ring_new(ring_pow2);
    f->fmap = new (std::nothrow) FlowSlot[fmap_pow2];
    if (!f->acq || !f->resp || !f->fmap) {
        if (f->acq) sx_ring_free(f->acq);
        if (f->resp) sx_ring_free(f->resp);
        delete[] f->fmap;
        delete f;
        return nullptr;
    }
    f->fmask = fmap_pow2 - 1;
    for (uint64_t i = 0; i < fmap_pow2; ++i) {
        f->fmap[i].key.store(0, std::memory_order_relaxed);
        f->fmap[i].row.store(-1, std::memory_order_relaxed);
        f->fmap[i].lane.store(0, std::memory_order_relaxed);
    }
    // INVARIANT: pending_cap <= ring capacity, so at most pending_cap
    // responses can ever be in flight and the response ring cannot fill —
    // sx_front_respond's failure branch is defensive, not expected
    if (pending_cap > ring_pow2) pending_cap = ring_pow2;
    f->pend.resize(pending_cap);
    f->freelist.reserve(pending_cap);
    for (int64_t i = (int64_t)pending_cap - 1; i >= 0; --i)
        f->freelist.push_back((int32_t)i);

    auto fail = [&]() {
        if (f->listen_fd >= 0) close(f->listen_fd);
        sx_ring_free(f->acq);
        sx_ring_free(f->resp);
        delete[] f->fmap;
        delete f;
        return (sx_front*)nullptr;
    };
    f->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
    if (f->listen_fd < 0) return fail();
    int one = 1;
    setsockopt(f->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    if (reuseport)
        setsockopt(f->listen_fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons((uint16_t)port);
    if (bind(f->listen_fd, (sockaddr*)&addr, sizeof addr) != 0 ||
        listen(f->listen_fd, 1024) != 0) {
        return fail();
    }
    socklen_t alen = sizeof addr;
    getsockname(f->listen_fd, (sockaddr*)&addr, &alen);
    f->port = ntohs(addr.sin_port);
    sxf_set_nonblock(f->listen_fd);
    return f;
}

int32_t sx_front_port(sx_front* f) { return f ? f->port : -1; }

// typed key: (flow_id << 1) | is_param — flow and param rule ids live in
// independent spaces (ClusterFlowRuleManager vs ClusterParamFlowRuleManager)
static int32_t sxf_map_put(sx_front* f, int64_t key, int32_t row, int32_t lane) {
    uint64_t h = (uint64_t)key * 0x9E3779B97F4A7C15ull;
    for (uint64_t i = 0; i <= f->fmask; ++i) {
        uint64_t idx = (h + i) & f->fmask;
        int64_t k = f->fmap[idx].key.load(std::memory_order_acquire);
        if (k == key || k == 0) {
            f->fmap[idx].row.store(row, std::memory_order_relaxed);
            f->fmap[idx].lane.store(lane, std::memory_order_relaxed);
            f->fmap[idx].key.store(key, std::memory_order_release);
            return 0;
        }
    }
    return -1;  // map full
}

// flow_id -> engine row; 0 is not a valid flow id (used as empty marker)
int32_t sx_front_map_flow(sx_front* f, int64_t flow_id, int32_t row) {
    if (!f || flow_id == 0) return -1;
    return sxf_map_put(f, flow_id << 1, row, 0);
}

// param flow_id -> engine row of its $cluster/param resource + hash lane.
// The event ring carries exactly two hash lanes (a0/a1): a mapping with
// lane>1 would silently hash to 0 in sxf_parse and pass unchecked, so
// refuse it here — such rules stay on the asyncio server, which handles
// arbitrary lanes.
int32_t sx_front_map_param(sx_front* f, int64_t flow_id, int32_t row,
                           int32_t lane) {
    if (!f || flow_id == 0 || lane < 0 || lane > 1) return -1;
    return sxf_map_put(f, (flow_id << 1) | 1, row, lane);
}

// wipe every flow mapping (rule reload re-adds the live set; clear-all
// avoids open-addressing tombstones).  Lookups racing a clear observe
// NO_RULE briefly, matching the asyncio server's reload window.
void sx_front_clear_flows(sx_front* f) {
    if (!f) return;
    for (uint64_t i = 0; i <= f->fmask; ++i) {
        f->fmap[i].row.store(-1, std::memory_order_relaxed);
        f->fmap[i].lane.store(0, std::memory_order_relaxed);
        f->fmap[i].key.store(0, std::memory_order_release);
    }
}

// acquire-ring backlog (tick-side: keep draining without a timer wait)
int64_t sx_front_acq_backlog(sx_front* f) {
    return f ? sx_ring_size(f->acq) : 0;
}

void sx_front_set_guard(sx_front* f, int64_t max_per_sec) {
    if (f) f->guard_max.store(max_per_sec, std::memory_order_relaxed);
}

static int32_t sxf_lookup(sx_front* f, int64_t key, int32_t* lane_out) {
    uint64_t h = (uint64_t)key * 0x9E3779B97F4A7C15ull;
    for (uint64_t i = 0; i <= f->fmask; ++i) {
        uint64_t idx = (h + i) & f->fmask;
        int64_t k = f->fmap[idx].key.load(std::memory_order_acquire);
        if (k == key) {
            if (lane_out) *lane_out = f->fmap[idx].lane.load(std::memory_order_relaxed);
            return f->fmap[idx].row.load(std::memory_order_relaxed);
        }
        if (k == 0) return -1;
    }
    return -1;
}

// hash_param parity with core/rule_tensors.hash_param: ints/bools multiply
// by the golden ratio constant (low bits survive mod-2^64 wrap, so this
// matches Python's arbitrary-precision product & 0x7FFFFFFF); strings are
// 32-bit FNV-1a masked to 31 bits; 0 maps to 1 ("no parameter" sentinel).
static int32_t sxf_hash_int(int64_t v) {
    uint64_t h = (uint64_t)v * 0x9E3779B1ull;
    int32_t r = (int32_t)(h & 0x7FFFFFFFull);
    return r == 0 ? 1 : r;
}
static int32_t sxf_hash_str(const uint8_t* s, size_t n) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < n; ++i) h = (h ^ s[i]) * 16777619u;
    int32_t r = (int32_t)(h & 0x7FFFFFFFu);
    return r == 0 ? 1 : r;
}

static void sxf_queue_resp(sx_conn* c, int32_t xid, uint8_t type, int8_t status,
                           int32_t remaining, int32_t wait_ms,
                           int64_t token_id = 0) {
    // 2-byte BE length + xid(4) type(1) status(1) + typed payload:
    //   flow/param/batch -> remaining(4) wait(4); concurrent acq -> token(8)
    uint8_t body[14];
    body[0] = (uint8_t)(xid >> 24); body[1] = (uint8_t)(xid >> 16);
    body[2] = (uint8_t)(xid >> 8);  body[3] = (uint8_t)xid;
    body[4] = type;
    body[5] = (uint8_t)status;
    size_t n = 6;
    if (type == 1 || type == 2 || type == 10) {
        body[6] = (uint8_t)(remaining >> 24); body[7] = (uint8_t)(remaining >> 16);
        body[8] = (uint8_t)(remaining >> 8);  body[9] = (uint8_t)remaining;
        body[10] = (uint8_t)(wait_ms >> 24);  body[11] = (uint8_t)(wait_ms >> 16);
        body[12] = (uint8_t)(wait_ms >> 8);   body[13] = (uint8_t)wait_ms;
        n = 14;
    } else if (type == 3) {
        for (int i = 0; i < 8; ++i)
            body[6 + i] = (uint8_t)(token_id >> (8 * (7 - i)));
        n = 14;
    }
    c->wbuf.push_back((uint8_t)(n >> 8));
    c->wbuf.push_back((uint8_t)n);
    c->wbuf.insert(c->wbuf.end(), body, body + n);
}

static void sxf_flush(sx_front* f, sx_conn* c) {
    while (c->woff < c->wbuf.size()) {
        ssize_t w = write(c->fd, c->wbuf.data() + c->woff, c->wbuf.size() - c->woff);
        if (w > 0) {
            c->woff += (size_t)w;
        } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            break;  // EPOLLOUT (level-triggered epoll retries us next loop)
        } else {
            c->closing = true;
            break;
        }
    }
    if (c->woff >= c->wbuf.size()) {
        c->wbuf.clear();
        c->woff = 0;
    } else if (c->woff > (1u << 20)) {
        c->wbuf.erase(c->wbuf.begin(), c->wbuf.begin() + c->woff);
        c->woff = 0;
    }
}

static bool sxf_guard_ok(sx_front* f) {
    int64_t mx = f->guard_max.load(std::memory_order_relaxed);
    if (mx < 0) return true;
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC_COARSE, &ts);
    if (ts.tv_sec != f->guard_epoch) {
        f->guard_epoch = ts.tv_sec;
        f->guard_count = 0;
    }
    return ++f->guard_count <= mx;
}

static void sxf_parse(sx_front* f, sx_conn* c) {
    size_t off = 0;
    auto& b = c->rbuf;
    while (b.size() - off >= 2) {
        size_t len = ((size_t)b[off] << 8) | b[off + 1];
        if (b.size() - off - 2 < len) break;
        const uint8_t* p = b.data() + off + 2;
        off += 2 + len;
        if (len < 5) continue;
        int32_t xid = ((int32_t)p[0] << 24) | ((int32_t)p[1] << 16) |
                      ((int32_t)p[2] << 8) | (int32_t)p[3];
        uint8_t type = p[4];
        if (type == 0) {  // PING — namespace payload ignored (single-tenant door)
            sxf_queue_resp(c, xid, 0, ST_OK, 0, 0);
            continue;
        }
        if (type == 1 && len >= 5 + 13) {  // FLOW
            int64_t flow_id = 0;
            for (int i = 0; i < 8; ++i) flow_id = (flow_id << 8) | p[5 + i];
            int32_t count = ((int32_t)p[13] << 24) | ((int32_t)p[14] << 16) |
                            ((int32_t)p[15] << 8) | (int32_t)p[16];
            uint8_t prio = p[17];
            int32_t row = sxf_lookup(f, flow_id << 1, nullptr);
            if (row < 0) {
                sxf_queue_resp(c, xid, 1, ST_NO_RULE, 0, 0);
                continue;
            }
            if (!sxf_guard_ok(f) || f->freelist.empty()) {
                sxf_queue_resp(c, xid, 1, ST_TOO_MANY, 0, 0);
                continue;
            }
            int32_t corr = f->freelist.back();
            f->freelist.pop_back();
            f->pend[corr] = Pend{c->fd, c->gen, xid, 1, 1, ST_OK};
            if (sx_ring_push(f->acq, row, count, 0, 0, (1 << 4) | (prio ? 2 : 0),
                             0.0f, 0, corr, 0, 0, 0, 0) != 0) {
                f->freelist.push_back(corr);
                sxf_queue_resp(c, xid, 1, ST_TOO_MANY, 0, 0);
            }
            continue;
        }
        if (type == 2 && len >= 5 + 12) {  // PARAM_FLOW
            int64_t flow_id = 0;
            for (int i = 0; i < 8; ++i) flow_id = (flow_id << 8) | p[5 + i];
            int32_t count = ((int32_t)p[13] << 24) | ((int32_t)p[14] << 16) |
                            ((int32_t)p[15] << 8) | (int32_t)p[16];
            int32_t lane = 0;
            int32_t row = sxf_lookup(f, (flow_id << 1) | 1, &lane);
            if (row < 0) {
                sxf_queue_resp(c, xid, 2, ST_NO_RULE, 0, 0);
                continue;
            }
            // parse typed params (ParamFlowRequestDataWriter envelope,
            // protocol.py tags): int(0x00 i32) long(0x01 i64) double(0x02)
            // string(0x03 u16+utf8) bool(0x04).  A double can't reproduce
            // Python's str() hashing in C — answer FAIL and let the caller
            // use the asyncio server.
            int32_t hashes[16];
            int k = 0;
            bool bad = false, dbl = false;
            size_t q = 17;  // offset of the params blob within the frame
            while (q < len && k < 16) {
                uint8_t tag = p[q++];
                if (tag == 0 && q + 4 <= len) {
                    int32_t v = ((int32_t)p[q] << 24) | ((int32_t)p[q + 1] << 16) |
                                ((int32_t)p[q + 2] << 8) | (int32_t)p[q + 3];
                    hashes[k++] = sxf_hash_int(v);
                    q += 4;
                } else if (tag == 1 && q + 8 <= len) {
                    int64_t v = 0;
                    for (int i = 0; i < 8; ++i) v = (v << 8) | p[q + i];
                    hashes[k++] = sxf_hash_int(v);
                    q += 8;
                } else if (tag == 4 && q + 1 <= len) {
                    hashes[k++] = sxf_hash_int(p[q] ? 1 : 0);
                    q += 1;
                } else if (tag == 3 && q + 2 <= len) {
                    size_t sn = ((size_t)p[q] << 8) | p[q + 1];
                    q += 2;
                    if (q + sn > len) { bad = true; break; }
                    hashes[k++] = sxf_hash_str(p + q, sn);
                    q += sn;
                } else if (tag == 2) {
                    dbl = true;
                    break;
                } else {
                    bad = true;
                    break;
                }
            }
            if (dbl) { sxf_queue_resp(c, xid, 2, ST_FAIL, 0, 0); continue; }
            if (k == 16 && q < len) {
                // more than 16 values: refuse loudly rather than silently
                // check a prefix (the asyncio server handles such requests)
                sxf_queue_resp(c, xid, 2, ST_FAIL, 0, 0);
                continue;
            }
            if (bad || k == 0) { sxf_queue_resp(c, xid, 2, ST_BAD, 0, 0); continue; }
            if (!sxf_guard_ok(f) || f->freelist.empty()) {
                sxf_queue_resp(c, xid, 2, ST_TOO_MANY, 0, 0);
                continue;
            }
            int32_t corr = f->freelist.back();
            f->freelist.pop_back();
            f->pend[corr] = Pend{c->fd, c->gen, xid, 2, (int16_t)k, ST_OK};
            int pushed = 0;
            for (int i = 0; i < k; ++i) {
                int32_t a0 = lane == 0 ? hashes[i] : 0;
                int32_t a1 = lane == 1 ? hashes[i] : 0;
                if (sx_ring_push(f->acq, row, count, 0, 0, (2 << 4), 0.0f, 0,
                                 corr, a0, a1, 0, 0) != 0)
                    break;
                ++pushed;
            }
            if (pushed == 0) {
                f->freelist.push_back(corr);
                sxf_queue_resp(c, xid, 2, ST_TOO_MANY, 0, 0);
            } else if (pushed < k) {
                // partial push: the join completes over the pushed items
                // with a TOO_MANY floor so the caller sees backpressure
                f->pend[corr].remaining = (int16_t)pushed;
                f->pend[corr].worst = ST_TOO_MANY;
            }
            continue;
        }
        if ((type == 3 && len >= 5 + 12) || (type == 4 && len >= 5 + 8)) {
            // CONCURRENT acquire/release: host-managed (TTL token table) —
            // ride the same ring, answered via sx_front_respond_ex
            int64_t v = 0;
            for (int i = 0; i < 8; ++i) v = (v << 8) | p[5 + i];
            int32_t count = 1;
            if (type == 3)
                count = ((int32_t)p[13] << 24) | ((int32_t)p[14] << 16) |
                        ((int32_t)p[15] << 8) | (int32_t)p[16];
            if (!sxf_guard_ok(f) || f->freelist.empty()) {
                sxf_queue_resp(c, xid, type, ST_TOO_MANY, 0, 0);
                continue;
            }
            int32_t corr = f->freelist.back();
            f->freelist.pop_back();
            f->pend[corr] = Pend{c->fd, c->gen, xid, type, 1, ST_OK};
            if (sx_ring_push(f->acq, -1, count, 0, 0, ((int32_t)type << 4),
                             0.0f, 0, corr, (int32_t)(v >> 32),
                             (int32_t)(v & 0xFFFFFFFF), 0, 0) != 0) {
                f->freelist.push_back(corr);
                sxf_queue_resp(c, xid, type, ST_TOO_MANY, 0, 0);
            }
            continue;
        }
        sxf_queue_resp(c, xid, type, ST_FAIL, 0, 0);
    }
    if (off) b.erase(b.begin(), b.begin() + off);
}

static void sxf_drain_responses(sx_front* f) {
    constexpr int64_t MAXB = 8192;
    static thread_local std::vector<int32_t> corr(MAXB), verdict(MAXB),
        wait(MAXB), th(MAXB), tl(MAXB), i2(MAXB), i3(MAXB), a0(MAXB), a1(MAXB),
        a2(MAXB), a3(MAXB);
    static thread_local std::vector<float> f0(MAXB);
    for (;;) {
        int64_t n = sx_ring_drain(f->resp, MAXB, corr.data(), verdict.data(),
                                  wait.data(), th.data(), tl.data(), f0.data(),
                                  i2.data(), i3.data(), a0.data(), a1.data(),
                                  a2.data(), a3.data());
        if (n <= 0) break;
        for (int64_t i = 0; i < n; ++i) {
            int32_t slot = corr[i];
            if (slot < 0 || (size_t)slot >= f->pend.size()) continue;
            Pend& pd = f->pend[slot];
            int8_t st = (int8_t)verdict[i];
            if (st != ST_OK && pd.worst == ST_OK) pd.worst = st;
            if (--pd.remaining > 0) continue;  // multi-param join pending
            Pend done = pd;
            f->freelist.push_back(slot);
            auto it = f->conns.find(done.fd);
            if (it == f->conns.end() || it->second->gen != done.gen) continue;
            int8_t final_st = done.type == 2 ? done.worst : st;
            int64_t tok = ((int64_t)(uint32_t)th[i] << 32) | (uint32_t)tl[i];
            sxf_queue_resp(it->second, done.xid, done.type, final_st, 0,
                           wait[i], tok);
        }
        if (n < MAXB) break;
    }
}

static void sxf_close(sx_front* f, sx_conn* c) {
    epoll_ctl(f->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
    f->conns.erase(c->fd);
    delete c;
}

static void sxf_io_loop(sx_front* f) {
    f->epfd = epoll_create1(0);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = f->listen_fd;
    epoll_ctl(f->epfd, EPOLL_CTL_ADD, f->listen_fd, &ev);
    std::vector<epoll_event> evs(256);
    uint8_t buf[65536];
    while (f->running.load(std::memory_order_relaxed)) {
        int n = epoll_wait(f->epfd, evs.data(), (int)evs.size(), 1);
        for (int i = 0; i < n; ++i) {
            int fd = evs[i].data.fd;
            if (fd == f->listen_fd) {
                for (;;) {
                    int cfd = accept(f->listen_fd, nullptr, nullptr);
                    if (cfd < 0) break;
                    sxf_set_nonblock(cfd);
                    int one = 1;
                    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
                    auto* c = new sx_conn();
                    c->fd = cfd;
                    c->gen = ++f->gen_seq;
                    f->conns[cfd] = c;
                    epoll_event cev{};
                    cev.events = EPOLLIN;
                    cev.data.fd = cfd;
                    epoll_ctl(f->epfd, EPOLL_CTL_ADD, cfd, &cev);
                }
                continue;
            }
            auto it = f->conns.find(fd);
            if (it == f->conns.end()) continue;
            sx_conn* c = it->second;
            for (;;) {
                ssize_t r = read(fd, buf, sizeof buf);
                if (r > 0) {
                    c->rbuf.insert(c->rbuf.end(), buf, buf + r);
                    if (r < (ssize_t)sizeof buf) break;
                } else if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
                    break;
                } else {
                    c->closing = true;
                    break;
                }
            }
            if (!c->closing) sxf_parse(f, c);
        }
        sxf_drain_responses(f);
        std::vector<sx_conn*> dead;
        for (auto& kv : f->conns) {
            sxf_flush(f, kv.second);
            if (kv.second->closing && kv.second->woff >= kv.second->wbuf.size())
                dead.push_back(kv.second);
        }
        for (auto* c : dead) sxf_close(f, c);
    }
    for (auto& kv : f->conns) {
        close(kv.first);
        delete kv.second;
    }
    f->conns.clear();
    close(f->epfd);
    f->epfd = -1;
}

int32_t sx_front_start(sx_front* f) {
    if (!f || f->running.load()) return -1;
    f->running.store(true);
    f->io = std::thread(sxf_io_loop, f);
    return 0;
}

void sx_front_stop(sx_front* f) {
    if (!f) return;
    if (f->running.exchange(false) && f->io.joinable()) f->io.join();
}

void sx_front_free(sx_front* f) {
    if (!f) return;
    sx_front_stop(f);
    if (f->listen_fd >= 0) close(f->listen_fd);
    sx_ring_free(f->acq);
    sx_ring_free(f->resp);
    delete[] f->fmap;
    delete f;
}

// tick side: drain pending FLOW acquires into batch columns.
// prio[i] receives 1 for prioritized requests (bit1 of the event flags).
int64_t sx_front_drain_acquires(sx_front* f, int64_t max_n, int32_t* row,
                                int32_t* count, int32_t* prio, int32_t* corr) {
    static thread_local std::vector<int32_t> scratch_i;
    static thread_local std::vector<float> scratch_f;
    if ((int64_t)scratch_i.size() < max_n * 7) scratch_i.resize(max_n * 7);
    if ((int64_t)scratch_f.size() < max_n) scratch_f.resize(max_n);
    int32_t* origin = scratch_i.data();
    int32_t* ph = origin + max_n;
    int32_t* err = ph + max_n;
    int32_t* a0 = err + max_n;
    int32_t* a1 = a0 + max_n;
    int32_t* a2 = a1 + max_n;
    int32_t* a3 = a2 + max_n;
    int64_t n = sx_ring_drain(f->acq, max_n, row, count, origin, ph, prio,
                              scratch_f.data(), err, corr, a0, a1, a2, a3);
    for (int64_t i = 0; i < n; ++i) prio[i] = (prio[i] >> 1) & 1;
    return n;
}

// tick side: typed drain — kind[i] = MSG_TYPE (1 flow, 2 param, 3/4
// concurrent acquire/release); a0/a1 carry param hash lanes (kind 2) or
// the 64-bit flow/token id halves (kinds 3/4)
int64_t sx_front_drain_acquires2(sx_front* f, int64_t max_n, int32_t* row,
                                 int32_t* count, int32_t* prio, int32_t* corr,
                                 int32_t* kind, int32_t* a0, int32_t* a1) {
    static thread_local std::vector<int32_t> scratch_i;
    static thread_local std::vector<float> scratch_f;
    if ((int64_t)scratch_i.size() < max_n * 5) scratch_i.resize(max_n * 5);
    if ((int64_t)scratch_f.size() < max_n) scratch_f.resize(max_n);
    int32_t* origin = scratch_i.data();
    int32_t* ph = origin + max_n;
    int32_t* err = ph + max_n;
    int32_t* a2 = err + max_n;
    int32_t* a3 = a2 + max_n;
    int64_t n = sx_ring_drain(f->acq, max_n, row, count, origin, ph, prio,
                              scratch_f.data(), err, corr, a0, a1, a2, a3);
    for (int64_t i = 0; i < n; ++i) {
        int32_t fl = prio[i];
        prio[i] = (fl >> 1) & 1;
        int32_t k = fl >> 4;
        kind[i] = k ? k : 1;  // legacy pushes carried no kind bits
    }
    return n;
}

// tick side: push verdicts for drained acquires
int32_t sx_front_respond(sx_front* f, int64_t n, const int32_t* corr,
                         const int32_t* status, const int32_t* wait_ms) {
    int32_t dropped = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (sx_ring_push(f->resp, corr[i], status[i], wait_ms[i], 0, 0, 0.0f,
                         0, 0, 0, 0, 0, 0) != 0)
            ++dropped;
    }
    return dropped;
}

// tick side: typed respond with 64-bit token ids (concurrent acquire)
int32_t sx_front_respond_ex(sx_front* f, int64_t n, const int32_t* corr,
                            const int32_t* status, const int32_t* wait_ms,
                            const int32_t* tok_hi, const int32_t* tok_lo) {
    int32_t dropped = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (sx_ring_push(f->resp, corr[i], status[i], wait_ms[i], tok_hi[i],
                         tok_lo[i], 0.0f, 0, 0, 0, 0, 0, 0) != 0)
            ++dropped;
    }
    return dropped;
}

// ---------------------------------------------------------------------------
// batch build: the tick builder's segment-key presort
// ---------------------------------------------------------------------------
//
// The client presorts every engine batch by the segment keys before
// upload (runtime/client._run_tick).  np.lexsort is the numpy fallback;
// these produce the IDENTICAL stable permutation (std::stable_sort with
// lexicographic key compare == np.lexsort with the keys reversed) plus
// the inverse permutation in the same pass, without numpy's per-key
// temporary allocations.  Keys are int32 columns of equal length n;
// `order` receives the argsort, `inv` (nullable) the inverse.

static void sx_inverse(int64_t n, const int32_t* order, int32_t* inv) {
    for (int64_t i = 0; i < n; ++i) inv[order[i]] = (int32_t)i;
}

// acquire side: np.lexsort((k4, k3, k2, k1, k0)) — k0 most significant
int64_t sx_batch_sort5(int64_t n, const int32_t* k0, const int32_t* k1,
                       const int32_t* k2, const int32_t* k3,
                       const int32_t* k4, int32_t* order, int32_t* inv) {
    for (int64_t i = 0; i < n; ++i) order[i] = (int32_t)i;
    std::stable_sort(order, order + n, [&](int32_t a, int32_t b) {
        if (k0[a] != k0[b]) return k0[a] < k0[b];
        if (k1[a] != k1[b]) return k1[a] < k1[b];
        if (k2[a] != k2[b]) return k2[a] < k2[b];
        if (k3[a] != k3[b]) return k3[a] < k3[b];
        return k4[a] < k4[b];
    });
    if (inv) sx_inverse(n, order, inv);
    return n;
}

// completion side: np.lexsort((k2, k1, k0))
int64_t sx_batch_sort3(int64_t n, const int32_t* k0, const int32_t* k1,
                       const int32_t* k2, int32_t* order, int32_t* inv) {
    for (int64_t i = 0; i < n; ++i) order[i] = (int32_t)i;
    std::stable_sort(order, order + n, [&](int32_t a, int32_t b) {
        if (k0[a] != k0[b]) return k0[a] < k0[b];
        if (k1[a] != k1[b]) return k1[a] < k1[b];
        return k2[a] < k2[b];
    });
    if (inv) sx_inverse(n, order, inv);
    return n;
}

// -- protocol v2 BATCH framing (cluster/protocol.py) ------------------------
//
// Fixed-width big-endian column entries:
//   request entry  (14 B): [kind:u8][id:i64][count:i32][flags:u8]
//   response entry (17 B): [status:i8][remaining:i32][wait:i32][token:i64]
// Pack/unpack is the per-frame hot loop on both sides of the wire; the
// numpy fallback (ring.py structured dtypes) produces IDENTICAL bytes.

static inline void sxw_be32(uint8_t* p, uint32_t v) {
    p[0] = (uint8_t)(v >> 24); p[1] = (uint8_t)(v >> 16);
    p[2] = (uint8_t)(v >> 8);  p[3] = (uint8_t)v;
}
static inline void sxw_be64(uint8_t* p, uint64_t v) {
    sxw_be32(p, (uint32_t)(v >> 32));
    sxw_be32(p + 4, (uint32_t)v);
}
static inline uint32_t sxr_be32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}
static inline uint64_t sxr_be64(const uint8_t* p) {
    return ((uint64_t)sxr_be32(p) << 32) | (uint64_t)sxr_be32(p + 4);
}

int64_t sx_frame_pack_entries(int64_t n, const uint8_t* kinds,
                              const int64_t* ids, const int32_t* counts,
                              const uint8_t* flags, uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint8_t* e = out + i * 14;
        e[0] = kinds[i];
        sxw_be64(e + 1, (uint64_t)ids[i]);
        sxw_be32(e + 9, (uint32_t)counts[i]);
        e[13] = flags[i];
    }
    return n;
}

int64_t sx_frame_unpack_entries(int64_t n, const uint8_t* buf, uint8_t* kinds,
                                int64_t* ids, int32_t* counts,
                                uint8_t* flags) {
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* e = buf + i * 14;
        kinds[i] = e[0];
        ids[i] = (int64_t)sxr_be64(e + 1);
        counts[i] = (int32_t)sxr_be32(e + 9);
        flags[i] = e[13];
    }
    return n;
}

int64_t sx_frame_pack_results(int64_t n, const int8_t* statuses,
                              const int32_t* remainings, const int32_t* waits,
                              const int64_t* tokens, uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint8_t* e = out + i * 17;
        e[0] = (uint8_t)statuses[i];
        sxw_be32(e + 1, (uint32_t)remainings[i]);
        sxw_be32(e + 5, (uint32_t)waits[i]);
        sxw_be64(e + 9, (uint64_t)tokens[i]);
    }
    return n;
}

int64_t sx_frame_unpack_results(int64_t n, const uint8_t* buf,
                                int8_t* statuses, int32_t* remainings,
                                int32_t* waits, int64_t* tokens) {
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* e = buf + i * 17;
        statuses[i] = (int8_t)e[0];
        remainings[i] = (int32_t)sxr_be32(e + 1);
        waits[i] = (int32_t)sxr_be32(e + 5);
        tokens[i] = (int64_t)sxr_be64(e + 9);
    }
    return n;
}

}  // extern "C"
