"""String → dense-id interning for resources, origins, contexts and stat rows.

The analog of the reference's copy-on-write resource→chain map
(CtSph.lookProcessChain, CtSph.java:194-216) and the per-resource /
per-origin node maps (ClusterBuilderSlot.java:69-88,
ContextUtil.trueEnter:120).  Dense ids index the engine's structure-of-
arrays tensors directly.

Capacity semantics mirror the reference:
- beyond ``max_resources`` new resources degrade to PASS-THROUGH
  (returned id is None), exactly as lookProcessChain returns null past
  MAX_SLOT_CHAIN_SIZE=6000 (Constants.java:37);
- beyond ``max_nodes`` origin/context stat rows degrade to the trash row
  (stats dropped, decisions still made on the resource node), akin to
  MAX_CONTEXT_NAME_SIZE overflow returning NullContext
  (ContextUtil.java:120).

Thread-safe; reads are lock-free dict lookups (GIL-atomic).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from sentinel_tpu.core.config import EngineConfig


class Registry:
    #: cap on interned origins (MAX_CONTEXT_NAME_SIZE-style degradation)
    MAX_ORIGINS = 10_000

    def __init__(self, cfg: EngineConfig):
        self.cfg = cfg
        self._lock = threading.RLock()
        # resource rows occupy [1, max_resources); row 0 is the ENTRY node.
        # The TOP of the row space is a PROMOTION RESERVE: ordinary
        # first-use interning stops short of it, so a rule arriving for a
        # tail resource can still claim an exact row (SALSA-style hot
        # promotion) after organic traffic has "filled" the space.
        self._resources: Dict[str, int] = {}
        self._resource_names: List[Optional[str]] = [None] * 1
        self._next_res = 1
        reserve = min(max(cfg.max_resources // 16, 1), max(cfg.max_resources // 2, 1))
        self._organic_limit = max(cfg.max_resources - reserve, 2)
        # extra stat rows (origin nodes, context default-nodes) live in
        # [max_resources, max_nodes)
        self._extra_rows: Dict[Tuple[str, str], int] = {}
        self._next_extra = cfg.max_resources
        # sketch resources: ids beyond node_rows, stats tracked in the
        # global CMS sketch (ops/gsketch.py) instead of exact rows
        self._sketch_names: Dict[int, str] = {}
        self._next_sketch = cfg.node_rows
        # exact rows freed by demotion, quarantined until their engine
        # window state has fully expired (reuse-after ordered by free
        # time): (row, reusable_at mono_s)
        self._quarantined_rows: List[Tuple[int, float]] = []
        # origins are a separate id space (matched against limitApp)
        self._origins: Dict[str, int] = {}
        self._origin_names: List[str] = []
        # context names (CHAIN-strategy matching)
        self._contexts: Dict[str, int] = {}

    # -- resources ----------------------------------------------------------

    def resource_id(self, name: str) -> Optional[int]:
        """Dense id for a resource, interning on first use.

        Returns None when capacity is exhausted → caller passes through
        (no stats, no rules), mirroring CtSph.java:200-205.
        """
        rid = self._resources.get(name)
        if rid is not None:
            return rid
        with self._lock:
            rid = self._resources.get(name)
            if rid is not None:
                return rid
            if self._next_res >= self._organic_limit:
                # organic rows exhausted (the remainder is the promotion
                # reserve) → sketch id, or pass-through when the sketch is
                # off (CtSph.java:200-205 degradation)
                if (
                    self.cfg.sketch_stats
                    and self._next_sketch - self.cfg.node_rows
                    < self.cfg.sketch_capacity
                ):
                    rid = self._next_sketch
                    self._next_sketch += 1
                    self._resources[name] = rid
                    self._sketch_names[rid] = name
                    return rid
                return None
            rid = self._next_res
            self._next_res += 1
            self._resources[name] = rid
            self._resource_names.append(name)
            return rid

    def peek_resource_id(self, name: str) -> Optional[int]:
        return self._resources.get(name)

    def _claim_quarantined_row(self) -> Optional[int]:
        """Reusable demoted row, or None (caller holds the lock).  Rows
        become reusable only after their quarantine lapses — the engine's
        window buckets for the old occupant must have rotated out (and
        in-flight entries completed) before a new name inherits the row,
        or the newcomer would start life with the old stats/concurrency."""
        from sentinel_tpu.utils.time_source import mono_s

        if self._quarantined_rows and mono_s() >= self._quarantined_rows[0][1]:
            return self._quarantined_rows.pop(0)[0]
        return None

    def promote_resource(self, name: str) -> Optional[int]:
        """Move a sketch-id resource into the exact row space (so rules can
        bind to real windows) — the SALSA-style hot-promotion half of tail
        enforcement.  Returns the exact row, or None when the exact space
        is full (the rule then enforces approximately via the tail CMS
        tables).  In-flight events carrying the old sketch id land in the
        sketch one last time — an observability-only transient.

        Demoted rows past quarantine are reclaimed first, so a hot-set
        promote/demote cycle does not burn through the reserve."""
        with self._lock:
            rid = self._resources.get(name)
            if rid is None or rid < self.cfg.node_rows:
                return rid  # unknown or already exact
            new = self._claim_quarantined_row()
            if new is None:
                if self._next_res >= self.cfg.max_resources:
                    return None  # even the reserve is spent
                new = self._next_res
                self._next_res += 1
            self._resources[name] = new
            while len(self._resource_names) <= new:
                self._resource_names.append(None)
            self._resource_names[new] = name
            self._sketch_names.pop(rid, None)
            return new

    def demote_resource(self, name: str, quarantine_s: float) -> Optional[int]:
        """Move an exact-row resource back into the sketch tail (the
        hot-set manager's cold path).  The freed row is quarantined for
        ``quarantine_s`` REAL (wall-clock) seconds before promotion may
        reuse it — the caller sizes it to outlive every engine window
        holding the old occupant's counts plus in-flight entries on the
        row (HotSetManager uses 2x the longest window interval + 30 s).
        Virtual-time harnesses whose engine clock is decoupled from wall
        time must pass a quarantine matched to their own advance rate
        (engine windows rotate on ENGINE time, this quarantine on wall
        time).  Returns the new sketch id, or None when the resource
        cannot demote (unknown, the ENTRY row, or sketch capacity
        exhausted)."""
        from sentinel_tpu.utils.time_source import mono_s

        with self._lock:
            rid = self._resources.get(name)
            if rid is None or rid >= self.cfg.node_rows:
                return rid if rid is not None else None  # already sketch
            if rid <= 0:
                return None  # ENTRY row never demotes
            if self._next_sketch - self.cfg.node_rows >= self.cfg.sketch_capacity:
                return None
            new = self._next_sketch
            self._next_sketch += 1
            self._resources[name] = new
            self._sketch_names[new] = name
            if rid < len(self._resource_names):
                self._resource_names[rid] = None
            self._quarantined_rows.append((rid, mono_s() + quarantine_s))
            return new

    def resource_name(self, rid: int) -> Optional[str]:
        if 0 < rid < len(self._resource_names):
            return self._resource_names[rid]
        return self._sketch_names.get(rid)

    def is_sketch_id(self, rid: int) -> bool:
        return rid >= self.cfg.node_rows

    @property
    def num_resources(self) -> int:
        return self._next_res

    def resources(self) -> Dict[str, int]:
        return dict(self._resources)

    # -- origin / context stat rows ----------------------------------------

    def extra_row(self, kind: str, key: str) -> int:
        """Stat row for an origin node ('origin', '<res>|<origin>') or a
        context DefaultNode ('ctx', '<res>|<ctx>').  Trash row on overflow."""
        k = (kind, key)
        row = self._extra_rows.get(k)
        if row is not None:
            return row
        with self._lock:
            row = self._extra_rows.get(k)
            if row is not None:
                return row
            if self._next_extra >= self.cfg.max_nodes:
                return self.cfg.trash_row
            row = self._next_extra
            self._next_extra += 1
            self._extra_rows[k] = row
            return row

    def origin_node_row(self, resource: str, origin: str) -> int:
        return self.extra_row("origin", f"{resource}\x00{origin}")

    def origin_row_if_exists(self, resource: str, origin: str) -> Optional[int]:
        """Non-creating lookup of an origin stat row (None until that
        caller has been seen) — the single place the key encoding lives
        besides origin_node_row."""
        row = self._extra_rows.get(("origin", f"{resource}\x00{origin}"))
        return None if row is None or row == self.cfg.trash_row else row

    def ctx_node_row(self, resource: str, ctx: str) -> int:
        return self.extra_row("ctx", f"{resource}\x00{ctx}")

    def extra_rows(self) -> Dict[Tuple[str, str], int]:
        return dict(self._extra_rows)

    # -- origins ------------------------------------------------------------

    def context_id(self, name: str) -> int:
        """Intern a context name (for CHAIN-strategy matching). '' → -1."""
        if not name:
            return -1
        cid = self._contexts.get(name)
        if cid is not None:
            return cid
        with self._lock:
            cid = self._contexts.get(name)
            if cid is not None:
                return cid
            cid = len(self._contexts)
            self._contexts[name] = cid
            return cid

    def origin_id(self, origin: str) -> int:
        """Intern an origin string. '' (no origin) maps to -1.

        Capped at MAX_ORIGINS distinct values: beyond that, new origins map
        to -1 (anonymous) instead of growing without bound — the analog of
        MAX_CONTEXT_NAME_SIZE pass-through degradation (Constants.java:36)
        for adversarial/high-cardinality origins (e.g. client IPs)."""
        if not origin:
            return -1
        oid = self._origins.get(origin)
        if oid is not None:
            return oid
        with self._lock:
            oid = self._origins.get(origin)
            if oid is not None:
                return oid
            if len(self._origin_names) >= self.MAX_ORIGINS:
                return -1
            oid = len(self._origin_names)
            self._origins[origin] = oid
            self._origin_names.append(origin)
            return oid

    def peek_origin_id(self, origin: str) -> int:
        if not origin:
            return -1
        return self._origins.get(origin, -1)
