"""The host runtime: micro-batching client around the device engine.

This layer replaces the reference's per-request machinery (CtSph.java:43,
CtEntry, the slot-chain walk) with an accumulate→tick→fan-out loop:

  entry("res")  ──► AcquireRequest + Future ──┐
  entry.exit()  ──► completion record ────────┤  pending queues
                                              ▼
                         tick thread (every ~tick_interval_ms, or manual):
                           drain queues → fixed-shape batches → jitted
                           engine tick → resolve futures with verdicts

Modes:
  * ``sync``    — every entry() runs a tick inline (batch of whatever is
                  queued).  Deterministic; pairs with VirtualTimeSource for
                  tests (the AbstractTimeBasedTest analog, SURVEY.md §4.1).
  * ``threaded``— a daemon tick loop services futures; entry() blocks.
                  This is the serving configuration.

Bulk paths: ``check_batch`` submits N acquires in one call (per-item
objects); ``submit_block``/``check_batch_ids`` submit COLUMN ARRAYS of
resource ids with zero per-item Python — the TPU-native surface used by
the cluster token server, gateway adapters, and the benchmark.

Fast-path integration (the config defaults to it on TPU — see
core.config.platform_engine_config): with the segment-compacted engine
enabled, the tick builder presorts every batch by the engine's segment
keys (np.lexsort; stable, so per-key arrival order and therefore every
rank/verdict is bit-identical) and maps verdicts back through the inverse
permutation; observed live-segment counts auto-grow cfg.seg_u via a
compile-then-swap resize; with ``pipeline_depth`` > 0 the loop runs up to
that many ticks ahead of verdict readback so the device→host transfer
overlaps compute (it drains fully before going idle).
"""

from __future__ import annotations

import threading
import time as _time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutTimeout
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from sentinel_tpu.adaptive import degrade as DG
from sentinel_tpu.adaptive.controller import AdaptiveConfig, AdaptiveController
from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core import rules as R
from sentinel_tpu.core.config import EngineConfig
from sentinel_tpu.core.rule_tensors import compile_system_rules, hash_param
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import window as W
from sentinel_tpu.ops import wire as WIRE
from sentinel_tpu.obs import flight as FL
from sentinel_tpu.obs import profile as PROF
from sentinel_tpu.obs import timeline as TLM
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.registry import REGISTRY as OBS
from sentinel_tpu.native import ring as RING
from sentinel_tpu.runtime import context as CTX
from sentinel_tpu.runtime.registry import Registry
from sentinel_tpu.metrics import extension as MEXT
from sentinel_tpu.utils.system_status import SystemStatusSampler
from sentinel_tpu.utils.time_source import TimeSource, VirtualTimeSource, mono_s

# -- observability plane (obs/): per-stage tick histograms, pipeline
# gauges, and incident counters.  Stage HISTOGRAMS update only while
# tracing is enabled (OT.t0() truthiness is the hot path's single flag
# check); pipeline gauges (one float store) and incident counters (seg
# drops, degrade transitions — rare) update unconditionally so the
# always-on /metrics surface is trustworthy even untraced.
_H_ASSEMBLE = OBS.histogram(
    "sentinel_tick_assemble_ms", "host batch assembly (columns + uploads) per tick"
)
_H_PRESORT = OBS.histogram(
    "sentinel_tick_presort_ms", "host segment-key presort (np.lexsort + permute) per tick"
)
_H_DISPATCH = OBS.histogram(
    "sentinel_tick_dispatch_ms", "engine tick dispatch (async jit call) per tick"
)
_H_DEVICE = OBS.histogram(
    "sentinel_tick_device_ms",
    "dispatch to verdicts-host-visible per tick (device compute + transfer; "
    "includes pipeline queue wait)",
)
_H_READBACK = OBS.histogram(
    "sentinel_tick_readback_ms", "verdict/wait/drop-count device-to-host reads per tick"
)
_H_RESOLVE = OBS.histogram(
    "sentinel_tick_resolve_ms", "verdict fan-out (futures, blocks, front doors) per tick"
)
_G_OCCUPANCY = OBS.gauge(
    "sentinel_pipeline_occupancy", "dispatched-but-unresolved engine ticks"
)
_G_RESOLVER_Q = OBS.gauge(
    "sentinel_resolver_queue_depth", "in-flight resolver-pool readbacks"
)
_C_SEG_DROPPED = OBS.counter(
    "sentinel_seg_dropped_total",
    "items whose effects a seg_fallback=False engine dropped on capacity overflow",
)
_G_DEGRADED = OBS.gauge(
    "sentinel_cluster_degraded", "1 while cluster enforcement is degraded to local rules"
)
_C_DEGRADE_ENTER = OBS.counter(
    "sentinel_cluster_degrade_transitions_total",
    "cluster degrade state transitions",
    labels={"transition": "enter"},
)
_C_DEGRADE_EXIT = OBS.counter(
    "sentinel_cluster_degrade_transitions_total",
    "cluster degrade state transitions",
    labels={"transition": "exit"},
)
_C_SEG_RESIZE = OBS.counter(
    "sentinel_seg_resizes_total", "seg_u capacity grow-and-hot-swap events"
)
_C_RESOLVE_FAILED = OBS.counter(
    "sentinel_resolve_failures_total",
    "tick resolutions that raised; their items failed CLOSED (system block)",
)
# -- adaptive protection / backpressure (adaptive/): shed accounting, the
# live admission ceiling, and the tick watchdog.  Registered at import so
# the exposition surface carries them from process start.
_SHED_HELP = "admissions shed before device dispatch, by stage and reason"
_C_SHED: Dict[tuple, Any] = {
    (st, rs): OBS.counter(
        "sentinel_shed_total", _SHED_HELP, labels={"stage": st, "reason": rs}
    )
    for st, rs in (
        ("admit", "queue_full"),
        ("admit", "low_priority"),
        ("admit", "fail_closed"),
        ("admit", "deadline"),
        ("admit", "chaos"),
        ("tick", "deadline"),
    )
}
_C_WATCHDOG = OBS.counter(
    "sentinel_watchdog_fired_total",
    "stalled engine ticks the watchdog failed CLOSED",
)
# -- device-resident telemetry (cfg.device_telemetry): the engine emits a
# compact stats row per tick (ops/engine.STAT_*) and the readback folds it
# here — the registry's verdict-mix/ceiling/window view comes from the
# DEVICE's accounting, not a host-side re-scan of the verdict array.
_DEV_VERDICTS_HELP = (
    "per-tick verdict mix reported by the device telemetry row, by verdict"
)
_C_DEV_VERDICTS: Dict[str, Any] = {
    v: OBS.counter(
        "sentinel_device_verdicts_total", _DEV_VERDICTS_HELP, labels={"verdict": v}
    )
    for v in (
        "pass",
        "pass_wait",
        "block_authority",
        "block_system",
        "block_param",
        "block_flow",
        "block_degrade",
    )
}
_C_DEV_TOKENS = {
    r: OBS.counter(
        "sentinel_device_tokens_total",
        "admitted/blocked token sums from the device telemetry row",
        labels={"result": r},
    )
    for r in ("pass", "block")
}
_C_DEV_FORCED = OBS.counter(
    "sentinel_device_forced_verdicts_total",
    "host-injected pre-verdicts (cluster token denials) the device recorded",
)
_G_DEV_WIN_PASS = OBS.gauge(
    "sentinel_device_entry_pass_window",
    "ENTRY-node sliding-window pass sum as computed on-device",
)
_G_DEV_MIN_RT = OBS.gauge(
    "sentinel_device_entry_min_rt_ms",
    "ENTRY-node windowed RT floor as computed on-device (0 = no completions)",
)
_G_DEV_CONC = OBS.gauge(
    "sentinel_device_entry_concurrency",
    "global inbound concurrency as computed on-device",
)
_G_DEV_CEIL_UTIL = OBS.gauge(
    "sentinel_device_ceiling_utilization",
    "windowed ENTRY pass over the active system qps ceiling (0 = no ceiling)",
)
_G_DEV_SEG_LIVE = OBS.gauge(
    "sentinel_device_seg_live",
    "live compacted segments in the last tick (seg path only)",
)
# -- wire byte accounting: what actually crosses the host<->device tunnel
# and the cluster protocol per tick — the 5.37 MB/tick ROADMAP item 1
# must shrink, so it is measured where it moves (bench emits the deltas
# as the stage_breakdown_ms sibling key `wire_bytes`).
_C_WIRE = {
    d: OBS.counter(
        "sentinel_wire_bytes_total",
        "bytes moved, by path (device|cluster) and direction (tx|rx)",
        labels={"path": "device", "direction": d},
    )
    for d in ("tx", "rx")
}
_C_PACKED_DECODE = OBS.counter(
    "sentinel_packed_decode_failures_total",
    "fused wire readbacks rejected by the packed decoder (tick fails CLOSED)",
)
_C_COLS_SKIPPED = OBS.counter(
    "sentinel_wire_cols_skipped_total",
    "batch-column uploads skipped because the column matched the previous tick",
)
# -- window rotation cadence (r14 running-sum windows, ops/window.py):
# refresh() is a pure function of the stamped tick timestamp, so the
# host derives the device's rotation/skip decisions from the timestamps
# it stamps — no readback.  "second" is the exact tier (g=1, every
# boundary rotates); "sketch" is the minute-scale tier where slack_frac
# batches the purge every g buckets (skips = deferred boundaries).
_C_WIN_ROT = {
    w: OBS.counter(
        "sentinel_window_rotations_total",
        "window bucket rotations whose batched expiry purge ran (host-derived"
        " from the tick timestamps; mirrors the device rotation condition)",
        labels={"window": w},
    )
    for w in ("second", "sketch")
}
_C_WIN_SLACK = {
    w: OBS.counter(
        "sentinel_window_slack_skips_total",
        "window bucket boundaries crossed with the expiry purge deferred by"
        " slack batching (bounded overestimate until the next rotation)",
        labels={"window": w},
    )
    for w in ("second", "sketch")
}


def _shed_counter(stage: str, reason: str):
    c = _C_SHED.get((stage, reason))
    if c is None:
        c = _C_SHED[(stage, reason)] = OBS.counter(
            "sentinel_shed_total", _SHED_HELP, labels={"stage": stage, "reason": reason}
        )
    return c

#: chaos failpoints (chaos/failpoints.py) on the tick loop's own failure
#: surfaces — one flag check per site when disarmed
_FP_TICK_CLOCK = FP.register(
    "runtime.tick.clock", "engine tick timestamp (skew shifts windows)",
    FP.SKEW_ACTIONS,
)
_FP_READBACK = FP.register(
    "runtime.resolve.readback", "verdict device-to-host readback", FP.HIT_ACTIONS
)
_FP_FANOUT = FP.register(
    "runtime.resolve.fanout", "verdict fan-out to futures/blocks/doors",
    FP.HIT_ACTIONS,
)
_FP_SEG_RESIZE = FP.register(
    "runtime.seg.resize", "background seg_u grow-and-swap compile", FP.HIT_ACTIONS
)
_FP_ADMIT = FP.register(
    "runtime.client.admit",
    "pre-engine admission shed check (a raise sheds the request CLOSED)",
    FP.HIT_ACTIONS,
)
_FP_WD_STALL = FP.register(
    "runtime.watchdog.stall",
    "verdict readback entry (a delay stalls the tick for the watchdog)",
    FP.HIT_ACTIONS,
)
_FP_PACKED_DECODE = FP.register(
    "transport.packed.decode",
    "fused packed-wire readback bytes (mangled bytes fail the tick CLOSED)",
    FP.PIPE_ACTIONS,
)


@dataclass
class AcquireRequest:
    res: int
    count: int
    prio: int
    origin_id: int
    origin_node: int
    ctx_node: int
    ctx_name: int
    inbound: int
    param_hash: tuple  # param_dims hashed hot-param lanes (0 = none)
    pre_verdict: int = 0  # host-decided verdict (cluster denial) to record
    #: absolute engine-time ms past which the answer is worthless to the
    #: caller (0 = none); expired entries shed CLOSED before dispatch
    deadline_ms: int = 0
    future: Optional[Future] = None


@dataclass
class Completion:
    res: int
    origin_node: int
    ctx_node: int
    inbound: int
    rt: float
    success: int
    error: int
    param_hash: tuple = ()  # THREAD-grade release lanes


@dataclass
class ArrayBlock:
    """A bulk acquire submission: column arrays, no per-item Python.

    The TPU-native high-throughput surface (gateway adapters, the cluster
    token server, the benchmark): resource IDS (registry currency) and
    optional per-item columns.  The tick loop slices blocks into engine
    batches; ``future`` resolves to (verdicts int8 [n], waits int32 [n])
    in submission order once every item has been decided."""

    res: np.ndarray  # int32 [n]
    count: Optional[np.ndarray] = None
    prio: Optional[np.ndarray] = None
    origin_id: Optional[np.ndarray] = None
    origin_node: Optional[np.ndarray] = None
    ctx_node: Optional[np.ndarray] = None
    ctx_name: Optional[np.ndarray] = None
    inbound: Optional[np.ndarray] = None
    param_hash: Optional[np.ndarray] = None  # int32 [n, param_dims]
    pre_verdict: Optional[np.ndarray] = None
    #: block-wide absolute engine-time deadline (0 = none); the untaken
    #: remainder of an expired block sheds CLOSED at the tick builder
    deadline_ms: int = 0
    future: Optional[Future] = None
    # internal progress
    taken: int = 0  # items already placed into ticks
    unresolved: int = 0  # items whose verdicts are still pending
    verdicts: Optional[np.ndarray] = None  # int8 [n] result buffer
    waits: Optional[np.ndarray] = None  # int32 [n] result buffer


@dataclass
class _PendingTick:
    """A dispatched engine tick whose outputs haven't been read back.

    The tick loop resolves these up to ``pipeline_depth`` ticks behind
    dispatch, so the device→host verdict transfer of tick t overlaps the
    host build + device compute of tick t+1 (on a tunnel-attached TPU the
    transfer RTT dominates; on host-attached PCIe this costs nothing and
    depth 0 behaves identically)."""

    acq: List[AcquireRequest]
    blocks: list  # [(ArrayBlock, src_off, take), ...] at batch offset n
    fronts: list
    inv_a: Optional[np.ndarray]
    out: Any  # TickOutput (device arrays)
    check_dropped: bool
    n_obj: int  # object-request count (blocks start here)
    n_blk: int  # block item count (fronts start at n_obj + n_blk)
    #: packed-wire offset table for this tick's batch shape (ops/wire.py);
    #: captured at DISPATCH so a concurrent cfg swap can't skew the decode
    wire_lo: Any = None
    tick_id: int = 0  # obs trace correlation id (0 = tracing disabled)
    dispatched_ns: int = 0  # obs: dispatch-complete stamp for the device span
    now_ms: int = 0  # engine timestamp the tick ran at (timeline fold key)
    # fan-out progress (count of blocks/fronts fully resolved): a failed
    # resolve must fail CLOSED only the consumers the normal path hadn't
    # reached — no double-decrement, no double-respond (_fail_tick)
    blocks_done: int = 0
    fronts_done: int = 0
    # watchdog handshake: exactly ONE side fans this tick out.  The
    # resolver claims "done" after readback, the watchdog (or the
    # resolve-failure path) claims "failed" — whoever wins the state
    # transition under state_lock owns the fan-out; the loser discards.
    state: str = "pending"  # pending | done | failed
    state_lock: threading.Lock = field(default_factory=threading.Lock)
    deadline_mono: float = 0.0  # mono_s() stall deadline (0 = unwatched)


class Entry:
    """Live entry handle (the reference's Entry/CtEntry).

    ``exit()`` records RT + success; ``trace(exc)`` marks a business
    exception for exception-ratio circuit breakers (Tracer.java).
    """

    __slots__ = (
        "client",
        "resource",
        "res",
        "origin_node",
        "ctx_node",
        "inbound",
        "count",
        "create_ms",
        "wait_ms",
        "param_hash",
        "_errors",
        "_exited",
        "slots",
        "slot_ctx",
    )

    def __init__(self, client, resource, res, origin_node, ctx_node, inbound, count, create_ms, wait_ms=0, param_hash=()):
        self.client = client
        self.resource = resource
        self.res = res
        self.origin_node = origin_node
        self.ctx_node = ctx_node
        self.inbound = inbound
        self.count = count
        self.create_ms = create_ms
        self.wait_ms = wait_ms
        self.param_hash = param_hash
        self._errors = 0
        self._exited = False
        self.slots = ()  # entered custom slots (runtime/slots.py)
        self.slot_ctx = None

    def trace(self, exc: Optional[BaseException] = None, count: int = 1) -> None:
        if exc is not None and isinstance(exc, ERR.BlockException):
            return  # block exceptions are not business errors (Tracer semantics)
        self._errors += count

    def exit(self, count: Optional[int] = None) -> None:
        if self._exited:
            return
        self._exited = True
        CTX.pop_entry(self)
        if self.res is None:
            return  # pass-through entry (capacity overflow)
        now = self.client.time.now_ms()
        rt = float(max(now - self.create_ms, 0))
        n = count if count is not None else self.count
        MEXT.safe_dispatch("on_complete", self.resource, rt, n, "")
        if self._errors:
            MEXT.safe_dispatch("on_exception", self.resource, self._errors, "")
        self.client._submit_completion(
            Completion(
                res=self.res,
                origin_node=self.origin_node,
                ctx_node=self.ctx_node,
                inbound=self.inbound,
                rt=rt,
                success=count if count is not None else self.count,
                error=self._errors,
                param_hash=self.param_hash,
            )
        )
        if self.slots:
            from sentinel_tpu.runtime.slots import run_exit

            self.slot_ctx.rt_ms = rt
            self.slot_ctx.success = n
            self.slot_ctx.errors = self._errors
            run_exit(self.slots, self.slot_ctx)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.trace(exc)
        self.exit()
        return False


class _PassThroughEntry(Entry):
    def __init__(self, client, resource):
        super().__init__(client, resource, None, 0, 0, 0, 1, 0)


class RuleManager:
    """Typed rule holder with push-style listeners.

    The analog of FlowRuleManager/DegradeRuleManager/...: ``load`` replaces
    the full rule set and triggers engine recompilation
    (FlowRuleManager.loadRules → property.updateValue → listener).
    """

    def __init__(self, client: "SentinelClient", kind: str):
        self._client = client
        self.kind = kind
        self._rules: list = []
        self._listeners: list = []
        self._property = None

    def load(self, rules: Sequence) -> None:
        self._rules = list(rules) if rules else []
        self._client._recompile_rules()
        for fn in list(self._listeners):
            fn(self._rules)

    def get(self) -> list:
        return list(self._rules)

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    def register_property(self, prop) -> None:
        """Subscribe this manager to a SentinelProperty so datasource pushes
        drive rule reloads (FlowRuleManager.register2Property analog)."""
        from sentinel_tpu.datasource.property import SimplePropertyListener

        if self._property is not None:
            self._property.remove_listener(self._prop_listener)
        self._property = prop
        # None means "property not populated yet" — keep existing rules
        # (FlowPropertyListener.configLoad null-check); an empty list is a
        # real "clear all rules" push.
        self._prop_listener = SimplePropertyListener(
            lambda rules: None if rules is None else self.load(rules)
        )
        prop.add_listener(self._prop_listener)


class SentinelClient:
    def __init__(
        self,
        app_name: Optional[str] = None,
        cfg: Optional[EngineConfig] = None,
        time_source: Optional[TimeSource] = None,
        mode: str = "threaded",  # "threaded" | "sync"
        tick_interval_ms: float = 1.0,
        entry_timeout_s: float = 5.0,
        metric_log: bool = False,
        metric_log_dir: Optional[str] = None,
        timeline_log: Any = False,  # bool | obs.timeline.MetricLog
        timeline_dir: Optional[str] = None,
        block_log: bool = False,
        pipeline_depth: int = 0,
        watchdog_timeout_s: float = 0.0,
        admission_queue_limit: int = 0,
        sketch_audit_k: int = 0,
        sketch_audit_period: int = 16,
    ):
        from sentinel_tpu.core.config import app_name as cfg_app_name
        from sentinel_tpu.core.config import platform_engine_config

        self.app_name = app_name or cfg_app_name()
        # default config is platform-detected: on TPU the fast path
        # (MXU tables + fused effects + segment compaction) is ON — the
        # product hot path IS the benchmarked engine configuration
        self.cfg = cfg or platform_engine_config()
        # tri-state packed_wire resolves to ON here and OFF everywhere
        # else (core/config.py): the client path is exactly where the
        # fused readback + narrow uploads pay; direct engine callers keep
        # the classic TickOutput arrays.  An explicit False opts out.
        if self.cfg.packed_wire is None:
            import dataclasses as _dc

            self.cfg = _dc.replace(self.cfg, packed_wire=True)
        self.time = time_source or TimeSource()
        self.mode = mode if not isinstance(self.time, VirtualTimeSource) else "sync"
        self.tick_interval_ms = tick_interval_ms
        self.entry_timeout_s = entry_timeout_s

        # global protection switch (Constants.ON / OnOffSetCommandHandler):
        # when off, every entry is a pass-through and nothing is counted
        self.enabled = True

        # custom entry hooks — the lightweight pre-check form: each hook
        # sees (resource, origin, args) before the engine check and may
        # raise a BlockException to reject
        self.entry_hooks: List[Any] = []
        # full custom-slot SPI (ProcessorSlot analog, runtime/slots.py):
        # ordered slots with entry AND exit hooks; register via
        # client.slots.register(slot)
        from sentinel_tpu.runtime.slots import SlotChain

        self.slots = SlotChain()

        self.registry = Registry(self.cfg)
        self.flow_rules = RuleManager(self, "flow")
        self.degrade_rules = RuleManager(self, "degrade")
        self.system_rules = RuleManager(self, "system")
        self.authority_rules = RuleManager(self, "authority")
        self.param_flow_rules = RuleManager(self, "param-flow")
        # gateway rules project onto param rules in a separate manager so
        # gateway pushes never clobber user param rules (GatewayRuleManager)
        self.gateway_param_rules = RuleManager(self, "gateway-param")

        # cluster-mode wiring (FlowRuleChecker.passClusterCheck analog):
        # cluster rules are checked against a TokenService; on token-server
        # loss the client degrades to local enforcement for rules that allow
        # it (fallbackToLocalOrPass:166) and re-probes after a cooldown.
        self.cluster = None  # Optional[ClusterStateManager]
        self._cluster_flow_by_res: Dict[str, R.FlowRule] = {}
        self._cluster_param_by_res: Dict[str, R.ParamFlowRule] = {}
        self._auth_host_rules: Dict[str, list] = {}
        self._param_lanes_by_res: Dict[str, list] = {}
        # the shared degrade-hysteresis primitive (adaptive/degrade.py):
        # enter-on-failure with cooldown, exit on first healthy probe —
        # same journal kinds / counters / gauge as before the refactor
        self._cluster_hy = DG.Hysteresis(
            "cluster.degrade",
            cooldown_s=5.0,
            counter_enter=_C_DEGRADE_ENTER,
            counter_exit=_C_DEGRADE_EXIT,
            gauge=_G_DEGRADED,
        )
        # guards degrade-state transitions AND every ruleset recompile, so
        # the degraded flag each compile reads matches the ruleset committed
        self._cluster_lock = threading.RLock()
        self.cluster_retry_interval_s = 5.0

        self._sys = SystemStatusSampler()
        # -- adaptive protection / deadline-aware backpressure -------------
        # disabled mode is one `is None` / one flag check per call site
        # (same contract as obs tracing and chaos failpoints, guarded by
        # tests); enable_adaptive() arms the closed loop.
        self._adaptive: Optional[AdaptiveController] = None
        #: host copy of the STATIC system-rule tensors — the base the
        #: controller folds its live ceilings into (tightest wins)
        self._system_static = None
        #: hard bound on the un-ticked acquire queue (0 = unbounded);
        #: enable_adaptive() defaults it from AdaptiveConfig.queue_max
        self._admission_max = max(0, int(admission_queue_limit))
        #: single pre-computed flag the submit paths check — True only
        #: while backpressure has anything to do (bound set or ladder up)
        self._bp_armed = self._admission_max > 0
        #: set on the first deadline-carrying submission; the tick
        #: builder's expiry sweep runs only while True
        self._deadlines_live = False
        #: tick watchdog: fail a dispatched tick CLOSED when its outputs
        #: are not host-visible within this budget (0 = off).  Threaded
        #: mode only — sync mode has no loop to stall independently.
        self.watchdog_timeout_s = max(0.0, float(watchdog_timeout_s))
        self._wd_thread: Optional[threading.Thread] = None
        #: dispatched ticks the watchdog may fail over; populated only
        #: while the watchdog is armed (zero cost otherwise)
        self._inflight_ticks: Dict[int, _PendingTick] = {}
        self._inflight_lock = threading.Lock()
        # the tick compiles only the stages the loaded rule set needs (the
        # SPI slot-chain analog: absent slots cost nothing); rule loads that
        # change the feature set swap in a freshly compiled tick
        self._features = self._select_features()
        # memory-ledger ownership (obs/profile.py): every device buffer
        # built FOR this client — engine state (the sketch tier registers
        # itself inside init_state), ruleset tensors, wire staging — is
        # claimed under this owner tag so stop() releases exactly them;
        # the first make_tick per config is a warmup retrace by contract
        self._ledger_name = f"client:{self.app_name}:{id(self):x}"
        with PROF.ledger_owner(self._ledger_name), \
                PROF.expected_retrace("client-init"):
            self._tick = E.make_tick(
                self.cfg, donate=True, features=self._features
            )
            self._state = E.init_state(self.cfg)
            self._rules_dev = E.compile_ruleset(self.cfg, self.registry)
        self._system_static = compile_system_rules([], self.cfg)
        self._rules_dirty = False

        self._front_doors: list = []
        self._lock = threading.Lock()  # guards the acquire queue
        self._engine_lock = threading.Lock()  # guards state/tick execution
        # resolver-pool shared-state guards: block progress accounting and
        # front-door response rings (single-producer C side)
        self._blk_lock = threading.Lock()
        self._respond_lock = threading.Lock()
        self._acquires: List[AcquireRequest] = []
        # bulk column-array submissions (ArrayBlock) + bulk completions
        self._acq_blocks: List[ArrayBlock] = []
        self._comp_blocks: List[tuple] = []
        # dispatched-but-unread ticks; under sustained load the loop runs
        # up to pipeline_depth ticks ahead of verdict readback so the
        # device→host transfer overlaps compute (it always drains to empty
        # before going idle, so latency at low rate is unchanged).  A small
        # resolver pool fetches concurrently — transfers overlap each
        # other AND the next tick's host build (the RTT of a remote/tunnel
        # transport pipelines; on host-attached PCIe this is near-free)
        self._pipeline_depth = max(0, int(pipeline_depth))
        self._pending_ticks: List[_PendingTick] = []
        self._resolver_pool = None  # created lazily (see _pool)
        self._resolve_futs: List[Future] = []
        # serializes whole tick iterations: sync-mode clients call
        # tick_once from arbitrary request threads, and the pending-tick
        # bookkeeping above must not interleave.  Reentrant for SYNC-mode
        # future callbacks (a callback runs on the resolving caller's
        # thread and may re-enter tick_once).  API contract for THREADED
        # clients with a resolver pool: done-callbacks must be
        # non-blocking — submit_block/submit_completion_block are fine,
        # but a BLOCKING entry()/check_batch_ids inside a callback waits
        # on a tick only the (currently waiting) tick thread can run and
        # stalls all traffic until its timeout
        self._tick_mutex = threading.RLock()
        # device-resident constant columns keyed by (fill, dtype, length):
        # a batch column equal to its fill everywhere re-uses one cached
        # device array instead of re-uploading B values every tick — on a
        # remote/tunnel transport the upload bandwidth is the product
        # bottleneck, and most columns (prio, ctx, pre_verdict, counts of
        # 1) are constant in bulk workloads
        self._const_cols: Dict[tuple, Any] = {}
        # dirty-column delta uploads: field -> (host column as last
        # uploaded, its device array).  A varying-but-unchanged column
        # (steady bulk traffic) reuses the device copy instead of
        # re-crossing the transport; _dev_col keeps the ref fresh every
        # tick so the two-slot staging below can never alias it.
        self._col_last: Dict[str, tuple] = {}
        # two-slot staging for batch assembly: per-column host buffers
        # reused on alternating parity, so the buffer an async upload of
        # tick t may still be reading is not rewritten until t+2 (one
        # tick after its dirty-ref comparison) — zero per-tick column
        # allocation on the steady path
        self._stage: Dict[tuple, list] = {}
        self._stage_parity = 0
        # packed-wire offset tables keyed by (cfg, batch shape)
        self._wire_layouts: Dict[tuple, Any] = {}
        # completions are fire-and-forget (no futures), so they ride the
        # native MPMC event ring: Entry.exit() from any request thread is
        # one C call, and the tick drains straight into numpy arrays
        from sentinel_tpu.native import EventRing

        self._comp_ring = EventRing(1 << 16)
        # completions must NEVER be lost (they release concurrency and feed
        # circuit breakers) — when the ring is full (tick thread stalled,
        # e.g. mid-recompile) they overflow into this unbounded list
        self._comp_overflow: List[Completion] = []

        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._started = False
        self.stats = ClientStats(self)

        # hot-set manager (sketch/hotset.py): folds the device's
        # TickOutput.hot candidate rows and promotes/demotes between the
        # exact tier and the sketch tail on its own cadence
        self.hotset = None
        if self.cfg.sketch_stats and E.hotset_k(self.cfg) > 0:
            from sentinel_tpu.sketch.hotset import HotSetManager

            self.hotset = HotSetManager(self)

        # online sketch-accuracy audit (obs/profile.SketchAudit): a
        # rotating exact shadow of up to sketch_audit_k sketched
        # resources, compared against the device estimates every
        # sketch_audit_period ticks.  Disarmed (k=0, the default) the
        # tick hot path pays exactly ONE `is not None` check.
        self._audit = None
        self._audit_scfg = None
        self._audit_provider = None
        self._audit_est = None
        if sketch_audit_k > 0 and self.cfg.sketch_stats:
            scfg = E.sketch_config(self.cfg)
            self._audit_scfg = scfg
            self._audit = PROF.SketchAudit(
                node_rows=self.cfg.node_rows,
                window_ms=scfg.window_ms,
                sample_count=scfg.sample_count,
                slack_buckets=scfg.slack_buckets,
                width=scfg.width,
                k=int(sketch_audit_k),
                period=int(sketch_audit_period),
                trash_row=self.cfg.trash_row,
            )

        # segment-compacted path bookkeeping: the tick builder presorts
        # batches by the engine's segment keys (see _presort_cols) and
        # tracks observed live-segment counts so seg_u can grow to fit the
        # real traffic (the seg_fallback=True safety net keeps overflow
        # ticks exact — just slower — while the resize compiles)
        self._seg_over_ticks = 0
        self._seg_obs_peak = 0
        self._seg_sample_ctr = 0
        self._seg_sample_ctr_c = 0  # completion side (ticks may lack acquires)
        self._seg_resizing = False
        self._build_ms_sum = 0.0
        self._build_ticks = 0
        # host mirror of the device window-rotation cadence: refresh is a
        # pure function of the stamped tick timestamp, so bucket-boundary
        # crossings and the slack-deferred purges are derivable here
        # without any readback ({window: (window_ms, slack_buckets,
        # last_wid, last_rot_wid)})
        self._rot_track = {
            "second": [cfg.second_window_ms, 1, None, None],
        }
        if cfg.sketch_stats:
            scfg = E.sketch_config(cfg)
            self._rot_track["sketch"] = [
                scfg.window_ms, scfg.slack_buckets, None, None,
            ]
        #: items whose EFFECTS a seg_fallback=False engine dropped on
        #: capacity overflow (verdicts fail closed; see EngineConfig.seg_u)
        self.seg_dropped_total = 0
        self._seg_drop_last_log_s = -1

        # host-side hot-param value tracking: the device CMS holds hashes
        # only; the command plane's topParams view needs the VALUES, so the
        # entry path keeps a small capped counter per resource
        self._hot_params: Dict[str, Dict[Any, int]] = {}
        self._hot_params_lock = threading.Lock()

        # observability plane (MetricTimerListener / EagleEye block log)
        self._metric_log_enabled = metric_log
        self._metric_log_dir = metric_log_dir
        # per-resource timeline (obs/timeline.py): created in start() when
        # the engine emits res_stats; an on-disk MetricLog is attached
        # only when asked for (timeline_log=True / a prebuilt MetricLog /
        # timeline_dir) — the in-memory ring serves /api/metric regardless
        self._timeline_log_opt = timeline_log
        self._timeline_dir = timeline_dir
        self.timeline = None
        self._timeline_provider = None
        self.metric_timer = None
        self.block_log = None
        if block_log:
            from sentinel_tpu.metrics.block_log import default_block_logger

            self.block_log = default_block_logger()

        # verdict provenance plane (obs/explain.py): decodes the fused
        # readback's explain section into per-resource "why blocked"
        # rings.  Rides only the packed wire (E.explain_k gates on
        # cfg.packed_wire); eps annotation comes from the sketch audit
        # when armed, names from the registry.  The plane carries no
        # client reference — both inputs are injected callables.
        self.explain_plane = None
        self._explain_provider = None
        if E.explain_k(self.cfg) > 0:
            from sentinel_tpu.obs.explain import ExplainPlane

            def _audit_eps() -> Optional[float]:
                au = self._audit
                if au is None:
                    return None
                return au._last_audit.get("eps_budget")

            self.explain_plane = ExplainPlane(
                eps_source=_audit_eps,
                name_source=self.registry.resource_name,
            )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._stop_evt = threading.Event()  # allow stop() → start() restart
        if self.timeline is None and E.timeline_k(self.cfg) > 0:
            log = None
            if isinstance(self._timeline_log_opt, TLM.MetricLog):
                log = self._timeline_log_opt
            elif self._timeline_log_opt or self._timeline_dir:
                import os as _os

                from sentinel_tpu.utils.record_log import log_dir

                # pid-suffixed like the text MetricWriter's file names: two
                # same-app processes sharing a log dir must never append to
                # (or "recover" = truncate) each other's live segments
                log = TLM.MetricLog(
                    _os.path.join(
                        self._timeline_dir or log_dir(),
                        f"{self.app_name}-timeline.pid{_os.getpid()}",
                    )
                )
            self.timeline = TLM.TimelineRecorder(
                self.registry.resource_name,
                self.cfg.second_window_ms,
                self.cfg.second_sample_count,
                log=log,
                name=self.app_name,
            )
            # flight bundles carry the last ~30 s of top-K rows — the
            # post-mortem's "what was each hot resource doing" table
            self._timeline_provider = self.timeline.flight_section
            FL.FLIGHT.register_provider("timeline", self._timeline_provider)
        if self.mode == "threaded":
            # Warm the compile cache before serving: the first jitted tick
            # can take tens of seconds; without this, early entry() futures
            # hit entry_timeout_s while XLA compiles.
            self._warm_shapes()
            self._thread = threading.Thread(
                target=self._tick_loop,
                args=(self._stop_evt,),
                name="sentinel-tpu-tick",
                daemon=True,
            )
            self._thread.start()
            if self.watchdog_timeout_s > 0:
                self._wd_thread = threading.Thread(
                    target=self._watchdog_loop,
                    args=(self._stop_evt,),
                    name="sentinel-tpu-watchdog",
                    daemon=True,
                )
                self._wd_thread.start()
        if self._metric_log_enabled and self.metric_timer is None:
            from sentinel_tpu.metrics.timer import MetricTimerListener
            from sentinel_tpu.metrics.writer import MetricWriter
            from sentinel_tpu.utils.record_log import log_dir

            writer = MetricWriter(self._metric_log_dir or log_dir(), self.app_name)
            self.metric_timer = MetricTimerListener(self, writer)
            if self.mode == "threaded":
                self.metric_timer.start()
        # black-box providers: every flight bundle captured while this
        # client serves includes its rule fingerprints, pipeline state,
        # and a config digest (last started client wins the name)
        self._flight_provider = self._flight_state
        FL.FLIGHT.register_provider("client", self._flight_provider)
        if self._audit is not None:
            self._audit_provider = self._audit.flight_section
            FL.FLIGHT.register_provider("audit", self._audit_provider)
        if self.explain_plane is not None:
            self._explain_provider = self.explain_plane.flight_section
            FL.FLIGHT.register_provider("explain", self._explain_provider)

    def _flight_state(self) -> dict:
        """Flight-bundle section: what a post-mortem needs to know about
        this client at capture time (obs/flight.py provider contract)."""
        import hashlib
        import json as _json
        from dataclasses import asdict

        fps = {}
        for name in (
            "flow_rules",
            "degrade_rules",
            "system_rules",
            "authority_rules",
            "param_flow_rules",
        ):
            rules = getattr(self, name).get()
            js = _json.dumps(R.rules_to_json_list(rules), sort_keys=True)
            fps[name] = {
                "count": len(rules),
                "sha1": hashlib.sha1(js.encode()).hexdigest()[:12],
            }
        cfg = {
            k: v
            for k, v in asdict(self.cfg).items()
            if isinstance(v, (int, float, str, bool))
        }
        ad = self._adaptive
        return {
            "app": self.app_name,
            "mode": self.mode,
            "enabled": self.enabled,
            "degraded": self._cluster_degraded_active,
            "adaptive": {
                "level": DG.LEVEL_NAMES[ad.ladder.level],
                "ceiling": (
                    -1.0 if ad.ceiling == float("inf") else round(ad.ceiling, 3)
                ),
            }
            if ad is not None
            else None,
            "pending_ticks": len(self._pending_ticks),
            "registered_resources": self.registry.num_resources,
            "rule_fingerprints": fps,
            "config": cfg,
        }

    def stop(self) -> None:
        fp = getattr(self, "_flight_provider", None)
        if fp is not None:
            # only if still ours — a newer client may have taken the slot
            FL.FLIGHT.unregister_provider("client", fp)
        ap = getattr(self, "_audit_provider", None)
        if ap is not None:
            FL.FLIGHT.unregister_provider("audit", ap)
            self._audit_provider = None
        ep = getattr(self, "_explain_provider", None)
        if ep is not None:
            FL.FLIGHT.unregister_provider("explain", ep)
            self._explain_provider = None
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._wd_thread is not None:
            self._wd_thread.join(timeout=2.0)
            self._wd_thread = None
        # flush deferred readbacks so no caller future is abandoned, then
        # release the resolver threads (start() re-creates the pool)
        try:
            with self._tick_mutex:
                self._drain_resolves()
        except Exception:  # pragma: no cover — surfaced via record log  # stlint: disable=fail-open — shutdown path: flush is best-effort, no admission decision rides on it
            from sentinel_tpu.utils.record_log import record_log

            record_log().warning("resolve flush failed in stop()", exc_info=True)
        if self._resolver_pool is not None:
            self._resolver_pool.shutdown(wait=True)
            self._resolver_pool = None
        if self.metric_timer is not None:
            self.metric_timer.stop()
            self.metric_timer = None
        if self.timeline is not None:
            if self._timeline_provider is not None:
                FL.FLIGHT.unregister_provider(
                    "timeline", self._timeline_provider
                )
                self._timeline_provider = None
            # flush the still-open second so shutdown loses no rows, then
            # release the log handles (start() rebuilds the recorder)
            self.timeline.close()
            self.timeline = None
        if self.block_log is not None:
            self.block_log.flush()
        # release this client's memory-ledger claims (engine state, rule
        # tensors, wire staging) — the owner tag brackets exactly them
        PROF.LEDGER.drop_owner(self._ledger_name)
        self._started = False

    # -- adaptive protection / backpressure ---------------------------------

    def enable_adaptive(self, cfg: Optional[AdaptiveConfig] = None) -> AdaptiveController:
        """Arm closed-loop system-adaptive protection (adaptive/): a
        per-tick controller republishes the SystemSlot ceilings
        (maxPass × minRT) as live rule-tensor column values — a scalar
        upload, never a recompile — and drives the unified degrade
        ladder whose rungs the admission path enforces.  Idempotent;
        returns the controller for inspection."""
        with self._cluster_lock:
            if self._adaptive is not None:
                return self._adaptive
            self._adaptive = AdaptiveController(cfg)
            if self._admission_max == 0:
                self._admission_max = int(self._adaptive.cfg.queue_max)
            self._bp_armed = True
        # the SystemSlot stage must exist in the compiled tick even with
        # no static system rule; _select_features now includes it
        self._recompile_rules()
        return self._adaptive

    def disable_adaptive(self) -> None:
        """Disarm the closed loop and restore the static thresholds."""
        with self._cluster_lock:
            ad, self._adaptive = self._adaptive, None
            if ad is None:
                return
            ad.disarm()
            self._bp_armed = self._admission_max > 0
        self._recompile_rules()

    def _admission_shed(self, prio: int) -> Optional[str]:
        """Pre-engine shed decision for one submission; returns the shed
        reason or None to admit.  Fast path (backpressure disarmed) is
        the single ``_bp_armed`` flag check."""
        if not self._bp_armed:
            return None
        try:
            FP.hit(_FP_ADMIT)  # chaos: a raise sheds this admission CLOSED
        except Exception:  # stlint: disable=fail-open — sheds CLOSED (the caller maps any reason to BLOCK_SYSTEM); nothing is admitted
            return "chaos"
        ad = self._adaptive
        level = ad.ladder.level if ad is not None else DG.NORMAL
        if level >= DG.FAIL_CLOSED:
            return "fail_closed"
        qmax = self._admission_max
        if qmax:
            # unlocked reads — approximate is fine; blocks count too (a
            # submit_block flood must not slip past the bound just
            # because its items sit in _acq_blocks, not _acquires)
            qd = len(self._acquires) + sum(
                len(b.res) - b.taken for b in self._acq_blocks
            )
            if qd >= qmax:
                return "queue_full"
            if (
                level >= DG.SHED_LOW_PRIORITY
                and not prio
                and ad is not None
                and qd >= qmax * ad.cfg.shed_lowprio_frac
            ):
                return "low_priority"
        elif level >= DG.SHED_LOW_PRIORITY and not prio:
            # no queue bound configured: the rung itself sheds the
            # non-prioritized share
            return "low_priority"
        return None

    def _shed_blocked(self, stage: str, reason: str, n: int = 1) -> None:
        _shed_counter(stage, reason).inc(n)

    def _adaptive_step(self, ad: AdaptiveController, now_ms: int, load, cpu) -> None:
        """One closed-loop control step, on the tick thread: collect the
        signals row, advance controller + ladder, apply rung effects,
        and publish changed ceilings into the live system columns."""
        with self._lock:
            qd = len(self._acquires) + sum(
                len(b.res) - b.taken for b in self._acq_blocks
            )
        sig = ad.signals.observe_tick(
            now_ms,
            qd,
            len(self._pending_ticks),
            len(self._resolve_futs),
            load,
            cpu,
        )
        want = ad.on_tick(sig)
        level = ad.ladder.level
        self._bp_armed = level > DG.NORMAL or self._admission_max > 0
        if level >= DG.CLUSTER_FALLBACK and (
            self._cluster_flow_by_res or self._cluster_param_by_res
        ):
            # rung effect: stop paying token-server round-trips on the
            # admission path; fallback-enabled cluster rules enforce
            # locally.  Re-entering every tick extends the cooldown, so
            # probes resume only after the ladder descends.
            self._enter_cluster_degraded()
        if want is not None:
            qps, max_thread = want
            sys_np = ad.system_columns(self._system_static, qps, max_thread)
            with self._engine_lock:
                # re-read under the lock: a concurrent rule recompile may
                # have swapped the whole ruleset; only the system leaves
                # are replaced (same shapes/dtypes — no recompile)
                self._rules_dev = E.replace_system_columns(self._rules_dev, sys_np)

    # -- tick watchdog -------------------------------------------------------

    def _watchdog_loop(self, stop_evt: threading.Event) -> None:
        period = max(self.watchdog_timeout_s / 4.0, 0.01)
        while not stop_evt.wait(period):
            try:
                self._watchdog_scan()
            except Exception:  # pragma: no cover  # stlint: disable=fail-open — a dead watchdog must not take serving down; next scan retries
                from sentinel_tpu.utils.record_log import record_log

                record_log().warning("watchdog scan failed", exc_info=True)

    def _watchdog_scan(self) -> None:
        """Fail CLOSED every dispatched tick whose outputs are not
        host-visible past its stall deadline.  The state handshake with
        the resolver guarantees exactly one side fans the tick out."""
        now = mono_s()
        with self._inflight_lock:
            stalled = [
                p
                for p in self._inflight_ticks.values()
                if p.deadline_mono and now > p.deadline_mono
            ]
        for p in stalled:
            if not self._claim_tick(p, "failed"):
                continue  # resolver won the race; tick is being fanned out
            _C_WATCHDOG.inc()
            OT.event("watchdog.fired")
            FL.note(
                "watchdog.fired",
                n_obj=p.n_obj,
                n_blk=p.n_blk,
                budget_s=self.watchdog_timeout_s,
            )
            ad = self._adaptive
            if ad is not None:
                ad.note_severe()  # a stalled device is overload evidence
            from sentinel_tpu.utils.record_log import record_log

            record_log().error(
                "tick watchdog: device tick stalled past %.2fs — failing "
                "%d object / %d block item(s) CLOSED",
                self.watchdog_timeout_s,
                p.n_obj,
                p.n_blk,
            )
            self._fail_tick(p)
            self._untrack_tick(p)

    @staticmethod
    def _claim_tick(p: _PendingTick, state: str) -> bool:
        """Atomically move a tick pending→done/failed; False if another
        side already owns the fan-out."""
        with p.state_lock:
            if p.state != "pending":
                return False
            p.state = state
            return True

    def _track_tick(self, p: _PendingTick) -> None:
        if self.watchdog_timeout_s > 0:
            p.deadline_mono = mono_s() + self.watchdog_timeout_s
            with self._inflight_lock:
                self._inflight_ticks[id(p)] = p

    def _untrack_tick(self, p: _PendingTick) -> None:
        if p.deadline_mono:
            with self._inflight_lock:
                self._inflight_ticks.pop(id(p), None)

    # -- rule compilation ---------------------------------------------------

    def _select_features(self, local_flow=None, local_param=None) -> frozenset:
        """Engine stages the current rule set needs.  'nodes' and 'occupy'
        stay on (their unused paths are runtime-gated and near-free);
        'warmup' joins when a warm-up shaper exists."""
        feats = {"nodes", "occupy", "flow"}
        flow = self.flow_rules.get() if local_flow is None else local_flow
        param = (
            (self.param_flow_rules.get() + self.gateway_param_rules.get())
            if local_param is None
            else local_param
        )
        if self.degrade_rules.get():
            feats.add("degrade")
        if param:
            feats.add("param")
        if self.authority_rules.get():
            feats.add("authority")
        if self.system_rules.get() or self._adaptive is not None:
            # the adaptive controller publishes live ceilings through the
            # system columns — the SystemSlot stage must be compiled in
            # even with no static rule loaded
            feats.add("system")
        if any(
            r.control_behavior in (R.CONTROL_WARM_UP, R.CONTROL_WARM_UP_RATE_LIMITER)
            for r in flow
        ):
            feats.add("warmup")
        if self.cfg.sketch_stats and any(
            (rid := self.registry.peek_resource_id(r.resource)) is not None
            and self.registry.is_sketch_id(rid)
            for r in flow
        ):
            feats.add("tail_flow")
        return frozenset(feats)

    def _recompile_rules(self) -> None:
        # cluster-mode rules are enforced via the TokenService, not the local
        # engine — except while degraded, when fallback-enabled cluster rules
        # are compiled in as local rules (fallbackToLocalOrPass semantics)
        with self._cluster_lock:
            changed = self._recompile_rules_noted()
        self._warm_after_recompile(changed)

    def _recompile_rules_noted(self) -> bool:
        """The traced + journaled recompile body; caller holds
        _cluster_lock.  Returns whether the compiled tick changed (the
        caller owns warming it — see _warm_after_recompile)."""
        with OT.TRACER.span("client.recompile_rules"):
            changed = self._recompile_rules_locked()
        FL.note(
            "rules.recompile",
            degraded=self._cluster_degraded_active,
            flow=len(self.flow_rules.get()),
            param=len(self.param_flow_rules.get()),
        )
        return changed

    def _warm_after_recompile(self, changed: bool) -> None:
        """Pre-compile a changed tick for both batch shapes, OUTSIDE
        _cluster_lock.  Lock order: _tick_mutex is the canonical OUTER
        lock — tick_once holds it across the serving tick, and the
        sync-mode seg-resize acquires _cluster_lock under it — so the
        warm-up (which needs _tick_mutex to keep first calls of the
        jitted tick from interleaving with serving ticks) must never run
        while _cluster_lock is held.  A recompile that lands between the
        release and the warm just means we warm the newer tick: warming
        is idempotent performance work, never a correctness gate."""
        if changed and self._started and self.mode == "threaded":
            with self._tick_mutex:
                self._warm_shapes()  # stlint: disable=blocking-under-lock — deliberate: warm-up first-calls must exclude serving ticks (concurrent first-calls corrupt the jitted dispatch fastpath); runs post-recompile on the control plane

    def _recompile_rules_locked(self) -> bool:
        flow = self.flow_rules.get()
        local_flow = [r for r in flow if not r.cluster_mode]
        cluster_flow = [r for r in flow if r.cluster_mode]
        self._cluster_flow_by_res = {r.resource: r for r in cluster_flow}

        # rules binding to sketch-tail resources first try PROMOTION into
        # the exact row space (Registry.promote_resource) so they get real
        # windows; whatever stays in the tail enforces approximately.
        # Priority when the reserve is short: rules the TAIL CANNOT SERVE
        # go first — the tail tables enforce only QPS/DEFAULT/DIRECT
        # default-limitApp flow rules (compile_ruleset), so a rate-limiter
        # / THREAD-grade / origin-scoped / RELATE rule or a circuit
        # breaker on a tail id is unenforceable unless it wins an exact
        # row, while a plain QPS rule still has its approximate fallback.
        def _tail_can_serve(r) -> bool:
            # must match engine.compile_ruleset's tail-table admission —
            # including limitApp: the tail table has no origin dimension,
            # so an origin-scoped rule there would throttle ALL origins
            return (
                isinstance(r, R.FlowRule)
                and r.grade == R.GRADE_QPS
                and r.control_behavior == R.CONTROL_DEFAULT
                and r.strategy == R.STRATEGY_DIRECT
                and (r.limit_app or "default") == "default"
            )

        candidates = sorted(
            local_flow + self.degrade_rules.get(),
            key=_tail_can_serve,  # False (must-promote) sorts first
        )
        # promotion routes through the hot-set guard (sketch/hotset.py):
        # a failed promotion leaves the rule on its sketch id, where the
        # tail tables still enforce it conservatively (fail-closed
        # verdicts) and the sketch keeps observing it (fail-open stats)
        from sentinel_tpu.sketch.hotset import guarded_promote

        for r in candidates:
            rid = self.registry.peek_resource_id(r.resource)
            if rid is not None and self.registry.is_sketch_id(rid):
                guarded_promote(self.registry, r.resource)

        param = self.param_flow_rules.get() + self.gateway_param_rules.get()
        local_param = [r for r in param if not r.cluster_mode]
        cluster_param = [r for r in param if r.cluster_mode]
        self._cluster_param_by_res = {r.resource: r for r in cluster_param}

        # host mirror of the authority gate, used ONLY to order cluster
        # token consumption after the authority slot (the reference checks
        # cluster INSIDE FlowSlot, after AuthoritySlot —
        # FlowRuleChecker.java:64-72): a request the authority gate will
        # reject must not consume a cluster token.  The device decision
        # stays authoritative, and the mirror MUST only ever be
        # host-LENIENT-or-equal — a host-stricter verdict would skip the
        # token check on traffic the device then passes, silently opening
        # an unenforced cluster-limit window.  It therefore replicates
        # compile_authority_rules' selection exactly: invalid rules
        # (empty origins) skipped, sketch-id / over-capacity resources
        # skipped, origins capped at KA, LAST rule per resource wins.
        # A rule origin past the intern cap is stored as -1 device-side,
        # where it matches every UN-INTERNED request origin: under WHITE
        # the device then passes traffic whose origin string the mirror
        # would reject, and under BLACK it blocks traffic the mirror would
        # pass — in both cases the mirror must never be the stricter side,
        # so any rule carrying a failed-intern origin drops out of the
        # mirror entirely (never pre-blocks; the device stays
        # authoritative).  ADVICE r5 medium, case (2).
        KA = self.cfg.authority_origins_per_resource
        auth_host: Dict[str, tuple] = {}
        for r in self.authority_rules.get():
            if not r.is_valid():
                continue
            rid = self.registry.resource_id(r.resource)
            if rid is None or rid > self.cfg.max_resources:
                continue
            origins = r.origins()[:KA]
            if any(self.registry.origin_id(o) == -1 for o in origins):
                # failed intern -> device matches -1 wildcard; mirror
                # cannot replicate that, so it must not pre-block AND a
                # later rule must not resurrect a stale entry: last-wins
                # means this rule's outcome for the resource is "no mirror"
                auth_host.pop(r.resource, None)
                continue
            auth_host[r.resource] = (frozenset(origins), r.strategy)
        self._auth_host_rules = auth_host
        # per-resource hash LANES: each entry hashes up to param_dims
        # distinct argument indices; every rule reads the lane its
        # param_idx was assigned (ParamFlowChecker.java:78 paramIdx
        # dispatch).  Gateway rules claim lanes first on shared resources:
        # gateway traffic supplies the (short) parsed gateway vector as
        # args, and a user rule's larger param_idx would index past it.
        # Lane 0 also feeds the cluster token request, so healthy
        # (token-service) and degraded (local-engine) modes throttle the
        # same argument.
        from sentinel_tpu.core.rule_tensors import param_lanes

        lane_map = param_lanes(
            param, self.cfg.param_dims, priority=self.gateway_param_rules.get()
        )
        self._param_lanes_by_res = lane_map

        if self._cluster_degraded_active:
            local_flow += [r for r in cluster_flow if r.cluster_fallback_to_local]
            local_param += cluster_param

        # engine specialization: with the client presorting every batch
        # (see _run_tick), a ruleset of single-lane DIRECT/default-limitApp
        # flow rules qualifies for the cond-free segmented-scan ranks
        # (EngineConfig.seg_static_ranks — the engine still verifies the
        # contract at runtime and fails closed, so a stale flip can never
        # misrank silently)
        import dataclasses as _dc

        static_flip = False
        if self.cfg.seg_effects:
            want_static = (
                self.cfg.flow_rules_per_resource == 1
                and self.cfg.degrade_rules_per_resource == 1
                and self.cfg.param_rules_per_resource == 1
                and all(
                    r.strategy == R.STRATEGY_DIRECT
                    and (r.limit_app or "default") == "default"
                    for r in local_flow
                )
            )
            if want_static != self.cfg.seg_static_ranks:
                self.cfg = _dc.replace(self.cfg, seg_static_ranks=want_static)
                self.registry.cfg = self.cfg
                static_flip = True

        with self._engine_lock:
            self._rules_dev = E.compile_ruleset(
                self.cfg,
                self.registry,
                flow_rules=local_flow,
                degrade_rules=self.degrade_rules.get(),
                param_rules=local_param,
                authority_rules=self.authority_rules.get(),
                system_rules=self.system_rules.get(),
                param_lanes=lane_map,
            )
            # host copy of the STATIC system thresholds: the adaptive
            # controller folds its live ceilings into these (tightest
            # wins), so a recompile resets the base, never the loop
            self._system_static = compile_system_rules(
                self.system_rules.get(), self.cfg
            )
            feats = self._select_features(local_flow, local_param)
            changed = static_flip or feats != self._features
            if changed:
                self._features = feats
                with PROF.expected_retrace("rule-feature-change"):
                    self._tick = E.make_tick(
                        self.cfg, donate=True, features=feats
                    )
        # the caller warms the changed tick for BOTH batch shapes once
        # _cluster_lock is released (_warm_after_recompile) so the first
        # post-reload entry doesn't eat the XLA compile inside its
        # entry_timeout_s window; warming under _tick_mutex keeps the
        # warm-up ticks from interleaving with the serving loop's tick
        # iterations — two threads first-calling the same jitted tick
        # concurrently corrupts the dispatch fastpath on this jaxlib
        # (observed as 'Execution supplied N buffers but compiled program
        # expected N+1' on subsequent calls)
        return changed

    # -- cluster consultation -----------------------------------------------

    def set_cluster(self, cluster_state_manager) -> None:
        """Attach a ClusterStateManager; cluster-mode rules consult its
        token service (client or embedded server role)."""
        self.cluster = cluster_state_manager

    # attribute-compatible views of the shared hysteresis state (tests
    # and the chaos harness read/poke these directly)
    @property
    def _cluster_degraded_active(self) -> bool:
        return self._cluster_hy.active

    @_cluster_degraded_active.setter
    def _cluster_degraded_active(self, v: bool) -> None:
        self._cluster_hy.active = bool(v)

    @property
    def _cluster_degraded_until(self) -> float:
        return self._cluster_hy.until

    @_cluster_degraded_until.setter
    def _cluster_degraded_until(self, v: float) -> None:
        self._cluster_hy.until = float(v)

    def _enter_cluster_degraded(self) -> None:
        """Token service unreachable: enforce fallback-enabled cluster rules
        locally until a probe succeeds.  Idempotent — extends the cooldown
        without recompiling if already degraded.  The flag flip and the
        recompile are atomic under _cluster_lock so a concurrent exit/enter
        pair can't commit a stale ruleset for the winning state.
        Transition mechanics (cooldown arithmetic, counters, gauge,
        journal) live in the shared adaptive.degrade.Hysteresis."""
        entered = False
        changed = False
        with self._cluster_lock:
            entered = self._cluster_hy.enter(
                cooldown_s=self.cluster_retry_interval_s
            )
            if entered:
                changed = self._recompile_rules_noted()
        if entered:
            self._warm_after_recompile(changed)
            # black box: freeze the state that produced the degrade —
            # outside the lock (bundle capture reads rule managers and
            # the registry) and rate-limited inside trigger()
            FL.FLIGHT.trigger("cluster-degrade-enter")

    def _exit_cluster_degraded(self) -> None:
        changed = False
        exited = False
        with self._cluster_lock:
            exited = self._cluster_hy.exit()
            if exited:
                changed = self._recompile_rules_noted()
        if exited:
            self._warm_after_recompile(changed)

    def _authority_pre_blocks(self, resource: str, origin: str) -> bool:
        """True when the device authority gate is going to reject this
        (resource, origin) — consult BEFORE spending a cluster token so
        the slot order matches the reference (AuthoritySlot before the
        in-FlowSlot cluster check).  Must stay host-lenient-or-equal vs
        the device gate; see the mirror construction in
        _recompile_rules_locked."""
        ent = self._auth_host_rules.get(resource)
        if ent is None:
            return False
        from sentinel_tpu.core.rules import AUTHORITY_BLACK, AUTHORITY_WHITE

        origins, strategy = ent
        listed = bool(origin) and origin in origins
        if strategy == AUTHORITY_WHITE:
            return not listed
        return strategy == AUTHORITY_BLACK and listed

    def _cluster_check(
        self, resource: str, count: int, prioritized: bool, param_value
    ) -> Tuple[int, int]:
        """Consult the token service for cluster-mode rules on `resource`.

        Returns (pre_verdict, wait_ms): pre_verdict > 0 forces a recorded
        block; wait_ms > 0 means SHOULD_WAIT pacing before proceeding.

        Degrade protocol: on transport failure (or namespace-guard overload,
        which the reference also routes to fallbackToLocalOrPass), flip to
        local enforcement of fallback-enabled cluster rules.  The fallback
        rules STAY compiled through re-probes — only a successful probe
        response drops them — so the token server being down never opens an
        unenforced window.

        Slot ordering vs the reference (cluster check inside FlowSlot,
        after AuthoritySlot/SystemSlot — FlowRuleChecker.java:64-72):
        AUTHORITY-doomed requests are filtered host-side before this runs
        (_authority_pre_blocks mirrors the device gate over the same rule
        data), so they consume no token.  The SYSTEM gate alone still
        evaluates after token consumption — its verdict needs the device's
        live window counters, and folding it in would cost a device
        round-trip per request; the residual divergence is bounded by the
        system-blocked share of cluster-ruled traffic and only matters in
        overload (documented).
        """
        from sentinel_tpu.cluster import constants as CC

        frule = self._cluster_flow_by_res.get(resource)
        prule = self._cluster_param_by_res.get(resource)
        if frule is None and prule is None:
            return 0, 0
        degraded = self._cluster_degraded_active
        if degraded and mono_s() < self._cluster_degraded_until:
            return 0, 0  # cooling down; local fallback rules enforce
        svc = self.cluster.token_service() if self.cluster is not None else None
        if svc is None:
            self._enter_cluster_degraded()
            return 0, 0

        wait_total = 0
        responded = False
        if frule is not None:
            try:
                r = svc.request_token(frule.cluster_flow_id, count, prioritized)
            except Exception:  # stlint: disable=fail-open — degrade-to-LOCAL: fallback rules recompile into the engine, enforcement continues (fallbackToLocalOrPass)
                # any service failure degrades, never escapes to the caller
                # (reference wraps acquisition → fallbackToLocalOrPass)
                if frule.cluster_fallback_to_local:
                    self._enter_cluster_degraded()
                return 0, 0
            if r.status in (CC.STATUS_FAIL, CC.STATUS_TOO_MANY_REQUEST):
                # unreachable or overloaded server → local fallback
                if frule.cluster_fallback_to_local:
                    self._enter_cluster_degraded()
                return 0, 0
            # BAD_REQUEST is synthesized client-side without touching the
            # network — it proves nothing about server health, so it must
            # not count as a successful probe out of degraded mode
            if r.status != CC.STATUS_BAD_REQUEST:
                responded = True
            if r.status == CC.STATUS_BLOCKED:
                if degraded:
                    self._exit_cluster_degraded()
                self._fold_remote_deny(resource, r, ERR.BLOCK_FLOW)
                return ERR.BLOCK_FLOW, 0
            if r.status == CC.STATUS_SHOULD_WAIT:
                wait_total += r.wait_ms
            # OK / NO_RULE → proceed

        if prule is not None and param_value is not None:
            try:
                r = svc.request_param_token(prule.cluster_flow_id, count, [param_value])
            except Exception:  # stlint: disable=fail-open — degrade-to-LOCAL: fallback rules recompile into the engine, enforcement continues
                self._enter_cluster_degraded()
                return 0, wait_total
            if r.status in (CC.STATUS_FAIL, CC.STATUS_TOO_MANY_REQUEST):
                self._enter_cluster_degraded()
                return 0, wait_total
            if r.status != CC.STATUS_BAD_REQUEST:
                responded = True
            if r.status == CC.STATUS_BLOCKED:
                if degraded:
                    self._exit_cluster_degraded()
                self._fold_remote_deny(resource, r, ERR.BLOCK_PARAM)
                return ERR.BLOCK_PARAM, 0

        if degraded and responded:
            self._exit_cluster_degraded()  # probe succeeded: back to remote
        return 0, wait_total

    def _fold_remote_deny(self, resource: str, r, default_kind: int, n: int = 1) -> None:
        """Land a cluster deny's provenance in the explain plane.  A v3
        peer's TokenResult carries (kind, rule, observed, limit); an
        embedded service fills the same fields; a pre-v3 peer leaves them
        None and the deny is counted unexplained — coverage stays honest."""
        plane = self.explain_plane
        if plane is None:
            return
        rid = self.registry.peek_resource_id(resource)
        if rid is None or n <= 0:
            return
        if r.prov_kind is None:
            plane.count_unexplained(n)
            return
        from sentinel_tpu.obs.explain import KIND_NAMES

        kind = int(r.prov_kind) if int(r.prov_kind) in KIND_NAMES else default_kind
        for _ in range(n):
            plane.fold_remote(
                rid,
                kind,
                r.prov_rule,
                r.prov_observed,
                r.prov_limit,
                ts_ms=int(self.time.wall_ms()),
            )

    def _cluster_check_bulk(
        self, resource: str, item_counts: List[int], param_value
    ) -> Tuple[List[int], List[int]]:
        """Bulk-path cluster consultation with partial grant: ONE
        request_token_batch roundtrip covers all items of a (resource,
        param) group; granted units are assigned to items greedily in
        order.  Falls back to the same degrade protocol as _cluster_check.
        """
        from sentinel_tpu.cluster import constants as CC

        n = len(item_counts)
        verdicts, waits = [0] * n, [0] * n
        frule = self._cluster_flow_by_res.get(resource)
        prule = self._cluster_param_by_res.get(resource)
        if frule is None and prule is None:
            return verdicts, waits
        degraded = self._cluster_degraded_active
        if degraded and mono_s() < self._cluster_degraded_until:
            return verdicts, waits
        svc = self.cluster.token_service() if self.cluster is not None else None
        if svc is None:
            self._enter_cluster_degraded()
            return verdicts, waits

        responded = False
        if frule is not None:
            total = sum(item_counts)
            try:
                r = svc.request_token_batch(frule.cluster_flow_id, total)
            except Exception:  # stlint: disable=fail-open — r=None routes to the degrade-to-LOCAL branch below
                r = None
            if r is None or r.status in (CC.STATUS_FAIL, CC.STATUS_TOO_MANY_REQUEST):
                if frule.cluster_fallback_to_local:
                    self._enter_cluster_degraded()
                return verdicts, waits
            if r.status != CC.STATUS_BAD_REQUEST:
                responded = True
            if r.status in (CC.STATUS_OK, CC.STATUS_SHOULD_WAIT, CC.STATUS_BLOCKED):
                granted = r.remaining if r.status != CC.STATUS_BLOCKED else 0
                acc = 0
                blocked_items = 0
                for i, c in enumerate(item_counts):
                    if acc + c <= granted:
                        acc += c
                        waits[i] = r.wait_ms
                    else:
                        verdicts[i] = ERR.BLOCK_FLOW
                        blocked_items += 1
                if blocked_items:
                    self._fold_remote_deny(
                        resource, r, ERR.BLOCK_FLOW, n=blocked_items
                    )
            # NO_RULE → proceed

        if prule is not None and param_value is not None:
            live = [i for i in range(n) if verdicts[i] == 0]
            if live:
                total = sum(item_counts[i] for i in live)
                try:
                    r = svc.request_param_token(
                        prule.cluster_flow_id, total, [param_value]
                    )
                except Exception:  # stlint: disable=fail-open — r=None routes to the degrade-to-LOCAL branch below
                    r = None
                if r is None or r.status in (CC.STATUS_FAIL, CC.STATUS_TOO_MANY_REQUEST):
                    self._enter_cluster_degraded()
                    return verdicts, waits
                if r.status != CC.STATUS_BAD_REQUEST:
                    responded = True
                if r.status == CC.STATUS_BLOCKED:
                    for i in live:
                        verdicts[i] = ERR.BLOCK_PARAM
                    self._fold_remote_deny(
                        resource, r, ERR.BLOCK_PARAM, n=len(live)
                    )

        if degraded and responded:
            self._exit_cluster_degraded()
        return verdicts, waits

    # -- public entry API ---------------------------------------------------

    def entry(
        self,
        resource: str,
        count: int = 1,
        prioritized: bool = False,
        args: Optional[Sequence[Any]] = None,
        inbound: bool = False,
        origin: Optional[str] = None,
        deadline_ms: int = 0,
        _ctx: Optional[Tuple[str, str]] = None,
        _push_ctx: bool = True,
    ) -> Entry:
        """Acquire; raises BlockException on rejection (SphU.entry).

        ``deadline_ms`` (absolute engine-time ms, 0 = none): past it the
        caller no longer wants the answer — still-queued expired entries
        shed CLOSED before device dispatch instead of burning a tick.

        ``_ctx``/``_push_ctx`` support entry_async: the context is captured
        in the awaiting task and the push happens there too."""
        if not self.enabled:
            e = _PassThroughEntry(self, resource)
            if _push_ctx:
                CTX.push_entry(e)
            return e
        ctx_name, ctx_origin = _ctx if _ctx is not None else CTX.current()
        origin = origin if origin is not None else ctx_origin
        # custom-slot hooks: a raised BlockException is carried as a
        # pre-verdict so the ENGINE records the block (stats + block log +
        # SPI, like a custom ProcessorSlot's exception flowing through
        # StatisticSlot) and the ORIGINAL exception is rethrown at the end
        hook_exc: Optional[ERR.BlockException] = None
        for hook in self.entry_hooks:
            try:
                hook(resource, origin, args)
            except ERR.BlockException as he:
                hook_exc = he
                break
        rid = self.registry.resource_id(resource)
        if rid is None:
            e = _PassThroughEntry(self, resource)
            if _push_ctx:
                CTX.push_entry(e)
            return e  # capacity overflow → pass-through (CtSph.java:200)
        if self._bp_armed:
            # backpressure rungs / bounded admission (adaptive/degrade.py):
            # shed CLOSED before any engine or cluster work — but AFTER
            # the pass-through branch (ungoverned traffic never enters
            # the queue, so backpressure must not turn it into a block)
            reason = self._admission_shed(1 if prioritized else 0)
            if reason is not None:
                self._shed_blocked("admit", reason)
                if self.mode == "sync":
                    # the control loop must keep stepping even when every
                    # submission sheds — a sync client's ONLY tick driver
                    # is its submissions, and without this FAIL_CLOSED
                    # could never observe calm and descend
                    self.tick_once()
                raise ERR.SystemBlockException(resource)
        if deadline_ms and deadline_ms < self.time.now_ms():
            self._shed_blocked("admit", "deadline")
            raise ERR.SystemBlockException(resource)

        # ordered custom slots (runtime/slots.py): entry side here; the
        # exit side unwinds on Entry.exit OR on rejection below.  Pass-
        # through entries above skip custom slots entirely — the analog of
        # lookProcessChain returning null (no chain runs at all).
        slot_ctx = None
        entered_slots: list = []
        slot_list = self.slots.snapshot()
        if slot_list and hook_exc is None:
            from sentinel_tpu.runtime.slots import SlotContext, run_entry

            slot_ctx = SlotContext(
                resource=resource,
                origin=origin or "",
                args=args,
                count=count,
                prioritized=prioritized,
                inbound=inbound,
            )
            entered_slots, slot_exc = run_entry(slot_list, slot_ctx)
            if slot_exc is not None:
                hook_exc = slot_exc

        origin_id = self.registry.origin_id(origin) if origin else -1
        origin_node = (
            self.registry.origin_node_row(resource, origin)
            if origin
            else self.cfg.trash_row
        )
        if ctx_name != CTX.DEFAULT_CONTEXT_NAME:
            ctx_node = self.registry.ctx_node_row(resource, ctx_name)
            ctx_id = self.registry.context_id(ctx_name)
        else:
            ctx_node = self.cfg.trash_row
            ctx_id = -1

        M = self.cfg.param_dims
        param_hashes = [0] * M
        param_value = None
        if args:
            # hash one argument per assigned lane (rule param_idx -> lane
            # mapping from rule_tensors.param_lanes); lane 0's value also
            # feeds the cluster token request.  At PARAM_TAIL_OFF and
            # above the ladder sheds the host-side param TAIL work (the
            # hot-param value counters) — enforcement hashes still flow.
            ad = self._adaptive
            tail_off = ad is not None and ad.ladder.level >= DG.PARAM_TAIL_OFF
            lanes = self._param_lanes_by_res.get(resource) or [0]
            for li, idx in enumerate(lanes[:M]):
                if 0 <= idx < len(args):
                    v = args[idx]
                    param_hashes[li] = hash_param(v)
                    if li == 0:
                        param_value = v
                    if not tail_off:
                        self._note_hot_param(resource, v)

        pre_verdict, cluster_wait = 0, 0
        if hook_exc is not None:
            code = getattr(hook_exc, "code", 0)
            pre_verdict = code if code > 0 else ERR.BLOCK_FLOW
        elif (
            self._cluster_flow_by_res or self._cluster_param_by_res
        ) and not self._authority_pre_blocks(resource, origin or ""):
            # authority-doomed requests skip the token service entirely:
            # slot order matches the reference (cluster check lives inside
            # FlowSlot, after AuthoritySlot — FlowRuleChecker.java:64-72)
            pre_verdict, cluster_wait = self._cluster_check(
                resource, count, prioritized, param_value
            )
        if cluster_wait > 0:
            # SHOULD_WAIT: pace before entering (TokenResultStatus.SHOULD_WAIT)
            self.time.sleep_ms(cluster_wait)

        req = AcquireRequest(
            res=rid,
            count=count,
            prio=1 if prioritized else 0,
            origin_id=origin_id,
            origin_node=origin_node,
            ctx_node=ctx_node,
            ctx_name=ctx_id,
            inbound=1 if inbound else 0,
            param_hash=tuple(param_hashes),
            pre_verdict=pre_verdict,
            deadline_ms=int(deadline_ms),
            future=Future(),
        )
        with self._lock:
            if deadline_ms:
                self._deadlines_live = True
            self._acquires.append(req)

        if self.mode == "sync":
            self.tick_once()
        verdict, wait_ms = req.future.result(timeout=self.entry_timeout_s)

        if verdict not in (ERR.PASS, ERR.PASS_WAIT):
            # the engine already counted the block; here only the
            # observability side-channels fire (block log + extension SPI)
            exc = (
                hook_exc
                if hook_exc is not None
                else ERR.exception_for_verdict(verdict, resource)
            )
            if self.block_log is not None:
                kind_name = rule_slot = None
                if self.explain_plane is not None:
                    from sentinel_tpu.obs.explain import KIND_NAMES

                    kind_name = KIND_NAMES.get(int(verdict))
                    # the resolver folded this tick's explain records
                    # BEFORE resolving our future, so the newest matching
                    # record is this block's provenance
                    rule_slot = self.explain_plane.latest_rule(rid, int(verdict))
                self.block_log.log(
                    self.time.wall_ms(), resource, type(exc).__name__,
                    origin or "", count, kind=kind_name, rule=rule_slot,
                )
            MEXT.safe_dispatch("on_block", resource, count, origin or "", exc, args)
            if entered_slots:
                from sentinel_tpu.runtime.slots import run_exit

                slot_ctx.block_exception = exc
                run_exit(entered_slots, slot_ctx)
            raise exc
        if verdict == ERR.PASS_WAIT and wait_ms > 0:
            self.time.sleep_ms(wait_ms)
        MEXT.safe_dispatch("on_pass", resource, count, origin or "", args)

        e = Entry(
            self,
            resource,
            rid,
            origin_node,
            ctx_node,
            1 if inbound else 0,
            count,
            self.time.now_ms(),
            wait_ms,
            tuple(param_hashes),
        )
        e.slots = entered_slots
        e.slot_ctx = slot_ctx
        if _push_ctx:
            CTX.push_entry(e)
        return e

    def try_entry(self, resource: str, **kw) -> Optional[Entry]:
        """SphO-style boolean variant."""
        try:
            return self.entry(resource, **kw)
        except ERR.BlockException:
            return None

    async def entry_async(self, resource: str, **kw) -> Entry:
        """AsyncEntry analog: the entry handshake (a blocking wait on the
        engine tick, ~ms) runs in an executor so the event loop never
        blocks; raises BlockException like entry().  Exit the returned
        Entry normally — exits are non-blocking (one ring push).

        The caller's context (ContextUtil name/origin) is captured HERE and
        the Entry is pushed onto the AWAITING task's context stack after the
        handshake — run_in_executor does not propagate contextvars, so both
        must happen on this side of the await (AsyncEntry's context capture,
        AsyncEntry.java)."""
        import asyncio
        import functools as _ft

        ctx = CTX.current()
        loop = asyncio.get_running_loop()
        e = await loop.run_in_executor(
            None, _ft.partial(self.entry, resource, _ctx=ctx, _push_ctx=False, **kw)
        )
        CTX.push_entry(e)
        return e

    _HOT_PARAM_CAP = 512

    def _note_hot_param(self, resource: str, value) -> None:
        """Count a parameter value sighting (ParameterMetric's value-keyed
        CacheMap analog, host side, capped with decimation on overflow)."""
        try:
            with self._hot_params_lock:
                counter = self._hot_params.setdefault(resource, {})
                counter[value] = counter.get(value, 0) + 1
                if len(counter) > self._HOT_PARAM_CAP:
                    top = sorted(counter.items(), key=lambda kv: -kv[1])
                    self._hot_params[resource] = dict(top[: self._HOT_PARAM_CAP // 2])
        except TypeError:
            pass  # unhashable param value — not trackable

    def rt_quantiles(self, qs=(0.5, 0.9, 0.99)) -> Dict[float, float]:
        """Service-level inbound RT quantiles over the trailing window
        (ops/rtq.py log-bucket histogram; ~11% bucket resolution)."""
        from sentinel_tpu.ops import rtq as RQ

        rcfg = E.rtq_config(self.cfg)
        now = jnp.int32(self.time.now_ms())
        with self._engine_lock:
            counts = np.asarray(RQ.windowed_counts(self._state.rtq, now, rcfg))
        return RQ.quantiles(counts, qs, rcfg)

    def top_params(self, resource: str, n: int = 16) -> list:
        """[(value, sightings)] — the hottest parameter values seen."""
        with self._hot_params_lock:
            counter = dict(self._hot_params.get(resource, {}))
        return sorted(counter.items(), key=lambda kv: -kv[1])[:n]

    def explain(self, resource: str, limit: int = 0) -> list:
        """Why was ``resource`` blocked?  Newest-first provenance records
        (obs/explain.ExplainRecord) from the device-packed explain section
        plus any cluster deny provenance.  Empty when the plane is off
        (cfg.packed_wire falsy or cfg.explain_k == 0) or nothing was
        blocked.  Accepts a resource name or a raw device id."""
        if self.explain_plane is None:
            return []
        if isinstance(resource, int):
            rid: Optional[int] = resource
        else:
            rid = self.registry.peek_resource_id(resource)
        if rid is None:
            return []
        return self.explain_plane.explain(rid, limit=limit)

    def explain_top_causes(self, n: int = 10) -> list:
        """Most frequent (resource, kind, rule, origin) block causes."""
        if self.explain_plane is None:
            return []
        return self.explain_plane.top_causes(n)

    def explain_coverage(self) -> dict:
        """Blocked-decision explainability: {blocked, explained, frac}."""
        if self.explain_plane is None:
            return {"blocked": 0, "explained": 0, "frac": 1.0}
        return self.explain_plane.coverage()

    def param_lane(self, resource: str, param_idx: int) -> Optional[int]:
        """Hash lane the compile assigned to ``param_idx`` on ``resource``,
        or None if that index holds no lane (rule unenforceable).  Public
        accessor for transports (e.g. the native front door) that must
        hash a value into the same lane the engine reads."""
        lanes = self._param_lanes_by_res.get(resource)
        if not lanes:
            return 0 if param_idx == 0 else None
        try:
            return lanes.index(param_idx)
        except ValueError:
            return None

    def trace(self, exc: BaseException, count: int = 1) -> None:
        e = CTX.current_entry()
        if e is not None:
            e.trace(exc, count)

    def enter_context(self, name: str, origin: str = ""):
        return CTX.enter(name, origin)

    def exit_context(self, token) -> None:
        CTX.exit_ctx(token)

    @contextmanager
    def context(self, name: str, origin: str = ""):
        """Context-manager form of ContextUtil.enter/exit."""
        token = CTX.enter(name, origin)
        try:
            yield
        finally:
            CTX.exit_ctx(token)

    # -- bulk API -----------------------------------------------------------

    def submit_acquire(
        self,
        resource: str,
        count: int = 1,
        prioritized: bool = False,
        inbound: bool = False,
        deadline_ms: int = 0,
    ) -> Optional[Future]:
        """Non-blocking single acquire: queue the request and return its
        Future of (verdict, wait_ms), or None for unknown resources
        (pass-through).  The async surface for event-loop callers (the
        cluster token server) — thousands of in-flight requests coalesce
        into engine micro-batches without a thread each."""
        if not self.enabled:
            return None
        rid = self.registry.resource_id(resource)
        if rid is None:
            return None  # pass-through: never queued, never backpressured
        if self._bp_armed:
            reason = self._admission_shed(1 if prioritized else 0)
            if reason is not None:
                self._shed_blocked("admit", reason)
                if self.mode == "sync":
                    self.tick_once()  # keep the control loop stepping
                f: Future = Future()
                f.set_result((int(ERR.BLOCK_SYSTEM), 0))
                return f
        req = AcquireRequest(
            res=rid,
            count=count,
            prio=1 if prioritized else 0,
            origin_id=-1,
            origin_node=self.cfg.trash_row,
            ctx_node=self.cfg.trash_row,
            ctx_name=-1,
            inbound=1 if inbound else 0,
            param_hash=(0,) * self.cfg.param_dims,
            pre_verdict=0,
            deadline_ms=int(deadline_ms),
            future=Future(),
        )
        with self._lock:
            if deadline_ms:
                self._deadlines_live = True
            self._acquires.append(req)
        if self.mode == "sync":
            self.tick_once()
        return req.future

    def check_batch(
        self,
        resources: Sequence[str],
        counts: Optional[Sequence[int]] = None,
        origins: Optional[Sequence[str]] = None,
        params: Optional[Sequence[Any]] = None,
        prioritized: Optional[Sequence[bool]] = None,
        inbound: bool = False,
        deadline_ms: int = 0,
    ) -> List[Tuple[int, int]]:
        """Vector acquire: returns [(verdict, wait_ms)] per resource.

        This is the TPU-native surface: N decisions in one tick.
        """
        if not self.enabled:
            return [(ERR.PASS, 0)] * len(resources)
        shed: List[Optional[str]] = [None] * len(resources)
        if self._bp_armed:
            for i in range(len(resources)):
                pr = 1 if (prioritized is not None and prioritized[i]) else 0
                shed[i] = self._admission_shed(pr)
        has_cluster = bool(self._cluster_flow_by_res or self._cluster_param_by_res)
        # cluster consultation happens OUTSIDE self._lock (it may block on a
        # token-server roundtrip, which must not stall the tick thread) and
        # is AGGREGATED: one request_token per distinct (resource, param)
        # group carrying the summed count — the protocol's count field exists
        # exactly for this — instead of one roundtrip per item
        pre_verdicts = [0] * len(resources)
        pre_waits = [0] * len(resources)
        if has_cluster:
            groups: Dict[Tuple[str, Any], List[int]] = {}
            for i, name in enumerate(resources):
                if shed[i] is not None:
                    continue  # shed CLOSED below; must consume no token
                if name in self._cluster_flow_by_res or name in self._cluster_param_by_res:
                    if self._authority_pre_blocks(
                        name, origins[i] if origins else ""
                    ):
                        continue  # engine rejects it; consume no token
                    groups.setdefault((name, params[i] if params else None), []).append(i)
            for (name, pv), idxs in groups.items():
                item_counts = [counts[i] if counts else 1 for i in idxs]
                vs, ws = self._cluster_check_bulk(name, item_counts, pv)
                for j, i in enumerate(idxs):
                    pre_verdicts[i], pre_waits[i] = vs[j], ws[j]
        futures = []
        with self._lock:
            if deadline_ms:
                # armed under the queue lock so the sweep's all-clear
                # check serializes with the items it must cover
                self._deadlines_live = True
            for i, name in enumerate(resources):
                rid = self.registry.resource_id(name)
                if rid is None:
                    # registry capacity exhausted -> contractually a
                    # pass-through (CtSph.java:200); it never enters the
                    # queue, so backpressure must not turn it into a block
                    futures.append(None)
                    continue
                if shed[i] is not None:
                    self._shed_blocked("admit", shed[i])
                    futures.append("shed")
                    continue
                origin = origins[i] if origins else ""
                pv = params[i] if params else None
                req = AcquireRequest(
                    res=rid,
                    count=counts[i] if counts else 1,
                    prio=1 if (prioritized is not None and prioritized[i]) else 0,
                    origin_id=self.registry.origin_id(origin) if origin else -1,
                    origin_node=self.registry.origin_node_row(name, origin)
                    if origin
                    else self.cfg.trash_row,
                    ctx_node=self.cfg.trash_row,
                    ctx_name=-1,
                    inbound=1 if inbound else 0,
                    param_hash=(hash_param(pv),) + (0,) * (self.cfg.param_dims - 1)
                    if pv is not None
                    else (0,) * self.cfg.param_dims,
                    pre_verdict=pre_verdicts[i],
                    deadline_ms=int(deadline_ms),
                    future=Future(),
                )
                self._acquires.append(req)
                futures.append(req.future)
        if self.mode == "sync":
            self.tick_once()
        out = []
        for i, f in enumerate(futures):
            if f is None:
                out.append((ERR.PASS, 0))
                continue
            if f == "shed":
                out.append((ERR.BLOCK_SYSTEM, 0))
                continue
            v, w = f.result(timeout=self.entry_timeout_s)
            if pre_waits[i] > 0 and v == ERR.PASS:
                # cluster SHOULD_WAIT pacing surfaces to bulk callers too
                v, w = ERR.PASS_WAIT, w + pre_waits[i]
            out.append((v, w))
        return out

    # -- bulk array API (TPU-native surface) --------------------------------

    def submit_block(
        self,
        res: np.ndarray,
        counts: Optional[np.ndarray] = None,
        prio: Optional[np.ndarray] = None,
        origin_id: Optional[np.ndarray] = None,
        origin_node: Optional[np.ndarray] = None,
        ctx_node: Optional[np.ndarray] = None,
        ctx_name: Optional[np.ndarray] = None,
        inbound: Optional[np.ndarray] = None,
        param_hash: Optional[np.ndarray] = None,
        pre_verdict: Optional[np.ndarray] = None,
        deadline_ms: int = 0,
    ) -> Optional[Future]:
        """Bulk acquire: COLUMN ARRAYS of engine resource ids (from
        ``registry.resource_id``), no per-item Python objects.  Returns a
        Future of (verdicts int8 [n], waits int32 [n]) in submission
        order; blocks larger than the batch size span multiple ticks.

        This is the product bulk path — the same batch assembly, host
        presort, engine tick, and verdict fan-out that serves ``entry()``,
        minus the per-request object overhead the reference also avoids
        in its hot loop.

        Done-callbacks on the returned future must be NON-BLOCKING in
        threaded mode: they may submit more work (submit_block /
        submit_completion_block), but a blocking entry()/check_batch_ids
        inside a callback waits on a tick the busy tick thread can't run
        and stalls traffic until its timeout (see _tick_mutex)."""
        if not self.enabled:
            return None
        res = np.ascontiguousarray(res, dtype=np.int32)
        n = len(res)
        if self._bp_armed:
            reason = self._admission_shed(1)  # blocks shed only on hard limits
            if reason in ("fail_closed", "queue_full", "chaos"):
                self._shed_blocked("admit", reason, n)
                if self.mode == "sync":
                    self.tick_once()  # keep the control loop stepping
                f: Future = Future()
                f.set_result(
                    (np.full(n, ERR.BLOCK_SYSTEM, np.int8), np.zeros(n, np.int32))
                )
                return f
        # negative ids would wrap in scatter paths — sanitize to trash
        if (res < 0).any():
            res = np.where(res < 0, np.int32(self.cfg.trash_row), res)

        def col(x):
            if x is None:
                return None
            x = np.ascontiguousarray(x, dtype=np.int32)
            assert len(x) == n
            return x

        blk = ArrayBlock(
            res=res,
            count=col(counts),
            prio=col(prio),
            origin_id=col(origin_id),
            origin_node=col(origin_node),
            ctx_node=col(ctx_node),
            ctx_name=col(ctx_name),
            inbound=col(inbound),
            param_hash=(
                np.ascontiguousarray(param_hash, dtype=np.int32)
                if param_hash is not None
                else None
            ),
            pre_verdict=col(pre_verdict),
            deadline_ms=int(deadline_ms),
            future=Future(),
            unresolved=n,
            verdicts=np.zeros(n, np.int8),
            waits=np.zeros(n, np.int32),
        )
        with self._lock:
            if deadline_ms:
                self._deadlines_live = True
            self._acq_blocks.append(blk)
        if self.mode == "sync":
            self.tick_once()
        return blk.future

    def check_batch_ids(
        self,
        res: np.ndarray,
        counts: Optional[np.ndarray] = None,
        timeout_s: Optional[float] = None,
        **cols,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking form of submit_block: (verdicts, waits) arrays."""
        fut = self.submit_block(res, counts=counts, **cols)
        if fut is None:
            n = len(res)
            return np.full(n, ERR.PASS, np.int8), np.zeros(n, np.int32)
        return fut.result(timeout=timeout_s or self.entry_timeout_s)

    def submit_completion_block(
        self,
        res: np.ndarray,
        rt: np.ndarray,
        success: Optional[np.ndarray] = None,
        error: Optional[np.ndarray] = None,
        inbound: Optional[np.ndarray] = None,
        origin_node: Optional[np.ndarray] = None,
        ctx_node: Optional[np.ndarray] = None,
        param_hash: Optional[np.ndarray] = None,
    ) -> None:
        """Bulk exits for block-acquired traffic: column arrays, queued
        for the next tick (completions are fire-and-forget)."""
        from sentinel_tpu.native.ring import FLAG_COMPLETION, FLAG_INBOUND

        res = np.ascontiguousarray(res, dtype=np.int32)
        n = len(res)
        trash = self.cfg.trash_row

        def col(x, fill, dt=np.int32):
            if x is None:
                return np.full(n, fill, dt)
            x = np.ascontiguousarray(x, dtype=dt)
            assert len(x) == n
            return x

        flags = np.full(n, FLAG_COMPLETION, np.int32) | np.where(
            col(inbound, 0) != 0, FLAG_INBOUND, 0
        )
        if param_hash is not None:
            ph = np.ascontiguousarray(param_hash, dtype=np.int32)
            aux = [ph[:, k] if k < ph.shape[1] else np.zeros(n, np.int32) for k in range(4)]
        else:
            aux = [np.zeros(n, np.int32)] * 4
        block = (
            res,
            col(success, 1),
            col(origin_node, trash),
            col(ctx_node, trash),
            flags,
            col(rt, 0.0, np.float32),
            col(error, 0),
            np.zeros(n, np.int32),
            *aux,
        )
        with self._lock:
            self._comp_blocks.append(block)
        if self.mode == "sync":
            self.tick_once()

    def _submit_completion(self, c: Completion) -> None:
        from sentinel_tpu.native.ring import FLAG_COMPLETION, FLAG_INBOUND

        ph = tuple(c.param_hash) + (0, 0, 0, 0)
        ok = self._comp_ring.push(
            res=c.res,
            count=c.success,
            origin_id=c.origin_node,
            param_hash=c.ctx_node,
            flags=FLAG_COMPLETION | (FLAG_INBOUND if c.inbound else 0),
            rt_ms=c.rt,
            error=c.error,
            aux0=ph[0],
            aux1=ph[1],
            aux2=ph[2],
            aux3=ph[3],
        )
        if not ok:
            with self._lock:
                self._comp_overflow.append(c)
        if self.mode == "sync":
            self.tick_once()

    # -- tick machinery -----------------------------------------------------

    def _tick_loop(self, stop_evt: threading.Event) -> None:
        # stop_evt is captured by argument: a restart swaps self._stop_evt,
        # and an old loop still draining a slow tick must keep observing the
        # event that stop() actually set, not the fresh one.
        interval = self.tick_interval_ms / 1000.0
        while not stop_evt.is_set():
            t0 = mono_s()
            try:
                self.tick_once()
            except Exception:  # pragma: no cover - keep the loop alive  # stlint: disable=fail-open — a dead tick loop strands EVERY pending future; failure is printed, next tick retries
                import traceback

                traceback.print_exc()
            dt = mono_s() - t0
            if dt < interval:
                stop_evt.wait(interval - dt)

    def tick_once(self, now_ms: Optional[int] = None) -> None:
        """Drain queues and run engine ticks until empty.

        Under sustained load, verdict readback runs up to pipeline_depth
        ticks behind dispatch (see _PendingTick); the loop always resolves
        everything before returning idle.  Whole iterations serialize on
        _tick_mutex — sync-mode clients call this from request threads."""
        with self._tick_mutex:
            self._tick_once_locked(now_ms)  # stlint: disable=blocking-under-lock — the tick IS the device dispatch: _tick_mutex exists to serialize exactly this work; readbacks ride the resolver pool, not this lock
        # hot-set promote/demote loop: one cheap cadence check per
        # iteration, outside the tick mutex (the manager takes its own
        # locks; a promotion-triggered rule recompile must not hold up
        # the serving path's mutex holders)
        hs = self.hotset
        if hs is not None:
            hs.maybe_evaluate()

    def _tick_once_locked(self, now_ms: Optional[int]) -> None:
        while True:
            if self._deadlines_live:
                # deadline-aware backpressure: work that has already
                # expired is worthless — shed it CLOSED here, BEFORE it
                # costs device dispatch (one queue pass, only while any
                # deadline-carrying submission is live)
                self._sweep_expired(now_ms)
            blocks = []
            with self._lock:
                acq = self._acquires[: self.cfg.batch_size]
                self._acquires = self._acquires[self.cfg.batch_size :]
                # bulk array blocks fill the rest of the batch (API
                # object requests first — they carry per-request futures
                # a human caller is actively blocked on)
                room_blk = self.cfg.batch_size - len(acq)
                while room_blk > 0 and self._acq_blocks:
                    blk = self._acq_blocks[0]
                    take = min(room_blk, len(blk.res) - blk.taken)
                    blocks.append((blk, blk.taken, take))
                    blk.taken += take
                    room_blk -= take
                    if blk.taken >= len(blk.res):
                        self._acq_blocks.pop(0)
            # Overflow entries spilled when the ring was FULL, so they
            # postdate everything that was in the ring at spill time; the
            # ring must drain first.  Consuming spill only when the ring
            # drains short (= empty) keeps spill after all pre-spill ring
            # entries; it can land after post-spill pushes, a bounded
            # delay in the "processed late" direction only — never a jump
            # ahead — which circuit-breaker probe resolution tolerates.
            comp = self._comp_ring.drain(self.cfg.complete_batch_size)
            n_comp = len(comp[0])
            if n_comp < self.cfg.complete_batch_size and self._comp_overflow:
                with self._lock:
                    spill = self._comp_overflow[: self.cfg.complete_batch_size - n_comp]
                    self._comp_overflow = self._comp_overflow[len(spill) :]
                if spill:
                    comp = tuple(
                        np.concatenate([col, np.asarray(extra, col.dtype)])
                        for col, extra in zip(
                            comp,
                            zip(
                                *[
                                    (s.res, s.success, s.origin_node, s.ctx_node,
                                     4 | (1 if s.inbound else 0), s.rt, s.error, 0)
                                    + (tuple(s.param_hash) + (0, 0, 0, 0))[:4]
                                    for s in spill
                                ]
                            ),
                        )
                    )
                    n_comp += len(spill)
            # bulk completion blocks join after ring + spill
            if n_comp < self.cfg.complete_batch_size and self._comp_blocks:
                with self._lock:
                    pieces = []
                    room_c = self.cfg.complete_batch_size - n_comp
                    while room_c > 0 and self._comp_blocks:
                        cb = self._comp_blocks[0]
                        k = len(cb[0])
                        if k <= room_c:
                            pieces.append(cb)
                            self._comp_blocks.pop(0)
                            room_c -= k
                        else:
                            pieces.append(tuple(col[:room_c] for col in cb))
                            self._comp_blocks[0] = tuple(
                                col[room_c:] for col in cb
                            )
                            room_c = 0
                if pieces:
                    comp = tuple(
                        np.concatenate([comp[j]] + [p[j] for p in pieces])
                        for j in range(len(comp))
                    )
                    n_comp = len(comp[0])
            fronts = []
            room = self.cfg.batch_size - len(acq) - sum(t for _b, _o, t in blocks)
            # rotate the drain order so a saturated first shard can't
            # starve later shards' rings across ticks
            doors = self._front_doors
            if len(doors) > 1:
                rr = self._door_rr = (getattr(self, "_door_rr", -1) + 1) % len(doors)
                doors = doors[rr:] + doors[:rr]
            for door in doors:
                if room <= 0:
                    break
                row, cnt, prio, corr, kind, a0, a1 = door.drain(room)
                if not len(row):
                    continue
                host = kind >= 3  # concurrent acquire/release
                if host.any():
                    door.handle_host_events(
                        kind[host], cnt[host], corr[host], a0[host], a1[host]
                    )
                eng = ~host
                if eng.any():
                    cols = (
                        row[eng].copy(), cnt[eng].copy(), prio[eng].copy(),
                        corr[eng].copy(), a0[eng].copy(), a1[eng].copy(),
                    )
                    fronts.append((door, cols))
                    room -= len(cols[0])
            if not acq and not n_comp and not fronts and not blocks and now_ms is None:
                ad = self._adaptive
                if ad is not None and (
                    ad.ladder.level > DG.NORMAL or ad.ceiling != float("inf")
                ):
                    # the closed loop must keep stepping on EMPTY ticks:
                    # at FAIL_CLOSED everything sheds before the engine,
                    # and without this the ladder would never observe the
                    # calm that lets it descend
                    load, cpu = self._sys.sample()
                    self._adaptive_step(ad, self.time.now_ms(), load, cpu)
                # idle: flush any deferred readbacks before returning
                self._drain_resolves()
                return
            pending = self._run_tick(
                acq, comp if n_comp else None, now_ms, fronts=fronts,
                blocks=blocks,
            )
            self._pending_ticks.append(pending)
            # unconditional: the gauges are on the always-on /metrics
            # surface (one float store each — cheaper than the flag test
            # dance would be worth)
            _G_OCCUPANCY.set(len(self._pending_ticks))
            with self._lock:
                more = (
                    bool(self._acquires)
                    or bool(self._acq_blocks)
                    or bool(self._comp_blocks)
                    or bool(self._comp_ring)
                    or bool(self._comp_overflow)
                )
            if not more:
                more = any(d.pending() > 0 for d in self._front_doors)
            depth = self._pipeline_depth if more else 0
            while len(self._pending_ticks) > depth:
                p = self._pending_ticks.pop(0)
                if self._pipeline_depth > 0:
                    self._resolve_futs.append(
                        self._pool().submit(self._resolve_tick, p)
                    )
                else:
                    self._resolve_tick(p)
            if self._resolve_futs:
                alive = []
                for f in self._resolve_futs:
                    if not f.done():
                        alive.append(f)
                        continue
                    exc = f.exception()
                    if exc is not None:
                        # a lost resolution strands its tick's futures —
                        # it must never vanish silently
                        from sentinel_tpu.utils.record_log import record_log

                        record_log().error(
                            "tick resolution failed: %r", exc, exc_info=exc
                        )
                self._resolve_futs = alive
            _G_RESOLVER_Q.set(len(self._resolve_futs))
            if not more:
                # wait out in-flight resolutions; their callbacks may
                # enqueue new work (closed-loop callers) — re-check
                self._drain_resolves()
                with self._lock:
                    more = bool(
                        self._acquires or self._acq_blocks or self._comp_blocks
                    )
                if not more:
                    return
            now_ms = None  # subsequent drain loops use fresh time

    def _sweep_expired(self, now_ms: Optional[int]) -> None:
        """Shed already-expired queued work CLOSED before device dispatch
        (the admission half of deadline-aware backpressure; the watchdog
        covers work already ON the device)."""
        now = now_ms if now_ms is not None else self.time.now_ms()
        expired: List[AcquireRequest] = []
        exp_blocks: List[ArrayBlock] = []
        with self._lock:
            if any(r.deadline_ms and r.deadline_ms < now for r in self._acquires):
                keep = []
                for r in self._acquires:
                    (expired if r.deadline_ms and r.deadline_ms < now else keep).append(r)
                self._acquires = keep
            if any(
                b.deadline_ms and b.deadline_ms < now for b in self._acq_blocks
            ):
                kept = []
                for b in self._acq_blocks:
                    (exp_blocks if b.deadline_ms and b.deadline_ms < now else kept).append(b)
                self._acq_blocks = kept
            if not any(r.deadline_ms for r in self._acquires) and not any(
                b.deadline_ms for b in self._acq_blocks
            ):
                # no deadline-carrying work left anywhere: disarm the
                # sweep (the flag re-arms under this same lock at the
                # next deadline submission, so nothing can slip between)
                self._deadlines_live = False
        for r in expired:
            if r.future is not None and not r.future.done():
                r.future.set_result((int(ERR.BLOCK_SYSTEM), 0))
        if expired:
            self._shed_blocked("tick", "deadline", len(expired))
        for blk in exp_blocks:
            remaining = len(blk.res) - blk.taken
            blk.verdicts[blk.taken :] = ERR.BLOCK_SYSTEM
            blk.waits[blk.taken :] = 0
            blk.taken = len(blk.res)
            with self._blk_lock:
                blk.unresolved -= remaining
                fire = blk.unresolved <= 0
            if fire and blk.future is not None and not blk.future.done():
                blk.future.set_result((blk.verdicts, blk.waits))
            self._shed_blocked("tick", "deadline", remaining)

    def update_window_shape(
        self,
        sample_count: Optional[int] = None,
        window_ms: Optional[int] = None,
        minute_sample_count: Optional[int] = None,
        minute_window_ms: Optional[int] = None,
    ) -> None:
        """LIVE window reshaping — the IntervalProperty/SampleCountProperty
        analog (node/IntervalProperty.java): swap the engine onto a new
        window grid under the tick lock, MIGRATING current windowed totals
        so admission budgets don't reopen mid-flight (the reference resets
        node metrics instead).  The new tick compiles before the swap
        completes, so serving never waits on XLA."""
        import dataclasses

        changes = {}
        if sample_count is not None:
            changes["second_sample_count"] = int(sample_count)
        if window_ms is not None:
            changes["second_window_ms"] = int(window_ms)
        if minute_sample_count is not None:
            changes["minute_sample_count"] = int(minute_sample_count)
        if minute_window_ms is not None:
            changes["minute_window_ms"] = int(minute_window_ms)
        if not changes:
            return
        new_cfg = dataclasses.replace(self.cfg, **changes)
        if new_cfg == self.cfg:
            return
        self._swap_engine(new_cfg, "window-reshape", **changes)

    def _swap_engine(self, new_cfg, cause: str, **span_attrs) -> None:
        """Compile-then-swap the engine onto ``new_cfg`` LIVE: compile +
        warm the new tick while the old engine keeps serving, then
        migrate state under the engine lock.  Every caller's recompile
        journals as an EXPECTED retrace under ``cause`` — a tuning or
        reshaping session must keep the surprise-retrace count flat."""
        _h = OT.TRACER.begin("client.engine_swap", cause=cause, **span_attrs)
        try:
            with PROF.ledger_owner(self._ledger_name), \
                    PROF.expected_retrace(cause):
                new_tick = E.make_tick(
                    new_cfg, donate=True, features=self._features
                )
            # pre-compile BOTH batch shapes against a throwaway state while
            # the old engine keeps serving: XLA compiles take seconds, and a
            # window whose budget migrated would legitimately EXPIRE during
            # that gap — compiling first makes the actual swap a few ms of
            # migration math
            z = jnp.float32(0.0)
            # ledger_owner: the throwaway state re-claims this client's
            # windows/sketch pool entries at the NEW config's sizes — the
            # same shapes the migrated state lands in below
            with PROF.ledger_owner(self._ledger_name):
                dummy = E.init_state(new_cfg)
            for bs in {min(256, new_cfg.batch_size), new_cfg.batch_size}:
                dummy, _ = new_tick(
                    dummy,
                    self._rules_dev,
                    E.empty_acquire(new_cfg, b=bs),
                    E.empty_complete(
                        new_cfg, b=min(bs, new_cfg.complete_batch_size)
                    ),
                    jnp.int32(self.time.now_ms()),
                    z,
                    z,
                )
            jax.block_until_ready(dummy.concurrency)
            with self._engine_lock:
                old_cfg = self.cfg
                self._state = E.migrate_state(
                    self._state, old_cfg, new_cfg, self.time.now_ms()
                )
                self.cfg = new_cfg
                self.registry.cfg = new_cfg
                self._tick = new_tick
            # ruleset tensors are capacity-shaped, not window-shaped — the
            # recompile only keeps future rule edits keyed to the active cfg
            self._recompile_rules()
        finally:
            OT.TRACER.end(_h)

    def apply_operating_point(self, op, cause: str = "tuner-retune") -> dict:
        """Apply a ``workload.OperatingPoint`` LIVE — the autotuner's
        actuator.  Host-only knobs (pipeline depth, audit cadence) are
        plain attribute writes with no compiled-program impact; engine
        knobs (batch/sketch shapes) ride the same compile-then-swap path
        as ``update_window_shape``, journaled as one expected retrace
        under ``cause``.  ``op`` is duck-typed (``engine_changes`` +
        the knob attributes) so runtime never imports workload.

        Returns ``{"engine": bool, "host": [knob, ...]}`` describing
        what actually changed (an identity apply returns all-empty)."""
        import dataclasses

        applied = {"engine": False, "host": []}
        depth = getattr(op, "pipeline_depth", None)
        if depth is not None and int(depth) != self._pipeline_depth:
            self._pipeline_depth = max(0, int(depth))
            applied["host"].append("pipeline_depth")
        period = getattr(op, "audit_period", None)
        if (
            period is not None
            and self._audit is not None
            and max(1, int(period)) != self._audit.period
        ):
            self._audit.period = max(1, int(period))
            applied["host"].append("audit_period")
        changes = op.engine_changes(self.cfg)
        if changes:
            self._swap_engine(
                dataclasses.replace(self.cfg, **changes), cause, **changes
            )
            applied["engine"] = True
        return applied

    def register_window_property(self, prop) -> None:
        """Subscribe window shape to a SentinelProperty pushing dicts like
        {"sampleCount": 4, "intervalMs": 1000} — datasource-driven live
        reshaping (SampleCountProperty.register2Property analog)."""
        from sentinel_tpu.datasource.property import SimplePropertyListener

        def apply(v):
            if not v:
                return
            # reference semantics: intervalMs is the TOTAL window and
            # sampleCount re-slices it — missing fields default to the
            # CURRENT values so a partial push never changes the other
            # dimension (a sampleCount-only push must not grow the window)
            cur_total = self.cfg.second_sample_count * self.cfg.second_window_ms
            sc = int(v.get("sampleCount") or self.cfg.second_sample_count)
            iv = int(v.get("intervalMs") or cur_total)
            if sc <= 0 or iv <= 0 or iv % sc:
                return
            self.update_window_shape(sample_count=sc, window_ms=iv // sc)

        prop.add_listener(SimplePropertyListener(apply))

    def attach_front_door(self, door) -> None:
        """Serve a NativeFrontDoor's traffic from this client's tick loop:
        its pending acquires join every engine batch as array lanes and
        their verdicts return through the door's response ring —
        per-request work never touches Python (cluster/front_door.py).
        May be called once per SO_REUSEPORT shard — every attached door is
        drained into the same engine batches."""
        self._front_doors.append(door)

    @property
    def host_build_ms_avg(self) -> float:
        """Mean host batch-build time per tick (assembly + presort +
        upload dispatch) since start — the serial host share of serving."""
        return self._build_ms_sum / self._build_ticks if self._build_ticks else 0.0

    def pending_acquires(self) -> int:
        """Depth of the un-ticked acquire queue (load-shedding probe)."""
        with self._lock:
            return len(self._acquires)

    def _dev_col(self, field: str, x: np.ndarray, fill) -> Any:
        """Upload a batch column — or reuse a cached device-resident
        constant when the column equals ``fill`` everywhere.  Bulk
        workloads keep most columns constant (prio, ctx ids, pre_verdict,
        counts of 1), and on a remote/tunnel transport the per-tick column
        upload is the product bottleneck; one equality pass per column
        (~50 µs at 128K) buys skipping the transfer.  Safe because the
        tick donates only the engine state, never batch inputs.

        Keyed by FIELD, not just (fill, shape): two leaves must never
        share one device buffer — XLA dedupes identical argument buffers
        at compile time, and a call whose sharing pattern differs from the
        compile-time call fails with a buffer-count mismatch.

        Varying columns get the dirty-skip: when the column is
        bit-identical to the previous tick's upload, the cached device
        array is reused.  The stored host ref is a PRIVATE COPY of the
        uploaded column, never the staging buffer itself — staging slots
        are reused on a parity cycle (and twice per round on paths that
        tick more than once), so a borrowed ref could be silently
        overwritten, or even BE the buffer under comparison, by the time
        the next tick compares against it.  The copy costs one host
        memcpy per CHANGED column; skipped ticks pay only the compare."""
        if (x == fill).all():
            key = (field, float(fill), x.dtype.str, x.shape)
            c = self._const_cols.get(key)
            if c is None:
                c = jnp.asarray(x)
                self._const_cols[key] = c
                _C_WIRE["tx"].inc(x.nbytes)  # first (only) upload of the const
                self._ledger_wire()  # cold: new (field, dtype, shape) const
            # the dirty ref would go stale while const ticks bypass it —
            # drop it so the next varying tick uploads fresh
            self._col_last.pop(field, None)
            return c
        # the dirty-column delta path is part of the packed transport:
        # packed_wire=False stays a true FULL-UPLOAD reference client
        # (the golden tests compare the packed client against it)
        if self.cfg.packed_wire:
            prev = self._col_last.get(field)
            if (
                prev is not None
                and prev[0].shape == x.shape
                and prev[0].dtype == x.dtype
                and np.array_equal(prev[0], x)
            ):
                _C_COLS_SKIPPED.inc()
                return prev[1]
        dev = jnp.asarray(x)  # copies: mutating x later never touches dev
        _C_WIRE["tx"].inc(x.nbytes)
        self._col_last[field] = (x.copy(), dev)
        return dev

    def _sbuf(self, name: str, shape, dt) -> np.ndarray:
        """Current-parity slot of the two-slot host staging buffer for one
        assembly column (see __init__) — caller fills it completely."""
        key = (name, shape, np.dtype(dt).str)
        s = self._stage.get(key)
        if s is None:
            s = self._stage[key] = [np.empty(shape, dt), np.empty(shape, dt)]
            self._ledger_wire()  # cold: new staging slot pair
        return s[self._stage_parity]

    def _ledger_wire(self) -> None:
        """Re-claim the wire pool (obs/profile.LEDGER) after a cold
        allocation: two-slot host staging buffers plus cached
        device-resident constant columns.  The dirty-column device copies
        (_col_last) churn with traffic and are excluded — ledger entries
        must change only on allocation events, never per tick."""
        nb = sum(
            s[0].nbytes + s[1].nbytes for s in self._stage.values()
        ) + sum(int(c.nbytes) for c in self._const_cols.values())
        with PROF.ledger_owner(self._ledger_name):
            PROF.LEDGER.set("wire", "client.staging", nb)

    def _wire_layout(self, cfg, b: int) -> WIRE.WireLayout:
        """Cached packed-wire offset table for (cfg, batch shape)."""
        key = (cfg, b)
        lo = self._wire_layouts.get(key)
        if lo is None:
            lo = self._wire_layouts[key] = WIRE.layout_for(cfg, b)
        return lo

    # -- segment-capacity adaptation ---------------------------------------

    @staticmethod
    def _host_seg_count(cols, pad_to: Optional[int] = None) -> int:
        """Live-segment count the engine will see for these (sorted) key
        columns — key-change heads plus ops/segment.heads_from_keys'
        synthetic BLOCK-boundary heads.  ``pad_to``: columns are about to
        be padded to this length with one equal-key run (trash rows)."""
        from sentinel_tpu.ops import segment as SG

        n = len(cols[0])
        if n == 0:
            return 0
        change = np.zeros(n - 1, dtype=bool)
        for c in cols:
            c = np.asarray(c)
            change |= c[1:] != c[:-1]
        pos = np.arange(1, n)
        segs = 1 + int(np.count_nonzero(change | (pos % SG.BLOCK == 0)))
        if pad_to is not None and pad_to > n:
            # padding: one key change at n + block heads inside the run
            segs += 1 + (pad_to - 1) // SG.BLOCK - n // SG.BLOCK
        return segs

    def _note_seg_count(self, segs: int, b: int) -> None:
        """Track observed live-segment counts; grow ``seg_u`` (recompile +
        hot-swap the tick) when traffic persistently overflows the
        compacted capacity.  With seg_fallback=True overflow ticks are
        exact but ride the slower per-item kernels, so the resize is a
        performance recovery; with seg_fallback=False it stops the
        fail-closed drops."""
        from sentinel_tpu.ops import engine_seg as ES

        if segs > self._seg_obs_peak:
            self._seg_obs_peak = segs
        cap = ES.seg_capacity(self.cfg, b)
        if segs <= cap:
            return
        self._seg_over_ticks += 1
        # fail-closed configs resize at the FIRST overflow (drops are
        # happening); fallback configs wait out a transient burst
        threshold = 1 if not self.cfg.seg_fallback else 4
        if self._seg_over_ticks < threshold or self._seg_resizing:
            return
        b_full = self.cfg.batch_size
        new_u = min(
            b_full, -(-int(self._seg_obs_peak * 1.25 + 128) // 128) * 128
        )
        if new_u <= ES.seg_capacity(self.cfg, b_full):
            return  # the full-shape capacity already covers the peak
        self._seg_resizing = True
        if self.mode == "threaded":
            threading.Thread(
                target=self._resize_seg_u,
                args=(new_u,),
                name="sentinel-seg-resize",
                daemon=True,
            ).start()
        else:
            self._resize_seg_u(new_u)

    def _resize_seg_u(self, new_u: int) -> None:
        """Compile a tick with the larger compacted capacity against a
        throwaway state (serving continues on the old tick), then swap —
        the update_window_shape compile-first pattern.

        The background compile is safe on host-attached TPU/CPU (XLA is
        thread-safe); a failure here must never take the serving thread
        down, so everything is caught and logged — the engine keeps
        running on the old capacity (exact via seg_fallback)."""
        import dataclasses

        _C_SEG_RESIZE.inc()
        FL.note("seg.resize", seg_u=int(new_u), old_u=int(self.cfg.seg_u))
        _h = OT.TRACER.begin("engine.seg_resize", seg_u=int(new_u))
        try:
            FP.hit(_FP_SEG_RESIZE)  # chaos: a raise keeps the old capacity
            feats = self._features
            new_cfg = dataclasses.replace(self.cfg, seg_u=int(new_u))
            with PROF.ledger_owner(self._ledger_name), \
                    PROF.expected_retrace("segment-resize"):
                new_tick = E.make_tick(new_cfg, donate=True, features=feats)
                z = jnp.float32(0.0)
                dummy = E.init_state(new_cfg)
            for bs in sorted({min(256, new_cfg.batch_size), new_cfg.batch_size}):
                dummy, _ = new_tick(
                    dummy,
                    self._rules_dev,
                    E.empty_acquire(new_cfg, b=bs),
                    E.empty_complete(
                        new_cfg, b=min(bs, new_cfg.complete_batch_size)
                    ),
                    jnp.int32(self.time.now_ms()),
                    z,
                    z,
                )
            jax.block_until_ready(dummy.concurrency)  # stlint: disable=host-sync — blocks on a THROWAWAY warmup state; threaded mode runs this off-loop
            with self._cluster_lock, self._engine_lock:
                if (
                    dataclasses.replace(self.cfg, seg_u=new_cfg.seg_u) != new_cfg
                    or feats != self._features
                ):
                    return  # cfg/features moved underneath us; next overflow retries
                self.cfg = new_cfg
                self.registry.cfg = new_cfg
                self._tick = new_tick
                self._seg_over_ticks = 0
        except Exception:  # stlint: disable=fail-open — background compile: on failure serving continues on the old capacity (exact via seg_fallback), logged
            from sentinel_tpu.utils.record_log import record_log

            record_log().warning(
                "seg_u resize to %d failed; serving continues on the old "
                "capacity", new_u, exc_info=True,
            )
        finally:
            OT.TRACER.end(_h)
            self._seg_resizing = False

    def _fold_device_stats(self, s) -> None:
        """Land one device telemetry row (ops/engine.STAT_* float32 vector,
        already host-resident) in the obs registry: verdict-mix counters
        plus window/ceiling gauges.  Runs on the resolver path once per
        tick — a dozen counter bumps against a ms-scale tick."""
        n_pass = int(s[E.STAT_PASS])
        n_wait = int(s[E.STAT_PASS_WAIT])
        if n_pass:
            _C_DEV_VERDICTS["pass"].inc(n_pass)
        if n_wait:
            _C_DEV_VERDICTS["pass_wait"].inc(n_wait)
        for key, idx in (
            ("block_authority", E.STAT_BLOCK_AUTHORITY),
            ("block_system", E.STAT_BLOCK_SYSTEM),
            ("block_param", E.STAT_BLOCK_PARAM),
            ("block_flow", E.STAT_BLOCK_FLOW),
            ("block_degrade", E.STAT_BLOCK_DEGRADE),
        ):
            n = int(s[idx])
            if n:
                _C_DEV_VERDICTS[key].inc(n)
        n = int(s[E.STAT_FORCED])
        if n:
            _C_DEV_FORCED.inc(n)
        n = int(s[E.STAT_PASS_TOKENS])
        if n:
            _C_DEV_TOKENS["pass"].inc(n)
        n = int(s[E.STAT_BLOCK_TOKENS])
        if n:
            _C_DEV_TOKENS["block"].inc(n)
        _G_DEV_WIN_PASS.set(float(s[E.STAT_WIN_PASS]))
        _G_DEV_MIN_RT.set(_mask_min_rt(float(s[E.STAT_WIN_RT_MIN])))
        _G_DEV_CONC.set(float(s[E.STAT_ENTRY_CONC]))
        _G_DEV_CEIL_UTIL.set(float(s[E.STAT_CEIL_UTIL]))
        _G_DEV_SEG_LIVE.set(float(s[E.STAT_SEG_LIVE]))

    def _record_seg_dropped(self, n: int) -> None:
        """Surface fail-closed segment-overflow drops: counter + block log
        (the reference logs every rejection, EagleEyeLogUtil.java:24-36) +
        rate-limited record-log warning."""
        from sentinel_tpu.ops import engine_seg as ES

        _C_SEG_DROPPED.inc(n)
        with self._blk_lock:
            self.seg_dropped_total += n
        now = self.time.wall_ms()
        if self.block_log is not None:
            self.block_log.log(now, "__seg_overflow__", "SegCapacityDrop", "", n)
        sec = int(now // 1000)
        if sec != self._seg_drop_last_log_s:
            self._seg_drop_last_log_s = sec
            from sentinel_tpu.utils.record_log import record_log

            record_log().warning(
                "segment capacity overflow: %d items FAILED CLOSED this tick "
                "(total %d) — seg_u=%d is undersized for the live traffic; "
                "raise seg_u or set seg_fallback=True",
                n,
                self.seg_dropped_total,
                ES.seg_capacity(self.cfg, self.cfg.batch_size),
            )

    def _audit_attempts(self, rids, now_ms: int):
        """SketchAudit reader: the device sketch's windowed ATTEMPTS
        estimate (PASS + BLOCK planes — exactly the units the engine
        folds: ``acq.count`` per valid entry) for the tracked ids.

        The estimate is jit-cached and the id column padded to the
        audit's fixed K, so steady-state audits dispatch ONE compiled
        executable instead of tracing op-by-op — this read is the whole
        serving-path cost of the audit, amortized over its period."""
        if self._audit_est is None:
            from sentinel_tpu.sketch import impl_for

            impl, scfg = impl_for(self.cfg), self._audit_scfg
            self._audit_est = jax.jit(
                lambda gs, t, r: impl.estimate(gs, t, r, scfg)
            )
        k = len(rids)
        ids = list(rids) + [self.cfg.node_rows] * (self._audit.k - k)
        with self._engine_lock:
            est = np.asarray(
                self._audit_est(
                    self._state.gs,
                    jnp.int32(now_ms),
                    jnp.asarray(ids, jnp.int32),
                )
            )[:k]
        return est[:, W.EV_PASS] + est[:, W.EV_BLOCK]

    def _warm_shapes(self) -> None:
        """Compile the tick for both batch shapes (small + full) with
        no-op batches so serving never waits on XLA."""
        _tw = _time.perf_counter()
        self._resolve_tick(self._run_tick([], None, self.time.now_ms()))
        PROF.RETRACE.observe_compile_ms(
            "engine.tick", (_time.perf_counter() - _tw) * 1000.0
        )
        if self.cfg.batch_size > 256:
            filler = AcquireRequest(
                res=self.cfg.trash_row, count=0, prio=0, origin_id=-1,
                origin_node=self.cfg.trash_row, ctx_node=self.cfg.trash_row,
                ctx_name=-1, inbound=0,
                param_hash=(0,) * self.cfg.param_dims,
            )
            # 257 trash-row entries force the full-shape executable; trash
            # rows are engine no-ops and carry no futures to resolve
            _tw = _time.perf_counter()
            self._resolve_tick(
                self._run_tick([filler] * 257, None, self.time.now_ms())
            )
            PROF.RETRACE.observe_compile_ms(
                "engine.tick", (_time.perf_counter() - _tw) * 1000.0
            )

    def _run_tick(
        self,
        acq: List[AcquireRequest],
        comp,  # Optional[Tuple[np.ndarray, ...]] — drained ring columns
        now_ms: Optional[int],
        fronts=(),  # [(door, (row, count, prio, corr, a0, a1)), ...]
        blocks=(),  # [(ArrayBlock, src_off, take), ...]
    ) -> _PendingTick:
        cfg = self.cfg
        M = cfg.param_dims
        trash = cfg.trash_row
        n_blk = sum(t for _b, _o, t in blocks)
        # flip the staging parity: every _sbuf below hands out the slot
        # the PREVIOUS tick did not touch (double-buffered async safety)
        self._stage_parity ^= 1
        t_build0 = _time.perf_counter()
        # process-unique trace id correlating this tick's spans across the
        # submitting thread and the resolver pool (per-client counters
        # would collide in multi-client processes sharing the ring)
        tick_id = OT.TRACER.next_trace_id()
        # stage brackets (obs/trace.py): _t_asm truthiness is the single
        # flag check; presort time is accumulated separately so the
        # assemble span reports pure column work
        _t_asm = OT.t0()
        _tp0 = 0
        _ns_presort = 0
        # concatenate every attached door's drained engine items; responses
        # route back per door by slice
        if fronts:
            f_cols = [
                np.concatenate([cols[j] for _d, cols in fronts]) for j in range(6)
            ]
            front = tuple(f_cols)
        else:
            front = None
        n_front = 0 if front is None else len(front[0])

        # adaptive batch shape: a light tick (queue <= 256) runs at a small
        # padded shape, anything bigger at the full configured batch — a
        # mostly-idle CPU-backed tick drops ~10x in cost.  Exactly TWO
        # shapes exist so both compile during start()/rule-load warmup;
        # an open-ended power-of-two ladder would push multi-second XLA
        # compiles into the serving path at the first load spike.
        def _shape_for(n: int, cap: int) -> int:
            return min(256, cap) if n <= 256 else cap

        B = _shape_for(len(acq) + n_blk + n_front, cfg.batch_size)
        B2 = _shape_for(0 if comp is None else len(comp[0]), cfg.complete_batch_size)

        from sentinel_tpu.ops.engine import _use_fused

        clamp = _use_fused(cfg)
        # the segment-compacted engine aggregates per key-run: presort
        # batches by its segment keys (stably — arrival order within equal
        # keys is preserved, so every rank/verdict is bit-identical; see
        # ops/segment.py module docstring) and map verdicts back through
        # the inverse permutation.  np.lexsort at the client's batch sizes
        # is tens of microseconds — host work that overlaps the previous
        # device tick anyway.
        presort = cfg.seg_effects and clamp

        a = E.empty_acquire(cfg, b=min(256, cfg.batch_size))
        inv_a = None
        _au_cols = None
        if acq or n_front or n_blk:
            n = len(acq)
            def arr(f, fill, dt, front_col=None, blk_default=None):
                """Column assembly: object requests [0:n], array-block
                slices [n:n+n_blk] (vectorized), front-door items after.
                Assembles into a two-slot staging buffer — the steady
                serving path allocates no per-tick columns."""
                out = self._sbuf("a." + f, B, dt)
                out.fill(fill)
                for i, r in enumerate(acq):
                    out[i] = getattr(r, f)
                o = n
                for blk, off, take in blocks:
                    src = getattr(blk, f)
                    if src is not None:
                        out[o : o + take] = src[off : off + take]
                    elif blk_default is not None and blk_default != fill:
                        out[o : o + take] = blk_default
                    o += take
                if front_col is not None and n_front:
                    out[n + n_blk : n + n_blk + n_front] = front_col
                return out
            f_row = front[0] if n_front else None
            f_cnt = front[1] if n_front else None
            f_prio = front[2] if n_front else None

            def _ph_cols():
                ph = self._sbuf("a.ph", (B, M), np.int32)
                ph.fill(0)
                for i, r in enumerate(acq):
                    t = tuple(r.param_hash)[:M]
                    ph[i, : len(t)] = t
                o = n
                for blk, off, take in blocks:
                    if blk.param_hash is not None:
                        src = blk.param_hash[off : off + take, :M]
                        ph[o : o + take, : src.shape[1]] = src
                    o += take
                if n_front:
                    # native param requests carry pre-hashed lane values
                    ph[n + n_blk : n + n_blk + n_front, 0] = front[4]
                    if M > 1:
                        ph[n + n_blk : n + n_blk + n_front, 1] = front[5]
                return ph

            res_np = arr("res", trash, np.int32, f_row)
            # the fused digit planes carry counts exactly up to
            # max_batch_count (EngineConfig docs); clamping at the
            # single batch-build choke point makes that envelope real
            # for every source (API, async, front door, cluster).  The
            # clamp tracks the ACTIVE path (engine._use_fused, incl.
            # the SENTINEL_NO_PALLAS kill switch) — the unfused paths
            # are exact to 65535 and stay unclamped.
            cnt_np = arr("count", 0, np.int32, f_cnt, blk_default=1)
            if clamp:
                np.minimum(cnt_np, cfg.max_batch_count, out=cnt_np)
            if self._audit is not None:
                # shadow-fold input: the CLAMPED columns, pre-presort
                # (fold order is irrelevant — sums) — exactly the units
                # the engine lands in the sketch.  The staging buffers
                # are not reused before observe() runs below this tick.
                _au_cols = (res_np, cnt_np)
            prio_np = arr("prio", 0, np.int32, f_prio)
            oid_np = arr("origin_id", -1, np.int32)
            onode_np = arr("origin_node", trash, np.int32)
            cnode_np = arr("ctx_node", trash, np.int32)
            cname_np = arr("ctx_name", -1, np.int32)
            inb_np = arr("inbound", 0, np.int32)
            pre_np = arr("pre_verdict", 0, np.int32)
            ph_np = _ph_cols()
            if presort:
                _tp = OT.t0()
                # key order matches engine_seg.prepare_acquire's segment
                # keys, res-major (seg ranks also need res nondecreasing);
                # trash-row padding sorts wherever its id lands — padding
                # items are engine no-ops at any position.  Native stable
                # argsort (native/ring.batch_sort5) with a bit-identical
                # np.lexsort fallback; inverse permutation comes from the
                # same call.
                order, inv_a = RING.batch_sort5(
                    res_np, cnode_np, onode_np, oid_np, cname_np
                )
                cols = [res_np, cnt_np, prio_np, oid_np, onode_np,
                        cnode_np, cname_np, inb_np, pre_np]
                for i, x in enumerate(cols):
                    dst = self._sbuf(f"s.{i}", B, x.dtype)
                    np.take(x, order, out=dst)
                    cols[i] = dst
                (res_np, cnt_np, prio_np, oid_np, onode_np, cnode_np,
                 cname_np, inb_np, pre_np) = cols
                dst = self._sbuf("s.ph", (B, M), np.int32)
                np.take(ph_np, order, axis=0, out=dst)
                ph_np = dst
                if _tp:
                    _tp0 = _tp0 or _tp
                    _ns_presort += OT.now_ns() - _tp
                # sampled (1-in-8 full-size ticks): a handful of numpy
                # passes over B — resize detection doesn't need every tick
                self._seg_sample_ctr += 1
                if B <= 4096 or (self._seg_sample_ctr & 7) == 0:
                    self._note_seg_count(
                        self._host_seg_count(
                            (res_np, cnode_np, onode_np, oid_np, cname_np)
                        ),
                        B,
                    )
            wd_a = WIRE.acquire_wire_dtypes(cfg)

            def _nar(name, key, x, fill):
                # narrow upload (ops/wire.py): flag / verdict-code /
                # clamped-count values fit the wire dtype by construction,
                # so the downcast is exact; the engine widens at tick entry
                dt = wd_a.get(key)
                if dt is not None and x.dtype != dt:
                    nx = self._sbuf("w." + name, x.shape, dt)
                    np.copyto(nx, x, casting="unsafe")
                    x = nx
                return self._dev_col(name, x, fill)

            a = E.AcquireBatch(
                res=self._dev_col("a.res", res_np, trash),
                count=_nar("a.count", "count", cnt_np, 1),
                prio=_nar("a.prio", "prio", prio_np, 0),
                origin_id=self._dev_col("a.oid", oid_np, -1),
                origin_node=self._dev_col("a.onode", onode_np, trash),
                ctx_node=self._dev_col("a.cnode", cnode_np, trash),
                ctx_name=self._dev_col("a.cname", cname_np, -1),
                inbound=_nar("a.inb", "inbound", inb_np, 0),
                param_hash=self._dev_col("a.ph", ph_np, 0),
                pre_verdict=_nar("a.pre", "pre_verdict", pre_np, 0),
            )
        c = E.empty_complete(cfg, b=min(256, cfg.complete_batch_size))
        if comp is not None:
            from sentinel_tpu.native.ring import FLAG_INBOUND

            (res_a, cnt_a, org_a, ctx_a, flags_a, rt_a, err_a, _tag,
             *aux_a) = comp
            n = len(res_a)
            if self._adaptive is not None and n:
                # BBR minRT input: this tick's completion RT floor
                self._adaptive.signals.note_completions(n, float(rt_a.min()))
            if presort and n > 1:
                _tp = OT.t0()
                # completions carry no futures — sort in place, no unsort
                # (all completion effects are order-independent sums/minima)
                order, _ = RING.batch_sort3(res_a, ctx_a, org_a)
                res_a, cnt_a, org_a, ctx_a, flags_a, rt_a, err_a = (
                    x[order]
                    for x in (res_a, cnt_a, org_a, ctx_a, flags_a, rt_a, err_a)
                )
                aux_a = [x[order] for x in aux_a]
                if _tp:
                    _tp0 = _tp0 or _tp
                    _ns_presort += OT.now_ns() - _tp
                self._seg_sample_ctr_c += 1
                if B2 <= 4096 or (self._seg_sample_ctr_c & 7) == 0:
                    self._note_seg_count(
                        self._host_seg_count((res_a, ctx_a, org_a), pad_to=B2),
                        B2,
                    )

            wd_c = WIRE.complete_wire_dtypes(cfg)

            def pad(name, a, fill, dt):
                # staged assembly; narrow wire dtypes downcast exactly
                # (0/1 flags, counts pre-clamped to max_batch_count)
                out = self._sbuf(name, B2, dt)
                out.fill(fill)
                out[:n] = a
                return self._dev_col(name, out, fill)

            ph_np = self._sbuf("c.ph", (B2, M), np.int32)
            ph_np.fill(0)
            for k in range(min(M, len(aux_a))):
                ph_np[:n, k] = aux_a[k]
            c = E.CompleteBatch(
                res=pad("c.res", res_a, trash, np.int32),
                origin_node=pad("c.onode", org_a, trash, np.int32),
                ctx_node=pad("c.cnode", ctx_a, trash, np.int32),
                inbound=pad(
                    "c.inb",
                    (flags_a & FLAG_INBOUND),
                    0,
                    wd_c.get("inbound", np.int32),
                ),
                rt=pad("c.rt", rt_a, 0.0, np.float32),
                # same max_batch_count envelope as the acquire side
                success=pad(
                    "c.succ",
                    np.minimum(cnt_a, cfg.max_batch_count)
                    if clamp
                    else cnt_a,
                    0,
                    wd_c.get("success", np.int32),
                ),
                error=pad(
                    "c.err",
                    np.minimum(err_a, cfg.max_batch_count)
                    if clamp
                    else err_a,
                    0,
                    wd_c.get("error", np.int32),
                ),
                param_hash=self._dev_col("c.ph", ph_np, 0),
            )

        _t_disp = OT.t0()
        if _t_asm:
            OT.stage_ns(
                "tick.assemble",
                _t_asm,
                (_t_disp or OT.now_ns()) - _t_asm - _ns_presort,
                _H_ASSEMBLE,
                trace=tick_id,
                attrs={"b": B, "b2": B2},
            )
            if _ns_presort:
                OT.stage_ns(
                    "tick.presort", _tp0, _ns_presort, _H_PRESORT, trace=tick_id
                )
        load, cpu = self._sys.sample()
        t = now_ms if now_ms is not None else self.time.now_ms()
        t += FP.skew_ms(_FP_TICK_CLOCK)  # chaos: deterministic clock skew
        self._count_rotations(int(t))
        au = self._audit
        if au is not None:
            # audit-then-fold (obs/profile.py): the estimate read and the
            # shadow both cover the stream through the PREVIOUS tick —
            # this tick's batch lands on device only in the dispatch
            # below.  Runs outside _engine_lock; fails OPEN internally.
            au.observe(
                int(t),
                _au_cols[0] if _au_cols is not None else None,
                _au_cols[1] if _au_cols is not None else None,
                self._audit_attempts,
            )
        ad = self._adaptive
        if ad is not None:
            # closed loop: signals row -> controller -> ladder + live
            # system-column ceilings (disabled mode: the one check above)
            self._adaptive_step(ad, t, load, cpu)
        # running average of host batch-build time (assembly + presort +
        # column upload dispatch) — the serial host share of a tick; read
        # via host_build_ms_avg (benchmark decomposition, ops dashboards)
        self._build_ms_sum += (_time.perf_counter() - t_build0) * 1000.0
        self._build_ticks += 1
        with self._engine_lock:
            self._state, out = self._tick(
                self._state,
                self._rules_dev,
                a,
                c,
                jnp.int32(t),
                jnp.float32(load),
                jnp.float32(cpu),
            )
        _disp_done = 0
        if _t_disp:
            _disp_done = OT.now_ns()
            OT.stage_ns(
                "tick.dispatch", _t_disp, _disp_done - _t_disp, _H_DISPATCH,
                trace=tick_id,
            )
        p = _PendingTick(
            acq=acq,
            blocks=list(blocks),
            fronts=list(fronts),
            inv_a=inv_a,
            out=out,
            check_dropped=bool(presort and not cfg.seg_fallback),
            n_obj=len(acq),
            n_blk=n_blk,
            wire_lo=self._wire_layout(cfg, B) if cfg.packed_wire else None,
            tick_id=tick_id,
            dispatched_ns=_disp_done,
            now_ms=int(t),
        )
        self._track_tick(p)  # watchdog coverage (no-op while disarmed)
        if self._pipeline_depth:
            # start the device→host transfer NOW so it overlaps the next
            # tick's host build + device compute (tunnel RTT / PCIe
            # latency hiding); resolution happens in _resolve_tick.
            # Packed mode prefetches the ONE fused buffer instead.
            try:
                (out.wire if out.wire is not None else out.verdict).copy_to_host_async()
            except Exception:  # stlint: disable=fail-open — prefetch hint only; _resolve_tick still reads the verdict synchronously
                pass
        return p

    def _count_rotations(self, t: int) -> None:
        """Advance the host mirror of the device window-rotation cadence
        for one stamped tick timestamp (see _C_WIN_ROT): a refresh at a
        new bucket rotates iff ``wid - last_rot_wid >= slack_buckets``
        (ops/window.refresh's cond), otherwise slack deferred it."""
        for key, tr in self._rot_track.items():
            wms, g, last_wid, last_rot = tr
            wid = (t & 0xFFFFFFFF) // wms  # uint32 view, as W.wid_of
            if last_wid is None:
                tr[2] = tr[3] = wid
                continue
            if wid == last_wid:
                continue
            if wid - last_rot >= g:
                _C_WIN_ROT[key].inc()
                tr[3] = wid
            else:
                _C_WIN_SLACK[key].inc()
            tr[2] = wid

    def _pool(self):
        """Lazily (re)create the resolver pool — stop() shuts it down."""
        if self._resolver_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._resolver_pool = ThreadPoolExecutor(
                max_workers=min(8, self._pipeline_depth + 2),
                thread_name_prefix="sentinel-resolve",
            )
        return self._resolver_pool

    def _drain_resolves(self) -> None:
        """Flush deferred readbacks: pendings not yet handed to the pool,
        then every in-flight pool resolution.  _resolve_tick fails its
        own tick closed instead of raising, so this wait cannot abort
        mid-drain and strand later ticks."""
        while self._pending_ticks:
            p = self._pending_ticks.pop(0)
            if self._pipeline_depth > 0:
                self._resolve_futs.append(self._pool().submit(self._resolve_tick, p))
            else:
                self._resolve_tick(p)
        futs, self._resolve_futs = self._resolve_futs, []
        # bounded drain: _resolve_tick fails its own tick closed, so a
        # future that does not complete means the resolver thread is
        # WEDGED (a readback that never returns), and stop() holds
        # _tick_mutex through this drain — an unbounded result() would
        # hang shutdown forever while blocking every admission thread.
        # One shared deadline across the batch: the ticks resolve
        # concurrently, so waiting entry_timeout_s per future would pay
        # N timeouts for one wedged device.
        deadline = mono_s() + max(2.0 * self.entry_timeout_s, 5.0)
        abandoned = 0
        for f in futs:
            try:
                f.result(timeout=max(0.0, deadline - mono_s()))  # stlint: disable=blocking-under-lock — the deadline above bounds the whole drain; see the wedge rationale
            except _FutTimeout:
                abandoned += 1  # still running; its watchdog fails it over
        if abandoned:
            from sentinel_tpu.utils.record_log import record_log

            record_log().warning(
                "resolve drain abandoned %d wedged tick(s) after %.1fs",
                abandoned, max(2.0 * self.entry_timeout_s, 5.0),
            )
        # the pipeline is empty here — zero the gauges so /metrics never
        # reports a stale occupancy while the loop idles
        _G_OCCUPANCY.set(0)
        _G_RESOLVER_Q.set(0)

    def _resolve_tick(self, p: _PendingTick) -> None:
        """Read back one dispatched tick's outputs and fan verdicts out —
        and if ANYTHING in that path raises (backend readback failure,
        chaos injection), fail the tick CLOSED instead of stranding its
        futures: every waiting caller gets a system-block verdict
        immediately rather than an entry_timeout_s hang.  The same
        degrade-never-break contract the seg-overflow path follows."""
        try:
            self._resolve_tick_inner(p)
        except Exception as exc:  # stlint: disable=fail-open — items fail CLOSED (BLOCK_SYSTEM) below; nothing is admitted or stranded
            if not self._claim_tick(p, "failed"):
                with p.state_lock:
                    if p.state == "failed":
                        return  # the watchdog already failed this tick over
                # state == "done": this thread claimed the fan-out and then
                # broke partway — finish the remaining consumers CLOSED
                # (_fail_tick is partial-fan-out safe)
            _C_RESOLVE_FAILED.inc()
            FL.note(
                "resolve.fail_closed",
                error=f"{type(exc).__name__}: {exc}",
                n_obj=p.n_obj,
                n_blk=p.n_blk,
            )
            from sentinel_tpu.utils.record_log import record_log

            record_log().error(
                "tick resolution failed (%r) — failing %d object / %d block "
                "item(s) CLOSED",
                exc,
                p.n_obj,
                p.n_blk,
                exc_info=True,
            )
            self._fail_tick(p)
        finally:
            self._untrack_tick(p)

    def _fail_tick(self, p: _PendingTick) -> None:
        """Resolve every consumer of a failed tick with a fail-closed
        system-block verdict.  Safe against partial fan-out: futures are
        done-guarded, and block/front-door slices the normal path already
        resolved (p.blocks_done / p.fronts_done) are left untouched — no
        double-decrement of block accounting, no double-respond."""
        v_fail, w_fail = int(ERR.BLOCK_SYSTEM), 0
        for r in p.acq:
            if r.future is not None and not r.future.done():
                r.future.set_result((v_fail, w_fail))
        for blk, off, take in p.blocks[p.blocks_done :]:
            blk.verdicts[off : off + take] = v_fail
            blk.waits[off : off + take] = w_fail
            with self._blk_lock:
                blk.unresolved -= take
                fire = blk.unresolved <= 0
            if fire and blk.future is not None and not blk.future.done():
                blk.future.set_result((blk.verdicts, blk.waits))
            p.blocks_done += 1
        if p.fronts_done < len(p.fronts):
            with self._respond_lock:
                for door, cols in p.fronts[p.fronts_done :]:
                    # advance FIRST: a door whose respond fails here
                    # failed the normal path too — retrying it would
                    # raise out of the fail-closed handler and strand
                    # every other pending tick (_drain_resolves aborts)
                    p.fronts_done += 1
                    k = len(cols[0])
                    try:
                        door.respond(
                            cols[3],
                            np.full(k, v_fail, np.int32),
                            np.zeros(k, np.int32),
                        )
                    except Exception:  # stlint: disable=fail-open — the door transport itself is broken; its clients time out while every OTHER consumer still fails closed
                        from sentinel_tpu.utils.record_log import record_log

                        record_log().error(
                            "front-door respond failed during fail-closed "
                            "fan-out; its clients will time out",
                            exc_info=True,
                        )

    def _resolve_tick_inner(self, p: _PendingTick) -> None:
        """The actual readback + fan-out; may run on a resolver-pool
        thread.  Everything it touches is per-tick (futures, disjoint
        block slices) or lock-protected (drop counters)."""
        FP.hit(_FP_READBACK)  # chaos: a raise fails this tick closed
        FP.hit(_FP_WD_STALL)  # chaos: a delay here stalls the readback —
        # the stand-in for a hung device tick the watchdog must fail over
        out = p.out
        frame = None
        if out.wire is not None:
            # THE single fused readback: verdict bitmap + wait sidecar +
            # telemetry row + timeline top-K + hot-set candidates in one
            # device→host transfer (ops/wire.py layout)
            lo = p.wire_lo
            # stlint: disable-next-line=host-sync — THE designed readback point (fused wire buffer)
            raw = np.asarray(out.wire)
            tl_bytes = lo.tl_rows * lo.tl_cols * 4
            _C_WIRE["rx"].inc(raw.nbytes - tl_bytes)
            if tl_bytes:
                # timeline rows keep their own wire accounting path
                TLM._C_WIRE["rx"].inc(tl_bytes)
            # chaos: mangled bytes must be DETECTED and fail the tick
            # CLOSED — never fan out garbage verdicts.  The pipe covers
            # only the fail-CLOSED main section; the trailing explain
            # section fails OPEN by design and has its own failpoint
            # (obs.explain.decode), so this site's corrupt action stays
            # a deterministic BLOCK_SYSTEM for every seed.
            buf = raw.tobytes()
            split = lo.off_expl * 4
            if lo.expl_k and len(buf) > split:
                data = FP.pipe(_FP_PACKED_DECODE, buf[:split]) + buf[split:]
            else:
                data = FP.pipe(_FP_PACKED_DECODE, buf)
            try:
                frame = WIRE.unpack(data, lo)
            except WIRE.WireDecodeError:
                _C_PACKED_DECODE.inc()
                raise
            verdict = frame.verdict
        else:
            # stlint: disable-next-line=host-sync — THE designed readback point (see class docstring)
            verdict = np.asarray(out.verdict)
            _C_WIRE["rx"].inc(verdict.nbytes)
        if p.dispatched_ns and OT.TRACER.enabled:
            # dispatch → verdicts host-visible: device compute + transfer,
            # plus queue wait when pipelined (spans may overlap in time —
            # that overlap IS the pipelining being measured)
            OT.stage_ns(
                "tick.device",
                p.dispatched_ns,
                OT.now_ns() - p.dispatched_ns,
                _H_DEVICE,
                trace=p.tick_id,
            )
        # readback starts AFTER the verdict wait so it measures only the
        # residual host reads (drop count, wait column) — the device span
        # above already owns the blocking verdict transfer
        _t_rb = OT.t0()
        # device telemetry row (ops/engine.STAT_*): one 96-byte transfer in
        # the same readback phase; replaces the host-side verdict re-scans
        # below (PASS_WAIT probe, adaptive pass/block accounting)
        stats = None
        if frame is not None:
            # packed mode: every block below was decoded from the ONE
            # fused transfer — no further device reads on this path
            # (except the rare wait-sidecar overflow escape hatch)
            stats = frame.stats
            if stats is not None:
                self._fold_device_stats(stats)
            if frame.res_stats is not None and self.timeline is not None:
                self.timeline.note_tick(
                    frame.res_stats, p.now_ms,
                    self.time.wall_ms(p.now_ms) - p.now_ms,
                )
            if frame.hot is not None and self.hotset is not None:
                self.hotset.fold(frame.hot)
            if frame.expl is not None and self.explain_plane is not None:
                # BEFORE the verdict fan-out below, so an entry() that
                # raises a BlockException can already look itself up in
                # the provenance rings (block-log key, explain())
                self.explain_plane.ingest_section(frame.expl, ts_ms=p.now_ms)
        else:
            if out.stats is not None:
                stats = np.asarray(out.stats)  # stlint: disable=host-sync — readback point
                _C_WIRE["rx"].inc(stats.nbytes)
                self._fold_device_stats(stats)
            # per-resource timeline matrix (ops/engine.TL_*): K rows in the
            # same readback phase, folded write-behind into per-second
            # records (obs/timeline.py) — its wire cost is accounted under
            # path="timeline" so the transport work sees it separately
            if out.res_stats is not None and self.timeline is not None:
                rs = np.asarray(out.res_stats)  # stlint: disable=host-sync — readback point
                TLM._C_WIRE["rx"].inc(rs.nbytes)
                self.timeline.note_tick(
                    rs, p.now_ms, self.time.wall_ms(p.now_ms) - p.now_ms
                )
            # hot-set candidate rows ([K, 2] id/estimate): folded into the
            # promotion loop's candidate map (sketch/hotset.py)
            if out.hot is not None and self.hotset is not None:
                hot = np.asarray(out.hot)  # stlint: disable=host-sync — readback point
                _C_WIRE["rx"].inc(hot.nbytes)
                self.hotset.fold(hot)
        if p.check_dropped:
            # fail-closed capacity overflow must be LOUD (an engine
            # rejecting traffic because seg_u is undersized is an incident,
            # not a silent counter)
            if frame is not None:
                dropped = frame.seg_dropped  # always in the packed header
            elif stats is not None:
                dropped = int(stats[E.STAT_SEG_DROPPED])
            else:
                dropped = int(np.asarray(out.seg_dropped))  # stlint: disable=host-sync — readback point
                _C_WIRE["rx"].inc(4)
            if dropped:
                self._record_seg_dropped(dropped)
        # the wait column is only nonzero when some verdict is PASS_WAIT
        # (engine zeroes wait for non-passing items) — skip the 4x-larger
        # transfer entirely on the common no-pacing tick.  The device
        # telemetry row answers "any PASS_WAIT?" without scanning the
        # verdict array on the host.
        if frame is not None:
            wait = frame.wait
            if wait is None:
                # > EXC_K pacing rows this tick: the sidecar overflowed —
                # the ONE escape-hatch read outside the fused transfer
                wait = np.asarray(out.wait_ms)  # stlint: disable=host-sync — sidecar-overflow escape hatch (rare by design)
                _C_WIRE["rx"].inc(wait.nbytes)
        elif stats is not None and not stats[E.STAT_PASS_WAIT] > 0:
            wait = np.zeros(verdict.shape[0], np.int32)
        elif stats is None and not (verdict == ERR.PASS_WAIT).any():
            wait = np.zeros(verdict.shape[0], np.int32)
        else:
            wait = np.asarray(out.wait_ms)  # stlint: disable=host-sync — readback point
            _C_WIRE["rx"].inc(wait.nbytes)
        if _t_rb:
            OT.stage("tick.readback", _t_rb, _H_READBACK, trace=p.tick_id)
        FP.hit(_FP_FANOUT)  # chaos: raise BEFORE any consumer resolves
        if not self._claim_tick(p, "done"):
            return  # the watchdog failed this tick over while we read back
        self._untrack_tick(p)
        _t_res = OT.t0()
        if p.inv_a is not None:
            # map sorted-batch verdicts back to submission order
            verdict = verdict[p.inv_a]
            wait = wait[p.inv_a]
        if self._adaptive is not None:
            if stats is not None:
                # device accounting: valid items ARE the real items (all
                # padding carries the trash row), so the telemetry row
                # replaces the host-side verdict scan
                n_real = int(stats[E.STAT_VALID])
                passed = int(stats[E.STAT_PASS] + stats[E.STAT_PASS_WAIT])
                if n_real:
                    self._adaptive.signals.note_resolved(passed, n_real - passed)
                self._adaptive.signals.note_device_stats(stats)
            else:
                n_real = p.n_obj + p.n_blk + sum(
                    len(cols[0]) for _d, cols in p.fronts
                )
                if n_real:
                    v = verdict[:n_real]
                    passed = int(((v == ERR.PASS) | (v == ERR.PASS_WAIT)).sum())
                    self._adaptive.signals.note_resolved(passed, n_real - passed)
        for i, r in enumerate(p.acq):
            if r.future is not None:
                r.future.set_result((int(verdict[i]), int(wait[i])))
        o = p.n_obj
        for blk, off, take in p.blocks:
            blk.verdicts[off : off + take] = verdict[o : o + take]
            blk.waits[off : off + take] = wait[o : o + take]
            with self._blk_lock:
                blk.unresolved -= take
                fire = blk.unresolved <= 0
            if fire and blk.future is not None:
                blk.future.set_result((blk.verdicts, blk.waits))
            p.blocks_done += 1
            o += take
        if p.fronts:
            off = p.n_obj + p.n_blk
            with self._respond_lock:
                for door, cols in p.fronts:
                    k = len(cols[0])
                    door.respond(
                        cols[3],
                        verdict[off : off + k].astype(np.int32),
                        wait[off : off + k].astype(np.int32),
                    )
                    p.fronts_done += 1
                    off += k
        if _t_res:
            OT.stage(
                "tick.resolve", _t_res, _H_RESOLVE, trace=p.tick_id,
                attrs={"n_obj": p.n_obj, "n_blk": p.n_blk},
            )


def _mask_min_rt(v: float) -> float:
    """RT_MIN_INIT (5000) is the 'no completions in window' sentinel
    (every backend maintains per-row minRt exactly — ops/rowmin.py).
    Report 0.0 instead of a phantom 5-second minimum."""
    return 0.0 if v >= W.RT_MIN_INIT else v


class ClientStats:
    """Read-side node statistics (the ClusterNode/StatisticNode getters:
    passQps/blockQps/successQps/exceptionQps/avgRt/curThreadNum)."""

    def __init__(self, client: SentinelClient):
        self._c = client

    def _row_stats(self, row: int) -> Dict[str, float]:
        c = self._c
        sec_cfg = W.WindowConfig(c.cfg.second_sample_count, c.cfg.second_window_ms)
        now = jnp.int32(c.time.now_ms())
        with c._engine_lock:
            st = c._state
            rows = jnp.asarray([row], dtype=jnp.int32)
            counts = np.asarray(W.gather_window_counts(st.win_sec, now, rows, sec_cfg))[0]
            rt_tot, rt_min = W.gather_window_rt(st.win_sec, now, rows, sec_cfg)
            conc = int(np.asarray(st.concurrency[row]))
        interval_s = sec_cfg.interval_ms / 1000.0
        succ = float(counts[W.EV_SUCCESS])
        return {
            "passQps": float(counts[W.EV_PASS]) / interval_s,
            "blockQps": float(counts[W.EV_BLOCK]) / interval_s,
            "successQps": succ / interval_s,
            "exceptionQps": float(counts[W.EV_EXCEPTION]) / interval_s,
            "occupiedPassQps": float(counts[W.EV_OCCUPIED]) / interval_s,
            "avgRt": float(np.asarray(rt_tot)[0]) / succ if succ > 0 else 0.0,
            "minRt": _mask_min_rt(float(np.asarray(rt_min)[0])),
            "curThreadNum": conc,
        }

    def resource(self, name: str) -> Optional[Dict[str, float]]:
        rid = self.registry_peek(name)
        if rid is None:
            return None
        if self._c.registry.is_sketch_id(rid):
            return self._sketch_stats([rid])[0]
        return self._row_stats(rid)

    def origin(self, resource: str, origin: str) -> Optional[Dict[str, float]]:
        """Per-(resource, caller) stats — the ClusterNode.getOriginNode
        read (ClusterBuilderSlot origin rows).  None until that caller has
        been seen (the row is created on first entry with the origin)."""
        row = self._c.registry.origin_row_if_exists(resource, origin)
        return None if row is None else self._row_stats(row)

    def _sketch_stats(self, rids, now_ms: Optional[int] = None) -> list:
        """Windowed CMS estimates for sketch-id resources (the salsa tier
        or the seed ops/gsketch.py, per cfg.sketch_salsa); pass/block are
        small overestimates bounded by the sketch (eps, delta)."""
        from sentinel_tpu.ops import engine as E
        from sentinel_tpu.ops import gsketch as GS
        from sentinel_tpu.sketch import impl_for

        c = self._c
        scfg = E.sketch_config(c.cfg)
        now = jnp.int32(c.time.now_ms() if now_ms is None else now_ms)
        with c._engine_lock:
            est = np.asarray(
                impl_for(c.cfg).estimate(
                    c._state.gs, now, jnp.asarray(rids, jnp.int32), scfg
                )
            )
        interval_s = scfg.interval_ms / 1000.0
        out = []
        for i in range(len(rids)):
            succ = float(est[i, W.EV_SUCCESS])
            out.append(
                {
                    "passQps": float(est[i, W.EV_PASS]) / interval_s,
                    "blockQps": float(est[i, W.EV_BLOCK]) / interval_s,
                    "successQps": succ / interval_s,
                    "exceptionQps": float(est[i, W.EV_EXCEPTION]) / interval_s,
                    "occupiedPassQps": float(est[i, W.EV_OCCUPIED]) / interval_s,
                    "avgRt": float(est[i, GS.RT_PLANE]) / GS.RT_SCALE / succ
                    if succ > 0
                    else 0.0,
                    "minRt": 0.0,
                    "curThreadNum": 0,
                }
            )
        return out

    def snapshot(self, now_ms: Optional[int] = None) -> Dict[str, Dict[str, float]]:
        """Trailing-second stats for ALL registered resources in ONE batched
        device gather — the TPU-shaped walk of the ClusterNode map that
        MetricTimerListener does per second.  Sketch-id resources (beyond
        the exact row space) are served from the global CMS in a second
        batched read."""
        c = self._c
        resources = c.registry.resources()
        if not resources:
            return {}
        # ONE timestamp for the whole snapshot: the read paths may jit-compile
        # on first use (hundreds of ms), and a per-phase `now` would let the
        # trailing window slide between the exact and sketch reads
        now_ms = c.time.now_ms() if now_ms is None else now_ms
        exact = {n: r for n, r in resources.items() if not c.registry.is_sketch_id(r)}
        sketch = {n: r for n, r in resources.items() if c.registry.is_sketch_id(r)}
        out: Dict[str, Dict[str, float]] = {}
        if exact:
            names = list(exact.keys())
            rows_np = np.asarray(list(exact.values()), dtype=np.int32)
            rows = jnp.asarray(rows_np)
            sec_cfg = W.WindowConfig(c.cfg.second_sample_count, c.cfg.second_window_ms)
            now = jnp.int32(now_ms)
            with c._engine_lock:
                st = c._state
                counts = np.asarray(
                    W.gather_window_counts(st.win_sec, now, rows, sec_cfg)
                )
                rt_tot, rt_min = W.gather_window_rt(st.win_sec, now, rows, sec_cfg)
                conc = np.asarray(st.concurrency)[rows_np]
            rt_tot = np.asarray(rt_tot)
            rt_min = np.asarray(rt_min)
            interval_s = sec_cfg.interval_ms / 1000.0
            for i, name in enumerate(names):
                succ = float(counts[i, W.EV_SUCCESS])
                out[name] = {
                    "passQps": float(counts[i, W.EV_PASS]) / interval_s,
                    "blockQps": float(counts[i, W.EV_BLOCK]) / interval_s,
                    "successQps": succ / interval_s,
                    "exceptionQps": float(counts[i, W.EV_EXCEPTION]) / interval_s,
                    "occupiedPassQps": float(counts[i, W.EV_OCCUPIED]) / interval_s,
                    "avgRt": float(rt_tot[i]) / succ if succ > 0 else 0.0,
                    "minRt": _mask_min_rt(float(rt_min[i])),
                    "curThreadNum": int(conc[i]),
                }
        if sketch:
            s_names = list(sketch.keys())
            stats = self._sketch_stats(list(sketch.values()), now_ms=now_ms)
            for name, s in zip(s_names, stats):
                out[name] = s
        return out

    def entry_node(self) -> Dict[str, float]:
        return self._row_stats(self._c.cfg.entry_node_row)

    def registry_peek(self, name: str) -> Optional[int]:
        return self._c.registry.peek_resource_id(name)
