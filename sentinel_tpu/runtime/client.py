"""The host runtime: micro-batching client around the device engine.

This layer replaces the reference's per-request machinery (CtSph.java:43,
CtEntry, the slot-chain walk) with an accumulate→tick→fan-out loop:

  entry("res")  ──► AcquireRequest + Future ──┐
  entry.exit()  ──► completion record ────────┤  pending queues
                                              ▼
                         tick thread (every ~tick_interval_ms, or manual):
                           drain queues → fixed-shape batches → jitted
                           engine tick → resolve futures with verdicts

Modes:
  * ``sync``    — every entry() runs a tick inline (batch of whatever is
                  queued).  Deterministic; pairs with VirtualTimeSource for
                  tests (the AbstractTimeBasedTest analog, SURVEY.md §4.1).
  * ``threaded``— a daemon tick loop services futures; entry() blocks.
                  This is the serving configuration.

Bulk path: ``check_batch`` submits N acquires in one call and ticks once —
the native TPU API used by the cluster token server and the benchmark.
"""

from __future__ import annotations

import threading
import time as _time
from concurrent.futures import Future
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core import rules as R
from sentinel_tpu.core.config import EngineConfig
from sentinel_tpu.core.rule_tensors import hash_param
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import window as W
from sentinel_tpu.runtime import context as CTX
from sentinel_tpu.runtime.registry import Registry
from sentinel_tpu.utils.system_status import SystemStatusSampler
from sentinel_tpu.utils.time_source import TimeSource, VirtualTimeSource


@dataclass
class AcquireRequest:
    res: int
    count: int
    prio: int
    origin_id: int
    origin_node: int
    ctx_node: int
    ctx_name: int
    inbound: int
    param_hash: int
    future: Optional[Future] = None


@dataclass
class Completion:
    res: int
    origin_node: int
    ctx_node: int
    inbound: int
    rt: float
    success: int
    error: int


class Entry:
    """Live entry handle (the reference's Entry/CtEntry).

    ``exit()`` records RT + success; ``trace(exc)`` marks a business
    exception for exception-ratio circuit breakers (Tracer.java).
    """

    __slots__ = (
        "client",
        "resource",
        "res",
        "origin_node",
        "ctx_node",
        "inbound",
        "count",
        "create_ms",
        "wait_ms",
        "_errors",
        "_exited",
    )

    def __init__(self, client, resource, res, origin_node, ctx_node, inbound, count, create_ms, wait_ms=0):
        self.client = client
        self.resource = resource
        self.res = res
        self.origin_node = origin_node
        self.ctx_node = ctx_node
        self.inbound = inbound
        self.count = count
        self.create_ms = create_ms
        self.wait_ms = wait_ms
        self._errors = 0
        self._exited = False

    def trace(self, exc: Optional[BaseException] = None, count: int = 1) -> None:
        if exc is not None and isinstance(exc, ERR.BlockException):
            return  # block exceptions are not business errors (Tracer semantics)
        self._errors += count

    def exit(self, count: Optional[int] = None) -> None:
        if self._exited:
            return
        self._exited = True
        CTX.pop_entry(self)
        if self.res is None:
            return  # pass-through entry (capacity overflow)
        now = self.client.time.now_ms()
        rt = float(max(now - self.create_ms, 0))
        self.client._submit_completion(
            Completion(
                res=self.res,
                origin_node=self.origin_node,
                ctx_node=self.ctx_node,
                inbound=self.inbound,
                rt=rt,
                success=count if count is not None else self.count,
                error=self._errors,
            )
        )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self.trace(exc)
        self.exit()
        return False


class _PassThroughEntry(Entry):
    def __init__(self, client, resource):
        super().__init__(client, resource, None, 0, 0, 0, 1, 0)


class RuleManager:
    """Typed rule holder with push-style listeners.

    The analog of FlowRuleManager/DegradeRuleManager/...: ``load`` replaces
    the full rule set and triggers engine recompilation
    (FlowRuleManager.loadRules → property.updateValue → listener).
    """

    def __init__(self, client: "SentinelClient", kind: str):
        self._client = client
        self.kind = kind
        self._rules: list = []
        self._listeners: list = []
        self._property = None

    def load(self, rules: Sequence) -> None:
        self._rules = list(rules) if rules else []
        self._client._recompile_rules()
        for fn in list(self._listeners):
            fn(self._rules)

    def get(self) -> list:
        return list(self._rules)

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    def register_property(self, prop) -> None:
        """Subscribe this manager to a SentinelProperty so datasource pushes
        drive rule reloads (FlowRuleManager.register2Property analog)."""
        from sentinel_tpu.datasource.property import SimplePropertyListener

        if self._property is not None:
            self._property.remove_listener(self._prop_listener)
        self._property = prop
        # None means "property not populated yet" — keep existing rules
        # (FlowPropertyListener.configLoad null-check); an empty list is a
        # real "clear all rules" push.
        self._prop_listener = SimplePropertyListener(
            lambda rules: None if rules is None else self.load(rules)
        )
        prop.add_listener(self._prop_listener)


class SentinelClient:
    def __init__(
        self,
        app_name: Optional[str] = None,
        cfg: Optional[EngineConfig] = None,
        time_source: Optional[TimeSource] = None,
        mode: str = "threaded",  # "threaded" | "sync"
        tick_interval_ms: float = 1.0,
        entry_timeout_s: float = 5.0,
    ):
        from sentinel_tpu.core.config import app_name as cfg_app_name

        self.app_name = app_name or cfg_app_name()
        self.cfg = cfg or EngineConfig()
        self.time = time_source or TimeSource()
        self.mode = mode if not isinstance(self.time, VirtualTimeSource) else "sync"
        self.tick_interval_ms = tick_interval_ms
        self.entry_timeout_s = entry_timeout_s

        self.registry = Registry(self.cfg)
        self.flow_rules = RuleManager(self, "flow")
        self.degrade_rules = RuleManager(self, "degrade")
        self.system_rules = RuleManager(self, "system")
        self.authority_rules = RuleManager(self, "authority")
        self.param_flow_rules = RuleManager(self, "param-flow")

        self._sys = SystemStatusSampler()
        self._tick = E.make_tick(self.cfg, donate=True)
        self._state = E.init_state(self.cfg)
        self._rules_dev = E.compile_ruleset(self.cfg, self.registry)
        self._rules_dirty = False

        self._lock = threading.Lock()  # guards queues
        self._engine_lock = threading.Lock()  # guards state/tick execution
        self._acquires: List[AcquireRequest] = []
        self._completions: List[Completion] = []

        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._started = False
        self.stats = ClientStats(self)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._stop_evt = threading.Event()  # allow stop() → start() restart
        if self.mode == "threaded":
            # Warm the compile cache before serving: the first jitted tick
            # can take tens of seconds; without this, early entry() futures
            # hit entry_timeout_s while XLA compiles.
            self._run_tick([], [], self.time.now_ms())
            self._thread = threading.Thread(
                target=self._tick_loop,
                args=(self._stop_evt,),
                name="sentinel-tpu-tick",
                daemon=True,
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._started = False

    # -- rule compilation ---------------------------------------------------

    def _recompile_rules(self) -> None:
        with self._engine_lock:
            self._rules_dev = E.compile_ruleset(
                self.cfg,
                self.registry,
                flow_rules=self.flow_rules.get(),
                degrade_rules=self.degrade_rules.get(),
                param_rules=self.param_flow_rules.get(),
                authority_rules=self.authority_rules.get(),
                system_rules=self.system_rules.get(),
            )

    # -- public entry API ---------------------------------------------------

    def entry(
        self,
        resource: str,
        count: int = 1,
        prioritized: bool = False,
        args: Optional[Sequence[Any]] = None,
        inbound: bool = False,
        origin: Optional[str] = None,
    ) -> Entry:
        """Acquire; raises BlockException on rejection (SphU.entry)."""
        ctx_name, ctx_origin = CTX.current()
        origin = origin if origin is not None else ctx_origin
        rid = self.registry.resource_id(resource)
        if rid is None:
            e = _PassThroughEntry(self, resource)
            CTX.push_entry(e)
            return e  # capacity overflow → pass-through (CtSph.java:200)

        origin_id = self.registry.origin_id(origin) if origin else -1
        origin_node = (
            self.registry.origin_node_row(resource, origin)
            if origin
            else self.cfg.trash_row
        )
        if ctx_name != CTX.DEFAULT_CONTEXT_NAME:
            ctx_node = self.registry.ctx_node_row(resource, ctx_name)
            ctx_id = self.registry.context_id(ctx_name)
        else:
            ctx_node = self.cfg.trash_row
            ctx_id = -1

        param_hash = 0
        if args:
            # hot-param limiting keys off the configured param index; host
            # hashes the first arg by convention, adapters pass the right one
            param_hash = hash_param(args[0])

        req = AcquireRequest(
            res=rid,
            count=count,
            prio=1 if prioritized else 0,
            origin_id=origin_id,
            origin_node=origin_node,
            ctx_node=ctx_node,
            ctx_name=ctx_id,
            inbound=1 if inbound else 0,
            param_hash=param_hash,
            future=Future(),
        )
        with self._lock:
            self._acquires.append(req)

        if self.mode == "sync":
            self.tick_once()
        verdict, wait_ms = req.future.result(timeout=self.entry_timeout_s)

        if verdict not in (ERR.PASS, ERR.PASS_WAIT):
            # record nothing extra here: the engine already counted the block
            ERR.raise_for_verdict(verdict, resource)
        if verdict == ERR.PASS_WAIT and wait_ms > 0:
            self.time.sleep_ms(wait_ms)

        e = Entry(
            self,
            resource,
            rid,
            origin_node,
            ctx_node,
            1 if inbound else 0,
            count,
            self.time.now_ms(),
            wait_ms,
        )
        CTX.push_entry(e)
        return e

    def try_entry(self, resource: str, **kw) -> Optional[Entry]:
        """SphO-style boolean variant."""
        try:
            return self.entry(resource, **kw)
        except ERR.BlockException:
            return None

    def trace(self, exc: BaseException, count: int = 1) -> None:
        e = CTX.current_entry()
        if e is not None:
            e.trace(exc, count)

    def enter_context(self, name: str, origin: str = ""):
        return CTX.enter(name, origin)

    def exit_context(self, token) -> None:
        CTX.exit_ctx(token)

    @contextmanager
    def context(self, name: str, origin: str = ""):
        """Context-manager form of ContextUtil.enter/exit."""
        token = CTX.enter(name, origin)
        try:
            yield
        finally:
            CTX.exit_ctx(token)

    # -- bulk API -----------------------------------------------------------

    def check_batch(
        self,
        resources: Sequence[str],
        counts: Optional[Sequence[int]] = None,
        origins: Optional[Sequence[str]] = None,
        params: Optional[Sequence[Any]] = None,
        inbound: bool = False,
    ) -> List[Tuple[int, int]]:
        """Vector acquire: returns [(verdict, wait_ms)] per resource.

        This is the TPU-native surface: N decisions in one tick.
        """
        futures = []
        with self._lock:
            for i, name in enumerate(resources):
                rid = self.registry.resource_id(name)
                if rid is None:
                    futures.append(None)
                    continue
                origin = origins[i] if origins else ""
                req = AcquireRequest(
                    res=rid,
                    count=counts[i] if counts else 1,
                    prio=0,
                    origin_id=self.registry.origin_id(origin) if origin else -1,
                    origin_node=self.registry.origin_node_row(name, origin)
                    if origin
                    else self.cfg.trash_row,
                    ctx_node=self.cfg.trash_row,
                    ctx_name=-1,
                    inbound=1 if inbound else 0,
                    param_hash=hash_param(params[i]) if params else 0,
                    future=Future(),
                )
                self._acquires.append(req)
                futures.append(req.future)
        if self.mode == "sync":
            self.tick_once()
        out = []
        for f in futures:
            if f is None:
                out.append((ERR.PASS, 0))
            else:
                out.append(f.result(timeout=self.entry_timeout_s))
        return out

    def _submit_completion(self, c: Completion) -> None:
        with self._lock:
            self._completions.append(c)
        if self.mode == "sync":
            self.tick_once()

    # -- tick machinery -----------------------------------------------------

    def _tick_loop(self, stop_evt: threading.Event) -> None:
        # stop_evt is captured by argument: a restart swaps self._stop_evt,
        # and an old loop still draining a slow tick must keep observing the
        # event that stop() actually set, not the fresh one.
        interval = self.tick_interval_ms / 1000.0
        while not stop_evt.is_set():
            t0 = _time.monotonic()
            try:
                self.tick_once()
            except Exception:  # pragma: no cover - keep the loop alive
                import traceback

                traceback.print_exc()
            dt = _time.monotonic() - t0
            if dt < interval:
                stop_evt.wait(interval - dt)

    def tick_once(self, now_ms: Optional[int] = None) -> None:
        """Drain queues and run engine ticks until empty."""
        while True:
            with self._lock:
                acq = self._acquires[: self.cfg.batch_size]
                self._acquires = self._acquires[self.cfg.batch_size :]
                comp = self._completions[: self.cfg.complete_batch_size]
                self._completions = self._completions[self.cfg.complete_batch_size :]
            if not acq and not comp and now_ms is None:
                return
            self._run_tick(acq, comp, now_ms)
            with self._lock:
                more = bool(self._acquires) or bool(self._completions)
            if not more:
                return
            now_ms = None  # subsequent drain loops use fresh time

    def _run_tick(
        self,
        acq: List[AcquireRequest],
        comp: List[Completion],
        now_ms: Optional[int],
    ) -> None:
        cfg = self.cfg
        B, B2 = cfg.batch_size, cfg.complete_batch_size
        trash = cfg.trash_row

        a = E.empty_acquire(cfg)
        if acq:
            n = len(acq)
            arr = lambda f, fill, dt: np.asarray(
                [getattr(r, f) for r in acq] + [fill] * (B - n), dtype=dt
            )
            a = E.AcquireBatch(
                res=jnp.asarray(arr("res", trash, np.int32)),
                count=jnp.asarray(arr("count", 0, np.int32)),
                prio=jnp.asarray(arr("prio", 0, np.int32)),
                origin_id=jnp.asarray(arr("origin_id", -1, np.int32)),
                origin_node=jnp.asarray(arr("origin_node", trash, np.int32)),
                ctx_node=jnp.asarray(arr("ctx_node", trash, np.int32)),
                ctx_name=jnp.asarray(arr("ctx_name", -1, np.int32)),
                inbound=jnp.asarray(arr("inbound", 0, np.int32)),
                param_hash=jnp.asarray(arr("param_hash", 0, np.int32)),
            )
        c = E.empty_complete(cfg)
        if comp:
            n = len(comp)
            arr = lambda f, fill, dt: np.asarray(
                [getattr(r, f) for r in comp] + [fill] * (B2 - n), dtype=dt
            )
            c = E.CompleteBatch(
                res=jnp.asarray(arr("res", trash, np.int32)),
                origin_node=jnp.asarray(arr("origin_node", trash, np.int32)),
                ctx_node=jnp.asarray(arr("ctx_node", trash, np.int32)),
                inbound=jnp.asarray(arr("inbound", 0, np.int32)),
                rt=jnp.asarray(arr("rt", 0.0, np.float32)),
                success=jnp.asarray(arr("success", 0, np.int32)),
                error=jnp.asarray(arr("error", 0, np.int32)),
            )

        load, cpu = self._sys.sample()
        t = now_ms if now_ms is not None else self.time.now_ms()
        with self._engine_lock:
            self._state, out = self._tick(
                self._state,
                self._rules_dev,
                a,
                c,
                jnp.int32(t),
                jnp.float32(load),
                jnp.float32(cpu),
            )
            verdict = np.asarray(out.verdict)
            wait = np.asarray(out.wait_ms)
        for i, r in enumerate(acq):
            if r.future is not None:
                r.future.set_result((int(verdict[i]), int(wait[i])))


class ClientStats:
    """Read-side node statistics (the ClusterNode/StatisticNode getters:
    passQps/blockQps/successQps/exceptionQps/avgRt/curThreadNum)."""

    def __init__(self, client: SentinelClient):
        self._c = client

    def _row_stats(self, row: int) -> Dict[str, float]:
        c = self._c
        sec_cfg = W.WindowConfig(c.cfg.second_sample_count, c.cfg.second_window_ms)
        now = jnp.int32(c.time.now_ms())
        with c._engine_lock:
            st = c._state
            rows = jnp.asarray([row], dtype=jnp.int32)
            counts = np.asarray(W.gather_window_counts(st.win_sec, now, rows, sec_cfg))[0]
            rt_tot, rt_min = W.gather_window_rt(st.win_sec, now, rows, sec_cfg)
            conc = int(np.asarray(st.concurrency[row]))
        interval_s = sec_cfg.interval_ms / 1000.0
        succ = float(counts[W.EV_SUCCESS])
        return {
            "passQps": float(counts[W.EV_PASS]) / interval_s,
            "blockQps": float(counts[W.EV_BLOCK]) / interval_s,
            "successQps": succ / interval_s,
            "exceptionQps": float(counts[W.EV_EXCEPTION]) / interval_s,
            "avgRt": float(np.asarray(rt_tot)[0]) / succ if succ > 0 else 0.0,
            "minRt": float(np.asarray(rt_min)[0]),
            "curThreadNum": conc,
        }

    def resource(self, name: str) -> Optional[Dict[str, float]]:
        rid = self.registry_peek(name)
        if rid is None:
            return None
        return self._row_stats(rid)

    def entry_node(self) -> Dict[str, float]:
        return self._row_stats(self._c.cfg.entry_node_row)

    def registry_peek(self, name: str) -> Optional[int]:
        return self._c.registry.peek_resource_id(name)
