"""Per-caller invocation context.

The analog of Context/ContextUtil (context/ContextUtil.java:45,
Context.java): the reference pins a Context to the current thread and
builds a DefaultNode tree per (resource, context).  Here the context is a
``contextvars.ContextVar`` (works across threads AND asyncio tasks), and
the "tree" is flat: context/origin stat rows are interned in the Registry.
"""

from __future__ import annotations

import contextvars
from typing import List, Optional, Tuple

# Constants.CONTEXT_DEFAULT_NAME in the reference
DEFAULT_CONTEXT_NAME = "sentinel_default_context"

_current: contextvars.ContextVar[Tuple[str, str]] = contextvars.ContextVar(
    "sentinel_tpu_context", default=(DEFAULT_CONTEXT_NAME, "")
)
# stack of live Entry objects (for Tracer.trace attribution)
_entries: contextvars.ContextVar[Tuple] = contextvars.ContextVar(
    "sentinel_tpu_entries", default=()
)


def current() -> Tuple[str, str]:
    """(context_name, origin)."""
    return _current.get()


def enter(name: str, origin: str = ""):
    """Returns a token for exit()."""
    return _current.set((name or DEFAULT_CONTEXT_NAME, origin or ""))


def exit_ctx(token) -> None:
    _current.reset(token)


def push_entry(entry) -> None:
    _entries.set(_entries.get() + (entry,))


def pop_entry(entry) -> None:
    stack = _entries.get()
    if stack and stack[-1] is entry:
        _entries.set(stack[:-1])
    else:
        # out-of-order exit: drop it wherever it is (CtEntry chain repair)
        _entries.set(tuple(e for e in stack if e is not entry))


def clear() -> None:
    """Reset this context's entry stack and context name — the
    ContextTestUtil.cleanUpContext analog for tests/tools."""
    _entries.set(())
    _current.set((DEFAULT_CONTEXT_NAME, ""))


def current_entry():
    stack = _entries.get()
    return stack[-1] if stack else None
