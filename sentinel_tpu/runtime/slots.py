"""Custom processor-slot SPI — ordered slots with entry AND exit hooks.

The reference lets users insert full ProcessorSlots anywhere in the chain
(slotchain/ProcessorSlot.java:29 — entry/fireEntry/exit/fireExit, ordered
by @SpiOrder, demo sentinel-demo-slot-chain-spi).  The TPU build's chain
is a fused device kernel, so custom slots run HOST-side around the engine
check, keeping the same contract:

- ``on_entry`` runs BEFORE the device decision, in ascending ``order``
  (negative orders run earlier, like @SpiOrder); raising a BlockException
  rejects the entry — the engine still RECORDS the block (the exception
  rides the batch as a pre-verdict, so stats/block-log/SPI all fire, the
  way a custom slot's exception flows through StatisticSlot).
- ``on_exit`` runs for every slot whose ``on_entry`` ran — including the
  slot that raised the BlockException — in REVERSE order (fireExit
  unwinds the chain LIFO), both on completion (with rt/success/errors)
  and on rejection (with ``block_exception`` set) — matching CtEntry.exit
  walking the chain even for blocked entries.  Slots later in the chain
  than the blocker never entered, so they do not exit (divergence from
  the reference's full-chain fireExit, which calls exit on slots whose
  entry never ran; pairing resources between entry and exit is safe
  here).
- ``SlotContext.attachments`` is scratch state shared between a slot's
  entry and exit sides for the same request (Context#customized data).

Slot exceptions other than BlockException propagate to the caller
unwrapped, like a throwing ProcessorSlot would.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple


@dataclass
class SlotContext:
    """Per-request view handed to custom slots."""

    resource: str
    origin: str = ""
    args: Optional[Sequence[Any]] = None
    count: int = 1
    prioritized: bool = False
    inbound: bool = False
    # exit-side fields (populated before on_exit)
    rt_ms: float = 0.0
    success: int = 0
    errors: int = 0
    block_exception: Optional[BaseException] = None
    attachments: dict = field(default_factory=dict)


class ProcessorSlot:
    """Base custom slot (subclass and override either hook)."""

    #: ascending execution order for on_entry (reverse for on_exit);
    #: mirror of @SpiOrder — negative = earlier
    order: int = 0

    def on_entry(self, ctx: SlotContext) -> None:  # pragma: no cover - base
        """Pre-decision hook; raise a BlockException to reject."""

    def on_exit(self, ctx: SlotContext) -> None:  # pragma: no cover - base
        """Unwind hook: completion (rt/success/errors) or rejection
        (block_exception set)."""


class SlotChain:
    """Ordered registry of custom slots (DefaultSlotChainBuilder analog:
    stable sort by order; same-order slots keep registration order)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slots: List[Tuple[int, int, ProcessorSlot]] = []
        self._seq = 0

    def register(self, slot: ProcessorSlot) -> ProcessorSlot:
        with self._lock:
            self._seq += 1
            bisect.insort(self._slots, (int(slot.order), self._seq, slot))
        return slot

    def unregister(self, slot: ProcessorSlot) -> None:
        with self._lock:
            self._slots = [t for t in self._slots if t[2] is not slot]

    def snapshot(self) -> List[ProcessorSlot]:
        with self._lock:
            return [t[2] for t in self._slots]

    def __len__(self) -> int:
        return len(self._slots)


def run_entry(slots: List[ProcessorSlot], ctx: SlotContext):
    """Run on_entry in order.  Returns (entered, block_exc): ``entered``
    are the slots to unwind LIFO — including the slot whose on_entry
    raised the BlockException (its entry ran up to the raise, and the
    reference fires exit through the raising slot too: CtEntry.exit walks
    the whole chain's fireExit).  Any non-Block exception unwinds the
    already-entered slots and propagates."""
    from sentinel_tpu.core import errors as ERR

    entered: List[ProcessorSlot] = []
    for s in slots:
        try:
            s.on_entry(ctx)
        except ERR.BlockException as be:
            entered.append(s)
            return entered, be
        except BaseException:
            ctx.block_exception = None
            run_exit(entered, ctx)
            raise
        entered.append(s)
    return entered, None


def run_exit(entered: List[ProcessorSlot], ctx: SlotContext) -> None:
    """Unwind on_exit in reverse order; slot exit errors are contained
    (an exit hook must never mask the request outcome)."""
    from sentinel_tpu.utils.record_log import record_log

    for s in reversed(entered):
        try:
            s.on_exit(ctx)
        except BaseException as e:  # noqa: BLE001  # stlint: disable=fail-open — exit-side isolation of USER slot code; the verdict was already decided at entry
            record_log().warning("custom slot %r on_exit failed: %s", s, e)
