"""Fleet metric aggregation (obs/fleet.py), histogram exemplars, and the
SLO burn-rate engine (obs/slo.py) — incl. the cross-process registry
merge contract: counter sums, correct merged-histogram quantiles, and no
double-count of the scraping process."""

from __future__ import annotations

import re

import numpy as np
import pytest

from sentinel_tpu.obs import fleet as F
from sentinel_tpu.obs import slo as S
from sentinel_tpu.obs.flight import FlightRecorder
from sentinel_tpu.obs.registry import MetricRegistry

#: the exposition-lines grammar the repo pins (tests/test_obs.py)
_LINE_PAT = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9][0-9a-zA-Z+.e-]*$"
)


def _assert_wellformed(text: str) -> None:
    for line in text.strip().split("\n"):
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ", "# EXEMPLAR ")), line
        else:
            assert _LINE_PAT.match(line), line


def _member_registry(i: int, hot: int = 0) -> MetricRegistry:
    """A synthetic per-process registry: scrape id, per-shard counters,
    a shared counter, a histogram (``hot`` samples land at 100 ms)."""
    r = MetricRegistry()
    r.gauge("sentinel_scrape_id", "id", labels={"id": f"proc-{i}"}).set(1)
    r.counter(
        "sentinel_shard_requests_total", "reqs", labels={"shard": f"shard-{i}"}
    ).inc(100 * (i + 1))
    r.counter("sentinel_token_decisions_total", "dec").inc(7)
    h = r.histogram("sentinel_cluster_rpc_ms", "rpc")
    for _ in range(100 - hot):
        h.observe(1.0)
    for _ in range(hot):
        h.observe(100.0)
    r.gauge("sentinel_pipeline_occupancy", "occ").set(float(i))
    return r


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------


def test_histogram_exemplar_in_exposition_and_snapshot():
    r = MetricRegistry()
    h = r.histogram("sentinel_tick_device_ms", "dev")
    for _ in range(99):
        h.observe(1.0)
    h.observe(200.0, exemplar="deadbeef123")
    text = r.exposition()
    ex = [l for l in text.splitlines() if l.startswith("# EXEMPLAR ")]
    assert len(ex) == 1
    assert "trace_id=deadbeef123" in ex[0]
    assert "sentinel_tick_device_ms_bucket" in ex[0]
    _assert_wellformed(text)
    e = h.p99_exemplar()
    assert e is not None and e["trace_id"] == "deadbeef123"
    assert e["value"] == 200.0
    snap = r.snapshot()
    assert snap["sentinel_tick_device_ms"]["p99_exemplar"]["trace_id"] == (
        "deadbeef123"
    )


def test_histogram_without_exemplars_emits_no_comment():
    """No exemplar recorded => exposition byte-identical to the golden
    shape (guards test_prometheus_exposition_golden)."""
    r = MetricRegistry()
    h = r.histogram("plain_ms", "p")
    h.observe(1.0)
    assert "# EXEMPLAR" not in r.exposition()
    assert h.p99_exemplar() is None


def test_stage_helpers_thread_trace_id_as_exemplar():
    from sentinel_tpu import obs
    from sentinel_tpu.obs import trace as OT

    r = MetricRegistry()
    h = r.histogram("sentinel_tick_device_ms", "dev")
    was = OT.TRACER.enabled
    obs.enable()
    try:
        t = OT.t0()
        OT.stage_ns("tick.device", t, 2_000_000, h, trace=0xABC123)
    finally:
        if not was:
            obs.disable()
    e = h.p99_exemplar()
    assert e is not None and e["trace_id"] == "abc123"


def test_postmortem_prints_p99_exemplars(tmp_path):
    """A flight bundle whose metrics carry a p99 exemplar surfaces the
    trace id in --postmortem output (the Perfetto jump-off point)."""
    import io
    import json

    from sentinel_tpu.obs.__main__ import _print_postmortem

    bundle = {
        "kind": "sentinel-flight-bundle",
        "reason": "test",
        "pid": 1,
        "captured_wall_ms": 0,
        "captured_mono_ns": 0,
        "journal": [],
        "metrics": {
            "sentinel_tick_device_ms": {
                "count": 100,
                "sum": 300.0,
                "p50": 1.0,
                "p99": 256.0,
                "p99_exemplar": {"le": "256", "value": 200.0, "trace_id": "feed1"},
            }
        },
        "spans": [],
        "providers": {},
    }
    p = tmp_path / "b.json"
    p.write_text(json.dumps(bundle))
    out = io.StringIO()
    _print_postmortem(str(p), out=out)
    text = out.getvalue()
    assert "p99 exemplars" in text and "trace_id=feed1" in text


# ---------------------------------------------------------------------------
# fleet merge (cross-process registry merge contract)
# ---------------------------------------------------------------------------


def test_fleet_merge_counter_sums_and_histogram_quantiles():
    texts = [_member_registry(i, hot=50 * i).exposition() for i in range(3)]
    merged = F.merge_scrapes([F.parse_exposition(t) for t in texts])
    assert merged.members == 3 and merged.duplicates == 0
    out = F.render_exposition(merged)
    _assert_wellformed(out)
    # per-shard labels preserved, per-series counters intact
    assert 'sentinel_shard_requests_total{shard="shard-0"} 100' in out
    assert 'sentinel_shard_requests_total{shard="shard-2"} 300' in out
    # same-series counters sum across processes
    assert "sentinel_token_decisions_total 21" in out
    # gauges: conservative max
    assert "sentinel_pipeline_occupancy 2" in out
    # histogram quantile over the MERGED buckets: 300 samples, 150 slow
    # -> p50 in the 1 ms bucket region, p99 in the 100 ms region
    back = F.parse_exposition(out)
    h = back.hists[("sentinel_cluster_rpc_ms", ())]
    assert h["count"] == 300
    assert h["sum"] == pytest.approx(150 * 1.0 + 150 * 100.0)
    # merged cumulative buckets: ~half the mass sits at/below 1 ms, all
    # of it at/below the top bucket — the quantile split survived
    by_bound = sorted(h["buckets"].items(), key=lambda kv: F._le_sort_key(kv[0]))
    le_1ms = next(cum for le, cum in by_bound if float(le) >= 1.0)
    assert le_1ms == 150
    assert by_bound[-1][1] == 300


def test_fleet_merge_drops_same_process_duplicate():
    """The scraping process's own exposition listed as a fleet member
    must merge exactly once (scrape-id dedupe)."""
    t = _member_registry(0).exposition()
    merged = F.merge_scrapes([F.parse_exposition(t), F.parse_exposition(t)])
    assert merged.members == 1 and merged.duplicates == 1
    out = F.render_exposition(merged)
    assert 'sentinel_shard_requests_total{shard="shard-0"} 100' in out
    assert "sentinel_scrape_id" not in out


def test_fleet_exposition_counts_errors_and_members():
    t1 = _member_registry(1).exposition()

    def fetch(url):
        if "dead" in url:
            raise OSError("connection refused")
        return t1

    text = F.fleet_exposition(targets=["peer:1", "dead:2"], fetch=fetch)
    _assert_wellformed(text)
    assert "sentinel_fleet_members 2" in text  # local + peer
    assert "sentinel_fleet_scrape_errors 1" in text


def test_fleet_target_registry_and_env(monkeypatch):
    F.set_fleet_targets([])
    F.add_fleet_target("a:1")
    F.add_fleet_target("a:1")  # idempotent
    monkeypatch.setenv("SENTINEL_FLEET_TARGETS", "b:2, a:1")
    assert F.fleet_targets() == ["a:1", "b:2"]
    F.set_fleet_targets([])
    assert F._normalize_url("a:1") == "http://a:1/metrics"
    assert F._normalize_url("http://a:1/metrics") == "http://a:1/metrics"


def test_metrics_fleet_param_over_live_n4_fleet(client_factory):
    """Acceptance: GET /metrics?fleet=1 over a live N=4 ShardFleet
    returns ONE well-formed exposition with per-shard labels preserved
    and remote histograms merged in."""
    from sentinel_tpu.cluster.shard import ShardFleet
    from sentinel_tpu.core.rules import FlowRule
    from sentinel_tpu.transport.command import CommandRequest
    from sentinel_tpu.transport.handlers import build_default_handlers

    f = ShardFleet(
        client_factory,
        n_shards=4,
        retry_interval_s=300.0,
        timeout_ms=5000,
        reconnect_interval_s=0.0,
    )
    try:
        f.load_flow_rules(
            "default",
            [
                FlowRule(
                    resource=f"res-{fid}",
                    count=1000.0,
                    cluster_mode=True,
                    cluster_flow_id=fid,
                    cluster_threshold_type=1,
                )
                for fid in (101, 202, 303, 404)
            ],
        )
        for fid in (101, 202, 303, 404):
            f.client.request_token(fid)
        # a "remote engine host" target answers with its own registry
        remote = _member_registry(9, hot=10).exposition()
        from sentinel_tpu.obs import fleet as FM

        FM.set_fleet_targets(["remote-host:8719"])
        try:
            registry = build_default_handlers(f.services["shard-0"].client)
            orig_fetch = FM._http_fetch
            FM._http_fetch = lambda url, timeout_s=2.0: remote
            try:
                rsp = registry.handle(
                    "metrics", CommandRequest(parameters={"fleet": "1"})
                )
            finally:
                FM._http_fetch = orig_fetch
        finally:
            FM.set_fleet_targets([])
        assert rsp.success
        text = rsp.result
        _assert_wellformed(text)
        assert "sentinel_fleet_members 2" in text
        # per-shard labels from all four LIVE shards survive the merge
        for name in ("shard-0", "shard-1", "shard-2", "shard-3"):
            assert f'shard="{name}"' in text, name
        # the remote member's shard label and histogram merged in
        assert 'shard="shard-9"' in text
        assert "sentinel_cluster_rpc_ms_bucket" in text
        # live topology decoration from /api/shards
        assert "sentinel_fleet_shard_info" in text
    finally:
        f.stop()


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


def _shed_spec() -> S.SloSpec:
    return S.SloSpec(
        "shed_ratio",
        objective=0.99,
        bad=S.CounterSum(("sentinel_shed_total",)),
        total=S.CounterSum(
            ("sentinel_shed_total", "sentinel_device_verdicts_total")
        ),
    )


def test_slo_burn_alert_fires_bundles_and_clears():
    reg, greg = MetricRegistry(), MetricRegistry()
    fl = FlightRecorder()
    good = reg.counter(
        "sentinel_device_verdicts_total", "v", labels={"verdict": "pass"}
    )
    shed = reg.counter(
        "sentinel_shed_total", "s", labels={"stage": "admit", "reason": "queue_full"}
    )
    eng = S.SloEngine(
        specs=(_shed_spec(),), registry=reg, flight=fl, gauge_registry=greg
    )
    good.inc(100)
    st = eng.step(0)[0]
    assert not st.alerting and st.budget_remaining == 1.0
    good.inc(1000)
    st = eng.step(60_000)[0]
    assert not st.alerting and not st.fired
    # storm: 40% shed >> the 1% budget -> both windows burn >= 14.4
    good.inc(600)
    shed.inc(400)
    st = eng.step(120_000)[0]
    assert st.fired and st.alerting
    assert max(st.burn.values()) > 14.4
    assert st.budget_remaining < 1.0
    # journal + auto bundle + provider section
    b = fl.last_bundle()
    assert b is not None and b["reason"] == "slo-burn-shed_ratio"
    assert "slo" in b["providers"]
    assert b["providers"]["slo"]["shed_ratio"]["alerting"] is True
    assert [e for e in fl.events() if e["kind"] == "slo.alert"]
    # a second breached step must NOT re-fire (alert is a transition)
    good.inc(60)
    shed.inc(40)
    st = eng.step(180_000)[0]
    assert st.alerting and not st.fired
    # calm traffic clears on the short windows
    good.inc(5000)
    st = eng.step(4_000_000)[0]
    assert not st.alerting
    assert [e for e in fl.events() if e["kind"] == "slo.alert.clear"]
    # gauges on the (injected) gauge registry
    burn = greg.get(
        "sentinel_slo_burn_rate", {"slo": "shed_ratio", "window": "300s"}
    )
    assert burn is not None
    assert greg.get("sentinel_slo_budget_remaining", {"slo": "shed_ratio"}) is not None
    eng.close()


def test_slo_latency_spec_histogram_over():
    reg, greg = MetricRegistry(), MetricRegistry()
    fl = FlightRecorder()
    h = reg.histogram("sentinel_tick_device_ms", "d")
    spec = S.SloSpec(
        "req_p99",
        objective=0.99,
        latency=S.HistogramOver("sentinel_tick_device_ms", 10.0),
        auto_bundle=False,
    )
    eng = S.SloEngine(specs=(spec,), registry=reg, flight=fl, gauge_registry=greg)
    eng.step(0)
    for _ in range(50):
        h.observe(1.0)
    for _ in range(50):
        h.observe(100.0)
    st = eng.step(60_000)[0]
    assert st.alerting and st.fired
    assert fl.last_bundle() is None  # auto_bundle=False respected
    eng.close()


def test_slo_default_specs_cover_the_six_objectives():
    names = {s.name for s in S.default_slos()}
    assert names == {
        "req_p99",
        "shed_ratio",
        "fail_closed",
        "fleet_error_budget",
        "sketch_eps",
        "hbm_capacity",
    }
    for s in S.default_slos():
        assert 0.0 < s.objective < 1.0 and s.windows


def test_slo_no_total_traffic_means_no_burn():
    reg, greg = MetricRegistry(), MetricRegistry()
    eng = S.SloEngine(
        specs=(_shed_spec(),), registry=reg, flight=FlightRecorder(),
        gauge_registry=greg,
    )
    eng.step(0)
    st = eng.step(60_000)[0]
    assert not st.alerting and st.budget_remaining == 1.0
    assert all(v == 0.0 for v in st.burn.values())
    eng.close()
