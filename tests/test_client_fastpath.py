"""Production client on the fast engine path (VERDICT r4 #1).

The client presorts batches by the segment keys host-side (np.lexsort in
_run_tick) and maps verdicts back through the inverse permutation; seg_u
grows automatically when traffic overflows the compacted capacity; fail-
closed overflow drops are surfaced loudly.  On CPU the fused kernels run
in Pallas interpret mode — semantics only (device speed is bench.py's
job).
"""

from __future__ import annotations

import numpy as np
import pytest

from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.runtime.client import SentinelClient
from sentinel_tpu.utils.time_source import VirtualTimeSource

# single-rule lanes so the segment CHECK phase engages too (engine gates
# seg_checks on *_rules_per_resource == 1)
SEG = dict(
    use_mxu_tables=True,
    fused_effects=True,
    seg_effects=True,
    flow_rules_per_resource=1,
    degrade_rules_per_resource=1,
    param_rules_per_resource=1,
)


def _mk(vt, **kw):
    cfg = small_engine_config(**{**SEG, **kw})
    return SentinelClient(cfg=cfg, time_source=vt, mode="sync")


def test_presorted_verdicts_map_back_to_submission_order(vt):
    """Verdicts must return to the REQUEST that submitted them, not to the
    sorted position — intern ids out of submission order so the presort
    permutation is nontrivial."""
    c = _mk(vt)
    # intern in an order unrelated to the submission order below
    for name in ("zz", "blocked", "open", "aa"):
        c.registry.resource_id(name)
    c.flow_rules.load(
        [
            FlowRule(resource="blocked", count=0.0),
            FlowRule(resource="open", count=1000.0),
        ]
    )
    resources = ["open", "blocked", "zz", "blocked", "open", "aa", "blocked"]
    out = c.check_batch(resources)
    for name, (v, _w) in zip(resources, out):
        if name == "blocked":
            assert v == ERR.BLOCK_FLOW, (name, v)
        else:
            assert v == ERR.PASS, (name, v)


@pytest.mark.jitted  # many small ticks: execution-bound, compiles amortize
def test_seg_client_matches_plain_client():
    """Same shuffled workload (origins + counts) through the seg-path
    client and the plain-path client: identical verdict sequences."""
    rng = np.random.default_rng(11)
    names = [f"res-{i}" for i in range(24)]
    batches = []
    for _ in range(4):
        k = rng.integers(8, 40)
        rs = [names[i] for i in rng.integers(0, len(names), k)]
        og = [("peer" if rng.random() < 0.3 else "") for _ in rs]
        cn = [int(rng.integers(1, 3)) for _ in rs]
        batches.append((rs, og, cn))

    def run(seg: bool):
        vt = VirtualTimeSource(start_ms=5_000)
        kw = dict(SEG) if seg else {}
        c = SentinelClient(
            cfg=small_engine_config(**kw), time_source=vt, mode="sync"
        )
        # shuffled interning order -> nontrivial presort permutation
        for n in reversed(names):
            c.registry.resource_id(n)
        c.flow_rules.load(
            [FlowRule(resource=n, count=3.0) for n in names[:12]]
        )
        got = []
        for rs, og, cn in batches:
            got.append(c.check_batch(rs, origins=og, counts=cn))
            vt.advance(50)
        return got

    assert run(seg=True) == run(seg=False)


def test_seg_u_auto_resize_grows_capacity(vt):
    """Persistent segment-capacity overflow grows seg_u (tick hot-swap);
    verdicts stay exact throughout via the seg_fallback safety net."""
    c = _mk(vt, seg_u=8, seg_fallback=True)
    names = [f"r{j}" for j in range(40)]
    for i in range(6):
        out = c.check_batch(names)
        assert all(v == ERR.PASS for v, _ in out), f"tick {i}"
        vt.advance(10)
    assert c.cfg.seg_u > 8, "seg_u should have grown past the observed peak"
    # the swapped tick keeps serving correctly
    out = c.check_batch(names)
    assert all(v == ERR.PASS for v, _ in out)


def test_seg_overflow_drop_surfaced_and_fails_closed(vt):
    """seg_fallback=False + undersized seg_u: overflow items BLOCK (never
    pass unchecked), the drop counter advances, and the block log gets the
    loud __seg_overflow__ row.  Resize inhibited to observe the drop path
    itself (normally the first overflow triggers the resize)."""
    c = _mk(vt, seg_u=8, seg_fallback=False)
    c._seg_resizing = True  # pin capacity for this test

    logged = []

    class _BL:
        def log(self, ts, res, exc, origin="", count=1):
            logged.append((res, exc, count))

        def flush(self):
            pass

    c.block_log = _BL()
    out = c.check_batch([f"r{j}" for j in range(40)])
    vs = [v for v, _ in out]
    assert c.seg_dropped_total > 0
    assert any(v == ERR.BLOCK_SYSTEM for v in vs), "overflow must fail closed"
    assert any(r == "__seg_overflow__" for r, _e, _n in logged)
    # low-id segments fit the capacity and keep passing
    assert vs[0] == ERR.PASS


def test_block_api_matches_object_api(vt):
    """check_batch_ids (column arrays, zero per-item Python) must decide
    exactly like the per-object check_batch on the same workload — and the
    block path rides the presorted seg engine here."""
    c = _mk(vt)
    names = [f"b{i}" for i in range(20)]
    ids = np.array([c.registry.resource_id(n) for n in names], np.int32)
    c.flow_rules.load([FlowRule(resource=n, count=2.0) for n in names[:10]])

    rng = np.random.default_rng(3)
    idx = rng.integers(0, len(names), 50)
    obj_out = c.check_batch([names[i] for i in idx])

    vt2 = VirtualTimeSource(start_ms=1_000)
    c2 = _mk(vt2)
    for n in names:
        c2.registry.resource_id(n)
    c2.flow_rules.load([FlowRule(resource=n, count=2.0) for n in names[:10]])
    verd, wait = c2.check_batch_ids(ids[idx])
    assert [int(v) for v in verd] == [v for v, _ in obj_out]
    assert all(int(w) == 0 for w in wait)


def test_block_api_spans_multiple_ticks(vt):
    """Blocks larger than the batch size stream through several ticks and
    still resolve one future with every verdict in submission order."""
    c = _mk(vt)  # batch_size = 64
    names = [f"s{i}" for i in range(8)]
    ids = np.array([c.registry.resource_id(n) for n in names], np.int32)
    c.flow_rules.load([FlowRule(resource=names[0], count=0.0)])
    res = np.tile(ids, 40)  # 320 items > 64-batch
    verd, _w = c.check_batch_ids(res)
    assert len(verd) == 320
    blocked = verd[res == ids[0]]
    passed = verd[res != ids[0]]
    assert (blocked == ERR.BLOCK_FLOW).all()
    assert (passed == ERR.PASS).all()


@pytest.mark.jitted  # many small ticks: execution-bound, compiles amortize
def test_pipelined_resolution_matches_inline():
    """pipeline_depth > 0 defers verdict readback behind dispatch; the
    resolved verdicts must be identical to depth-0 operation."""
    names = [f"p{i}" for i in range(12)]

    def run(depth):
        vt = VirtualTimeSource(start_ms=2_000)
        c = SentinelClient(
            cfg=small_engine_config(**SEG),
            time_source=vt,
            mode="sync",
            pipeline_depth=depth,
        )
        ids = np.array([c.registry.resource_id(n) for n in names], np.int32)
        c.flow_rules.load([FlowRule(resource=names[0], count=3.0)])
        outs = []
        for t in range(3):
            # several blocks queued at once so the drain loop actually
            # runs multiple ticks back-to-back (where deferral engages)
            futs = [
                c.submit_block(np.tile(ids, 8))  # 96 items
                for _ in range(3)
            ]
            outs.append([tuple(map(int, f.result(timeout=30)[0][:8])) for f in futs])
            vt.advance(25)
        return outs

    assert run(0) == run(2)


def test_seg_static_ranks_auto_specialization(vt):
    """The client flips seg_static_ranks on when every flow rule is
    DIRECT/default-limitApp (the presort makes the contract hold), and
    back off when a rule stops qualifying."""
    from sentinel_tpu.core.rules import STRATEGY_RELATE

    c = _mk(vt)
    names = ["sa", "sb"]
    for n in names:
        c.registry.resource_id(n)
    c.flow_rules.load([FlowRule(resource="sa", count=5.0)])
    assert c.cfg.seg_static_ranks
    out = c.check_batch(["sa", "sb", "sa"])
    assert [v for v, _ in out] == [0, 0, 0]  # ERR.PASS == 0
    c.flow_rules.load(
        [FlowRule(resource="sa", count=5.0, strategy=STRATEGY_RELATE,
                  ref_resource="sb")]
    )
    assert not c.cfg.seg_static_ranks
    out = c.check_batch(["sa", "sb"])
    assert all(v == ERR.PASS for v, _ in out)


def test_platform_engine_config_detects_backend(monkeypatch):
    import sentinel_tpu.core.config as C

    monkeypatch.setattr(C, "_backend_is_tpu", lambda: True)
    cfg = C.platform_engine_config()
    assert cfg.use_mxu_tables and cfg.fused_effects and cfg.seg_effects
    assert cfg.seg_fallback  # safety net stays ON by default
    # explicit overrides win over detection
    cfg_o = C.platform_engine_config(seg_effects=False, fused_effects=False)
    assert cfg_o.use_mxu_tables and not cfg_o.seg_effects

    monkeypatch.setattr(C, "_backend_is_tpu", lambda: False)
    cfg2 = C.platform_engine_config()
    assert not (cfg2.use_mxu_tables or cfg2.fused_effects or cfg2.seg_effects)


@pytest.mark.jitted  # the POINT: no disable_jit — pin jit-only buffer behavior
def test_jitted_const_column_cache_and_empty_batches(vt):
    """ADVICE r5 low #4: the jit-only buffer-dedup failure class (per-leaf
    empty_acquire buffers, the field-keyed _dev_col constant cache —
    'Execution supplied N buffers but compiled program expected N+1')
    only manifests under REAL jit dispatch, which the eager-heavy fixture
    normally bypasses.  Interleave empty ticks (every column a cached
    device constant), all-default batches (most columns hit the _dev_col
    cache), and distinct-value batches (cache misses) through one jitted
    tick and require exact verdicts throughout."""
    c = _mk(vt)
    names = [f"j{i}" for i in range(8)]
    for n in names:
        c.registry.resource_id(n)
    c.flow_rules.load(
        [FlowRule(resource=names[0], count=0.0),
         FlowRule(resource=names[1], count=1000.0)]
    )

    # repeated EMPTY batches: tick_once with nothing queued reuses the
    # empty_acquire/empty_complete constants call after call
    for _ in range(3):
        c.tick_once()
        vt.advance(10)

    for round_ in range(3):
        # all-default columns (count=1, no origin/ctx/params): every
        # column except res equals its fill -> _dev_col cache round-trips
        out = c.check_batch([names[0], names[1], names[2]])
        assert [v for v, _ in out] == [
            ERR.BLOCK_FLOW, ERR.PASS, ERR.PASS,
        ], f"round {round_}"
        # distinct values force fresh uploads on the same executable
        out2 = c.check_batch(
            [names[1], names[1]], counts=[2, 3], origins=["peer", ""]
        )
        assert [v for v, _ in out2] == [ERR.PASS, ERR.PASS]
        # back to empty: the cached constants must still be aliasing-safe
        c.tick_once()
        vt.advance(25)

    # completions ride the jitted tick too (exit path buffers)
    e = c.entry(names[3])
    e.exit()
    c.tick_once()
