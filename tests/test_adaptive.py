"""sentinel_tpu.adaptive: closed-loop protection, degrade ladder,
deadline-aware backpressure, reconnect backoff, tick watchdog.

Covers the PR-7 acceptance surface:

* SystemSlot BBR math against reference semantics (negative-field = off,
  minRT floor / >=1 concurrency estimate, maxSuccessQps x minRt product)
  — black-box through the client with a patched load sampler;
* the adaptive-column path: live ceilings uploaded into the system
  rule-tensor columns decide EXACTLY like an equivalent static rule, and
  never recompile the tick (jaxpr fingerprints untouched);
* unified ladder + shared hysteresis semantics;
* full-jitter reconnect backoff on virtual time;
* deadline shedding before dispatch and the stalled-tick watchdog;
* <5 µs disabled-mode guards (obs/failpoints contract).
"""

import threading
import time as _time

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.adaptive import degrade as DG
from sentinel_tpu.adaptive.controller import AdaptiveConfig, AdaptiveController
from sentinel_tpu.adaptive.degrade import Backoff, DegradeLadder, Hysteresis
from sentinel_tpu.adaptive.signals import SignalCollector
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.obs.registry import REGISTRY as OBS
from sentinel_tpu.utils.time_source import mono_s


def _loaded(client, load, cpu=0.0):
    """Pin the host load/CPU sample the tick feeds the SystemSlot."""
    client._sys.sample = lambda: (load, cpu)


# ---------------------------------------------------------------------------
# SystemSlot BBR math (reference: SystemRuleManager.checkBbr)
# ---------------------------------------------------------------------------


def test_system_negative_fields_are_off(client, vt):
    """Negative = unset (SystemRuleManager treats negatives as off): a
    default SystemRule gates nothing even under absurd load."""
    client.system_rules.load([st.SystemRule()])
    _loaded(client, 99.0, 0.99)
    got = [client.try_entry("api", inbound=True) for _ in range(8)]
    assert all(e is not None for e in got)
    for e in got:
        e.exit()


def test_bbr_min_rt_floor_admits_one(client, vt):
    """Under load with NO completions in the window, the BBR capacity
    estimate floors at 1 concurrent entry (max(maxQps*minRt/1000, 1)) —
    the gate degrades to strict serialization, never to zero."""
    client.system_rules.load([st.SystemRule(highest_system_load=0.5)])
    _loaded(client, 2.0)
    entries = [client.try_entry("api", inbound=True) for _ in range(3)]
    assert [e is not None for e in entries] == [True, False, False]
    entries[0].exit()


def test_bbr_concurrency_estimate_tracks_product(client, vt):
    """maxSuccessQps × minRt: seed the window with known RT/success, then
    check admitted concurrency matches the product."""
    client.system_rules.load([st.SystemRule(highest_system_load=0.5)])
    _loaded(client, 0.0)  # healthy: seed the stats without gating
    # 8 successes at 500 ms RT in the current second window:
    # maxSuccessQps ≈ bucket_max(8) × sample_count(2) = 16/s,
    # capacity = 16 × 500/1000 = 8 concurrent entries
    seed = [client.entry("api", inbound=True) for _ in range(8)]
    vt.advance(500)
    for e in seed:
        e.exit()
    _loaded(client, 2.0)  # now overloaded: the BBR branch takes over
    got = [client.try_entry("api", inbound=True) for _ in range(12)]
    admitted = sum(1 for e in got if e is not None)
    assert admitted == 8
    for e in got:
        if e is not None:
            e.exit()


def test_bbr_inactive_below_load_threshold(client, vt):
    client.system_rules.load([st.SystemRule(highest_system_load=0.5)])
    _loaded(client, 0.2)
    got = [client.try_entry("api", inbound=True) for _ in range(6)]
    assert all(e is not None for e in got)
    for e in got:
        e.exit()


# ---------------------------------------------------------------------------
# adaptive-column path: live ceilings == static rule, no recompile
# ---------------------------------------------------------------------------


def test_adaptive_columns_decide_like_static_rule(client_factory, vt):
    import jax

    static = client_factory()
    static.system_rules.load([st.SystemRule(qps=2)])
    adaptive = client_factory()
    ad = adaptive.enable_adaptive()
    tick_before = adaptive._tick
    rules_before = adaptive._rules_dev
    # publish the SAME threshold through the live column path
    from sentinel_tpu.ops import engine as E

    sys_np = ad.system_columns(adaptive._system_static, qps=2.0, max_thread=-1.0)
    with adaptive._engine_lock:
        adaptive._rules_dev = E.replace_system_columns(
            adaptive._rules_dev, sys_np
        )
    want = [
        v in (ERR.PASS, ERR.PASS_WAIT)
        for v, _ in static.check_batch(["api"] * 5, inbound=True)
    ]
    got = [
        v in (ERR.PASS, ERR.PASS_WAIT)
        for v, _ in adaptive.check_batch(["api"] * 5, inbound=True)
    ]
    assert got == want
    assert sum(got) == 2  # the qps=2 budget, both paths
    # the upload swapped VALUES only: same compiled tick, same tree shape
    assert adaptive._tick is tick_before
    assert jax.tree_util.tree_structure(
        adaptive._rules_dev
    ) == jax.tree_util.tree_structure(rules_before)


def test_adaptive_tightest_wins_against_static(client_factory, vt):
    """An operator rule stricter than the controller keeps enforcing."""
    c = client_factory()
    c.system_rules.load([st.SystemRule(qps=1)])
    ad = c.enable_adaptive()
    sys_np = ad.system_columns(c._system_static, qps=50.0, max_thread=100.0)
    assert float(sys_np.qps) == 1.0  # static is tighter
    assert float(sys_np.max_thread) == 100.0  # static unset -> adaptive
    sys_np = ad.system_columns(c._system_static, qps=-1.0, max_thread=-1.0)
    assert float(sys_np.qps) == 1.0  # disarmed controller restores static


def test_enable_adaptive_compiles_system_stage_once(client_factory):
    c = client_factory()
    assert "system" not in c._features
    c.enable_adaptive()
    assert "system" in c._features
    tick = c._tick
    # controller uploads must never swap the tick
    c._adaptive.ceiling = 4.0
    c.registry.resource_id("api")
    for _ in range(5):
        c.try_entry("api", inbound=True)
    assert c._tick is tick
    c.disable_adaptive()
    assert "system" not in c._features


# ---------------------------------------------------------------------------
# degrade ladder + shared hysteresis
# ---------------------------------------------------------------------------


def test_ladder_climbs_and_descends_one_rung_at_a_time():
    lad = DegradeLadder(climb_hold_ms=100, cool_hold_ms=200)
    t = 0
    # overload held: one rung per climb_hold
    for _ in range(50):
        lad.observe(t, True)
        t += 10
        if lad.level == DG.FAIL_CLOSED:
            break
    assert lad.level == DG.FAIL_CLOSED
    # calm: one rung down per cool_hold
    for _ in range(200):
        lad.observe(t, False)
        t += 10
        if lad.level == DG.NORMAL:
            break
    assert lad.level == DG.NORMAL
    assert all(abs(to - frm) == 1 for _t, frm, to in lad.transitions)
    ups = [(f, to) for _t, f, to in lad.transitions if to > f]
    downs = [(f, to) for _t, f, to in lad.transitions if to < f]
    assert len(ups) == len(downs) == 4


def test_ladder_hysteresis_resets_on_contradiction():
    lad = DegradeLadder(climb_hold_ms=100, cool_hold_ms=100)
    t = 0
    for _ in range(9):  # 90 ms of pressure — under the hold
        lad.observe(t, True)
        t += 10
    lad.observe(t, False)  # contradiction resets the climb hold
    t += 10
    for _ in range(9):
        lad.observe(t, True)
        t += 10
    assert lad.level == DG.NORMAL  # never held long enough


def test_ladder_severe_climbs_without_hold():
    lad = DegradeLadder(climb_hold_ms=10_000, cool_hold_ms=100)
    lad.observe(0, True, severe=True)
    assert lad.level == DG.SHED_LOW_PRIORITY  # no wait, but ONE rung only
    lad.observe(1, True, severe=True)
    assert lad.level == DG.PARAM_TAIL_OFF


def test_hysteresis_enter_exit_cooling():
    now = [100.0]
    hy = Hysteresis("test.degrade", cooldown_s=5.0, clock=lambda: now[0])
    assert not hy.active and not hy.cooling
    assert hy.enter() is True
    assert hy.enter() is False  # idempotent, extends cooldown
    assert hy.active and hy.cooling and not hy.probe_due
    now[0] += 5.1
    assert hy.probe_due and not hy.cooling
    assert hy.exit() is True
    assert hy.exit() is False
    assert not hy.active


# ---------------------------------------------------------------------------
# full-jitter reconnect backoff (virtual time)
# ---------------------------------------------------------------------------


def test_backoff_full_jitter_bounds_and_reset():
    import random

    now = [0.0]
    bo = Backoff(1.0, cap_s=8.0, rng=random.Random(42), clock=lambda: now[0])
    assert bo.ready()
    delays = []
    for _ in range(6):
        delays.append(bo.failure())
        now[0] += 100.0  # past any delay; ready again
        assert bo.ready()
    # full jitter: delay_n ∈ [0, min(cap, base·2^n)]
    for n, d in enumerate(delays):
        assert 0.0 <= d <= min(8.0, 2.0**n)
    assert bo.attempt == 6
    bo.success()
    assert bo.attempt == 0 and bo.ready()


def test_backoff_throttles_until_delay_elapses():
    import random

    now = [0.0]
    bo = Backoff(4.0, cap_s=30.0, rng=random.Random(7), clock=lambda: now[0])
    d = bo.failure()
    if d > 0:
        assert not bo.ready()
        now[0] += d
        assert bo.ready()


def test_backoff_decorrelates_clients():
    """The stampede property: two clients that fail in lockstep must NOT
    retry in lockstep (that is the whole point of the jitter)."""
    import random

    a = Backoff(1.0, rng=random.Random(1), clock=lambda: 0.0)
    b = Backoff(1.0, rng=random.Random(2), clock=lambda: 0.0)
    da = [a.failure() for _ in range(8)]
    db = [b.failure() for _ in range(8)]
    assert da != db


def test_backoff_zero_base_never_throttles():
    bo = Backoff(0.0, clock=lambda: 123.0)
    for _ in range(5):
        assert bo.failure() == 0.0
        assert bo.ready()


def test_cluster_client_reconnect_uses_backoff():
    from sentinel_tpu.cluster import constants as CC
    from sentinel_tpu.cluster.client import ClusterTokenClient

    tok = ClusterTokenClient("127.0.0.1", 1, timeout_ms=50, reconnect_interval_s=0.01)
    try:
        assert tok.request_token(1).status == CC.STATUS_FAIL  # dead port
        assert tok._backoff.attempt >= 1  # the failed connect armed it
    finally:
        tok.close()


# ---------------------------------------------------------------------------
# deadline-aware backpressure
# ---------------------------------------------------------------------------


def test_expired_deadline_sheds_before_dispatch(client, vt):
    client.registry.resource_id("api")
    before = OBS.counter(
        "sentinel_shed_total",
        labels={"stage": "tick", "reason": "deadline"},
    ).value
    f = client.submit_acquire("api", deadline_ms=vt.now_ms() - 1)
    assert f.result(timeout=5) == (ERR.BLOCK_SYSTEM, 0)
    after = OBS.counter(
        "sentinel_shed_total", labels={"stage": "tick", "reason": "deadline"}
    ).value
    assert after == before + 1


def test_live_deadline_passes(client, vt):
    client.registry.resource_id("api")
    f = client.submit_acquire("api", deadline_ms=vt.now_ms() + 1000)
    v, _w = f.result(timeout=5)
    assert v in (ERR.PASS, ERR.PASS_WAIT)


def test_expired_block_deadline_fails_whole_block(client, vt):
    rid = client.registry.resource_id("api")
    res = np.full(4, rid, np.int32)
    v, w = client.check_batch_ids(res, deadline_ms=vt.now_ms() - 1)
    assert (v == ERR.BLOCK_SYSTEM).all()
    assert (w == 0).all()


def test_entry_deadline_already_expired_raises(client, vt):
    client.registry.resource_id("api")
    with pytest.raises(ERR.SystemBlockException):
        client.entry("api", deadline_ms=vt.now_ms() - 5)


# ---------------------------------------------------------------------------
# ladder rung effects at the admission gate
# ---------------------------------------------------------------------------


def test_shed_low_priority_spares_prioritized(client, vt):
    client.registry.resource_id("api")
    ad = client.enable_adaptive(AdaptiveConfig(queue_max=0))
    ad.ladder.level = DG.SHED_LOW_PRIORITY
    client._bp_armed = True
    with pytest.raises(ERR.SystemBlockException):
        client.entry("api")
    e = client.entry("api", prioritized=True)
    e.exit()


def test_fail_closed_sheds_everything(client, vt):
    client.registry.resource_id("api")
    ad = client.enable_adaptive(AdaptiveConfig(queue_max=0))
    ad.ladder.level = DG.FAIL_CLOSED
    client._bp_armed = True
    with pytest.raises(ERR.SystemBlockException):
        client.entry("api", prioritized=True)
    f = client.submit_acquire("api")
    assert f.result(timeout=5) == (ERR.BLOCK_SYSTEM, 0)
    v, _ = client.check_batch_ids(
        np.full(3, client.registry.resource_id("api"), np.int32)
    )
    assert (v == ERR.BLOCK_SYSTEM).all()


def test_cluster_fallback_rung_stops_token_rpcs(client_factory, vt):
    """At CLUSTER_FALLBACK the runtime's cluster hysteresis arms and the
    admission path stops paying token-server round-trips — local
    fallback rules enforce instead."""
    from sentinel_tpu.cluster import constants as CC
    from sentinel_tpu.cluster.token_service import TokenResult

    calls = []

    class _Svc:
        def request_token(self, *a, **k):
            calls.append(a)
            return TokenResult(CC.STATUS_OK)

    class _Mgr:
        def token_service(self):
            return _SVC

    _SVC = _Svc()
    c = client_factory()
    c.set_cluster(_Mgr())
    c.flow_rules.load(
        [st.FlowRule(resource="api", count=100, cluster_mode=True, cluster_flow_id=9)]
    )
    ad = c.enable_adaptive(AdaptiveConfig(queue_max=0))
    ad.ladder.level = DG.CLUSTER_FALLBACK
    assert not c._cluster_degraded_active
    c.registry.resource_id("api")
    # prioritized rides through the SHED_LOW_PRIORITY rung; its tick runs
    # the control step that applies the fallback effect
    e = c.try_entry("api", prioritized=True)
    if e:
        e.exit()
    assert c._cluster_degraded_active
    n_before = len(calls)
    e = c.try_entry("api", prioritized=True)
    if e:
        e.exit()
    assert len(calls) == n_before  # degraded-and-cooling: no round-trip


def test_sync_fail_closed_ladder_still_descends(client, vt):
    """Liveness: a sync-mode client at FAIL_CLOSED sheds every
    submission BEFORE queueing — the shed path itself must keep the
    control loop stepping, or the ladder could never observe calm and
    FAIL_CLOSED would be a permanent outage."""
    client.registry.resource_id("api")
    ad = client.enable_adaptive(AdaptiveConfig(queue_max=0, cool_hold_ms=100))
    ad.ladder.level = DG.FAIL_CLOSED
    client._bp_armed = True
    with pytest.raises(ERR.SystemBlockException):
        client.entry("api", prioritized=True)  # fully closed: sheds...
    descended = False
    for _ in range(30):
        try:
            e = client.entry("api", prioritized=True)
            e.exit()  # ...but the shed-driven control steps observe calm
        except ERR.SystemBlockException:
            pass
        vt.advance(50)
        if ad.ladder.level < DG.FAIL_CLOSED:
            descended = True
            break
    assert descended


def test_admission_bound_counts_block_items(client_factory):
    """submit_block traffic must not bypass the admission bound just
    because its items sit in _acq_blocks rather than _acquires."""
    from sentinel_tpu.runtime.client import ArrayBlock

    c = client_factory(admission_queue_limit=8)
    assert c._bp_armed
    assert c._admission_shed(1) is None
    c._acq_blocks.append(ArrayBlock(res=np.zeros(10, np.int32)))
    try:
        assert c._admission_shed(1) == "queue_full"
    finally:
        c._acq_blocks.clear()


def test_disable_adaptive_resets_gauges(client_factory):
    from sentinel_tpu.adaptive.signals import SystemSignals

    c = client_factory()
    ad = c.enable_adaptive(AdaptiveConfig(queue_high=4, climb_hold_ms=0))
    ad.on_tick(
        SystemSignals(now_ms=500, queue_depth=100, max_pass_rate=100.0, min_rt_ms=20.0)
    )
    assert OBS.gauge("sentinel_adaptive_ceiling").value > 0
    c.disable_adaptive()
    assert OBS.gauge("sentinel_adaptive_ceiling").value == -1
    assert OBS.gauge("sentinel_adaptive_level").value == 0


# ---------------------------------------------------------------------------
# tick watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fails_stalled_tick_closed():
    """A stalled verdict readback (chaos delay) must resolve the caller
    CLOSED within the watchdog budget instead of hanging to timeout."""
    from sentinel_tpu.chaos import failpoints as FP
    from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime.client import SentinelClient

    c = SentinelClient(
        cfg=small_engine_config(),
        mode="threaded",
        tick_interval_ms=1.0,
        entry_timeout_s=10.0,
        watchdog_timeout_s=0.25,
    )
    c.start()
    try:
        c.registry.resource_id("wd/api")
        f0 = c.submit_acquire("wd/api")
        assert f0.result(timeout=10.0)[0] == ERR.PASS  # warm, unstalled
        before = OBS.counter("sentinel_watchdog_fired_total").value
        plan = FaultPlan(
            name="wd",
            seed=3,
            faults=[
                FaultSpec(
                    "runtime.watchdog.stall", "delay",
                    delay_ms=1500, max_fires=1,
                )
            ],
        )
        with FP.armed(plan):
            t0 = mono_s()
            f = c.submit_acquire("wd/api")
            v, _w = f.result(timeout=5.0)
            took = mono_s() - t0
            assert v == ERR.BLOCK_SYSTEM  # failed CLOSED, not hung
            assert took < 1.4  # well before the 1.5 s stall ends
            assert OBS.counter("sentinel_watchdog_fired_total").value == before + 1
            # let the stalled resolver drain before disarming so the
            # delayed readback cannot fire a second plan's spec
            _time.sleep(1.6)
    finally:
        c.stop()


def test_watchdog_disabled_runs_no_thread(client_factory):
    c = client_factory()
    assert c._wd_thread is None
    c.registry.resource_id("api")
    e = c.try_entry("api")
    if e:
        e.exit()
    assert c._inflight_ticks == {}


# ---------------------------------------------------------------------------
# disabled-mode overhead guards (<5 µs/call, obs/failpoints contract)
# ---------------------------------------------------------------------------


def test_admission_shed_disabled_overhead_guard(client_factory):
    c = client_factory()
    assert not c._bp_armed
    n = 20_000
    t0 = mono_s()
    acc = 0
    for _ in range(n):
        if c._admission_shed(1) is not None:  # pragma: no cover
            acc += 1
    elapsed = mono_s() - t0
    assert acc == 0
    assert elapsed / n < 5e-6, f"disabled shed check {elapsed / n * 1e9:.0f} ns/call"


def test_adaptive_disabled_tick_hook_overhead_guard(client_factory):
    """The per-tick adaptive hook is `self._adaptive is None` — measure
    the exact expression the tick loop evaluates."""
    c = client_factory()
    assert c._adaptive is None
    n = 20_000
    t0 = mono_s()
    hits = 0
    for _ in range(n):
        ad = c._adaptive
        if ad is not None:  # pragma: no cover
            hits += 1
    elapsed = mono_s() - t0
    assert hits == 0
    assert elapsed / n < 5e-6


def test_signal_collector_note_overhead():
    sc = SignalCollector()
    n = 20_000
    t0 = mono_s()
    for _ in range(n):
        sc.note_resolved(1, 0)
    elapsed = mono_s() - t0
    assert elapsed / n < 5e-6


# ---------------------------------------------------------------------------
# metrics surface
# ---------------------------------------------------------------------------


def test_adaptive_metrics_registered_on_global_registry():
    text = OBS.exposition()
    assert "sentinel_shed_total" in text
    assert "sentinel_watchdog_fired_total" in text
    assert "sentinel_adaptive_ceiling" in text
    assert "sentinel_adaptive_level" in text


def test_controller_publishes_ceiling_gauge():
    from sentinel_tpu.adaptive.signals import SystemSignals

    ad = AdaptiveController(
        AdaptiveConfig(queue_high=4, climb_hold_ms=0, min_ceiling=2.0)
    )
    # overload: deep queue → arm + shrink; gauge mirrors the ceiling
    ad.on_tick(
        SystemSignals(now_ms=1000, queue_depth=100, max_pass_rate=100.0, min_rt_ms=20.0)
    )
    g = OBS.gauge("sentinel_adaptive_ceiling").value
    assert g == pytest.approx(ad.ceiling)
    assert ad.ceiling == pytest.approx(2.0)  # maxPass×minRT = 2 concurrency
