"""Metric-catalog lint (analysis/metrics_catalog.py): the repo's
registered metric names vs the README catalog table — THE tier-1 gate
that keeps the catalog true."""

from __future__ import annotations

import os
import textwrap

from sentinel_tpu.analysis.metrics_catalog import (
    check_catalog,
    readme_catalog_names,
    scan_registered_metrics,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_catalog_is_clean():
    """Every registered sentinel_* metric is cataloged in the README,
    every catalog row is live, every name is snake_case."""
    problems = check_catalog(
        os.path.join(REPO, "sentinel_tpu"), os.path.join(REPO, "README.md")
    )
    assert problems == [], "\n".join(problems)


def test_scanner_finds_literal_registrations(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        textwrap.dedent(
            """\
            REG.counter("sentinel_good_total", "h")
            REG.gauge("sentinel_some_gauge", "h", labels={"a": "b"})
            (x or REG).histogram("sentinel_lat_ms", "h")
            REG.counter("sentinel_BadName_total", "h")
            REG.counter(dynamic_name, "not a literal — skipped")
            other.counter("not_sentinel_prefixed")
            """
        )
    )
    found = scan_registered_metrics(str(pkg))
    assert set(found) == {
        "sentinel_good_total",
        "sentinel_some_gauge",
        "sentinel_lat_ms",
        "sentinel_BadName_total",
    }
    assert found["sentinel_good_total"][0][1] == 1  # (path, line)


def test_check_flags_all_three_problem_classes(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        'REG.counter("sentinel_undocumented_total", "h")\n'
        'REG.gauge("sentinel_CamelCase", "h")\n'
        'REG.counter("sentinel_documented_total", "h")\n'
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| metric | type | labels | meaning |\n"
        "|---|---|---|---|\n"
        "| `sentinel_documented_total` | counter | — | fine |\n"
        "| `sentinel_CamelCase` | gauge | — | documented but mis-named |\n"
        "| `sentinel_stale_row_total` | counter | — | no longer registered |\n"
    )
    problems = check_catalog(str(pkg), str(readme))
    text = "\n".join(problems)
    assert "sentinel_undocumented_total" in text and "missing from" in text
    assert "snake_case" in text and "sentinel_CamelCase" in text
    assert "sentinel_stale_row_total" in text and "stale" in text


def test_readme_parser_reads_only_table_rows(tmp_path):
    readme = tmp_path / "README.md"
    readme.write_text(
        "prose mentioning `sentinel_not_a_row_total` inline\n"
        "| `sentinel_in_table_total` | counter | — | yes |\n"
        "  | `sentinel_indented_total` | gauge | — | yes |\n"
    )
    names = readme_catalog_names(str(readme))
    assert names == ["sentinel_in_table_total", "sentinel_indented_total"]
