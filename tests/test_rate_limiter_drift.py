"""Bound the closed-form latestPassedTime drift vs the reference's CAS
semantics (VERDICT r3 weak #5).

The engine advances a rate-limited rule's latestPassedTime once per tick
with a closed form over the tick's admitted (cost, count) sums
(engine._apply_latest); the reference CASes it per request
(RateLimiterController.java:50-105).  The closed form is exact when a
tick's admitted costs are uniform; with MIXED per-item counts the
reset-anchor term uses the mean admitted cost where the sequential replay
uses the FIRST admitted item's cost, so each idle->busy transition can
miss by up to one within-tick cost spread.  Crucially the error does NOT
compound: on a saturated rule the busy branch (latest + T) is exact, and
every idle reset re-anchors to `now`, wiping prior error.

This test replays the reference's sequential semantics item by item over
many saturated mixed-count ticks and asserts the cumulative drift stays
under one maximum item cost (one "bucket" of the pacer).
"""

import numpy as np

import jax.numpy as jnp

from sentinel_tpu.core import rules as R
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.ops import engine as E
from sentinel_tpu.runtime.registry import Registry

RATE = 100.0  # permits/s
MAXQ = 400  # max queueing ms


def _oracle_step(latest, now, counts, verdicts_out):
    """Sequential RateLimiterController.canPass over one tick's items."""
    admitted = 0
    for c in counts:
        cost = float(c) / RATE * 1000.0
        expected = latest + cost
        if expected <= now:
            latest = float(now)
            admitted += 1
            verdicts_out.append(True)
        elif expected - now > MAXQ:
            verdicts_out.append(False)
        else:
            latest += cost
            admitted += 1
            verdicts_out.append(True)
    return latest, admitted


def _run(saturated: bool):
    cfg = small_engine_config()
    reg = Registry(cfg)
    rid = reg.resource_id("rl")
    ruleset = E.compile_ruleset(
        cfg,
        reg,
        flow_rules=[
            R.FlowRule(
                resource="rl",
                count=RATE,
                control_behavior=R.CONTROL_RATE_LIMITER,
                max_queueing_time_ms=MAXQ,
            )
        ],
    )
    slot = int(np.asarray(ruleset.flow.res_rules)[rid, 0])
    tick = E.make_tick(cfg, donate=False, features=frozenset({"flow"}))
    state = E.init_state(cfg)
    rng = np.random.default_rng(7)
    b = cfg.batch_size
    latest_oracle = 0.0
    now = 1_000
    max_item_cost = 5.0 / RATE * 1000.0  # 50 ms
    drifts, adm_diffs = [], []
    for _t in range(80):
        if saturated:
            n_items = b  # way beyond 100/s: the pacer queue stays full
        else:
            # sparse ticks: bucket often idle -> exercises the reset path
            n_items = int(rng.integers(1, 6))
        counts = np.zeros(b, np.int32)
        counts[:n_items] = rng.integers(1, 6, n_items)
        res = np.full(b, 0, np.int32)
        res[:n_items] = rid
        acq = E.empty_acquire(cfg)._replace(
            res=jnp.asarray(np.where(res == 0, cfg.trash_row, res)),
            count=jnp.asarray(counts),
        )
        state, out = tick(
            state, ruleset, acq, E.empty_complete(cfg),
            jnp.int32(now), jnp.float32(0.0), jnp.float32(0.0),
        )
        verd = np.asarray(out.verdict)[:n_items]
        adm_engine = int((verd != 1).sum())  # 1 = BLOCK_FLOW
        overdicts = []
        latest_oracle, adm_oracle = _oracle_step(
            latest_oracle, now, counts[:n_items], overdicts
        )
        eng_latest = float(np.asarray(state.latest_passed_ms)[slot])
        drifts.append(abs(eng_latest - latest_oracle))
        adm_diffs.append(adm_engine - adm_oracle)
        now += 200 if not saturated else 50
    return drifts, adm_diffs, max_item_cost


def test_saturated_mixed_count_drift_under_one_bucket():
    drifts, adm_diffs, max_cost = _run(saturated=True)
    # the headline bound: latestPassedTime drift bounded by ONE max item
    # cost at every tick — it bounces within the bucket, never compounds
    assert max(drifts) <= max_cost, (max(drifts), max_cost)
    # admission divergence per tick is a few items out of ~5 admitted;
    # its RUNNING direction is conservative (the drifted latest sits
    # AHEAD of the oracle's as often as behind, so the engine under-admits
    # slightly rather than bursting past the configured rate)
    assert max(abs(d) for d in adm_diffs) <= 4, adm_diffs
    total = sum(adm_diffs)
    n_oracle = 80 * 5  # ~rate * tick_interval admitted per tick
    assert -0.10 * n_oracle <= total <= 1, total


def test_idle_reset_mixed_count_drift_under_one_bucket():
    drifts, adm_diffs, max_cost = _run(saturated=False)
    assert max(drifts) <= max_cost, (max(drifts), max_cost)
    assert max(abs(d) for d in adm_diffs) <= 1, adm_diffs
