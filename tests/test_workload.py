"""Workload engine + closed-loop autotuner (ROADMAP item 3).

Covers the offered half (shapes are pure arithmetic, the generator
replays bit-identically from its seed, emit faults drop exactly), the
shared ``OperatingPoint`` definition all three consumers ride, the
service model's tradeoff surface, and the tuner itself: live
``apply_operating_point`` swaps under expected-retrace journaling,
fail-open on raising steps, the HBM guardrail, and the acceptance
claim — the tuned loop beats the static default on SLO-bad fraction
with a bit-replayable decision journal.
"""

import math

import pytest

import sentinel_tpu as st
from sentinel_tpu import workload as WL
from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.obs import profile as PROF
from sentinel_tpu.obs.registry import REGISTRY
from sentinel_tpu.obs.slo import SloEngine
from sentinel_tpu.utils.time_source import VirtualTimeSource


def _cval(name, labels=None):
    m = REGISTRY.get(name, labels)
    return float(m.value) if m is not None else 0.0


# -- shapes ------------------------------------------------------------------


def test_flash_crowd_envelope_is_pure_arithmetic():
    fc = WL.FlashCrowd(peak=8.0, start_step=10, ramp_steps=4, hold_steps=6, decay_steps=2)
    assert fc.rate_at(9) == 0.0
    assert fc.rate_at(10) == pytest.approx(2.0)  # ramp: peak*(t+1)/ramp
    assert fc.rate_at(13) == pytest.approx(8.0)
    assert fc.rate_at(14) == 8.0 and fc.rate_at(19) == 8.0  # hold
    assert fc.rate_at(20) == pytest.approx(8.0)  # decay start
    assert fc.rate_at(21) == pytest.approx(4.0)
    assert fc.rate_at(22) == 0.0
    d = WL.Diurnal(base=4.0, amplitude=0.5, period_steps=8)
    assert d.rate_at(0) == pytest.approx(4.0)
    assert d.rate_at(2) == pytest.approx(6.0)  # sin peak
    # pure functions: re-evaluation is identical, no hidden state
    assert [d.rate_at(s) for s in range(16)] == [d.rate_at(s) for s in range(16)]
    hp = WL.HotParamFlood(rate=5.0, start_step=2, duration_steps=3, key="wl/t")
    assert [hp.rate_at(s) for s in range(6)] == [0.0, 0.0, 5.0, 5.0, 5.0, 0.0]
    assert hp.keys.key_for(0, 0.3, hp.keys._cdf()) == "wl/t"


def test_zipf_churn_rotates_hot_set():
    z = WL.ZipfKeys(n_keys=8, churn_every_steps=10, churn_shift=3, prefix="k")
    cdf = z._cdf()
    # rank 0 (hottest) rotates by churn_shift each churn epoch
    assert z.key_for(0, 0.0, cdf) == "k0"
    assert z.key_for(10, 0.0, cdf) == "k3"
    assert z.key_for(20, 0.0, cdf) == "k6"
    sk = WL.SkewedKeys(keys=(("hot", 0.9), ("cold", 0.1)))
    c2 = sk._cdf()
    assert sk.key_for(0, 0.5, c2) == "hot"
    assert sk.key_for(0, 0.95, c2) == "cold"


# -- generator ---------------------------------------------------------------


def test_generator_bit_replay_and_seed_divergence():
    spec = WL.flash_crowd_2x(seed=11, base=2.0, steps=40, start_step=10)
    a = WL.TrafficGenerator(spec).all_events()
    b = WL.TrafficGenerator(spec).all_events()
    assert a == b and len(a) > 0
    assert WL.TrafficGenerator(spec.with_seed(12)).all_events() != a
    # error-diffusion accounting: per-shape event counts are exactly the
    # floor of the shape's cumulative rate — zero entropy in the counts
    for shape in spec.shapes:
        want = math.floor(sum(shape.rate_at(s) for s in range(spec.steps)))
        got = sum(1 for ev in a if ev.shape == shape.name)
        assert got == want


def test_gen_emit_failpoint_drops_steps_exactly():
    spec = WL.flash_crowd_2x(seed=5, base=2.0, steps=30, start_step=8)
    baseline = WL.TrafficGenerator(spec).all_events()
    drops0 = _cval("sentinel_workload_emit_drops_total")
    plan = FaultPlan(
        seed=3,
        faults=[
            FaultSpec(
                "workload.gen.emit",
                "raise",
                every_nth=7,
                max_fires=2,
                exc="RuntimeError",
            )
        ],
    )
    with FP.armed(plan) as armed:
        got = WL.TrafficGenerator(spec).all_events()
    assert armed.injected() == {"workload.gen.emit:raise": 2}
    assert _cval("sentinel_workload_emit_drops_total") - drops0 == 2.0
    # a fault drops whole steps, nothing else: the survivor stream is the
    # baseline minus the dropped steps' events
    dropped = {ev.step for ev in baseline} - {ev.step for ev in got}
    assert 0 < len(got) < len(baseline)
    assert got == [ev for ev in baseline if ev.step not in dropped]


# -- the shared OperatingPoint -----------------------------------------------


def test_operating_point_is_the_shared_definition():
    cfg = small_engine_config()
    op = WL.sim_default_op()
    # identity against the small config — seeded sim/chaos goldens safe
    assert op.engine_changes(cfg) == {}
    assert op.apply_to_config(cfg) is cfg
    op2 = op.replace(batch_size=16, complete_batch_size=16)
    assert op2.engine_changes(cfg) == {"batch_size": 16, "complete_batch_size": 16}
    cfg2 = op2.apply_to_config(cfg)
    assert (cfg2.batch_size, cfg2.complete_batch_size) == (16, 16)
    assert op2.describe().startswith("b16/c16/")
    # the simulator preset derives its queue bound from the same point
    from sentinel_tpu.adaptive.simload import storm_controller_preset

    assert storm_controller_preset().queue_max == int(op.pipeline_depth)
    assert storm_controller_preset(op.replace(pipeline_depth=3)).queue_max == 3
    # bench window rows are presets of the same dataclass
    assert WL.BENCH_WINDOW_EXACT.sketch_slack_frac == 0.0
    assert WL.BENCH_WINDOW_MINUTE.sketch_sample_count == 60
    assert WL.BENCH_WINDOW_MINUTE_SLACK.sketch_slack_frac > 0.0


def test_service_model_has_a_real_tradeoff_surface():
    m = WL.ServiceModel()
    small = WL.OperatingPoint(batch_size=2, complete_batch_size=2)
    mid = WL.OperatingPoint(batch_size=16, complete_batch_size=16)
    big = WL.OperatingPoint(batch_size=64, complete_batch_size=64)
    # bigger batches cost more per tick and earn fewer ticks per step
    assert m.tick_us(small) < m.tick_us(mid) < m.tick_us(big)
    assert m.ticks_per_step(small) >= m.ticks_per_step(mid) >= m.ticks_per_step(big)
    # pipelining buys tick budget but charges readback latency
    piped = mid.replace(pipeline_depth=2)
    assert m.ticks_per_step(piped) >= m.ticks_per_step(mid)
    assert m.extra_wait_ms(piped) > m.extra_wait_ms(mid) == 0.0
    # audit cadence and slack windows amortize tick cost
    assert m.tick_us(mid.replace(audit_period=4)) > m.tick_us(mid.replace(audit_period=64))
    slacked = mid.replace(sketch_sample_count=60, sketch_slack_frac=0.1)
    exact = mid.replace(sketch_sample_count=60, sketch_slack_frac=0.0)
    assert m.tick_us(slacked) < m.tick_us(exact)


def test_service_backend_batches_and_flushes():
    m = WL.ServiceModel(flush_steps=3)
    b = WL.ServiceBackend(m, WL.OperatingPoint(batch_size=4, complete_batch_size=4))
    b.submit(0, 1)
    # a lone item waits for the batch to fill (the big-batch cost)...
    assert b.advance(1) == [] and b.depth() == 1
    # ...until flush age forces the tick
    assert b.advance(3) == []  # fired into service, due next step
    done = b.advance(4)
    assert len(done) == 1
    lat, rid = done[0]
    assert rid == 1 and lat > 4 * m.step_ms  # queue wait dominates
    assert b.depth() == 0


# -- live apply + tuner ------------------------------------------------------


def test_apply_operating_point_live_swap(client):
    surprise0 = PROF.RETRACE.surprise_count()
    op0 = WL.OperatingPoint.from_engine_config(client.cfg)
    assert client.apply_operating_point(op0) == {"engine": False, "host": []}
    # host-only knob: attribute write, no compiled-program impact
    out = client.apply_operating_point(op0.replace(pipeline_depth=2))
    assert out == {"engine": False, "host": ["pipeline_depth"]}
    # engine knob: compile-then-swap, journaled as ONE expected retrace
    op1 = op0.replace(batch_size=16, complete_batch_size=16, pipeline_depth=2)
    out = client.apply_operating_point(op1)
    assert out["engine"] is True
    assert client.cfg.batch_size == 16 and client.cfg.complete_batch_size == 16
    # decisions keep flowing through the swapped engine
    verdicts = client.check_batch(["wl/after-swap"] * 3, inbound=True)
    assert len(verdicts) == 3
    assert PROF.RETRACE.surprise_count() == surprise0


def test_tuner_step_fail_open_rolls_back_to_last_good(client):
    slo = SloEngine(specs=WL.workload_slos(), registry=REGISTRY)
    try:
        op0 = WL.OperatingPoint.from_engine_config(client.cfg)
        cand = op0.replace(batch_size=16, complete_batch_size=16)
        t = WL.AutoTuner(
            client,
            slo,
            op0,
            [cand],
            seed=3,
            tcfg=WL.TunerConfig(settle_steps=1, warmup_steps=0),
        )
        fails0 = _cval("sentinel_tuner_step_failures_total")
        t.step(client.time.now_ms())  # measures the incumbent, moves to cand
        assert t.current == cand and t.best == op0
        plan = FaultPlan(
            seed=1,
            faults=[
                FaultSpec("workload.tuner.step", "raise", max_fires=1, exc="RuntimeError")
            ],
        )
        with FP.armed(plan) as armed:
            t.step(client.time.now_ms())
        assert armed.injected() == {"workload.tuner.step:raise": 1}
        assert _cval("sentinel_tuner_step_failures_total") - fails0 == 1.0
        # failed OPEN: back on the last-good point, client included
        assert t.current == op0 and t.best == op0
        assert client.cfg.batch_size == op0.batch_size
        assert t.decisions[-1]["action"] == "fail_open"
        # serving continues after the fail-open
        assert len(client.check_batch(["wl/post-fail"] * 2, inbound=True)) == 2
    finally:
        slo.close()


def test_tuner_rejects_candidate_that_would_breach_hbm(client_factory):
    client = client_factory(cfg=small_engine_config(sketch_stats=True))
    slo = SloEngine(specs=WL.workload_slos(), registry=REGISTRY)
    cap0 = int(PROF.LEDGER.snapshot().get("capacity_bytes") or 0)
    PROF.LEDGER.set_capacity(PROF.LEDGER.total_bytes() + 1)
    try:
        op0 = WL.OperatingPoint.from_engine_config(client.cfg)
        grown = op0.replace(sketch_sample_count=max(8, op0.sketch_sample_count) * 8)
        t = WL.AutoTuner(
            client,
            slo,
            op0,
            [grown],
            seed=3,
            tcfg=WL.TunerConfig(settle_steps=1, warmup_steps=0),
        )
        breach0 = _cval("sentinel_hbm_capacity_breaches_total")
        t.step(client.time.now_ms())
        acts = [d["action"] for d in t.decisions]
        assert "rejected_hbm" in acts and "converged" in acts
        # never applied: the client still runs the incumbent point
        assert t.current == op0 and t.best == op0 and t.converged
        assert client.cfg.sketch_sample_count == op0.sketch_sample_count
        assert _cval("sentinel_hbm_capacity_breaches_total") == breach0
    finally:
        PROF.LEDGER.set_capacity(cap0)
        slo.close()


# -- the closed loop (acceptance) --------------------------------------------


def _fresh_client(client_factory):
    return client_factory(time_source=VirtualTimeSource(start_ms=1_000))


def test_closed_loop_tuner_beats_static_default(client_factory):
    """ISSUE 19 acceptance: under the seeded flash-crowd-at-2× shape the
    tuner converges to an operating point with a LOWER SLO-bad fraction
    than the static default, with zero surprise retraces."""
    spec = WL.flash_crowd_2x(seed=7, steps=160)  # the perf-smoke shape
    surprise0 = PROF.RETRACE.surprise_count()

    def run(tune):
        c = _fresh_client(client_factory)
        op0 = WL.OperatingPoint.from_engine_config(c.cfg)  # static b64
        cands = [
            op0.replace(batch_size=16, complete_batch_size=16),
            op0.replace(batch_size=8, complete_batch_size=8),
        ]
        out = WL.run_closed_loop(
            c, spec, op0, candidates=cands if tune else (), tune=tune
        )
        c.stop()
        return op0, out

    op0, static = run(False)
    _, tuned = run(True)
    for r in (static, tuned):
        assert r.submitted == r.passed + r.blocked > 0
        assert len(r.latencies_ms) == r.passed  # every admit completed
    assert static.decisions == [] and static.converged_op == op0
    # the tuner moved off the default and earned a lower bad fraction
    assert tuned.converged_op != op0
    assert any(d["action"] == "applied" for d in tuned.decisions)
    assert tuned.decisions[-1]["action"] in ("converged", "rollback")
    assert tuned.bad_frac() < static.bad_frac()
    # retrace guardrail: every move was an EXPECTED retrace
    assert PROF.RETRACE.surprise_count() == surprise0


@pytest.mark.slow
def test_closed_loop_decisions_replay_bit_identically(client_factory):
    """Two tuned runs at one seed produce IDENTICAL offered streams,
    decision journals and latency sequences (the replay half of the
    acceptance)."""
    spec = WL.flash_crowd_2x(seed=7, base=3.0, steps=60, start_step=10)
    assert (
        WL.TrafficGenerator(spec).all_events()
        == WL.TrafficGenerator(spec).all_events()
    )

    def run():
        c = _fresh_client(client_factory)
        op0 = WL.OperatingPoint.from_engine_config(c.cfg)
        out = WL.run_closed_loop(
            c,
            spec,
            op0,
            candidates=[
                op0.replace(batch_size=16, complete_batch_size=16),
                op0.replace(batch_size=8, complete_batch_size=8),
            ],
            tune=True,
            tune_every=4,
            tcfg=WL.TunerConfig(settle_steps=3, warmup_steps=1),
        )
        c.stop()
        return out

    a, b = run(), run()
    assert a.decisions == b.decisions and len(a.decisions) > 0
    assert a.latencies_ms == b.latencies_ms
    assert (a.submitted, a.passed, a.blocked) == (b.submitted, b.passed, b.blocked)
    assert a.converged_op == b.converged_op
