"""Command-plane tests: handler dispatch, the HTTP command center over a
real socket, heartbeat formatting, and write-back to a writable datasource
(reference: sentinel-transport-common handler tests +
SimpleHttpCommandCenter)."""

import json
import urllib.parse
import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource.base import FileWritableDataSource
from sentinel_tpu.datasource.converters import json_rule_encoder
from sentinel_tpu.transport import (
    SimpleHttpCommandCenter,
    WritableDataSourceRegistry,
    build_default_handlers,
)
from sentinel_tpu.transport.command import CommandRequest


@pytest.fixture()
def registry(client):
    return build_default_handlers(client)


def _call(registry, name, **params):
    return registry.handle(name, CommandRequest(parameters={k: str(v) for k, v in params.items()}))


def test_version_and_basic_info(registry, client):
    assert _call(registry, "version").success
    info = _call(registry, "basicInfo").result
    assert info["appName"] == client.app_name
    assert info["enabled"] is True


def test_unknown_command(registry):
    rsp = _call(registry, "nope")
    assert not rsp.success


def test_get_set_rules_roundtrip(registry, client):
    data = json.dumps([{"resource": "cmd-res", "count": 5}])
    assert _call(registry, "setRules", type="flow", data=data).success
    assert client.flow_rules.get()[0].count == 5
    got = _call(registry, "getRules", type="flow").result
    assert got[0]["resource"] == "cmd-res"
    assert not _call(registry, "setRules", type="bogus", data=data).success


def test_set_rules_write_back(client, tmp_path):
    wreg = WritableDataSourceRegistry()
    path = tmp_path / "flow.json"
    wreg.register("flow", FileWritableDataSource(str(path), json_rule_encoder))
    registry = build_default_handlers(client, writable_registry=wreg)
    data = json.dumps([{"resource": "persisted", "count": 9}])
    assert _call(registry, "setRules", type="flow", data=data).success
    on_disk = json.loads(path.read_text())
    assert on_disk[0]["resource"] == "persisted"


def test_switch_gates_entries(registry, client, vt):
    client.flow_rules.load([st.FlowRule(resource="sw", count=0)])
    with pytest.raises(st.BlockException):
        client.entry("sw")
    assert _call(registry, "setSwitch", value="false").success
    with client.entry("sw"):  # switch off → pass-through
        pass
    assert _call(registry, "getSwitch").result == {"enabled": False}
    _call(registry, "setSwitch", value="true")
    with pytest.raises(st.BlockException):
        client.entry("sw")


def test_cluster_node_and_json_tree(registry, client, vt):
    client.flow_rules.load([st.FlowRule(resource="treed", count=100)])
    with client.context("ctx-a", origin="caller-1"):
        with client.entry("treed", origin="caller-1"):
            vt.advance(5)
    nodes = _call(registry, "clusterNode").result
    named = {n["resource"]: n for n in nodes}
    assert named["treed"]["passQps"] >= 1
    tree = _call(registry, "jsonTree").result
    assert tree["resource"] == "machine-root"
    treed = [c for c in tree["children"] if c["resource"] == "treed"][0]
    origins = [c["origin"] for c in treed["children"]]
    assert origins == ["caller-1"]
    per_origin = _call(registry, "origin", id="treed").result
    assert per_origin[0]["origin"] == "caller-1"


def test_metric_command(client, vt, tmp_path):
    from sentinel_tpu.metrics import MetricSearcher, MetricTimerListener, MetricWriter

    client.flow_rules.load([st.FlowRule(resource="m", count=10)])
    with client.entry("m"):
        pass
    timer = MetricTimerListener(client, MetricWriter(str(tmp_path), "tapp"))
    timer.run_once()
    timer.writer.close()
    registry = build_default_handlers(
        client, metric_searcher=MetricSearcher(str(tmp_path), "tapp")
    )
    out = _call(registry, "metric", startTime=0).result
    assert "|m|" in out
    by_id = _call(registry, "metric", startTime=0, identity="m").result
    assert "|m|" in by_id
    assert _call(registry, "metric", startTime=0, identity="absent").result == ""


def test_http_command_center_end_to_end(client):
    center = SimpleHttpCommandCenter(build_default_handlers(client), host="127.0.0.1", port=0)
    center.start()
    try:
        base = f"http://127.0.0.1:{center.port}"
        with urllib.request.urlopen(f"{base}/basicInfo", timeout=3) as rsp:
            assert rsp.status == 200
            assert json.loads(rsp.read())["appName"] == client.app_name
        # POST form-encoded setRules (the dashboard's push shape)
        body = urllib.parse.urlencode(
            {"type": "flow", "data": json.dumps([{"resource": "http-res", "count": 3}])}
        ).encode()
        req = urllib.request.Request(f"{base}/setRules", data=body, method="POST")
        with urllib.request.urlopen(req, timeout=3) as rsp:
            assert rsp.read() == b"success"
        assert client.flow_rules.get()[0].resource == "http-res"
        # unknown command → 400
        try:
            urllib.request.urlopen(f"{base}/bogus", timeout=3)
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code == 400
        assert raised
    finally:
        center.stop()


def test_metrics_and_traces_endpoints_round_trip(client):
    """GET /metrics serves Prometheus text (tick histograms + pipeline
    gauge present) and GET /api/traces serves the span ring as Chrome
    trace JSON — the obs plane's exposition surface (ISSUE 3)."""
    from sentinel_tpu import obs

    obs.TRACER.reset()
    obs.enable()
    try:
        client.flow_rules.load([st.FlowRule(resource="prom-res", count=100)])
        with client.entry("prom-res"):
            pass
    finally:
        obs.disable()
    center = SimpleHttpCommandCenter(build_default_handlers(client), host="127.0.0.1", port=0)
    center.start()
    try:
        base = f"http://127.0.0.1:{center.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=3) as rsp:
            assert rsp.status == 200
            assert rsp.headers["Content-Type"].startswith("text/plain")
            text = rsp.read().decode()
        assert "# TYPE sentinel_tick_device_ms histogram" in text
        assert 'sentinel_tick_device_ms_bucket{le="+Inf"}' in text
        assert "# TYPE sentinel_pipeline_occupancy gauge" in text
        # the traced entry above landed at least one device-tick sample
        count_line = [
            l for l in text.splitlines() if l.startswith("sentinel_tick_device_ms_count")
        ][0]
        assert float(count_line.split()[-1]) >= 1
        with urllib.request.urlopen(f"{base}/api/traces", timeout=3) as rsp:
            doc = json.loads(rsp.read())
        names = {e["name"] for e in doc["traceEvents"]}
        assert "tick.device" in names and "tick.resolve" in names
        assert all(e["ph"] == "X" for e in doc["traceEvents"])
        # ?enable=false flips tracing off via the command plane
        with urllib.request.urlopen(f"{base}/api/traces?enable=false", timeout=3):
            pass
        from sentinel_tpu.obs import TRACER

        assert not TRACER.enabled
    finally:
        center.stop()


def test_api_flight_endpoint_round_trip(client):
    """GET /api/flight serves a fresh black-box bundle (journal, metrics,
    the running client's provider section); ?stored=N returns the
    auto-triggered bundle list (ISSUE 5)."""
    from sentinel_tpu.obs.flight import FLIGHT

    FLIGHT.note("cluster.degrade.enter", cooldown_s=1.0)
    center = SimpleHttpCommandCenter(build_default_handlers(client), host="127.0.0.1", port=0)
    center.start()
    try:
        base = f"http://127.0.0.1:{center.port}"
        with urllib.request.urlopen(f"{base}/api/flight", timeout=3) as rsp:
            assert rsp.status == 200
            b = json.loads(rsp.read())
        assert b["kind"] == "sentinel-flight-bundle" and b["reason"] == "api"
        assert any(e["kind"] == "cluster.degrade.enter" for e in b["journal"])
        assert isinstance(b["metrics"], dict)
        # the fixture client registered its provider on start()
        assert "client" in b["providers"]
        assert "rule_fingerprints" in b["providers"]["client"]
        with urllib.request.urlopen(f"{base}/api/flight?stored=2", timeout=3) as rsp:
            stored = json.loads(rsp.read())
        assert isinstance(stored, list)
    finally:
        center.stop()


def test_heartbeat_against_local_receiver(client):
    """Heartbeat posts land on an HTTP receiver (a stand-in dashboard)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    seen = []

    class Recv(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            seen.append((self.path, self.rfile.read(n).decode()))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Recv)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        from sentinel_tpu.transport import HeartbeatSender

        hb = HeartbeatSender(
            client.app_name, 8719, [f"127.0.0.1:{srv.server_address[1]}"]
        )
        assert hb.send_once()
        path, body = seen[0]
        assert path == "/registry/machine"
        params = dict(urllib.parse.parse_qsl(body))
        assert params["app"] == client.app_name
        assert params["port"] == "8719"
        assert hb.sent_ok == 1
    finally:
        srv.shutdown()
        srv.server_close()
