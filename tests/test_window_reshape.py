"""Live window reshaping under traffic (IntervalProperty analog).

The reference pushes IntervalProperty/SampleCountProperty updates that
reshape LeapArrays at runtime (node/IntervalProperty.java — resetting
metrics); here the reshape migrates current windowed totals so budgets
hold across the swap.
"""

import sentinel_tpu as st
from sentinel_tpu.datasource.property import DynamicSentinelProperty


def test_reshape_preserves_budget_under_traffic(client, vt):
    client.flow_rules.load([st.FlowRule(resource="api", count=5)])
    assert client.cfg.second_sample_count == 2
    got = sum(1 for _ in range(3) if client.try_entry("api"))
    assert got == 3

    # reshape 2x500ms -> 4x250ms mid-window
    client.update_window_shape(sample_count=4, window_ms=250)
    assert client.cfg.second_sample_count == 4
    assert client.cfg.second_window_ms == 250

    # the 3 admitted entries migrated: only 2 more fit the budget
    got2 = sum(1 for _ in range(5) if client.try_entry("api"))
    assert got2 == 2

    # stats survived the reshape too
    snap = client.stats.resource("api")
    assert snap["passQps"] == 5.0
    assert snap["blockQps"] == 3.0

    # after the (new) interval passes, the budget reopens
    vt.advance(1100)
    assert client.try_entry("api") is not None


def test_reshape_via_property_push(client, vt):
    client.flow_rules.load([st.FlowRule(resource="p", count=4)])
    prop = DynamicSentinelProperty()
    client.register_window_property(prop)
    assert sum(1 for _ in range(2) if client.try_entry("p")) == 2

    prop.update_value({"sampleCount": 5, "intervalMs": 1000})
    assert client.cfg.second_sample_count == 5
    assert client.cfg.second_window_ms == 200

    # budget continuity: 2 consumed before the push, 2 remain
    assert sum(1 for _ in range(4) if client.try_entry("p")) == 2


def test_reshape_rejects_capacity_changes(client, vt):
    import dataclasses

    import pytest

    from sentinel_tpu.ops import engine as E

    bad = dataclasses.replace(client.cfg, max_flow_rules=client.cfg.max_flow_rules * 2)
    with pytest.raises(ValueError):
        E.migrate_state(client._state, client.cfg, bad, client.time.now_ms())
