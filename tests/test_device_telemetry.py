"""Device-resident telemetry (ops/engine._device_stats, TickOutput.stats):
on-device verdict-mix/window/ceiling accounting vs a host recompute, the
256-byte readback budget, the client-side registry fold, and the adaptive
signals feed."""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.core.rules import FlowRule, SystemRule
from sentinel_tpu.obs import REGISTRY
from sentinel_tpu.ops import engine as E


class _Reg:
    def resource_id(self, n):
        return 1


def _tick_once(cfg, res, counts=None, inbound=None, pre=None, rules=None):
    rules = rules if rules is not None else E._compile_ruleset(
        cfg, _Reg(), [], [], [], [], [], None
    )
    st = E.init_state(cfg)
    tick = E.make_tick(cfg, donate=False)
    b = len(res)
    acq = E.empty_acquire(cfg, b=b)._replace(
        res=jnp.asarray(res, jnp.int32),
        count=jnp.asarray(
            counts if counts is not None else np.ones(b), jnp.int32
        ),
        inbound=jnp.asarray(
            inbound if inbound is not None else np.ones(b), jnp.int32
        ),
        pre_verdict=jnp.asarray(
            pre if pre is not None else np.zeros(b), jnp.int32
        ),
    )
    comp = E.empty_complete(cfg, b=b)
    z = jnp.float32(0.0)
    st, out = tick(st, rules, acq, comp, jnp.int32(1000), z, z)
    return st, out, acq


def test_stats_row_matches_host_verdict_scan():
    """The device row's verdict mix must equal what a host scan of the
    verdict array computes — padding excluded, forced counted."""
    cfg = small_engine_config()
    trash = cfg.trash_row
    res = [1, 1, 2, 3, trash, trash, 2, 1]
    pre = [0, 0, 0, int(ERR.BLOCK_SYSTEM), 0, 0, 0, 0]
    _st, out, _acq = _tick_once(cfg, res, pre=pre)
    s = np.asarray(out.stats)
    v = np.asarray(out.verdict)
    valid = np.asarray(res) != trash
    assert s[E.STAT_VALID] == valid.sum()
    assert s[E.STAT_PASS] == ((v == ERR.PASS) & valid).sum()
    assert s[E.STAT_BLOCK_SYSTEM] == ((v == ERR.BLOCK_SYSTEM) & valid).sum()
    assert s[E.STAT_FORCED] == 1
    assert s[E.STAT_PASS_TOKENS] == ((v == ERR.PASS) & valid).sum()
    assert s[E.STAT_BLOCK_TOKENS] == 1  # the forced item's count


def test_stats_row_counts_flow_blocks_and_window_sums():
    cfg = small_engine_config()
    rules = E._compile_ruleset(
        cfg, _Reg(), [FlowRule(resource="r", count=2.0)], [], [], [], [], None
    )
    _st, out, _acq = _tick_once(cfg, [1] * 6, rules=rules)
    s = np.asarray(out.stats)
    assert s[E.STAT_PASS] == 2
    assert s[E.STAT_BLOCK_FLOW] == 4
    # post-effects ENTRY-window sums include this tick (O(1) window read)
    assert s[E.STAT_WIN_PASS] == 2
    assert s[E.STAT_WIN_BLOCK] == 4
    assert s[E.STAT_ENTRY_CONC] == 2


def test_stats_ceiling_utilization_tracks_system_rule():
    cfg = small_engine_config()
    rules = E._compile_ruleset(
        cfg, _Reg(), [], [], [], [], [SystemRule(qps=100.0)], None
    )
    _st, out, _acq = _tick_once(cfg, [1] * 8, rules=rules)
    s = np.asarray(out.stats)
    assert s[E.STAT_CEIL_QPS] == 100.0
    assert s[E.STAT_CEIL_UTIL] == pytest.approx(8 / 100.0)


def test_stats_readback_budget_and_off_mode():
    """<= 256 bytes of added readback; telemetry off => stats is None
    (the traced program reverts)."""
    cfg = small_engine_config()
    _st, out, _acq = _tick_once(cfg, [1, 2, 3])
    assert np.asarray(out.stats).nbytes <= 256
    assert E.N_STATS * 4 <= 256
    cfg_off = small_engine_config(device_telemetry=False)
    _st, out_off, _acq = _tick_once(cfg_off, [1, 2, 3])
    assert out_off.stats is None


def test_client_folds_stats_into_registry(client_factory):
    """The registry's sentinel_device_* series must be fed by the
    readback fold, agreeing with the client-visible verdicts."""

    def _dev(name, **labels):
        m = REGISTRY.get(name, labels or None)
        return float(m.value) if m is not None else 0.0

    pass0 = _dev("sentinel_device_verdicts_total", verdict="pass")
    blk0 = _dev("sentinel_device_verdicts_total", verdict="block_flow")
    c = client_factory()
    c.flow_rules.load([FlowRule(resource="dtm/r", count=3.0)])
    verdicts = c.check_batch(["dtm/r"] * 8, inbound=True)
    passed = sum(1 for v, _ in verdicts if v in (ERR.PASS, ERR.PASS_WAIT))
    assert passed == 3
    assert _dev("sentinel_device_verdicts_total", verdict="pass") - pass0 == passed
    assert _dev("sentinel_device_verdicts_total", verdict="block_flow") - blk0 == 5
    assert _dev("sentinel_device_entry_pass_window") >= passed


def test_signals_consume_device_min_rt():
    """A verdict-only workload (no completion batches) gets its BBR minRT
    floor from the device window row instead of 0."""
    from sentinel_tpu.adaptive.signals import SignalCollector

    col = SignalCollector()
    row = np.zeros(E.N_STATS, np.float32)
    row[E.STAT_WIN_RT_MIN] = 7.5
    row[E.STAT_WIN_PASS] = 42.0
    col.note_device_stats(row)
    sig = col.observe_tick(1000, 0, 0, 0, 0.0, 0.0)
    assert sig.min_rt_ms == 7.5
    # the RT_MIN_INIT sentinel (no completions in window) masks to 0
    row[E.STAT_WIN_RT_MIN] = 5000.0
    col.note_device_stats(row)
    sig = col.observe_tick(2000, 0, 0, 0, 0.0, 0.0)
    assert sig.min_rt_ms == 0.0


def test_wire_byte_accounting_moves_with_traffic(client_factory):
    tx = REGISTRY.get(
        "sentinel_wire_bytes_total", {"path": "device", "direction": "tx"}
    )
    rx = REGISTRY.get(
        "sentinel_wire_bytes_total", {"path": "device", "direction": "rx"}
    )
    tx0, rx0 = tx.value, rx.value
    c = client_factory()
    c.registry.resource_id("wire/r")
    c.check_batch(["wire/r"] * 16)
    assert tx.value > tx0  # batch columns uploaded
    assert rx.value >= rx0 + 16 + E.N_STATS * 4  # verdicts + stats row
