"""Circuit-breaker integration tests under virtual time.

Counterpart of the reference's CircuitBreakingIntegrationTest and the
ResponseTime/ExceptionCircuitBreaker unit tests (SURVEY.md §4.3): full
entry/exit loops against DegradeRules, state transitions driven by the
virtual clock.
"""

import sentinel_tpu as st


def _roundtrip(client, vt, resource, rt_ms, error=False):
    """One entry+exit taking rt_ms of virtual time. Returns verdict ok."""
    try:
        e = client.entry(resource)
    except st.BlockException:
        return False
    vt.advance(rt_ms)
    if error:
        e.trace(RuntimeError("biz"))
    e.exit()
    return True


def test_slow_ratio_trips_and_recovers(client, vt):
    client.degrade_rules.load(
        [
            st.DegradeRule(
                resource="svc",
                grade=st.CB_STRATEGY_SLOW_REQUEST_RATIO,
                count=10,  # max RT ms
                slow_ratio_threshold=0.5,
                stat_interval_ms=1000,
                time_window=2,  # retry after 2 s
                min_request_amount=5,
            )
        ]
    )
    # 5 slow requests (60 > 10 ms) → at the 5th completion total=5 ≥
    # minRequestAmount and ratio 1.0 > 0.5 → OPEN
    for _ in range(5):
        assert _roundtrip(client, vt, "svc", 60)
    assert not _roundtrip(client, vt, "svc", 1)  # breaker open

    # before the retry window: still open
    vt.advance(1000)
    assert not _roundtrip(client, vt, "svc", 1)

    # after retry timeout: exactly one probe is let through
    vt.advance(2500)
    probe = client.try_entry("svc")
    assert probe is not None
    assert client.try_entry("svc") is None  # half-open: probe in flight
    # fast probe completion closes the breaker
    vt.advance(2)
    probe.exit()
    assert _roundtrip(client, vt, "svc", 1)


def test_half_open_regression(client, vt):
    client.degrade_rules.load(
        [
            st.DegradeRule(
                resource="svc2",
                grade=st.CB_STRATEGY_SLOW_REQUEST_RATIO,
                count=10,
                slow_ratio_threshold=0.5,
                stat_interval_ms=1000,
                time_window=1,
                min_request_amount=3,
            )
        ]
    )
    for _ in range(3):
        assert _roundtrip(client, vt, "svc2", 50)
    assert not _roundtrip(client, vt, "svc2", 1)
    vt.advance(1500)
    # probe admitted but SLOW again → breaker re-opens
    assert _roundtrip(client, vt, "svc2", 80)
    assert not _roundtrip(client, vt, "svc2", 1)


def test_error_ratio(client, vt):
    client.degrade_rules.load(
        [
            st.DegradeRule(
                resource="err",
                grade=st.CB_STRATEGY_ERROR_RATIO,
                count=0.5,
                stat_interval_ms=1000,
                time_window=5,
                min_request_amount=4,
            )
        ]
    )
    for _ in range(3):
        assert _roundtrip(client, vt, "err", 1, error=True)
    assert _roundtrip(client, vt, "err", 1, error=False)
    # 3/4 errors > 0.5 → open
    assert not _roundtrip(client, vt, "err", 1)


def test_error_count(client, vt):
    client.degrade_rules.load(
        [
            st.DegradeRule(
                resource="ec",
                grade=st.CB_STRATEGY_ERROR_COUNT,
                count=3,
                stat_interval_ms=1000,
                time_window=5,
                min_request_amount=1,
            )
        ]
    )
    assert _roundtrip(client, vt, "ec", 1, error=True)
    assert _roundtrip(client, vt, "ec", 1, error=True)
    assert _roundtrip(client, vt, "ec", 1, error=True)
    assert not _roundtrip(client, vt, "ec", 1)


def test_window_expiry_resets_ratio(client, vt):
    client.degrade_rules.load(
        [
            st.DegradeRule(
                resource="w",
                grade=st.CB_STRATEGY_SLOW_REQUEST_RATIO,
                count=10,
                slow_ratio_threshold=0.5,
                stat_interval_ms=1000,
                time_window=1,
                min_request_amount=5,
            )
        ]
    )
    # 4 slow requests — under minRequestAmount, no trip
    for _ in range(4):
        assert _roundtrip(client, vt, "w", 30)
    # window slides past them
    vt.advance(2000)
    # fresh fast traffic keeps it closed
    for _ in range(6):
        assert _roundtrip(client, vt, "w", 1)
