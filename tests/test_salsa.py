"""sentinel_tpu.sketch.salsa — self-adjusting sketch tier correctness.

Pins the tentpole invariants: packed-counter merge semantics (SALSA,
arXiv 2102.12531), O(1) running-window sums (arXiv 1604.02450), the
width-bitmap round trip, the fail-closed overestimate bias, and the
no-retrace contract of the cached table plans."""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import gsketch as GS
from sentinel_tpu.ops import window as W
from sentinel_tpu.runtime.registry import Registry
from sentinel_tpu.sketch import impl_for, salsa as SA


def _cfg(depth=2, width=512, nb=2, wms=500):
    return GS.SketchConfig(sample_count=nb, window_ms=wms, depth=depth, width=width)


def _add_ids(s, now, ids, counts, cfg, plane=W.EV_PASS, max_int=65535):
    vals = jnp.asarray(np.asarray(counts, np.int32)[:, None])
    return SA.add(
        s,
        jnp.int32(now),
        jnp.asarray(ids, jnp.int32),
        vals,
        (plane,),
        jnp.ones((len(ids),), bool),
        cfg,
        max_int=max_int,
    )


def _est(s, now, ids, cfg, plane=W.EV_PASS):
    return np.asarray(
        SA.estimate(s, jnp.int32(now), jnp.asarray(ids, jnp.int32), cfg)
    )[:, plane]


# -- width bitmap ------------------------------------------------------------


def test_width_bitmap_decode_roundtrip():
    rng = np.random.default_rng(3)
    for shape in [(64,), (2, 6, 128), (3, 2, 6, 64)]:
        lvl = jnp.asarray(rng.integers(0, 3, size=shape), jnp.int32)
        packed = SA.pack_levels(lvl)
        assert packed.shape == shape[:-1] + (shape[-1] // 16,)
        back = SA.unpack_levels(packed, shape[-1])
        assert bool(jnp.all(back == lvl))


def test_packed_word_decode_covers_all_levels():
    # one word per level: lvl0 lanes [1,2,3,4]; lvl1 halves [300, 70];
    # lvl2 total 70000 — decode must expand each at its own granularity
    words = jnp.asarray(
        [1 | (2 << 8) | (3 << 16) | (4 << 24), 300 | (70 << 16), 70000],
        jnp.int32,
    )
    lvl = jnp.asarray([0, 1, 2], jnp.int32)
    dec = np.asarray(SA._decode(words, lvl))
    assert dec.tolist() == [1, 2, 3, 4, 300, 300, 70, 70, 70000, 70000, 70000, 70000]


# -- counter saturation / merge ----------------------------------------------


def test_counter_saturation_escalates_and_stays_exact_for_single_id():
    cfg = _cfg(width=256)
    s = SA.init_sketch(cfg)
    # int8 -> int16 on the 256-boundary, int16 -> int32 past 65535 (the
    # GS max_int envelope), values exact throughout for an isolated id
    s = _add_ids(s, 0, [7], [200], cfg)
    assert _est(s, 0, [7], cfg)[0] == 200
    lv = np.asarray(SA.level_histogram(s, cfg))
    assert lv[1] == 0 and lv[2] == 0
    s = _add_ids(s, 0, [7], [200], cfg)  # 400 > 255: merge to int16
    assert _est(s, 0, [7], cfg)[0] == 400
    assert np.asarray(SA.level_histogram(s, cfg))[1] == cfg.depth
    s = _add_ids(s, 0, [7], [65535], cfg)  # 65935 > 65535: merge to int32
    assert _est(s, 0, [7], cfg)[0] == 65935
    assert np.asarray(SA.level_histogram(s, cfg))[2] == cfg.depth


def test_merge_widens_neighbors_conservatively():
    # saturate one logical column; its word-neighbors now read the MERGED
    # counter — an overestimate (fail-closed direction), never an
    # underestimate for anyone
    cfg = _cfg(depth=1, width=256)
    s = SA.init_sketch(cfg)
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 10_000, size=64)
    s = _add_ids(s, 0, ids, np.full(64, 3), cfg)
    exact = {int(i): 0 for i in ids}
    for i in ids:
        exact[int(i)] += 3
    s = _add_ids(s, 0, [777], [1000], cfg)  # escalates its word
    exact[777] = exact.get(777, 0) + 1000
    qs = sorted(exact)
    est = _est(s, 0, qs, cfg)
    for q, e in zip(qs, est):
        assert e >= exact[q], (q, e, exact[q])


# -- O(1) windowed reads -----------------------------------------------------


def test_running_sums_match_seed_cms_without_saturation():
    """Below every saturation threshold the salsa estimate must equal the
    seed CMS bit-for-bit (same hashes, same window) — the O(1) running
    sums replace the per-read bucket sum, not the semantics."""
    cfg = _cfg(depth=2, width=512, nb=4, wms=250)
    sa = SA.init_sketch(cfg)
    gs = GS.init_sketch(cfg)
    rng = np.random.default_rng(11)
    for t in [0, 260, 510, 760, 1010, 1260]:  # slides across the window
        ids = rng.integers(100, 5_000, size=128)
        cnt = rng.integers(1, 4, size=128)
        vals = jnp.asarray(cnt[:, None].astype(np.int32))
        args = (
            jnp.int32(t),
            jnp.asarray(ids, jnp.int32),
            vals,
            (W.EV_PASS,),
            jnp.ones((128,), bool),
            cfg,
        )
        sa = SA.add(sa, *args)
        gs = GS.add(gs, *args)
        q = jnp.asarray(np.unique(ids), jnp.int32)
        ea = np.asarray(SA.estimate(sa, jnp.int32(t), q, cfg))
        eg = np.asarray(GS.estimate(gs, jnp.int32(t), q, cfg))
        np.testing.assert_array_equal(ea, eg)


def test_epoch_rollover_across_idle_gap():
    """Idle gaps > interval_ms: lazily-expired buckets may overestimate
    (documented fail-closed transient), sweep_expired collapses it, and
    after one full rotation the estimate is exact again."""
    cfg = _cfg(depth=2, width=256, nb=2, wms=500)
    s = SA.init_sketch(cfg)
    s = _add_ids(s, 0, [42], [5], cfg)
    s = _add_ids(s, 600, [42], [7], cfg)
    assert _est(s, 600, [42], cfg)[0] == 12
    # idle 10 s (>> interval 1 s): nothing rotated the old buckets out
    t = 10_600
    est_lazy = _est(s, t, [42], cfg)[0]
    assert est_lazy >= 0  # stale overestimate allowed, never negative
    assert est_lazy <= 12  # bounded by one pre-gap window volume
    swept = SA.sweep_expired(s, jnp.int32(t), cfg)
    assert _est(swept, t, [42], cfg)[0] == 0
    # organic path: adds after the gap rotate the grid clean within one
    # interval — estimate is exactly the fresh traffic
    s = _add_ids(s, t, [42], [3], cfg)
    s = _add_ids(s, t + 500, [42], [4], cfg)
    assert _est(s, t + 500, [42], cfg)[0] == 7
    # epochs really rolled: another gap, then a single fresh bucket
    s = _add_ids(s, t + 5_000, [42], [9], cfg)
    s = _add_ids(s, t + 5_500, [42], [0], cfg)
    assert _est(s, t + 5_500, [42], cfg)[0] == 9


def test_estimate_never_underestimates_across_rotation():
    """Fail-closed bias: at every point of a windowed stream, the salsa
    estimate >= the true in-window count (CMS collision + merge + lazy
    expiry all err upward) — tail blocks fire early, never late."""
    cfg = _cfg(depth=2, width=256, nb=3, wms=400)
    s = SA.init_sketch(cfg)
    rng = np.random.default_rng(23)
    events = []  # (t, id, count)
    t = 0
    for step in range(12):
        ids = rng.integers(0, 2_000, size=64)
        cnt = rng.integers(1, 5, size=64)
        s = _add_ids(s, t, ids, cnt, cfg)
        events += [(t, int(i), int(c)) for i, c in zip(ids, cnt)]
        lo = t - (cfg.interval_ms - cfg.window_ms)  # conservative window
        true = {}
        for et, ei, ec in events:
            if et >= lo:
                true[ei] = true.get(ei, 0) + ec
        qs = sorted(true)
        est = _est(s, t, qs, cfg)
        for q, e in zip(qs, est):
            assert e >= true[q], (step, q, e, true[q])
        t += int(rng.integers(100, 700))


# -- error bound on a seeded Zipf stream -------------------------------------


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_estimate_error_bound_on_zipf(depth):
    """Classic CMS guarantee on the salsa tier: per query,
    P(err > e/width * V) <= e^-depth.  Seeded stream -> deterministic;
    assert the observed violation rate at each depth plus the absolute
    overestimate invariant."""
    width = 1024
    cfg = _cfg(depth=depth, width=width, nb=2, wms=500)
    s = SA.init_sketch(cfg)
    rng = np.random.default_rng(7)
    ids = (rng.zipf(1.2, size=4096).astype(np.int64) - 1) % 50_000 + 1_000_000
    exact = {}
    for lo in range(0, len(ids), 512):
        chunk = ids[lo : lo + 512]
        s = _add_ids(s, 100, chunk, np.ones(len(chunk)), cfg)
        for i in chunk:
            exact[int(i)] = exact.get(int(i), 0) + 1
    V = float(len(ids))
    qs = sorted(exact)
    est = _est(s, 100, qs, cfg)
    errs = np.asarray([e - exact[q] for q, e in zip(qs, est)], np.float64)
    assert (errs >= 0).all()  # overestimate only
    bound = math.e / width * V
    viol = float((errs > bound).mean())
    assert viol <= math.exp(-depth) * 1.2 + 1e-9, (depth, viol, bound)
    # and the typical error is far inside the bound on real (Zipf) traffic
    assert float(errs.mean()) <= bound


# -- HBM accounting ----------------------------------------------------------


def test_salsa_hbm_stretch_vs_seed_cms():
    """At minute windows the packed tier stores ~4x less per bucket than
    the int32 seed; the BENCH sketch_tier row reports hbm_bytes."""
    cfg = _cfg(depth=2, width=1 << 14, nb=60, wms=1000)
    salsa_b = SA.hbm_bytes(cfg)
    seed_b = 4 * (cfg.sample_count * cfg.depth * cfg.width * GS.PLANES)
    assert salsa_b < seed_b / 3  # bitmap + running sums cost < 1/4 extra
    st = SA.init_sketch(cfg)
    live = sum(int(np.asarray(x).nbytes) for x in st)
    assert live == salsa_b


# -- cached plans / no-retrace (tick identity) -------------------------------


def test_plan_cache_returns_shared_instance():
    from sentinel_tpu.ops import mxu_table as MX

    a = MX.plan_for(1 << 14, 512)
    b = MX.plan_for(1 << 14, 512)
    assert a is b
    assert a == MX.make_plan(1 << 14, 512)


@pytest.mark.parametrize("salsa", [False, True])
def test_sketch_tick_identity_no_retrace(salsa):
    """The sketch-enabled tick compiles ONCE: repeated calls with fresh
    now_ms values (and the per-call plan lookups inside gsketch/salsa
    add) must hit the same executable — the hoisted plan cache keeps the
    traced constants identical."""
    cfg = small_engine_config(
        max_resources=16, max_nodes=32, sketch_stats=True, sketch_width=256,
        sketch_salsa=salsa,
    )
    fn = E.make_tick(cfg, donate=False)
    state = E.init_state(cfg)
    rules = E.compile_ruleset(cfg, Registry(cfg))
    acq = E.empty_acquire(cfg)._replace(
        res=jnp.full((cfg.batch_size,), cfg.node_rows + 5, jnp.int32),
        count=jnp.ones((cfg.batch_size,), jnp.int32),
    )
    comp = E.empty_complete(cfg)
    z = jnp.float32(0.0)
    state, _ = fn(state, rules, acq, comp, jnp.int32(1_000), z, z)
    assert fn._cache_size() == 1
    for t in (1_500, 2_100, 60_000):
        state, _ = fn(state, rules, acq, comp, jnp.int32(t), z, z)
    assert fn._cache_size() == 1  # no retrace across ticks


# -- pre-refreshed handle ----------------------------------------------------


@pytest.mark.parametrize("impl_name", ["gsketch", "salsa"])
def test_pre_refreshed_second_write_is_equivalent(impl_name):
    """The tick's second sketch write of a tick (acquire side) skips
    refresh; landing the same events with and without the skip must be
    bit-identical whenever a first write already stamped the bucket."""
    impl = GS if impl_name == "gsketch" else SA
    cfg = _cfg(depth=2, width=256)
    ids = jnp.asarray([9, 9, 1234], jnp.int32)
    vals = jnp.asarray([[2], [3], [4]], jnp.int32)
    ok = jnp.ones((3,), bool)

    def both(pre):
        s = impl.init_sketch(cfg)
        # completion-side write stamps the bucket...
        s = impl.add(s, jnp.int32(700), ids, vals, (W.EV_SUCCESS,), ok, cfg)
        # ...acquire-side write may then skip the refresh copy
        return impl.add(
            s, jnp.int32(700), ids, vals, (W.EV_PASS,), ok, cfg,
            pre_refreshed=pre,
        )

    a, b = both(False), both(True)
    for la, lb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_engine_dispatch_selects_impl():
    cfg_s = small_engine_config(sketch_stats=True)
    cfg_g = small_engine_config(sketch_stats=True, sketch_salsa=False)
    assert impl_for(cfg_s) is SA
    assert impl_for(cfg_g) is GS
    st = E.init_state(cfg_s)
    assert isinstance(st.gs, SA.SalsaState)
    assert isinstance(E.init_state(cfg_g).gs, GS.SketchState)
