"""NumPy oracle reimplementing the reference's BucketLeapArray semantics.

A deliberately naive, per-row, per-bucket Python model of
sentinel-core/.../slots/statistic/base/LeapArray.java (bucket index
``(t / windowLen) % n``, lazy reset on wrap) used to cross-check the
vectorized window kernel.  Mirrors the role of BucketLeapArrayTest /
LeapArrayTest in the reference test suite (SURVEY.md §4.2).
"""

from __future__ import annotations

import numpy as np


class OracleLeapArray:
    NUM_EVENTS = 5

    def __init__(self, rows: int, sample_count: int, window_ms: int):
        self.nb = sample_count
        self.wm = window_ms
        self.rows = rows
        self.counts = np.zeros((rows, self.nb, self.NUM_EVENTS), dtype=np.int64)
        self.rt_sum = np.zeros((rows, self.nb), dtype=np.float64)
        self.rt_min = np.full((rows, self.nb), 5000.0)
        self.starts = np.full((self.nb,), -1, dtype=np.int64)  # window start ms

    def _bucket(self, now_ms: int) -> int:
        wid = now_ms // self.wm
        idx = wid % self.nb
        start = wid * self.wm
        if self.starts[idx] != start:
            # lazy reset (LeapArray.java:205-232)
            self.counts[:, idx, :] = 0
            self.rt_sum[:, idx] = 0.0
            self.rt_min[:, idx] = 5000.0
            self.starts[idx] = start
        return idx

    def add(self, now_ms: int, row: int, event: int, n: int = 1):
        idx = self._bucket(now_ms)
        self.counts[row, idx, event] += n

    def add_rt(self, now_ms: int, row: int, rt: float):
        idx = self._bucket(now_ms)
        self.rt_sum[row, idx] += rt
        self.rt_min[row, idx] = min(self.rt_min[row, idx], rt)

    def _valid(self, now_ms: int) -> np.ndarray:
        # !isWindowDeprecated: now - start < interval (LeapArray.java:241-245)
        interval = self.nb * self.wm
        return (self.starts >= 0) & (now_ms - self.starts < interval) & (
            self.starts <= now_ms
        )

    def window_event(self, now_ms: int, event: int) -> np.ndarray:
        v = self._valid(now_ms)
        return (self.counts[:, :, event] * v[None, :]).sum(axis=1)

    def window_rt(self, now_ms: int):
        v = self._valid(now_ms)
        total = (self.rt_sum * v[None, :]).sum(axis=1)
        mn = np.where(v[None, :], self.rt_min, 5000.0).min(axis=1)
        return total, mn
