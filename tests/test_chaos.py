"""sentinel_tpu.chaos — failpoints, plans, invariants, scenarios.

Covers the ISSUE-4 contracts: the failpoint catalog (site names unique,
registered, scheme-conformant — mirroring obs's single-site clock
assertion), the disarmed-site overhead guard (<5 µs/site-call, the obs
bound), seeded plan JSON round-trips and schedule determinism, the
fail-closed resolve hardening, the RemoteShard mid-window partition
driven through the new failpoint sites (no monkeypatching), the
front-door unenforceable-rule counter satellite, the labeled cluster
RPC failure kinds satellite, and the tier-1 scenario subset.  The full
scenario matrix and the two-run determinism contract run under
``@pytest.mark.slow``.
"""

from __future__ import annotations

import ast
import os
import re
import time

import pytest

from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec
from sentinel_tpu.core import errors as ERR

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_guard():
    """No test may leak an armed plan into the rest of the suite."""
    yield
    FP.disarm()


def _import_instrumented_modules():
    """Import every module that registers failpoints (idempotent)."""
    import sentinel_tpu.analysis.concurrency.witness  # noqa: F401
    import sentinel_tpu.chaos.runner  # noqa: F401
    import sentinel_tpu.cluster.client  # noqa: F401
    import sentinel_tpu.cluster.front_door  # noqa: F401
    import sentinel_tpu.cluster.server  # noqa: F401
    import sentinel_tpu.cluster.shard  # noqa: F401
    import sentinel_tpu.datasource.stores  # noqa: F401
    import sentinel_tpu.obs.profile  # noqa: F401
    import sentinel_tpu.obs.timeline  # noqa: F401
    import sentinel_tpu.parallel.remote_shard  # noqa: F401
    import sentinel_tpu.runtime.client  # noqa: F401
    import sentinel_tpu.sketch.hotset  # noqa: F401
    import sentinel_tpu.transport.heartbeat  # noqa: F401
    import sentinel_tpu.transport.http_server  # noqa: F401
    import sentinel_tpu.workload.generator  # noqa: F401
    import sentinel_tpu.workload.tuner  # noqa: F401


# ---------------------------------------------------------------------------
# failpoint catalog
# ---------------------------------------------------------------------------

_SCHEME = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")
_LAYERS = {
    "transport", "cluster", "runtime", "parallel", "datasource", "obs",
    "sketch", "workload",
}


def test_catalog_sites_unique_registered_and_scheme_conformant():
    """Every registered site follows <layer>.<component>.<operation>, the
    layer set is closed, and the source's register() literals match the
    live catalog exactly — a renamed site cannot drift from its docs."""
    _import_instrumented_modules()
    cat = FP.catalog()
    assert len(cat) >= 15, f"expected the documented ~15-20 sites, got {len(cat)}"
    for name, site in cat.items():
        assert _SCHEME.match(name), f"{name!r} violates the naming scheme"
        assert name.split(".")[0] in _LAYERS
        assert site.kinds, f"{name!r} registered without action kinds"

    # source scan: FP.register("<literal>", ...) across the package
    registered_in_source = set()
    pkg = os.path.join(REPO_ROOT, "sentinel_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "register"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "FP"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                ):
                    registered_in_source.add(node.args[0].value)
    assert registered_in_source == set(cat), (
        "source register() literals and the live catalog diverge: "
        f"{registered_in_source ^ set(cat)}"
    )


def test_register_rejects_bad_names_and_conflicts():
    with pytest.raises(ValueError):
        FP.register("cluster.rpc")  # two segments
    with pytest.raises(ValueError):
        FP.register("kitchen.sink.op")  # unknown layer
    with pytest.raises(ValueError):
        FP.register("cluster.rpc.send", "different", ("drop",))  # conflict
    # identical re-registration is idempotent (module re-import)
    site = FP.catalog()["cluster.rpc.send"]
    assert FP.register("cluster.rpc.send", site.desc, site.kinds) == "cluster.rpc.send"


def test_disarmed_overhead_guard():
    """A disarmed site costs one flag check: 20k hit() probes must stay
    under 5 µs/call — the same bound the obs tracer guards."""
    from sentinel_tpu.utils.time_source import mono_s

    assert not FP._ARMED
    n = 20_000
    t0 = mono_s()
    for _ in range(n):
        FP.hit("cluster.rpc.send")
    elapsed = mono_s() - t0
    assert elapsed / n < 5e-6, f"disarmed-site cost {elapsed / n * 1e9:.0f} ns/call"


# ---------------------------------------------------------------------------
# plans: JSON round-trip, validation, schedules
# ---------------------------------------------------------------------------


def test_plan_json_roundtrip():
    plan = FaultPlan(
        name="demo",
        seed=42,
        faults=[
            FaultSpec("cluster.rpc.send", "raise", burst_start=2, burst_len=3),
            FaultSpec("cluster.rpc.recv", "corrupt", probability=0.25),
            FaultSpec("runtime.tick.clock", "clock_skew", every_nth=4, skew_ms=500),
        ],
    )
    assert FaultPlan.from_json(plan.to_json()) == plan


def test_plan_validation_rejects_unknown_site_action_and_exc():
    with pytest.raises(ValueError):
        FaultPlan(faults=[FaultSpec("cluster.rpc.nope", "raise")]).validate(FP.catalog())
    with pytest.raises(ValueError):
        # hit-style site does not honor byte mangling
        FaultPlan(faults=[FaultSpec("cluster.token.decide", "drop")]).validate(
            FP.catalog()
        )
    with pytest.raises(ValueError):
        FaultPlan(
            faults=[FaultSpec("cluster.token.decide", "raise", exc="KeyboardInterrupt")]
        ).validate(FP.catalog())
    with pytest.raises(ValueError):
        # a lone burst_start would fire every hit, not a window
        FaultPlan(
            faults=[FaultSpec("cluster.token.decide", "raise", burst_start=5)]
        ).validate(FP.catalog())


def test_schedule_gates_and_actions():
    site = "cluster.token.decide"
    plan = FaultPlan(
        seed=9,
        faults=[FaultSpec(site, "raise", every_nth=3, max_fires=2, exc="ValueError")],
    )
    fired = []
    with FP.armed(plan) as st:
        for i in range(12):
            try:
                FP.hit(site)
            except ValueError:
                fired.append(i)
        assert st.hit_counts()[site] == 12
    assert fired == [2, 5]  # every 3rd hit, capped at 2 fires
    assert st.injected() == {f"{site}:raise": 2}
    # the event log records each fire's (site, action, site-hit index) —
    # the replay-confirmation trail a failing chaos run is debugged from
    assert st.events == [(site, "raise", 2), (site, "raise", 5)]


def test_pipe_actions_drop_corrupt_short_read_and_skew():
    data = bytes(range(32))
    with FP.armed(
        FaultPlan(seed=1, faults=[FaultSpec("cluster.rpc.send", "drop", max_fires=1)])
    ):
        assert FP.pipe("cluster.rpc.send", data) == b""
        assert FP.pipe("cluster.rpc.send", data) == data  # max_fires spent
    with FP.armed(
        FaultPlan(seed=1, faults=[FaultSpec("cluster.rpc.send", "corrupt")])
    ):
        mangled = FP.pipe("cluster.rpc.send", data)
        assert len(mangled) == len(data) and mangled != data
    with FP.armed(
        FaultPlan(seed=1, faults=[FaultSpec("cluster.rpc.send", "short_read")])
    ):
        short = FP.pipe("cluster.rpc.send", data)
        assert 1 <= len(short) < len(data)
        assert short == data[: len(short)]
    with FP.armed(
        FaultPlan(
            seed=1,
            faults=[FaultSpec("runtime.tick.clock", "clock_skew", skew_ms=1500)],
        )
    ):
        assert FP.skew_ms("runtime.tick.clock") == 1500
    assert FP.skew_ms("runtime.tick.clock") == 0  # disarmed


def test_probability_schedule_replays_from_seed():
    site = "cluster.token.decide"

    def pattern(seed: int):
        plan = FaultPlan(
            seed=seed, faults=[FaultSpec(site, "raise", probability=0.5)]
        )
        out = []
        with FP.armed(plan):
            for i in range(64):
                try:
                    FP.hit(site)
                    out.append(0)
                except OSError:
                    out.append(1)
        return out

    a, b = pattern(123), pattern(123)
    assert a == b, "same seed must replay the exact decision stream"
    assert 0 < sum(a) < 64  # actually probabilistic, not constant


def test_arm_is_exclusive_and_disarm_idempotent():
    plan = FaultPlan(seed=0, faults=[])
    st = FP.arm(plan)
    with pytest.raises(RuntimeError):
        FP.arm(plan)
    assert FP.disarm() is st
    assert FP.disarm() is None


# ---------------------------------------------------------------------------
# fail-closed resolve hardening (runtime/client._fail_tick)
# ---------------------------------------------------------------------------


def test_resolve_failure_fails_entries_closed_not_stranded(client_factory):
    """An injected fan-out failure must surface as an immediate
    SystemBlockException — never an entry_timeout_s hang."""
    c = client_factory()
    c.registry.resource_id("chaos/ft")
    f = c.submit_acquire("chaos/ft")
    if f is not None:
        f.result(timeout=60.0)  # prime the compile outside the plan
    plan = FaultPlan(
        seed=2,
        faults=[FaultSpec("runtime.resolve.fanout", "raise", max_fires=1)],
    )
    t0 = time.perf_counter()
    with FP.armed(plan):
        with pytest.raises(ERR.SystemBlockException):
            c.entry("chaos/ft")
    assert time.perf_counter() - t0 < c.entry_timeout_s, "fail-closed, not timeout"
    # the engine recovered: the next entry serves normally
    e = c.entry("chaos/ft")
    e.exit()


# ---------------------------------------------------------------------------
# satellite: RemoteShard mid-window partition via failpoint sites
# ---------------------------------------------------------------------------


class _MarkerFallback:
    """Fallback whose verdicts carry wait_ms=7 so remote vs degraded
    decisions are distinguishable in the combined result."""

    def __init__(self):
        self.batches = []

    def check_batch(self, resources, **kw):
        self.batches.append(list(resources))
        return [(ERR.PASS, 7)] * len(resources)


def test_remote_shard_mid_window_partition_no_replay():
    """Socket drop between chunk dispatch and reply, through the REAL
    transport and the new failpoint sites (no monkeypatching): answered
    chunks keep their remote verdicts, written-but-unanswered chunks
    degrade to the fallback, and the shard host never sees a chunk
    twice."""
    from sentinel_tpu.chaos.runner import _make_token_server
    from sentinel_tpu.obs.registry import REGISTRY
    from sentinel_tpu.parallel.remote_shard import RemoteShard

    decision, svc, server = _make_token_server(flow_count=100.0)
    fb = _MarkerFallback()
    shard = RemoteShard(
        "127.0.0.1", server.port, timeout_s=2.0, fallback=fb, retry_interval_s=60.0
    )
    shard.CHUNK = 4
    names = [f"chaos/part{i}" for i in range(12)]
    answered0 = REGISTRY.counter("sentinel_shard_chunks_total").value
    degraded0 = REGISTRY.counter("sentinel_shard_chunks_degraded_total").value

    def _server_chunks(st, want, deadline_s=10.0):
        from sentinel_tpu.utils.time_source import mono_s

        deadline = mono_s() + deadline_s
        while (
            st.hit_counts().get("cluster.server.process", 0) < want
            and mono_s() < deadline
        ):
            time.sleep(0.01)
        return st.hit_counts().get("cluster.server.process", 0)

    try:
        # healthy window: 3 chunks served remotely
        with FP.armed(FaultPlan(seed=0, faults=[])) as st:
            out_a = shard.check_batch(names)
            seen_a = _server_chunks(st, 3)
        # partition mid-window: first reply read drops -> peer-closed ->
        # every in-flight chunk forfeited, degraded, NOT re-sent
        plan = FaultPlan(
            seed=0,
            faults=[FaultSpec("parallel.shard.recv", "drop", max_fires=1)],
        )
        with FP.armed(plan) as st:
            out_b = shard.check_batch(names)
            seen_b = _server_chunks(st, 3)
    finally:
        shard.close()
        server.stop()
        decision.stop()

    assert [w for _v, w in out_a] == [0] * 12  # remote verdicts, no marker
    assert [w for _v, w in out_b] == [7] * 12  # every span degraded locally
    assert fb.batches == [names[0:4], names[4:8], names[8:12]]
    # no replay: the server processed each written chunk at most once
    assert seen_a == 3 and seen_b == 3
    answered = REGISTRY.counter("sentinel_shard_chunks_total").value - answered0
    degraded = (
        REGISTRY.counter("sentinel_shard_chunks_degraded_total").value - degraded0
    )
    assert (answered, degraded) == (3, 3)
    assert shard._down_until > 0.0  # mid-exchange death armed the cool-down


# ---------------------------------------------------------------------------
# satellite: front-door unenforceable-rule counter
# ---------------------------------------------------------------------------


def test_front_door_unenforceable_param_rule_counts(client_factory):
    """A decision param rule whose param_idx 0 lost its hash lane (lanes
    claimed by gateway rules) must increment the registry counter, not
    only log; a healthy rule maps without counting."""
    from sentinel_tpu.cluster.front_door import _C_UNENFORCEABLE, resolve_param_lane
    from sentinel_tpu.cluster.rules import param_resource
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.core import rules as R
    from sentinel_tpu.obs.registry import REGISTRY

    decision = client_factory()
    svc = DefaultTokenService(decision)
    name = param_resource(7)
    # gateway rules claim both hash lanes of the shared resource first,
    # so the cluster decision rule's param_idx 0 gets none
    decision.gateway_param_rules.load(
        [
            R.ParamFlowRule(resource=name, count=5.0, param_idx=1),
            R.ParamFlowRule(resource=name, count=5.0, param_idx=2),
        ]
    )
    svc.param_rules.load(
        "default",
        [
            R.ParamFlowRule(
                resource="res-7", count=3.0, cluster_mode=True, cluster_flow_id=7
            )
        ],
    )
    before = _C_UNENFORCEABLE.value
    assert resolve_param_lane(svc, 7, name) is None
    assert _C_UNENFORCEABLE.value == before + 1
    # visible on the /metrics surface
    assert "sentinel_front_door_unenforceable_rules" in REGISTRY.exposition()

    # healthy service: lane resolves, nothing counted
    decision2 = client_factory()
    svc2 = DefaultTokenService(decision2)
    svc2.param_rules.load(
        "default",
        [
            R.ParamFlowRule(
                resource="res-8", count=3.0, cluster_mode=True, cluster_flow_id=8
            )
        ],
    )
    before2 = _C_UNENFORCEABLE.value
    assert resolve_param_lane(svc2, 8, param_resource(8)) == 0
    assert _C_UNENFORCEABLE.value == before2


# ---------------------------------------------------------------------------
# satellite: labeled cluster RPC failure kinds
# ---------------------------------------------------------------------------


def test_rpc_failure_kind_connect_refused():
    from sentinel_tpu.cluster import constants as C
    from sentinel_tpu.cluster.client import ClusterTokenClient
    from sentinel_tpu.obs.registry import REGISTRY

    c_connect = REGISTRY.counter(
        "sentinel_cluster_rpc_failures_total", labels={"kind": "connect"}
    )
    before = c_connect.value
    tok = ClusterTokenClient("127.0.0.1", 1, timeout_ms=200)  # nothing listens
    try:
        assert tok.request_token(5).status == C.STATUS_FAIL
    finally:
        tok.close()
    assert c_connect.value == before + 1


def test_rpc_failure_kind_send_via_failpoint():
    """An injected send failure lands on kind=send — the label chaos
    scenarios assert to prove WHICH fault fired."""
    from sentinel_tpu.chaos.runner import _make_token_server
    from sentinel_tpu.cluster import constants as C
    from sentinel_tpu.cluster.client import ClusterTokenClient
    from sentinel_tpu.obs.registry import REGISTRY

    c_send = REGISTRY.counter(
        "sentinel_cluster_rpc_failures_total", labels={"kind": "send"}
    )
    decision, svc, server = _make_token_server(flow_count=100.0)
    tok = ClusterTokenClient("127.0.0.1", server.port, timeout_ms=3000)
    tok.reconnect_interval_s = 0.0  # no throttle: reconnect right after the fault
    tok.start()
    before = c_send.value
    plan = FaultPlan(
        seed=0, faults=[FaultSpec("cluster.rpc.send", "raise", max_fires=1)]
    )
    try:
        with FP.armed(plan):
            assert tok.request_token(101).status == C.STATUS_FAIL
        assert tok.request_token(101).status == C.STATUS_OK  # reconnects
    finally:
        tok.close()
        server.stop()
        decision.stop()
    assert c_send.value == before + 1


# ---------------------------------------------------------------------------
# scenarios: tier-1 fast subset + determinism; full matrix under slow
# ---------------------------------------------------------------------------

def _fast_scenarios():
    # single source of truth: the Scenario.fast flags in the runner —
    # the CLI --fast subset and the tier-1 subset can never diverge
    from sentinel_tpu.chaos.runner import SCENARIOS

    return [n for n, s in SCENARIOS.items() if s.fast]


_FAST_SCENARIOS = _fast_scenarios()


@pytest.mark.parametrize("name", _FAST_SCENARIOS)
def test_fast_scenario_invariants_green(name):
    from sentinel_tpu.chaos.runner import report, run_scenario

    r = run_scenario(name, seed=7)
    assert r.ok, report([r])


def test_scenario_determinism_fast():
    """Two same-seed runs of a scenario inject identical event counts."""
    from sentinel_tpu.chaos.runner import run_scenario

    a = run_scenario("datasource_flap", seed=11)
    b = run_scenario("datasource_flap", seed=11)
    assert a.injected == b.injected and a.injected


def test_cli_list_and_sites(capsys):
    from sentinel_tpu.chaos.__main__ import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in ("rpc_error_burst", "seg_overflow_storm", "shard_reconnect"):
        assert name in out
    assert main(["--sites"]) == 0
    out = capsys.readouterr().out
    assert "cluster.rpc.send" in out and "runtime.resolve.readback" in out


@pytest.mark.slow
def test_full_scenario_matrix_and_determinism():
    from sentinel_tpu.chaos.runner import report, run_all

    first = run_all(seed=7)
    assert len(first) >= 6
    assert all(r.ok for r in first), report([r for r in first if not r.ok])
    again = run_all(seed=7)
    assert [r.injected for r in first] == [r.injected for r in again]
