"""Sharded engine over the virtual 8-device CPU mesh.

Verifies that the tick jitted with row-sharded state produces the same
verdicts as the single-device engine (the multi-chip path of SURVEY.md
§2.9: data parallelism over the resource axis via sharding annotations).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.ops import engine as E
from sentinel_tpu.parallel import spmd
from sentinel_tpu.runtime.registry import Registry


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs multi-device mesh")
def test_sharded_tick_matches_single_device():
    cfg = small_engine_config()
    reg = Registry(cfg)
    rules = E.compile_ruleset(
        cfg,
        reg,
        flow_rules=[
            FlowRule(resource=f"res-{i}", count=5 + i) for i in range(10)
        ],
    )
    rids = [reg.peek_resource_id(f"res-{i}") for i in range(10)]

    mesh = spmd.make_mesh(8)
    tick_sh = spmd.make_sharded_tick(cfg, mesh, donate=False)
    tick_1 = E.make_tick(cfg, donate=False)

    state_1 = E.init_state(cfg)
    state_sh = spmd.shard_state(E.init_state(cfg), cfg, mesh)

    rng = np.random.default_rng(3)
    for t in (100, 300, 900, 1600):
        res = rng.choice(rids, size=cfg.batch_size).astype(np.int32)
        acq = E.empty_acquire(cfg)._replace(
            res=jnp.asarray(res),
            count=jnp.ones(cfg.batch_size, dtype=jnp.int32),
        )
        comp = E.empty_complete(cfg)
        now = jnp.int32(t)
        state_1, out_1 = tick_1(
            state_1, rules, acq, comp, now, jnp.float32(0), jnp.float32(0)
        )
        state_sh, out_sh = tick_sh(
            state_sh, rules, acq, comp, now, jnp.float32(0), jnp.float32(0)
        )
        np.testing.assert_array_equal(
            np.asarray(out_1.verdict), np.asarray(out_sh.verdict)
        )

    # sharded state really is distributed over the mesh
    shards = state_sh.win_sec.counts.sharding
    assert shards.spec == jax.sharding.PartitionSpec("res")
