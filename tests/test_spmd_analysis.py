"""sentinel_tpu.analysis.spmd — the tier-4 SPMD/sharding analyzer.

Three jobs, mirroring the tier-3 suite:

1. unit-test every pass on synthetic :class:`SpmdProgram` fixtures — one
   triggering and one clean per rule (a NEW collective vs the golden, a
   full-leaf and a slice-of-sharded-dim all-gather, an oversized
   replicated const/leaf, an indivisible sharded dim, an over-budget
   shard), plus HLO parsing, the golden round-trip, and the scoped
   ``--update-baseline`` contract;
2. THE CI GATE: run the whole tier against the real repo — zero
   findings, the committed ``collectives.json`` must exactly match the
   worker's current inventory, and the projected 1M-resource per-shard
   footprint must clear the HBM capacity SLO;
3. topology hygiene: lowering under the forced 8-device mesh happens in
   a SUBPROCESS, so the calling process's jax device count must be
   byte-for-byte unchanged after a full tier-4 run.

The fixture tests are pure plain-data work (no jax); the gate pays one
worker subprocess (~10 s, cached per process) shared across tests.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from sentinel_tpu.analysis import REPO_ROOT, rule_catalog
from sentinel_tpu.analysis.framework import format_sarif
from sentinel_tpu.analysis.spmd import (
    COLLECTIVES_PATH,
    build_program,
    capacity_slo_bytes,
    run_spmd_analysis,
    update_collectives,
)
from sentinel_tpu.analysis.spmd.framework import (
    Collective,
    ConfigCase,
    ConstInfo,
    LeafPlacement,
    ShardedEntry,
    SpmdProgram,
    group_collectives,
    ledger_bytes,
    parse_hlo_collectives,
)
from sentinel_tpu.analysis.spmd.passes import (
    ALL_SPMD_PASSES,
    CollectiveLedgerPass,
    ImplicitReshardPass,
    ReplicationHazardPass,
    ShardDivisibilityPass,
    ShardHbmBudgetPass,
)
from sentinel_tpu.parallel.meshspec import force_cpu_mesh_env, mesh_spec

N = mesh_spec().n_devices


def _leaf(name, shape, spec, itemsize=4, dtype="float32"):
    """LeafPlacement with the byte math the real fold performs."""
    g = itemsize
    s = itemsize
    for d, a in zip(shape, spec):
        g *= d
        s *= -(-d // N) if a is not None else d
    return LeafPlacement(
        name=name, dtype=dtype, shape=tuple(shape), spec=tuple(spec),
        global_bytes=g, shard_bytes=s,
    )


def _prog(**kw):
    kw.setdefault("n_devices", N)
    kw.setdefault("axis", mesh_spec().axis)
    return SpmdProgram(**kw)


def _golden_for(*entries):
    """A golden dict that exactly pins the given entries' inventories."""
    out = {}
    for e in entries:
        groups = group_collectives(e.collectives)
        out[e.name] = {
            "collectives": groups,
            "bytes_per_tick": ledger_bytes(groups),
        }
    return {"entries": out}


def _run(p, program):
    return list(p.run(program))


# ---------------------------------------------------------------------------
# HLO parsing
# ---------------------------------------------------------------------------

_HLO = textwrap.dedent(
    """\
    %all-gather.1 = s32[2,512]{1,0} all-gather(s32[2,64]{1,0} %p), dimensions={1}, metadata={op_name="x" source_file="@ROOT@/sentinel_tpu/ops/tables.py" source_line=255}
    %ar = f32[63]{0} all-reduce(f32[63]{0} %q), to_apply=%add
    %ag2 = s32[2,512]{1,0} all-gather-start(s32[2,64]{1,0} %r), dimensions={1}
    %cp = s32[7,5]{1,0} collective-permute(s32[7,5]{1,0} %s), source_target_pairs={{0,1}}
    %elsewhere = f32[8]{0} all-reduce(f32[8]{0} %t), metadata={source_file="/somewhere/else/x.py" source_line=3}
    """
).replace("@ROOT@", REPO_ROOT)


def test_parse_hlo_collectives_kinds_shapes_and_sources():
    colls = parse_hlo_collectives(_HLO, REPO_ROOT)
    assert [(c.kind, c.dtype, c.shape) for c in colls] == [
        ("all-gather", "s32", (2, 512)),
        ("all-reduce", "f32", (63,)),
        ("all-gather", "s32", (2, 512)),  # -start folds into the base kind
        ("collective-permute", "s32", (7, 5)),
        ("all-reduce", "f32", (8,)),
    ]
    # in-repo source metadata is relativized; out-of-repo dropped
    assert colls[0].source == "sentinel_tpu/ops/tables.py"
    assert colls[0].line == 255
    assert colls[4].source is None
    assert colls[0].nbytes == 2 * 512 * 4


def test_group_collectives_merges_and_ignores_source_lines():
    a = Collective("all-gather", "s32", (2, 512), "f.py", 10)
    b = Collective("all-gather", "s32", (2, 512), "g.py", 99)
    groups = group_collectives([a, b])
    assert len(groups) == 1
    assert groups[0]["count"] == 2
    assert groups[0]["bytes_each"] == 4096
    assert ledger_bytes(groups) == 8192


# ---------------------------------------------------------------------------
# collective-ledger
# ---------------------------------------------------------------------------


def _entry(name="tick/fix", colls=()):
    return ShardedEntry(name=name, collectives=list(colls))


def test_ledger_clean_when_inventory_matches_golden():
    e = _entry(colls=[Collective("all-gather", "s32", (2, 512))] * 2)
    prog = _prog(entries=[e], golden=_golden_for(e))
    assert _run(CollectiveLedgerPass(), prog) == []


def test_ledger_new_collective_is_error():
    e = _entry(colls=[Collective("all-gather", "s32", (2, 512))])
    golden = _golden_for(_entry(colls=[]))
    prog = _prog(entries=[e], golden=golden)
    found = _run(CollectiveLedgerPass(), prog)
    new = [f for f in found if "NEW collective" in f.message]
    assert len(new) == 1
    f = new[0]
    assert f.rule == "collective-ledger" and f.severity == "error"
    assert f.path == "spmd://tick/fix"
    assert "all-gather" in f.message
    # the added bytes also blow the pinned total — both angles report
    assert any("bytes/tick" in f.message for f in found)


def test_ledger_count_growth_is_error():
    pinned = _entry(colls=[Collective("all-reduce", "f32", (63,))])
    cur = _entry(colls=[Collective("all-reduce", "f32", (63,))] * 3)
    prog = _prog(entries=[cur], golden=_golden_for(pinned))
    found = _run(CollectiveLedgerPass(), prog)
    # count growth AND the byte total blowing past tolerance
    assert any("count grew 1 -> 3" in f.message for f in found)


def test_ledger_bytes_regression_past_tolerance():
    e = _entry(colls=[Collective("all-gather", "s32", (2, 512))] * 2)
    golden = _golden_for(e)
    # same inventory, but the pinned byte total was smaller: regression
    golden["entries"]["tick/fix"]["bytes_per_tick"] = 1000
    found = _run(CollectiveLedgerPass(), _prog(entries=[e], golden=golden))
    assert len(found) == 1
    assert "bytes/tick" in found[0].message and "ceiling" in found[0].message


def test_ledger_within_tolerance_is_clean():
    e = _entry(colls=[Collective("all-gather", "s32", (2, 512))] * 2)
    golden = _golden_for(e)
    golden["entries"]["tick/fix"]["bytes_per_tick"] = 8000  # 8192 < 8000*1.25
    assert _run(CollectiveLedgerPass(), _prog(entries=[e], golden=golden)) == []


def test_ledger_stale_golden_entry_and_unpinned_entry():
    e = _entry(name="tick/live", colls=[])
    golden = _golden_for(_entry(name="tick/gone", colls=[]))
    found = _run(CollectiveLedgerPass(), _prog(entries=[e], golden=golden))
    msgs = "\n".join(f.message for f in found)
    assert "no pinned collective ledger" in msgs  # tick/live unpinned
    assert "stale pin" in msgs  # tick/gone no longer lowered
    paths = {f.path for f in found}
    assert "spmd://tick/gone" in paths


def test_ledger_missing_golden_is_one_loud_error():
    prog = _prog(entries=[_entry()], golden=None)
    found = _run(CollectiveLedgerPass(), prog)
    assert len(found) == 1
    assert "--update-collectives" in found[0].message


def test_worker_error_surfaces_once_and_quiets_hlo_passes():
    prog = _prog(worker_error="boom: exit 3", golden={"entries": {}})
    found = _run(CollectiveLedgerPass(), prog)
    assert len(found) == 1 and "boom" in found[0].message
    assert found[0].path == "spmd://analyzer"
    assert _run(ImplicitReshardPass(), prog) == []
    # the placement passes still run (they need no HLO)
    assert _run(ShardDivisibilityPass(), prog) == []


# ---------------------------------------------------------------------------
# implicit-reshard
# ---------------------------------------------------------------------------


def test_reshard_full_leaf_rematerialization():
    leaf = _leaf(".tab", (8, 512), (None, "res"))  # 16 KiB global
    e = ShardedEntry(
        name="tick/fix",
        collectives=[
            Collective("all-gather", "f32", (8, 512), "sentinel_tpu/x.py", 7)
        ],
        placements=[leaf],
    )
    found = _run(ImplicitReshardPass(), _prog(entries=[e]))
    assert len(found) == 1
    f = found[0]
    assert f.path == "sentinel_tpu/x.py" and f.line == 7
    assert "re-materializes the full sharded leaf .tab" in f.message


def test_reshard_slice_of_sharded_dim_is_caught():
    """The salsa-read class: the gather result is only a SLICE of the
    leaf, but it spans the sharded dimension at global size."""
    leaf = _leaf(".gs.run", (2, 8, 512), (None, None, "res"), dtype="int32")
    e = ShardedEntry(
        name="tick/fix",
        collectives=[
            Collective("all-gather", "s32", (2, 512), "sentinel_tpu/y.py", 9)
        ],
        placements=[leaf],
    )
    found = _run(ImplicitReshardPass(), _prog(entries=[e]))
    assert len(found) == 1
    f = found[0]
    assert f.path == "sentinel_tpu/y.py" and f.line == 9
    assert "full sharded dimension of .gs.run" in f.message


def test_reshard_small_gather_and_nonmatching_dims_are_clean():
    leaf = _leaf(".gs.run", (2, 8, 512), (None, None, "res"), dtype="int32")
    e = ShardedEntry(
        name="tick/fix",
        collectives=[
            # 256 B: below the match floor even though 64 is a real dim
            Collective("all-gather", "s32", (64,), "sentinel_tpu/z.py", 1),
            # large dims but none is a sharded-dim size: no slice match
            Collective("all-gather", "s32", (3, 100)),
        ],
        placements=[leaf],
    )
    assert _run(ImplicitReshardPass(), _prog(entries=[e])) == []


def test_reshard_big_unmatched_gather_is_flagged():
    e = ShardedEntry(
        name="tick/fix",
        collectives=[Collective("all-gather", "f32", (1 << 16,))],  # 256 KiB
    )
    found = _run(ImplicitReshardPass(), _prog(entries=[e]))
    assert len(found) == 1
    assert "large all-gather" in found[0].message
    assert found[0].path == "spmd://tick/fix"  # no source metadata


# ---------------------------------------------------------------------------
# replication-hazard
# ---------------------------------------------------------------------------


def test_replicated_const_over_threshold_is_error():
    e = ShardedEntry(
        name="tick/fix",
        consts=[ConstInfo("f32", (512, 512), 512 * 512 * 4)],  # 1 MiB
    )
    found = _run(ReplicationHazardPass(), _prog(entries=[e]))
    assert len(found) == 1
    assert "jaxpr const" in found[0].message
    assert found[0].path == "spmd://tick/fix"


def test_small_const_and_sharded_big_leaf_are_clean():
    e = ShardedEntry(name="tick/fix", consts=[ConstInfo("f32", (64,), 256)])
    big_but_sharded = _leaf(".win.counts", (1 << 22, 4), ("res", None))
    case = ConfigCase(name="bench/big", placements=[big_but_sharded])
    prog = _prog(entries=[e], configs=[case])
    assert _run(ReplicationHazardPass(), prog) == []


def test_replicated_big_leaf_at_config_scale_is_error():
    lazy = _leaf(".gs.words", (4, 1 << 21), (None, None))  # 32 MiB replicated
    case = ConfigCase(name="bench/sketch-1m", placements=[lazy])
    found = _run(ReplicationHazardPass(), _prog(configs=[case]))
    assert len(found) == 1
    f = found[0]
    assert f.path == "spmd://config/bench/sketch-1m"
    assert ".gs.words" in f.message and "replicated" in f.message


# ---------------------------------------------------------------------------
# shard-divisibility
# ---------------------------------------------------------------------------


def test_indivisible_sharded_dim_is_error():
    bad = _leaf(".win.counts", (137, 4), ("res", None))
    case = ConfigCase(name="engine/odd", placements=[bad])
    found = _run(ShardDivisibilityPass(), _prog(configs=[case]))
    assert len(found) == 1
    f = found[0]
    assert f.rule == "shard-divisibility"
    assert "137" in f.message and f"{N}-device" in f.message


def test_divisible_and_replicated_dims_are_clean():
    case = ConfigCase(
        name="engine/even",
        placements=[
            _leaf(".a", (136, 4), ("res", None)),
            _leaf(".b", (137, 4), (None, None)),  # odd but replicated
        ],
    )
    assert _run(ShardDivisibilityPass(), _prog(configs=[case])) == []


# ---------------------------------------------------------------------------
# shard-hbm-budget
# ---------------------------------------------------------------------------


def test_budget_overflow_names_the_heaviest_leaves():
    case = ConfigCase(
        name="bench/sketch-1m",
        placements=[
            _leaf(".big", (1 << 20, 8), ("res", None)),  # 4 MiB/shard
            _leaf(".small", (64,), (None,)),
        ],
    )
    prog = _prog(
        configs=[case], budget_config="bench/sketch-1m",
        capacity_bytes=1 << 20,
    )
    found = _run(ShardHbmBudgetPass(), prog)
    assert len(found) == 1
    f = found[0]
    assert f.path == "spmd://config/bench/sketch-1m"
    assert ".big" in f.message and "capacity SLO" in f.message


def test_budget_under_capacity_is_clean_and_missing_case_is_loud():
    case = ConfigCase(
        name="bench/sketch-1m",
        placements=[_leaf(".t", (1024,), ("res",))],
    )
    ok = _prog(
        configs=[case], budget_config="bench/sketch-1m",
        capacity_bytes=1 << 30,
    )
    assert _run(ShardHbmBudgetPass(), ok) == []
    wired_wrong = _prog(configs=[], budget_config="bench/sketch-1m",
                        capacity_bytes=1 << 30)
    found = _run(ShardHbmBudgetPass(), wired_wrong)
    assert len(found) == 1 and "wiring" in found[0].message


# ---------------------------------------------------------------------------
# meshspec: the one shared topology contract
# ---------------------------------------------------------------------------


def test_force_cpu_mesh_env_fresh_environment():
    env = {}
    n = force_cpu_mesh_env(env)
    assert n == N
    assert env["JAX_PLATFORMS"] == "cpu"
    assert f"--xla_force_host_platform_device_count={N}" in env["XLA_FLAGS"]


def test_force_cpu_mesh_env_keep_existing_count():
    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    n = force_cpu_mesh_env(env, keep_existing_count=True)
    assert n == 4
    assert "device_count=4" in env["XLA_FLAGS"]
    # without the keep flag the blessed width wins (and dupes collapse)
    n2 = force_cpu_mesh_env(env)
    assert n2 == N
    assert env["XLA_FLAGS"].count("device_count") == 1


def test_runtime_mesh_axis_comes_from_meshspec():
    """Every axis any runtime PartitionSpec names IS the meshspec axis —
    the analyzer and the runtime cannot shard on different names."""
    import jax
    from jax.sharding import PartitionSpec as PS

    from sentinel_tpu.core.config import EngineConfig
    from sentinel_tpu.parallel import spmd

    specs = spmd.state_partition_specs(EngineConfig())
    leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, PS)
    )
    axes = {a for ps in leaves for a in ps if a is not None}
    assert axes == {mesh_spec().axis}


# ---------------------------------------------------------------------------
# golden round-trip + scoped baseline update
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_update_collectives_round_trip(tmp_path):
    """--update-collectives writes a reviewable golden that a fresh
    build_program round-trips to zero ledger findings."""
    path = str(tmp_path / "collectives.json")
    n = update_collectives(path)
    assert n == 3  # the three blessed entries
    data = json.loads(open(path).read())
    assert "--update-collectives" in data["comment"]
    assert data["mesh"] == {"axis": mesh_spec().axis, "n_devices": N}
    assert set(data["entries"]) == {
        "tick/sketch-salsa", "window/add-batch", "cluster/token-col",
    }
    prog = build_program(golden_path=path)
    assert _run(CollectiveLedgerPass(), prog) == []


def test_update_baseline_scoped_to_spmd_preserves_other_tiers(tmp_path):
    """--tier spmd --update-baseline must not evict other tiers' accepted
    debt: only spmd-owned entries are in scope for the rewrite."""
    from sentinel_tpu.analysis.__main__ import main

    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"accepted": {"fail-open:sentinel_tpu/foo.py": 2}}))
    rc = main(["--tier", "spmd", "--update-baseline", "--baseline", str(path)])
    assert rc == 0
    kept = json.loads(path.read_text())["accepted"]
    assert kept.get("fail-open:sentinel_tpu/foo.py") == 2
    # the tier itself is clean, so nothing spmd-owned was added
    spmd_rules = {p.name for p in ALL_SPMD_PASSES}
    assert [k for k in kept if k.split(":")[0] in spmd_rules] == []


# ---------------------------------------------------------------------------
# CLI / reporting integration
# ---------------------------------------------------------------------------


def test_rule_catalog_spans_four_tiers():
    cat = rule_catalog()
    for p in ALL_SPMD_PASSES:
        assert p.name in cat and cat[p.name]
    assert len(ALL_SPMD_PASSES) == 5


def test_sarif_spmd_pseudo_paths_claim_no_uri_base():
    e = ShardedEntry(
        name="tick/fix",
        collectives=[Collective("all-gather", "f32", (1 << 16,))],
    )
    case = ConfigCase(
        name="engine/odd",
        placements=[_leaf(".w", (137,), ("res",))],
    )
    prog = _prog(entries=[e], configs=[case], golden=None)
    findings = []
    for p in ALL_SPMD_PASSES:
        findings.extend(p.run(prog))
    assert findings
    doc = json.loads(format_sarif(findings, findings, rule_catalog()))
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"collective-ledger", "implicit-reshard", "shard-divisibility"} <= rule_ids
    locs = [
        r["locations"][0]["physicalLocation"]["artifactLocation"]
        for r in run["results"]
    ]
    pseudo = [l for l in locs if l["uri"].startswith("spmd://")]
    assert pseudo and all("uriBaseId" not in l for l in pseudo)


# ---------------------------------------------------------------------------
# THE repo gate
# ---------------------------------------------------------------------------


def test_repo_gate_zero_findings_golden_matches_and_budget_clears():
    """The CI contract for this tier: the committed collectives.json is
    exactly the current partitioned program's inventory, every reshard/
    replication hazard is fixed or carries a written rationale, and the
    1M-resource per-shard projection clears the capacity SLO."""
    program = build_program()
    assert program.worker_error is None, program.worker_error

    golden = json.loads(open(COLLECTIVES_PATH).read())
    assert set(golden["entries"]) == {e.name for e in program.entries}
    for e in program.entries:
        g = golden["entries"][e.name]
        cur = group_collectives(e.collectives)
        assert cur == g["collectives"], f"{e.name}: ledger drifted — review, then --update-collectives"
        assert ledger_bytes(cur) == g["bytes_per_tick"]

    case = program.budget_case()
    assert case is not None and case.shard_bytes > 0
    assert case.shard_bytes < capacity_slo_bytes()

    findings = run_spmd_analysis(program=program)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line} [{f.rule}] {f.message}" for f in findings
    )


def test_known_salsa_read_reshard_is_pinned_and_rationalized():
    """The hazard this tier found: the salsa running-sum read flattens
    the width-sharded table, so XLA all-gathers the full [depth, width]
    slice each tick.  It must stay pinned in the golden (2 gathers) and
    carry a written rationale at the flatten site — if either goes, the
    analyzer's empty-findings gate above is lying."""
    golden = json.loads(open(COLLECTIVES_PATH).read())
    tick = golden["entries"]["tick/sketch-salsa"]
    gathers = [
        g for g in tick["collectives"]
        if g["kind"] == "all-gather" and g["shape"] == [2, 512]
    ]
    assert gathers and gathers[0]["count"] == 2
    src = open(os.path.join(REPO_ROOT, "sentinel_tpu/ops/tables.py")).read()
    assert "stlint: disable-next-line=implicit-reshard" in src


def test_tier4_baseline_is_empty():
    """Tier 4 launched with ZERO accepted debt — hazards get fixed or a
    written rationale, never a baseline bump."""
    from sentinel_tpu.analysis import DEFAULT_BASELINE, load_baseline

    spmd_rules = {p.name for p in ALL_SPMD_PASSES}
    offenders = [
        k for k in load_baseline(DEFAULT_BASELINE) if k.split(":")[0] in spmd_rules
    ]
    assert offenders == []


def test_spmd_gauges_exported_on_registry():
    """The analyzer's measurements ride the obs registry so the
    profiling plane and the README catalog can see them."""
    from sentinel_tpu.obs.registry import REGISTRY

    build_program()  # cached worker; idempotent re-export
    series = REGISTRY.series("sentinel_spmd_collective_bytes_per_tick")
    entries = {dict(m.labels)["entry"]: m.value for m in series}
    assert "tick/sketch-salsa" in entries
    assert entries["tick/sketch-salsa"] > 0
    hbm = REGISTRY.get("sentinel_spmd_shard_hbm_projected_bytes")
    assert hbm is not None and 0 < hbm.value < capacity_slo_bytes()


# ---------------------------------------------------------------------------
# topology hygiene: tier-4 never touches the parent's devices
# ---------------------------------------------------------------------------


def test_parent_device_topology_unchanged_by_tier4_run():
    """The worker forces an 8-device CPU platform in a SUBPROCESS; the
    tier-1 suite's own jax topology must be identical before and after a
    full tier-4 run (backend re-init inside a live process would poison
    every cached executable)."""
    import jax

    before = [str(d) for d in jax.devices()]
    backend_before = jax.default_backend()
    findings = run_spmd_analysis()  # full tier, worker cached or fresh
    assert [str(d) for d in jax.devices()] == before
    assert jax.default_backend() == backend_before
    assert isinstance(findings, list)
