"""Dashboard-driven cluster assignment round trip
(ClusterAssignServiceImpl.java analog): assign one machine as token
server + the rest as clients in ONE operation, then verify the clients'
token traffic actually flows to the new server.
"""

import json
import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import constants as CC
from sentinel_tpu.cluster import state as CS
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.dashboard import DashboardServer, MachineInfo
from sentinel_tpu.runtime.client import SentinelClient
from sentinel_tpu.transport import SimpleHttpCommandCenter, build_default_handlers


def _machine(name):
    """One 'machine': threaded client + token service + cluster state +
    command center on an ephemeral port."""
    client = SentinelClient(cfg=small_engine_config(), mode="threaded", tick_interval_ms=2.0)
    client.start()
    svc = DefaultTokenService(client)
    svc.flow_rules.load(
        "default",
        [
            st.FlowRule(
                resource="res-101", count=3.0, cluster_mode=True, cluster_flow_id=101
            )
        ],
    )
    cluster = CS.ClusterStateManager()
    cluster._embedded = svc
    cc = SimpleHttpCommandCenter(
        build_default_handlers(client, cluster=cluster), host="127.0.0.1", port=0
    )
    cc.start()
    return client, svc, cluster, cc


@pytest.fixture()
def assign_world():
    a = _machine("a")
    b = _machine("b")
    dash = DashboardServer(host="127.0.0.1", port=0, fetch_metrics=False)
    for cc in (a[3], b[3]):
        dash.discovery.register(MachineInfo(app="app", ip="127.0.0.1", port=cc.port))
    dash.start()
    yield a, b, dash
    dash.stop()
    for client, _svc, cluster, cc in (a, b):
        cc.stop()
        cluster.stop()
        client.stop()


def test_assign_round_trip(assign_world):
    (ca, sa, cla, cca), (cb, sb, clb, ccb), dash = assign_world

    body = json.dumps(
        {
            "server": {"ip": "127.0.0.1", "port": cca.port},
            "clients": [{"ip": "127.0.0.1", "port": ccb.port}],
        }
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{dash.port}/cluster/assign",
        data=body,
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=20) as rsp:
        out = json.loads(rsp.read())

    # machines flipped
    assert cla.mode == CS.CLUSTER_SERVER
    assert clb.mode == CS.CLUSTER_CLIENT
    assert out["server"]["tokenPort"] > 0
    assert out["clients"][0]["ok"] is True

    # the client machine's token traffic reaches the new server: count=3
    tok = clb._token_client
    statuses = [tok.request_token(101).status for _ in range(5)]
    assert statuses.count(CC.STATUS_OK) == 3
    assert statuses.count(CC.STATUS_BLOCKED) == 2


def test_assign_rejects_unknown_machines(assign_world):
    (_a, _sa, _cla, _cca), _b, dash = assign_world
    body = json.dumps(
        {"server": {"ip": "10.9.9.9", "port": 1}, "clients": []}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{dash.port}/cluster/assign",
        data=body,
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
