"""Custom processor-slot SPI (VERDICT r2 missing #5): ordered slots with
entry AND exit hooks — ProcessorSlot.java:29 / sentinel-demo-slot-chain-spi
semantics on the host side of the batched engine."""

from __future__ import annotations

import pytest

from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.runtime.slots import ProcessorSlot, SlotContext


class Recorder(ProcessorSlot):
    def __init__(self, name, order=0, log=None, block=None):
        self.name = name
        self.order = order
        self.log = log if log is not None else []
        self.block = block

    def on_entry(self, ctx: SlotContext):
        self.log.append(("entry", self.name, ctx.resource))
        ctx.attachments.setdefault("path", []).append(self.name)
        if self.block is not None and self.block(ctx):
            raise ERR.FlowException(ctx.resource)

    def on_exit(self, ctx: SlotContext):
        self.log.append(
            (
                "exit",
                self.name,
                "block" if ctx.block_exception is not None else "ok",
                ctx.errors,
            )
        )


def test_slot_ordering_and_lifo_exit(client):
    log = []
    client.slots.register(Recorder("late", order=100, log=log))
    client.slots.register(Recorder("early", order=-100, log=log))
    client.slots.register(Recorder("mid", order=0, log=log))
    with client.entry("slot-res"):
        pass
    # entry ascending by order, exit reversed (fireExit unwinds LIFO)
    assert [x[:2] for x in log] == [
        ("entry", "early"),
        ("entry", "mid"),
        ("entry", "late"),
        ("exit", "late"),
        ("exit", "mid"),
        ("exit", "early"),
    ]
    assert all(x[2] == "ok" for x in log if x[0] == "exit")


def test_exit_carries_rt_and_errors(client, vt):
    seen = {}

    class Obs(ProcessorSlot):
        def on_exit(self, ctx):
            seen.update(rt=ctx.rt_ms, errors=ctx.errors, success=ctx.success)

    client.slots.register(Obs())
    with pytest.raises(ValueError):
        with client.entry("slot-rt") as e:
            vt.advance(37)
            raise ValueError("biz")
    assert seen == {"rt": 37.0, "errors": 1, "success": 1}


def test_blocking_slot_is_counted_by_engine(client):
    calls = []
    client.slots.register(
        Recorder("guard", log=calls, block=lambda ctx: ctx.args and ctx.args[0] == "vip")
    )
    with client.entry("slot-blk", args=["normal"]):
        pass
    with pytest.raises(ERR.FlowException):
        client.entry("slot-blk", args=["vip"])
    s = client.stats.resource("slot-blk")
    # the slot's rejection flowed through the engine as a pre-verdict:
    # the block is COUNTED (StatisticSlot parity), not just raised
    assert s["passQps"] == 1 and s["blockQps"] == 1
    assert s["curThreadNum"] == 0


def test_blocked_entry_unwinds_entered_slots(client):
    log = []
    client.slots.register(Recorder("a", order=-1, log=log))
    client.slots.register(
        Recorder("blocker", order=0, log=log, block=lambda ctx: True)
    )
    client.slots.register(Recorder("never", order=1, log=log))
    with pytest.raises(ERR.FlowException):
        client.entry("slot-unwind")
    # 'a' entered and must see the exit with the block exception; the
    # raising slot unwinds too (reference CtEntry.exit fires exit through
    # the whole chain, raising slot included); 'never' never ran
    assert ("entry", "a", "slot-unwind") in log
    assert ("exit", "a", "block", 0) in log
    assert ("exit", "blocker", "block", 0) in log
    assert not any(x[1] == "never" for x in log)
    # LIFO: the blocker exits before 'a'
    exits = [x[1] for x in log if x[0] == "exit"]
    assert exits.index("blocker") < exits.index("a")


def test_engine_block_reaches_slot_exit(client, vt):
    log = []
    client.slots.register(Recorder("s", log=log))
    client.flow_rules.load([FlowRule(resource="slot-eng", count=1.0)])
    with client.entry("slot-eng"):
        pass
    with pytest.raises(ERR.BlockException):
        client.entry("slot-eng")
    exits = [x for x in log if x[0] == "exit"]
    assert exits == [("exit", "s", "ok", 0), ("exit", "s", "block", 0)]


def test_attachments_flow_entry_to_exit(client):
    got = {}

    class Tag(ProcessorSlot):
        order = -5

        def on_entry(self, ctx):
            ctx.attachments["trace_id"] = "t-123"

        def on_exit(self, ctx):
            got["trace_id"] = ctx.attachments.get("trace_id")

    client.slots.register(Tag())
    with client.entry("slot-att"):
        pass
    assert got == {"trace_id": "t-123"}


def test_unregister(client):
    log = []
    r = client.slots.register(Recorder("x", log=log))
    client.slots.unregister(r)
    with client.entry("slot-un"):
        pass
    assert log == []
