"""Fused Pallas effects kernels (ops/fused.py): exactness vs oracles and
engine-path equivalence.

On CPU the kernels run in Pallas interpret mode — semantics only; the
device-speed path is exercised by bench.py and the on-TPU equivalence
test (test_tpu_equivalence.py)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from sentinel_tpu.ops import fused as FU


def test_scatter_many_exact_vs_numpy():
    rng = np.random.default_rng(7)
    N = 700
    rows1 = rng.integers(-5, 320, (3, N)).astype(np.int32)
    vals1 = np.stack(
        [
            rng.integers(0, 60000, N),
            rng.integers(0, 2, N),
            rng.integers(0, 40000, N),
        ]
    ).astype(np.int32)
    rows2 = rng.integers(-2, 90, (2, N)).astype(np.int32)
    vals2 = rng.integers(0, 200, (2, 2, N)).astype(np.int32)

    o1, o2 = FU.scatter_many(
        [
            FU.Job("a", 300, jnp.asarray(rows1), jnp.asarray(vals1), (2, 1, 2)),
            FU.Job("b", 77, jnp.asarray(rows2), jnp.asarray(vals2), (1, 1)),
        ],
        tb=256,
        interpret=True,
    )
    ref1 = np.zeros((300, 3), np.int64)
    for r in range(3):
        ok = (rows1[r] >= 0) & (rows1[r] < 300)
        for p in range(3):
            np.add.at(ref1[:, p], rows1[r][ok], vals1[p][ok])
    assert np.array_equal(np.asarray(o1).astype(np.int64), ref1)
    ref2 = np.zeros((77, 2), np.int64)
    for r in range(2):
        ok = (rows2[r] >= 0) & (rows2[r] < 77)
        for p in range(2):
            np.add.at(ref2[:, p], rows2[r][ok], vals2[r, p][ok])
    assert np.array_equal(np.asarray(o2).astype(np.int64), ref2)


def test_gather_many_exact_vs_numpy():
    rng = np.random.default_rng(8)
    N = 500
    ids = rng.integers(-3, 310, N).astype(np.int32)
    tab = rng.integers(0, 1 << 24, (300, 2)).astype(np.int32)
    (g,) = FU.gather_many(
        [FU.GatherJob("g", jnp.asarray(ids), jnp.asarray(tab), (3, 3))],
        tb=256,
        interpret=True,
    )
    ok = (ids >= 0) & (ids < 300)
    ref = np.zeros((N, 2), np.int64)
    ref[ok] = tab[ids[ok]]
    assert np.array_equal(np.asarray(g).astype(np.int64), ref)


def _tick_once(cfg, seed=0, sort_batches=False):
    """Run a few full-feature ticks exercising every fused plane: default +
    rate-limiter + warm-up flow rules, prioritized occupy-ahead, ctx/origin
    stat fan, QPS + THREAD param rules, slow-ratio breakers.  Returns
    (state, outputs).

    sort_batches: stably presort each batch by resource id (the segment
    engine's fast-rank precondition) and report verdicts in arrival
    order."""
    import jax

    from sentinel_tpu.core.rules import (
        CONTROL_RATE_LIMITER,
        CONTROL_WARM_UP,
        DegradeRule,
        FlowRule,
        ParamFlowRule,
    )
    from sentinel_tpu.ops import engine as E
    from sentinel_tpu.runtime.registry import Registry

    reg = Registry(cfg)
    flow, deg, par = [], [], []
    for i in range(12):
        name = f"r{i}"
        reg.resource_id(name)
        behavior = (
            CONTROL_RATE_LIMITER
            if i % 3 == 1
            else (CONTROL_WARM_UP if i % 3 == 2 else 0)
        )
        flow.append(
            FlowRule(
                resource=name,
                count=5.0,
                control_behavior=behavior,
                max_queueing_time_ms=40 if behavior == CONTROL_RATE_LIMITER else 0,
            )
        )
        deg.append(DegradeRule(resource=name, grade=0, count=2.0, time_window=5))
        if i < 4:
            par.append(
                ParamFlowRule(resource=name, param_idx=0, count=3.0, grade=1 if i % 2 else 0)
            )
    rules = E.compile_ruleset(cfg, reg, flow_rules=flow, degrade_rules=deg, param_rules=par)
    state = E.init_state(cfg)
    rng = np.random.default_rng(seed)
    B = cfg.batch_size
    outs = []
    origin_row = reg.origin_node_row("r0", "peer")
    ctx_row = reg.ctx_node_row("r1", "ctx-a")
    ctx_id = reg.context_id("ctx-a")
    for t in range(4):
        ids = rng.integers(1, 14, B).astype(np.int32)
        witho = rng.random(B) < 0.3
        withc = rng.random(B) < 0.25
        prio = (rng.random(B) < 0.3).astype(np.int32)
        a_inb = (rng.random(B) < 0.5).astype(np.int32)
        a_ph = np.stack([rng.integers(1, 5, B), np.zeros(B)], axis=1).astype(np.int32)
        rt = rng.uniform(0.5, 8.0, B).astype(np.float32)
        err = (rng.random(B) < 0.3).astype(np.int32)
        c_inb = (rng.random(B) < 0.5).astype(np.int32)
        c_ph = np.stack([rng.integers(1, 5, B), np.zeros(B)], axis=1).astype(np.int32)
        if sort_batches:
            order = np.lexsort((np.arange(B), ids))
            inv = np.empty(B, np.int64)
            inv[order] = np.arange(B)
            ids, witho, withc, prio = ids[order], witho[order], withc[order], prio[order]
            a_inb, a_ph, rt, err = a_inb[order], a_ph[order], rt[order], err[order]
            c_inb, c_ph = c_inb[order], c_ph[order]
        acq = E.empty_acquire(cfg)._replace(
            res=jnp.asarray(ids),
            count=jnp.ones((B,), jnp.int32),
            prio=jnp.asarray(prio),
            origin_node=jnp.asarray(
                np.where(witho, origin_row, cfg.trash_row).astype(np.int32)
            ),
            ctx_node=jnp.asarray(
                np.where(withc, ctx_row, cfg.trash_row).astype(np.int32)
            ),
            ctx_name=jnp.asarray(
                np.where(withc, ctx_id, -1).astype(np.int32)
            ),
            inbound=jnp.asarray(a_inb),
            param_hash=jnp.asarray(a_ph),
        )
        comp = E.empty_complete(cfg)._replace(
            res=jnp.asarray(ids),
            rt=jnp.asarray(rt),
            success=jnp.ones((B,), jnp.int32),
            error=jnp.asarray(err),
            inbound=jnp.asarray(c_inb),
            param_hash=jnp.asarray(c_ph),
        )
        state, out = E.tick(
            state,
            rules,
            acq,
            comp,
            jnp.int32(1000 + 333 * t),
            jnp.float32(0.0),
            jnp.float32(0.0),
            cfg=cfg,
        )
        v = np.asarray(out.verdict)
        outs.append(v[inv] if sort_batches else v)
    return jax.tree.map(np.asarray, state), outs


@pytest.mark.slow  # full-tick equivalence: ~minutes on a 1-core host; see test_engine_seg.py note
@pytest.mark.parametrize("sketch", [False, True])
def test_fused_tick_matches_mxu_path(sketch):
    """Full ticks through the fused-effects path must be bit-identical to
    the unfused MXU path (which test_engine_backends pins to the scatter
    oracle)."""
    from sentinel_tpu.core.config import small_engine_config

    base = dict(
        batch_size=96,
        complete_batch_size=96,
        use_mxu_tables=True,
        sketch_stats=sketch,
        enable_minute_window=True,
    )
    cfg_mxu = small_engine_config(**base)
    cfg_fused = small_engine_config(**base, fused_effects=True)
    st1, out1 = _tick_once(cfg_mxu)
    st2, out2 = _tick_once(cfg_fused)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)
    import jax

    l1, treedef = jax.tree.flatten(st1)
    l2 = jax.tree.leaves(st2)
    paths = [str(p) for p, _ in jax.tree_util.tree_flatten_with_path(st1)[0]]
    for p, x, y in zip(paths, l1, l2):
        np.testing.assert_array_equal(x, y, err_msg=p)
