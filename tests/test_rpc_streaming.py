"""RPC chained-resource adapter + async-streaming adapter (VERDICT r2 #8).

Reference patterns: SentinelDubboProviderFilter (app→interface→method
chained entries with origin propagation) and SentinelReactorSubscriber
(entry on subscribe, exit on complete/error)."""

from __future__ import annotations

import asyncio

import pytest

from sentinel_tpu.adapters.rpc import (
    consumer_call,
    consumer_entry,
    provider_call,
    provider_entry,
)
from sentinel_tpu.adapters.streaming import (
    guard_aiter,
    guard_awaitable,
    guard_stream,
)
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.rules import FlowRule


IFACE = "com.demo.OrderService"
METHOD = "com.demo.OrderService:place(Order)"


def test_provider_chain_counts_both_nodes(client):
    for _ in range(3):
        assert provider_call(IFACE, METHOD, lambda: "ok", origin="caller-app", client=client) == "ok"
    si = client.stats.resource(IFACE)
    sm = client.stats.resource(METHOD)
    assert si["passQps"] == 3 and sm["passQps"] == 3
    assert si["curThreadNum"] == 0 and sm["curThreadNum"] == 0
    # origin-attributed rows exist for the caller app (ClusterBuilderSlot
    # origin node analog)
    so = client.stats.origin(IFACE, "caller-app")
    assert so is not None and so["passQps"] == 3


def test_method_rule_blocks_only_method(client, vt):
    client.flow_rules.load([FlowRule(resource=METHOD, count=2.0)])
    passed = blocked = 0
    for _ in range(5):
        try:
            provider_call(IFACE, METHOD, lambda: "ok", origin="caller-app", client=client)
            passed += 1
        except ERR.BlockException:
            blocked += 1
    assert passed == 2 and blocked == 3
    si = client.stats.resource(IFACE)
    sm = client.stats.resource(METHOD)
    # the interface entry SUCCEEDED for all 5 (block happened below it);
    # its concurrency must fully release even on the blocked calls
    assert si["passQps"] == 5
    assert sm["passQps"] == 2 and sm["blockQps"] == 3
    assert si["curThreadNum"] == 0 and sm["curThreadNum"] == 0


def test_interface_rule_blocks_before_method(client, vt):
    client.flow_rules.load([FlowRule(resource=IFACE, count=1.0)])
    results = []
    for _ in range(3):
        try:
            provider_call(IFACE, METHOD, lambda: "ok", client=client)
            results.append("pass")
        except ERR.BlockException:
            results.append("block")
    assert results == ["pass", "block", "block"]
    sm = client.stats.resource(METHOD)
    assert sm["passQps"] == 1 and sm["blockQps"] == 0  # never reached


def test_provider_exception_traces_both(client):
    with pytest.raises(ValueError):
        provider_call(IFACE, METHOD, lambda: (_ for _ in ()).throw(ValueError("x")), client=client)
    si = client.stats.resource(IFACE)
    sm = client.stats.resource(METHOD)
    assert si["exceptionQps"] == 1 and sm["exceptionQps"] == 1


def test_consumer_chain(client):
    assert consumer_call(IFACE, METHOD, lambda: 42, client=client) == 42
    assert client.stats.resource(IFACE)["passQps"] == 1
    assert client.stats.resource(METHOD)["passQps"] == 1


# -- streaming --------------------------------------------------------------


async def _numbers(n, fail_at=None):
    for i in range(n):
        if fail_at is not None and i == fail_at:
            raise RuntimeError("mid-stream")
        yield i


def test_stream_entry_on_subscribe_exit_on_complete(client):
    async def main():
        stream = guard_stream("stream-res", _numbers(4), client=client)
        # assembly does NOT acquire (lazy subscription)
        assert client.stats.resource("stream-res") is None
        got = [x async for x in stream]
        return got

    got = asyncio.run(main())
    assert got == [0, 1, 2, 3]
    s = client.stats.resource("stream-res")
    assert s["passQps"] == 1 and s["successQps"] == 1
    assert s["curThreadNum"] == 0


def test_stream_error_traces_exception(client):
    async def main():
        got = []
        with pytest.raises(RuntimeError):
            async for x in guard_stream("stream-err", _numbers(5, fail_at=2), client=client):
                got.append(x)
        return got

    got = asyncio.run(main())
    assert got == [0, 1]
    s = client.stats.resource("stream-err")
    assert s["passQps"] == 1 and s["exceptionQps"] == 1
    assert s["curThreadNum"] == 0


def test_stream_block_surfaces_at_first_pull(client, vt):
    client.flow_rules.load([FlowRule(resource="stream-lim", count=1.0)])

    async def main():
        ok = [x async for x in guard_stream("stream-lim", _numbers(2), client=client)]
        assert ok == [0, 1]
        with pytest.raises(ERR.BlockException):
            async for _ in guard_stream("stream-lim", _numbers(2), client=client):
                pass

    asyncio.run(main())
    s = client.stats.resource("stream-lim")
    assert s["passQps"] == 1 and s["blockQps"] == 1
    assert s["curThreadNum"] == 0


def test_stream_early_break_releases_entry(client):
    """Consumer breaks mid-stream (the subscriber cancel() path): the
    entry must release its concurrency slot WITHOUT error accounting."""

    async def main():
        got = []
        async for x in guard_stream("stream-brk", _numbers(100), client=client):
            got.append(x)
            if x == 1:
                break
        import gc

        gc.collect()  # make generator aclose deterministic on any runtime
        await asyncio.sleep(0)
        return got

    got = asyncio.run(main())
    assert got == [0, 1]
    s = client.stats.resource("stream-brk")
    assert s["curThreadNum"] == 0
    assert s["exceptionQps"] == 0
    assert s["successQps"] == 1


def test_guard_aiter_decorator_and_awaitable(client):
    @guard_aiter("gen-res", client=client)
    async def gen():
        yield "a"
        yield "b"

    async def one():
        return 7

    async def main():
        items = [x async for x in gen()]
        r = await guard_awaitable("mono-res", one(), client=client)
        return items, r

    items, r = asyncio.run(main())
    assert items == ["a", "b"] and r == 7
    assert client.stats.resource("gen-res")["successQps"] == 1
    assert client.stats.resource("mono-res")["successQps"] == 1
