"""Generated flash-crowd traffic through the REAL adapter surfaces
(ISSUE 19 satellite: gateway + streaming under the workload engine),
with exact verdict accounting and timeline rows — every offered event
is accounted pass-or-block, and the persisted per-second metric rows
sum to the driver's own counts."""

import pytest

import sentinel_tpu as st
from sentinel_tpu import workload as WL
from sentinel_tpu.adapters import GatewayAdapter, GatewayFlowRule, SentinelASGIMiddleware
from sentinel_tpu.obs import timeline as TL

BIG = 1 << 60


def _spec(seed=7, steps=24, base=1.5, start=8, prefix=None, n_keys=4):
    keys = WL.ZipfKeys(n_keys=n_keys, alpha=1.2, prefix=prefix) if prefix else None
    return WL.flash_crowd_2x(
        seed=seed, base=base, steps=steps, step_ms=10, start_step=start, keys=keys
    )


def test_gateway_flash_crowd_verdicts_and_timeline(tmp_path, vt, client_factory):
    log = TL.MetricLog(str(tmp_path))
    c = client_factory(timeline_log=log)
    gw = GatewayAdapter(c)
    gw.rules.load_rules([GatewayFlowRule(resource="wl-route", count=20)])
    spec = _spec()
    events = WL.TrafficGenerator(spec).all_events()
    res = WL.drive_gateway(gw, WL.TrafficGenerator(spec))
    # exact verdict accounting: every offered event landed pass-or-block
    assert res.submitted == len(events) > 0
    assert res.passed + res.blocked == res.submitted
    assert res.passed > 0 and res.blocked > 0
    assert c.stats.resource("wl-route")["curThreadNum"] == 0  # entries exited
    c.stop()  # final timeline flush
    rows = TL.MetricLog(str(tmp_path)).find("wl-route", 0, BIG)
    assert sum(r.pass_count for r in rows) == res.passed
    assert sum(r.block_count for r in rows) == res.blocked


def test_streaming_flash_crowd_verdicts_and_timeline(tmp_path, vt, client_factory):
    log = TL.MetricLog(str(tmp_path))
    c = client_factory(timeline_log=log)
    c.flow_rules.load([st.FlowRule(resource="wl/s0", count=2)])
    spec = _spec(seed=9, steps=20, prefix="wl/s")
    events = WL.TrafficGenerator(spec).all_events()
    res = WL.drive_streaming(c, WL.TrafficGenerator(spec))
    assert res.submitted == len(events) > 0
    assert res.passed + res.blocked == res.submitted
    # only wl/s0 carries a rule: exactly its overflow is blocked
    offered_s0 = sum(1 for ev in events if ev.key == "wl/s0")
    assert offered_s0 > 2  # zipf head actually hit the limited key
    assert res.blocked == offered_s0 - 2
    c.stop()
    cold = TL.MetricLog(str(tmp_path))
    keys = sorted({ev.key for ev in events})
    rows = {k: cold.find(k, 0, BIG) for k in keys}
    assert sum(r.pass_count for k in keys for r in rows[k]) == res.passed
    assert sum(r.block_count for k in keys for r in rows[k]) == res.blocked
    # ...and the blocks all sit on the limited key's rows
    assert sum(r.block_count for r in rows["wl/s0"]) == res.blocked


def test_asgi_driver_accounts_verdicts(client_factory):
    async def app(scope, receive, send):
        await send({"type": "http.response.start", "status": 200, "headers": []})
        await send({"type": "http.response.body", "body": b"ok"})

    c = client_factory()
    mw = SentinelASGIMiddleware(app, client=c)
    c.flow_rules.load([st.FlowRule(resource="GET:/wl/a0", count=3)])
    spec = _spec(seed=3, steps=12, prefix="wl/a", n_keys=2)
    events = WL.TrafficGenerator(spec).all_events()
    res = WL.drive_asgi(mw, WL.TrafficGenerator(spec))
    assert res.submitted == len(events) > 0
    assert res.passed + res.blocked == res.submitted
    offered_a0 = sum(1 for ev in events if ev.key == "wl/a0")
    assert res.blocked == max(0, offered_a0 - 3) > 0


def test_grpc_driver_accounts_verdicts(client_factory):
    c = client_factory()
    c.flow_rules.load([st.FlowRule(resource="/wl/g0", count=3)])
    spec = _spec(seed=4, steps=12, prefix="wl/g", n_keys=2)
    res = WL.drive_grpc(c, WL.TrafficGenerator(spec))
    if res is None:
        pytest.skip("grpc not installed")
    events = WL.TrafficGenerator(spec).all_events()
    assert res.submitted == len(events) > 0
    assert res.passed + res.blocked == res.submitted
    offered_g0 = sum(1 for ev in events if ev.key == "wl/g0")
    assert res.blocked == max(0, offered_g0 - 3) > 0
