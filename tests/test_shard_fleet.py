"""Sharded cluster token fleet (cluster/shard.py) — the ISSUE-6 tentpole
contracts: ring-routed token decisions across N real token servers,
per-shard failover with the degrade-hysteresis shape, bounded-slack
budget leases (fallback passes are pre-debited, exhaustion fails
CLOSED), the LEASE wire extension, the RLS front door governing traffic
through the fleet, the ``/api/shards`` exposition, and the one-trace
client → RLS → shard timeline.
"""

import pytest

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.shard import ShardFleet, describe_fleets
from sentinel_tpu.core import rules as R

pytestmark = pytest.mark.jitted  # TCP servers need real (cached) jit programs


def flow_rule(fid, count=100.0):
    return R.FlowRule(
        resource=f"res-{fid}",
        count=count,
        cluster_mode=True,
        cluster_flow_id=fid,
        cluster_threshold_type=1,  # GLOBAL
    )


@pytest.fixture()
def fleet(client_factory):
    f = ShardFleet(
        client_factory,
        n_shards=2,
        lease_slack=0.5,
        retry_interval_s=300.0,  # failover heals explicitly in tests
        lease_ttl_ms=600_000,
        timeout_ms=5000,
        reconnect_interval_s=0.0,
        lease_refresh_async=False,  # top-ups run inline: exact sequences below
    )
    yield f
    f.stop()


def owned_flow(fleet, shard_name, lo=101, hi=900):
    return next(f for f in range(lo, hi) if fleet.client.owner_of(f) == shard_name)


# ---------------------------------------------------------------------------
# routing + budgets
# ---------------------------------------------------------------------------


def test_fleet_routes_flows_to_ring_owners_and_enforces(fleet):
    fid_a = owned_flow(fleet, "shard-0")
    fid_b = owned_flow(fleet, "shard-1")
    fleet.load_flow_rules("default", [flow_rule(fid_a, 3.0), flow_rule(fid_b, 3.0)])
    # rules landed ONLY on their owners (partitioned, not broadcast)
    assert fleet.services["shard-0"].flow_rules.get_by_id(fid_a) is not None
    assert fleet.services["shard-0"].flow_rules.get_by_id(fid_b) is None
    assert fleet.services["shard-1"].flow_rules.get_by_id(fid_b) is not None
    # leasing off for exact budget arithmetic in this test
    fleet.client.lease_slack = 0.0
    ok_a = sum(fleet.client.request_token(fid_a).ok for _ in range(5))
    ok_b = sum(fleet.client.request_token(fid_b).ok for _ in range(5))
    assert (ok_a, ok_b) == (3, 3)  # independent per-shard budgets


def test_unknown_flow_is_no_rule(fleet):
    assert fleet.client.request_token(999_999).status == C.STATUS_NO_RULE


def test_concurrent_token_roundtrips_through_owner(fleet):
    fid = owned_flow(fleet, "shard-1")
    fleet.load_flow_rules("default", [flow_rule(fid, 2.0)])
    r1 = fleet.client.request_concurrent_token(fid)
    r2 = fleet.client.request_concurrent_token(fid)
    assert r1.ok and r2.ok and r1.token_id != r2.token_id
    assert fleet.client.request_concurrent_token(fid).blocked  # limit 2
    # composite ids route the release back to the grantor
    assert fleet.client.release_concurrent_token(r1.token_id).status == C.STATUS_RELEASE_OK
    assert fleet.client.request_concurrent_token(fid).ok


# ---------------------------------------------------------------------------
# failover + leases
# ---------------------------------------------------------------------------


def test_shard_kill_degrades_only_its_flows_and_lease_fails_closed(fleet):
    fid_a = owned_flow(fleet, "shard-0")
    fid_b = owned_flow(fleet, "shard-1")
    fleet.load_flow_rules(
        "default", [flow_rule(fid_a, 4.0), flow_rule(fid_b, 100.0)]
    )
    # healthy traffic establishes the slack lease (ceil(4 * 0.5) = 2)
    assert fleet.client.request_token(fid_a).ok
    lease = fleet.client._shards["shard-0"].leases[fid_a]
    assert lease.granted == 2 and lease.used == 0

    fleet.kill("shard-0")
    import time

    time.sleep(0.2)
    # failover: the first dead-socket request enters degraded and serves
    # from the lease; capacity 2, then FAIL-CLOSED — never an unmetered pass
    statuses = [fleet.client.request_token(fid_a).status for _ in range(4)]
    assert statuses == [
        C.STATUS_OK,
        C.STATUS_OK,
        C.STATUS_BLOCKED,
        C.STATUS_BLOCKED,
    ]
    assert fleet.client.shard_degraded("shard-0")
    # the OTHER shard's flows are untouched by the failover
    assert fleet.client.request_token(fid_b).ok
    assert not fleet.client.shard_degraded("shard-1")

    # rejoin on the original port + explicit cooldown expiry: the next
    # request probes and exits degraded within one hysteresis window
    fleet.rejoin("shard-0")
    st = fleet.client._shards["shard-0"]
    with st.lock:
        st.degraded_until = 0.0
    r = fleet.client.request_token(fid_a)
    assert r.status in (C.STATUS_OK, C.STATUS_BLOCKED)  # a real engine verdict
    assert not fleet.client.shard_degraded("shard-0")


def test_param_flows_fail_closed_while_degraded(fleet):
    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid)])
    fleet.kill("shard-0")
    import time

    time.sleep(0.2)
    assert fleet.client.request_param_token(fid, 1, ["u1"]).status == C.STATUS_BLOCKED


def test_no_lease_means_fail_closed(client_factory):
    f = ShardFleet(
        client_factory,
        n_shards=1,
        lease_slack=0.0,  # leasing disabled entirely
        retry_interval_s=300.0,
        timeout_ms=5000,
        reconnect_interval_s=0.0,
    )
    try:
        fid = owned_flow(f, "shard-0")
        f.load_flow_rules("default", [flow_rule(fid)])
        assert f.client.request_token(fid).ok
        f.kill("shard-0")
        import time

        time.sleep(0.2)
        assert f.client.request_token(fid).status == C.STATUS_BLOCKED
    finally:
        f.stop()


# ---------------------------------------------------------------------------
# LEASE wire extension
# ---------------------------------------------------------------------------


def test_lease_request_roundtrips_on_the_wire(fleet):
    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid, 10.0)])
    st = fleet.client._shards["shard-0"]
    r = st.client.request_lease(fid, 4)
    assert r.status == C.STATUS_OK
    assert r.remaining == 4
    assert r.wait_ms == 600_000  # the fleet's configured lease TTL
    # leased units were debited from the same global window
    fleet.client.lease_slack = 0.0
    ok = sum(fleet.client.request_token(fid).ok for _ in range(10))
    assert ok == 6


def test_lease_units_are_capped_both_sides(fleet):
    """An uncapped lease against a huge-threshold rule (slack × 1e9)
    would build a 250M-item engine batch and stall every flow on the
    shard — found by the cluster_sharded bench.  Both the client sizing
    and the server grant clamp to MAX_LEASE_UNITS."""
    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid, 1e9)])
    assert fleet.client._lease_units(fid) == C.MAX_LEASE_UNITS
    st = fleet.client._shards["shard-0"]
    r = st.client.request_lease(fid, 10_000_000)  # hostile oversize ask
    assert r.status == C.STATUS_OK
    assert 0 < r.remaining <= C.MAX_LEASE_UNITS


def test_lease_frame_codec_roundtrip():
    req = P.ClusterRequest(xid=7, type=C.MSG_TYPE_LEASE, flow_id=12345, count=16)
    body = P.encode_request(req)[2:]
    back = P.decode_request(body)
    assert (back.type, back.flow_id, back.count) == (C.MSG_TYPE_LEASE, 12345, 16)
    rsp = P.ClusterResponse(
        xid=7, type=C.MSG_TYPE_LEASE, status=C.STATUS_OK, remaining=12, wait_ms=1000
    )
    back_r = P.decode_response(P.encode_response(rsp)[2:])
    assert (back_r.status, back_r.remaining, back_r.wait_ms) == (C.STATUS_OK, 12, 1000)


def test_dropped_rule_evicts_standing_lease(fleet):
    """A rule push that drops a flow must drop its standing lease too —
    otherwise a dead shard's fallback keeps admitting deleted-rule
    traffic until the lease TTL runs out."""
    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid, 10.0)])
    fleet.client.request_token(fid)  # establishes the lease
    st = fleet.client._shards["shard-0"]
    assert fid in st.leases
    fleet.load_flow_rules("default", [])  # rule dropped
    assert fid not in st.leases
    fleet.kill("shard-0")
    assert fleet.client.request_token(fid).status == C.STATUS_BLOCKED


def test_lease_transport_fail_is_not_cached_as_denial(fleet):
    """STATUS_FAIL from the LEASE RPC is a transport failure, not an
    admission denial: caching it as a zero-unit lease would pin the
    flow's failover slack at zero for a whole TTL window."""
    from sentinel_tpu.cluster.token_service import TokenResult

    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid, 10.0)])
    st = fleet.client._shards["shard-0"]
    orig = st.client.request_lease
    st.client.request_lease = lambda f, u: TokenResult(C.STATUS_FAIL)
    try:
        assert fleet.client.request_token(fid).status == C.STATUS_OK
        assert fid not in st.leases  # FAIL left uncached
    finally:
        st.client.request_lease = orig
    fleet.client.request_token(fid)  # next request re-leases normally
    assert st.leases[fid].granted > 0


def test_bare_client_flow_rules_facade(fleet):
    """A hand-built ShardedTokenClient (no fleet) must work behind the
    RLS rule manager: the built-in ``_ClientFlowRules`` facade learns
    thresholds (lease sizing) instead of crashing on ``load``, and
    forgets flows a later push drops."""
    from sentinel_tpu.cluster.shard import ShardedTokenClient
    from sentinel_tpu.rls import (
        EnvoyRlsRule,
        EnvoyRlsRuleManager,
        RlsKeyValue,
        RlsResourceDescriptor,
    )

    members = {n: ("127.0.0.1", fleet._ports[n]) for n in fleet.names}
    bare = ShardedTokenClient(members, lease_slack=0.5, reconnect_interval_s=0.0)
    try:
        mgr = EnvoyRlsRuleManager(bare)
        mgr.load(
            [
                EnvoyRlsRule(
                    domain="d",
                    descriptors=[
                        RlsResourceDescriptor(
                            key_values=[RlsKeyValue("k", "v")], count=8.0
                        )
                    ],
                )
            ]
        )
        fid = mgr.lookup_flow_id("d", [("k", "v")])
        assert fid is not None
        assert bare._lease_units(fid) == 4  # ceil(8 × 0.5)
        mgr.load([])  # dropping the domain forgets the threshold
        assert bare._lease_units(fid) == 0
    finally:
        bare.close()


def test_set_to_sharded_client_routes_through_fleet(fleet):
    """The runtime-facing entry point (ClusterStateManager): flip to
    fleet mode, teach thresholds through the client's facade, and get
    ring-routed decisions with sized leases."""
    from sentinel_tpu.cluster.state import CLUSTER_CLIENT, ClusterStateManager

    state = ClusterStateManager()
    state.set_to_sharded_client(
        {n: ("127.0.0.1", fleet._ports[n]) for n in fleet.names},
        timeout_ms=5000,  # must not collide with the explicit default
        reconnect_interval_s=0.0,
    )
    try:
        assert state.mode == CLUSTER_CLIENT
        tc = state.token_service()
        fid = owned_flow(fleet, "shard-1")
        fleet.load_flow_rules("default", [flow_rule(fid, 8.0)])
        tc.flow_rules.load("default", [flow_rule(fid, 8.0)])
        assert tc._lease_units(fid) == 2  # default lease_slack 0.25
        assert tc.request_token(fid).status == C.STATUS_OK
    finally:
        state.token_service().close()


# ---------------------------------------------------------------------------
# RLS front door over the fleet
# ---------------------------------------------------------------------------


def test_rls_routes_descriptors_through_the_ring(fleet):
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from sentinel_tpu.rls import rls_pb2 as pb
    from sentinel_tpu.rls.rules import (
        EnvoyRlsRule,
        RlsKeyValue,
        RlsResourceDescriptor,
        descriptor_identifier,
        identifier_flow_id,
    )
    from sentinel_tpu.rls.server import SentinelEnvoyRlsService

    fleet.client.lease_slack = 0.0  # exact budget arithmetic below
    rls = SentinelEnvoyRlsService(fleet.client)
    rules = [
        EnvoyRlsRule(
            domain="mesh",
            descriptors=[
                RlsResourceDescriptor(
                    key_values=[RlsKeyValue("dest", f"svc-{i}")], count=2.0
                )
                for i in range(6)
            ],
        )
    ]
    rls.rules.load(rules)
    # every descriptor's flow id landed on its ring owner's shard service
    for i in range(6):
        fid = identifier_flow_id(
            descriptor_identifier("mesh", [("dest", f"svc-{i}")])
        )
        owner = fleet.client.owner_of(fid)
        assert fleet.services[owner].flow_rules.get_by_id(fid) is not None
        other = next(n for n in fleet.names if n != owner)
        assert fleet.services[other].flow_rules.get_by_id(fid) is None

    def ask(value):
        req = pb.RateLimitRequest(domain="mesh", hits_addend=1)
        d = req.descriptors.add()
        e = d.entries.add()
        e.key, e.value = "dest", value
        return rls.should_rate_limit(req).overall_code

    codes = [ask("svc-0") for _ in range(4)]
    assert codes.count(pb.RateLimitResponse.OK) == 2
    assert codes.count(pb.RateLimitResponse.OVER_LIMIT) == 2
    # a different descriptor has its own (possibly other-shard) budget
    assert ask("svc-1") == pb.RateLimitResponse.OK


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_one_trace_spans_client_rls_and_shard(fleet):
    """The acceptance timeline: one ShouldRateLimit request produces
    rls.should_rate_limit → cluster.rpc → token.decision spans sharing a
    single trace id with parent links — exactly what
    ``python -m sentinel_tpu.obs --merge`` joins into one Perfetto flow
    when the tiers run as separate processes."""
    pytest.importorskip("grpc")
    from sentinel_tpu import obs
    from sentinel_tpu.rls import rls_pb2 as pb
    from sentinel_tpu.rls.rules import EnvoyRlsRule, RlsKeyValue, RlsResourceDescriptor
    from sentinel_tpu.rls.server import SentinelEnvoyRlsService

    rls = SentinelEnvoyRlsService(fleet.client)
    rls.rules.load(
        [
            EnvoyRlsRule(
                domain="mesh",
                descriptors=[
                    RlsResourceDescriptor(
                        key_values=[RlsKeyValue("dest", "svc-t")], count=50.0
                    )
                ],
            )
        ]
    )
    req = pb.RateLimitRequest(domain="mesh", hits_addend=1)
    d = req.descriptors.add()
    e = d.entries.add()
    e.key, e.value = "dest", "svc-t"

    obs.TRACER.reset()
    obs.enable()
    try:
        assert rls.should_rate_limit(req).overall_code == pb.RateLimitResponse.OK
        import time

        time.sleep(0.1)  # server-side decision span lands async
    finally:
        obs.disable()
    spans = obs.TRACER.snapshot()
    rls_spans = [s for s in spans if s["name"] == "rls.should_rate_limit"]
    assert rls_spans, [s["name"] for s in spans]
    root = rls_spans[0]
    trace = root["trace"]
    assert trace != 0
    rpc = [s for s in spans if s["name"] == "cluster.rpc" and s["trace"] == trace]
    assert rpc, "cluster RPC span missing from the request's trace"
    # the RPC span parents to the RLS front-door span...
    assert rpc[0]["attrs"].get("parent") == root["attrs"]["span_id"]
    # ...and the shard's decision span joined the same trace over the wire
    decision = [s for s in spans if s["name"] == "token.decision" and s["trace"] == trace]
    assert decision, "shard-side decision span did not adopt the wire trace"
    assert decision[0]["attrs"].get("parent") == rpc[0]["attrs"]["span_id"]


def test_four_shard_fleet_grpc_end_to_end(client_factory):
    """The acceptance topology: a REAL gRPC ShouldRateLimit front door
    over a 4-shard fleet — Envoy-shaped requests resolve to flow ids,
    route through the ring to their owning shards, and come back
    governed."""
    pytest.importorskip("grpc")
    from sentinel_tpu.rls import rls_pb2 as pb
    from sentinel_tpu.rls.rules import (
        EnvoyRlsRule,
        RlsKeyValue,
        RlsResourceDescriptor,
        descriptor_identifier,
        identifier_flow_id,
    )
    from sentinel_tpu.rls.server import SentinelRlsGrpcServer, make_channel_stub

    f = ShardFleet(
        client_factory,
        n_shards=4,
        lease_slack=0.0,  # exact budgets below
        retry_interval_s=300.0,
        timeout_ms=5000,
        reconnect_interval_s=0.0,
    )
    server = None
    try:
        server = SentinelRlsGrpcServer(f.client, host="127.0.0.1", port=0)
        values = [f"svc-{i}" for i in range(8)]
        server.rules.load(
            [
                EnvoyRlsRule(
                    domain="mesh",
                    descriptors=[
                        RlsResourceDescriptor(
                            key_values=[RlsKeyValue("dest", v)], count=2.0
                        )
                        for v in values
                    ],
                )
            ]
        )
        server.start()
        fids = [
            identifier_flow_id(descriptor_identifier("mesh", [("dest", v)]))
            for v in values
        ]
        owners = {f.client.owner_of(fid) for fid in fids}
        assert len(owners) >= 2, "8 descriptors should spread over the ring"
        channel, call = make_channel_stub(f"127.0.0.1:{server.port}")

        def ask(value):
            req = pb.RateLimitRequest(domain="mesh", hits_addend=1)
            d = req.descriptors.add()
            e = d.entries.add()
            e.key, e.value = "dest", value
            return call(req).overall_code

        # every descriptor gets its own owner-enforced budget of 2
        for v in values:
            codes = [ask(v) for _ in range(3)]
            assert codes.count(pb.RateLimitResponse.OK) == 2, v
            assert codes.count(pb.RateLimitResponse.OVER_LIMIT) == 1, v
        channel.close()
    finally:
        if server is not None:
            server.stop()
        f.stop()


def test_merged_perfetto_trace_links_the_timeline(fleet, tmp_path):
    """``obs --merge`` on the dumped trace produces Chrome flow events
    (``ph: s``/``f``) binding the request's rls → cluster.rpc →
    token.decision spans — the acceptance's one-request timeline."""
    pytest.importorskip("grpc")
    import json

    from sentinel_tpu import obs
    from sentinel_tpu.obs.__main__ import merge_traces
    from sentinel_tpu.rls import rls_pb2 as pb
    from sentinel_tpu.rls.rules import EnvoyRlsRule, RlsKeyValue, RlsResourceDescriptor
    from sentinel_tpu.rls.server import SentinelEnvoyRlsService

    rls = SentinelEnvoyRlsService(fleet.client)
    rls.rules.load(
        [
            EnvoyRlsRule(
                domain="mesh",
                descriptors=[
                    RlsResourceDescriptor(
                        key_values=[RlsKeyValue("dest", "svc-m")], count=50.0
                    )
                ],
            )
        ]
    )
    req = pb.RateLimitRequest(domain="mesh", hits_addend=1)
    d = req.descriptors.add()
    e = d.entries.add()
    e.key, e.value = "dest", "svc-m"
    obs.TRACER.reset()
    obs.enable()
    try:
        rls.should_rate_limit(req)
        import time

        time.sleep(0.1)
    finally:
        obs.disable()
    dump = tmp_path / "proc.json"
    dump.write_text(json.dumps(obs.TRACER.chrome_trace()))
    merged = merge_traces([str(dump)])
    events = merged["traceEvents"]
    names = {ev.get("name") for ev in events if ev.get("ph") == "X"}
    assert {"rls.should_rate_limit", "cluster.rpc", "token.decision"} <= names
    flow_ids = {ev.get("id") for ev in events if ev.get("ph") in ("s", "f")}
    # the rls→rpc and rpc→decision parent links each became a flow pair
    assert len(flow_ids) >= 2, merged["otherData"]


def test_api_shards_exposition(fleet):
    from sentinel_tpu.transport.command import CommandRequest
    from sentinel_tpu.transport.handlers import build_default_handlers

    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid)])
    registry = build_default_handlers(fleet.services["shard-0"].client)
    rsp = registry.handle("api/shards", CommandRequest())
    assert rsp.success
    ours = [
        f
        for f in rsp.result
        if {s["name"] for s in f["shards"]} == {"shard-0", "shard-1"}
    ]
    assert ours, "fleet missing from /api/shards"
    desc = ours[0]
    assert desc["vnodes"] > 0 and desc["flows_registered"] >= 1
    for s in desc["shards"]:
        assert set(s) >= {"name", "addr", "connected", "degraded", "leases"}
    assert describe_fleets()  # module surface the handler rides


def test_shard_metrics_are_labeled(fleet):
    from sentinel_tpu.obs import REGISTRY

    fid = owned_flow(fleet, "shard-1")
    fleet.load_flow_rules("default", [flow_rule(fid)])
    assert fleet.client.request_token(fid).ok
    snap = REGISTRY.snapshot()
    assert snap['sentinel_shard_requests_total{shard="shard-1"}'] >= 1
    assert 'sentinel_shard_degraded{shard="shard-1"}' in snap


# ---------------------------------------------------------------------------
# lease-first admission (protocol v2)
# ---------------------------------------------------------------------------


def test_lease_first_steady_state_is_rpc_free(fleet):
    """After the bootstrap round-trip a healthy flow admits locally
    against its standing lease: zero routed RPCs per decision."""
    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid, 100.0)])
    st = fleet.client._shards["shard-0"]
    assert fleet.client.request_token(fid).ok  # remote + lease bootstrap
    base = st.c_requests.value
    lease = st.leases[fid]
    assert (lease.granted, lease.used) == (50, 0)  # slack 0.5 × count 100
    admits0 = st.c_local_admits.value
    for _ in range(10):
        assert fleet.client.request_token(fid).ok
    assert st.c_requests.value == base  # no further routed requests
    assert st.c_local_admits.value == admits0 + 10
    assert st.leases[fid].used == 10


def test_lease_tops_up_ahead_of_exhaustion(fleet):
    """Once the spendable remainder dips to refresh_frac of the grant
    the top-up (inline here: the fixture sets async off) refills the
    lease before it empties — the flow never pays a remote decision."""
    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid, 100.0)])
    st = fleet.client._shards["shard-0"]
    fleet.client.request_token(fid)  # bootstrap: granted 50, used 0
    base = st.c_requests.value
    for _ in range(25):  # 25th admit leaves remaining == 25 → top-up fires
        assert fleet.client.request_token(fid).ok
    lease = st.leases[fid]
    assert (lease.granted, lease.used) == (50, 0)  # refilled, carry folded in
    assert st.c_requests.value == base  # top-up was a LEASE frame, not a route


def test_async_refresher_tops_up_in_background(client_factory):
    """The default configuration hands top-ups to the background
    refresher thread; flush_lease_refresh() sequences the assertion."""
    f = ShardFleet(
        client_factory,
        n_shards=2,
        lease_slack=0.5,
        retry_interval_s=300.0,
        lease_ttl_ms=600_000,
        timeout_ms=5000,
        reconnect_interval_s=0.0,
    )
    try:
        fid = owned_flow(f, "shard-0")
        f.load_flow_rules("default", [flow_rule(fid, 100.0)])
        st = f.client._shards["shard-0"]
        f.client.request_token(fid)
        base = st.c_requests.value
        for _ in range(25):
            assert f.client.request_token(fid).ok
        assert f.client.flush_lease_refresh(5.0)
        lease = st.leases[fid]
        assert (lease.granted, lease.used) == (50, 0)
        assert st.c_requests.value == base
    finally:
        f.stop()


def test_request_token_many_one_exchange_per_owner(fleet):
    """Multi-flow admission groups by ring owner and rides one batched
    exchange per shard, preserving per-entry order semantics."""
    fid_a = owned_flow(fleet, "shard-0")
    fid_b = owned_flow(fleet, "shard-1")
    fleet.load_flow_rules("default", [flow_rule(fid_a, 3.0), flow_rule(fid_b, 3.0)])
    fleet.client.lease_slack = 0.0  # exact budgets: every decision remote
    rs = fleet.client.request_token_many(
        [(fid_a, 1), (fid_b, 1), (fid_a, 1), (999_999, 1), (fid_a, 2)]
    )
    assert [r.status for r in rs] == [
        C.STATUS_OK,
        C.STATUS_OK,
        C.STATUS_OK,
        C.STATUS_NO_RULE,
        C.STATUS_BLOCKED,  # 2 more against count 3 with 2 spent
    ]


def test_request_token_many_admits_locally_against_leases(fleet):
    fid = owned_flow(fleet, "shard-0")
    fleet.load_flow_rules("default", [flow_rule(fid, 100.0)])
    st = fleet.client._shards["shard-0"]
    fleet.client.request_token(fid)  # bootstrap lease
    base = st.c_requests.value
    rs = fleet.client.request_token_many([(fid, 1)] * 5)
    assert all(r.ok for r in rs)
    assert st.c_requests.value == base  # all five admitted off the lease


def test_request_token_many_fails_over_per_shard(fleet):
    """A dead owner degrades only its own entries; with leasing off the
    fallback fails CLOSED, and the other shard's entries are untouched."""
    fid_a = owned_flow(fleet, "shard-0")
    fid_b = owned_flow(fleet, "shard-1")
    fleet.load_flow_rules("default", [flow_rule(fid_a, 4.0), flow_rule(fid_b, 4.0)])
    fleet.client.lease_slack = 0.0
    fleet.kill("shard-0")
    rs = fleet.client.request_token_many([(fid_a, 1), (fid_b, 1)])
    assert rs[0].status == C.STATUS_BLOCKED
    assert rs[1].status == C.STATUS_OK
    assert fleet.client.shard_degraded("shard-0")
