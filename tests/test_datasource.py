"""Datasource / property layer tests (SURVEY.md §2.3, L4).

Mirrors the reference's datasource-extension tests: property fan-out and
skip-unchanged semantics, file poll with mtime detection, writable
round-trip, and the datasource→RuleManager→engine wiring end-to-end.
"""

import json
import os
import time


from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.datasource import (
    DynamicSentinelProperty,
    FileRefreshableDataSource,
    FileWritableDataSource,
    SimplePropertyListener,
    json_rule_converter,
    json_rule_encoder,
)


def test_dynamic_property_fanout_and_skip_unchanged():
    prop = DynamicSentinelProperty()
    seen = []
    prop.add_listener(SimplePropertyListener(lambda v: seen.append(v)))
    assert seen == [None]  # config_load replay on subscribe

    assert prop.update_value(1) is True
    assert prop.update_value(1) is False  # unchanged → no fan-out
    assert prop.update_value(2) is True
    assert seen == [None, 1, 2]


def test_property_late_listener_gets_current_value():
    prop = DynamicSentinelProperty()
    prop.update_value("x")
    seen = []
    prop.add_listener(SimplePropertyListener(seen.append))
    assert seen == ["x"]


def test_file_refreshable_datasource(tmp_path):
    p = tmp_path / "flow-rules.json"
    p.write_text(json.dumps([{"resource": "a", "count": 10}]))
    ds = FileRefreshableDataSource(str(p), json_rule_converter("flow"), refresh_ms=60_000)
    try:
        got = ds.get_property().get_value()
        assert len(got) == 1 and got[0].resource == "a" and got[0].count == 10

        # unchanged mtime → no reload
        assert ds.refresh() is False

        p.write_text(json.dumps([{"resource": "b", "count": 5}]))
        os.utime(str(p), (time.time() + 5, time.time() + 5))
        assert ds.refresh() is True
        got = ds.get_property().get_value()
        assert got[0].resource == "b"
    finally:
        ds.close()


def test_file_writable_datasource_roundtrip(tmp_path):
    p = tmp_path / "out.json"
    w = FileWritableDataSource(str(p), json_rule_encoder)
    w.write([FlowRule(resource="hello", count=20.0)])
    back = json_rule_converter("flow")(p.read_text())
    assert back[0].resource == "hello" and back[0].count == 20.0


def test_datasource_drives_engine(client_factory, tmp_path):
    """File push → property → FlowRuleManager → engine recompile → enforcement."""
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([{"resource": "svc", "count": 2}]))

    client = client_factory()
    ds = FileRefreshableDataSource(str(p), json_rule_converter("flow"), refresh_ms=60_000)
    try:
        client.flow_rules.register_property(ds.get_property())
        assert len(client.flow_rules.get()) == 1

        passed = blocked = 0
        for _ in range(6):
            try:
                with client.entry("svc"):
                    passed += 1
            except ERR.FlowException:
                blocked += 1
        assert passed == 2 and blocked == 4

        # push a higher limit through the file
        p.write_text(json.dumps([{"resource": "svc", "count": 100}]))
        os.utime(str(p), (time.time() + 5, time.time() + 5))
        assert ds.refresh() is True
        assert client.flow_rules.get()[0].count == 100
    finally:
        ds.close()
