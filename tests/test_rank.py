"""Rank primitives vs numpy oracle — grouped exclusive cumsum (the batched
CAS-replacement), the MXU-chunked prefix sum, and running max."""

import numpy as np

import jax.numpy as jnp

from sentinel_tpu.ops.rank import (
    fast_cumsum,
    fast_running_max,
    grouped_exclusive_cumsum,
    grouped_first,
)


def test_fast_cumsum_matches_numpy():
    rng = np.random.default_rng(0)
    for n in (1, 100, 128, 129, 4096, 70_001):
        v = rng.integers(0, 100, n).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(fast_cumsum(jnp.asarray(v))), np.cumsum(v), rtol=1e-6
        )


def test_fast_running_max_matches_numpy():
    rng = np.random.default_rng(1)
    for n in (1, 127, 128, 1000, 33_000):
        v = rng.normal(0, 1000, n).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(fast_running_max(jnp.asarray(v))), np.maximum.accumulate(v)
        )


def test_grouped_exclusive_cumsum_oracle():
    rng = np.random.default_rng(2)
    n = 5000
    keys = rng.integers(0, 37, n).astype(np.int32)
    v1 = rng.integers(1, 5, n).astype(np.float32)
    v2 = rng.uniform(0, 10, n).astype(np.float32)
    elig = rng.random(n) < 0.8

    r1, r2 = grouped_exclusive_cumsum(
        jnp.asarray(keys), [jnp.asarray(v1), jnp.asarray(v2)], jnp.asarray(elig)
    )
    running = {}
    o1 = np.zeros(n, np.float32)
    o2 = np.zeros(n, np.float32)
    for i in range(n):
        s1, s2 = running.get(keys[i], (0.0, 0.0))
        o1[i], o2[i] = s1, s2
        if elig[i]:
            running[keys[i]] = (s1 + v1[i], s2 + v2[i])
    # the csum-minus-base formulation carries f32 cancellation noise of
    # ~1e-3 relative on float values; integer-valued inputs stay exact
    np.testing.assert_allclose(np.asarray(r1), o1, rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(np.asarray(r2), o2, rtol=1e-3, atol=1e-2)


def test_grouped_exclusive_cumsum_small_matches_sort_version():
    from sentinel_tpu.ops.rank import grouped_exclusive_cumsum_small

    rng = np.random.default_rng(5)
    n, S = 10_000, 97
    keys = rng.integers(0, S, n).astype(np.int32)
    v1 = rng.integers(1, 4, n).astype(np.float32)
    v2 = rng.uniform(0, 5, n).astype(np.float32)
    elig = rng.random(n) < 0.7
    ref = grouped_exclusive_cumsum(
        jnp.asarray(keys), [jnp.asarray(v1), jnp.asarray(v2)], jnp.asarray(elig)
    )
    got = grouped_exclusive_cumsum_small(
        jnp.asarray(keys),
        [jnp.asarray(v1), jnp.asarray(v2)],
        jnp.asarray(elig),
        key_space=S,
        chunk=1024,
    )
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=2e-2)


def test_grouped_first_oracle():
    keys = jnp.asarray([5, 3, 5, 3, 7, 5], jnp.int32)
    elig = jnp.asarray([False, True, True, True, True, True])
    first = np.asarray(grouped_first(keys, elig))
    np.testing.assert_array_equal(first, [False, True, True, False, True, False])
