"""Perf-regression sentry (bench.py --smoke + PERF_BASELINE.json): the
comparison logic must flag a synthetic 2x engine-throughput regression,
and (slow) the real smoke run must pass against the committed baseline —
protecting the r01→r07 perf trajectory while the hot path is rewritten."""

from __future__ import annotations

import os

import pytest

import bench

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def baseline():
    return bench.load_perf_baseline()


def test_baseline_is_committed_and_well_formed(baseline):
    assert os.path.basename(bench.PERF_BASELINE_PATH) == "PERF_BASELINE.json"
    assert set(baseline) >= {"metrics", "tolerances"}
    for key, tol in baseline["tolerances"].items():
        assert set(tol) & {"min_ratio", "max_ratio", "max_abs"}, key
    # every floored/ceilinged ratio metric has a baseline value to
    # compare against (max_abs-only bounds don't need one)
    for key, tol in baseline["tolerances"].items():
        if "min_ratio" in tol or "max_ratio" in tol:
            assert key in baseline["metrics"], key


def test_detects_synthetic_2x_throughput_regression(baseline):
    """The acceptance case: fabricate a measurement where engine
    throughput halved — the sentry must flag it."""
    degraded = {"metrics": dict(baseline["metrics"])}
    degraded["metrics"]["engine_tick_dps"] = (
        baseline["metrics"]["engine_tick_dps"] / 2.0
    )
    regressions = bench.compare_to_baseline(degraded, baseline)
    assert any("engine_tick_dps" in r and "regression" in r for r in regressions)
    # ...and ONLY that metric is flagged
    assert all("engine_tick_dps" in r for r in regressions), regressions


def test_passes_on_identical_measurement(baseline):
    measured = {"metrics": dict(baseline["metrics"])}
    assert bench.compare_to_baseline(measured, baseline) == []


def test_detects_latency_and_absolute_ceilings(baseline):
    worse = {"metrics": dict(baseline["metrics"])}
    # host_build_ms carries a deliberately loose 2.5x ceiling (wall-clock
    # noise) — 3x must still be flagged
    worse["metrics"]["host_build_ms"] = baseline["metrics"]["host_build_ms"] * 3.0
    worse["metrics"]["telemetry_overhead_pct"] = 9.0
    regs = bench.compare_to_baseline(worse, baseline)
    assert any("host_build_ms" in r for r in regs)
    assert any("telemetry_overhead_pct" in r for r in regs)


def test_missing_metric_is_ignored_not_fatal(baseline):
    """A baseline pinned before a metric existed must not fail the run
    (and vice versa) — re-pinning picks new metrics up."""
    measured = {"metrics": dict(baseline["metrics"])}
    measured["metrics"].pop("client_path_dps", None)
    assert bench.compare_to_baseline(measured, baseline) == []


@pytest.mark.slow
def test_real_smoke_run_passes_committed_baseline(baseline):
    """The sentry's real half: measure this machine and compare.  Slow
    (tens of seconds of jitted tick loops) and timing-sensitive by
    nature — the tolerances carry the noise headroom."""
    measured = bench.smoke_bench()
    regressions = bench.compare_to_baseline(measured, baseline)
    assert regressions == [], "\n".join(regressions)
    # the PR 8 acceptance bound, measured fresh: device telemetry costs
    # <= 5% of the engine tick
    assert measured["metrics"]["telemetry_overhead_pct"] <= 5.0
