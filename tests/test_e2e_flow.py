"""End-to-end flow control through the public API under virtual time.

TPU-native counterpart of the reference's FlowPartialIntegrationTest and
the sentinel-demo-basic FlowQpsDemo scenario (BASELINE config #1):
resource 'HelloWorld' pinned to 20 pass/s under heavy offered load.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.runtime.client import SentinelClient
from sentinel_tpu.utils.time_source import VirtualTimeSource


@pytest.fixture()
def client(vt):
    c = SentinelClient(cfg=small_engine_config(), time_source=vt, mode="sync")
    c.start()
    yield c
    c.stop()


def test_helloworld_qps20(client, vt):
    client.flow_rules.load([st.FlowRule(resource="HelloWorld", count=20)])
    passes_per_sec = []
    for sec in range(3):
        passed = blocked = 0
        for i in range(100):
            vt.advance(10)  # 100 attempts spread over the second
            try:
                with client.entry("HelloWorld"):
                    pass
            except st.BlockException:
                blocked += 1
            else:
                passed += 1
        passes_per_sec.append(passed)
    # sliding 1 s window over 2x500 ms buckets: 20/s steady-state, with the
    # classic ±1 at bucket-expiry boundaries (same as the reference LeapArray)
    assert all(19 <= p <= 21 for p in passes_per_sec), passes_per_sec
    assert sum(passes_per_sec) <= 62


def test_batched_admission_exact(client, vt):
    """A burst bigger than the remaining quota admits exactly the quota."""
    client.flow_rules.load([st.FlowRule(resource="burst", count=7)])
    results = client.check_batch(["burst"] * 30)
    passed = sum(1 for v, _ in results if v == 0)
    assert passed == 7
    # same window: nothing left
    results = client.check_batch(["burst"] * 10)
    assert sum(1 for v, _ in results if v == 0) == 0
    vt.advance(1000)
    results = client.check_batch(["burst"] * 10)
    assert sum(1 for v, _ in results if v == 0) == 7


def test_try_entry_and_stats(client, vt):
    client.flow_rules.load([st.FlowRule(resource="r1", count=5)])
    got = 0
    for _ in range(10):
        e = client.try_entry("r1")
        if e:
            got += 1
            e.exit()
    assert got == 5
    s = client.stats.resource("r1")
    assert s["passQps"] == 5.0
    assert s["blockQps"] == 5.0
    assert s["curThreadNum"] == 0


def test_thread_grade_concurrency(client, vt):
    client.flow_rules.load(
        [st.FlowRule(resource="conc", count=3, grade=st.GRADE_THREAD)]
    )
    held = []
    for _ in range(5):
        e = client.try_entry("conc")
        if e:
            held.append(e)
    assert len(held) == 3
    # releasing one frees a slot
    held.pop().exit()
    assert client.try_entry("conc") is not None


def test_rate_limiter_pacing(client, vt):
    # 10 QPS leaky bucket → 100 ms spacing, queue up to 500 ms
    client.flow_rules.load(
        [
            st.FlowRule(
                resource="paced",
                count=10,
                control_behavior=st.CONTROL_RATE_LIMITER,
                max_queueing_time_ms=500,
            )
        ]
    )
    results = client.check_batch(["paced"] * 8)
    verdicts = [v for v, _ in results]
    waits = [w for _, w in results]
    # first passes immediately, the next five queue 100 ms apart, and the
    # two whose delay would exceed 500 ms are rejected
    assert verdicts[0] == 0 and waits[0] == 0
    assert all(v == 6 for v in verdicts[1:6])
    assert [round(w, -1) for w in waits[1:6]] == [100, 200, 300, 400, 500]
    assert verdicts[6] == 1 and verdicts[7] == 1
    # the bucket is full for the next 500 ms → still blocked
    results = client.check_batch(["paced"] * 3)
    assert all(v == 1 for v, _ in results)
    # after time passes the queue drains
    vt.advance(2000)
    results = client.check_batch(["paced"])
    assert results[0][0] == 0


def test_unruled_resource_passes(client, vt):
    results = client.check_batch(["no-rule"] * 50)
    assert all(v == 0 for v, _ in results)
