"""sentinel_tpu.analysis.concurrency — the tier-3 concurrency analyzer.

Three jobs:

1. unit-test every pass on fixture trees — one triggering and one clean
   per rule (a seeded two-lock cycle, a blocking call routed through a
   helper that intra-procedural scanning would miss, an unjoined
   non-daemon thread), plus the golden round-trip and the
   ``--update-lock-order`` scoping contract;
2. THE CI GATE: run the whole tier over the real ``sentinel_tpu/`` tree
   and require zero findings — the committed ``lock_order.json`` must be
   acyclic and exactly match the tree, and every blocking-under-lock /
   thread-lifecycle site must be fixed or carry a written rationale;
3. check the static claims against reality: a witness-instrumented
   threaded ``SentinelClient`` run must record zero order violations and
   no dynamic edge the static graph missed, and the concurrency fixes
   this tier motivated (non-blocking cluster connect, bounded resolver
   drain, timeout-carrying worker waits) each keep a regression test.

The fixture tests are pure AST work; the gate builds one whole-package
summary DB (~2 s); only the witness smoke and drain tests import jax.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from sentinel_tpu.analysis import REPO_ROOT, rule_catalog
from sentinel_tpu.analysis.concurrency import (
    LOCK_ORDER_PATH,
    current_edges,
    load_lock_order,
    run_concurrency_analysis,
    save_lock_order,
    update_lock_order,
)
from sentinel_tpu.analysis.concurrency.passes import (
    ALL_CONCURRENCY_PASSES,
    GRAPH_PATH,
    BlockingUnderLockPass,
    LockOrderCyclePass,
    LockOrderNewEdgePass,
    ThreadLifecyclePass,
    _sccs,
)
from sentinel_tpu.analysis.concurrency.summaries import build_db


def _db(tmp_path, files):
    """Summary DB over an inline fixture tree (uncached)."""
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return build_db([str(tmp_path)], str(tmp_path), cached=False)


def _run(p, db, golden=None):
    return list(p.run(db, golden))


# ---------------------------------------------------------------------------
# lock-order-cycle
# ---------------------------------------------------------------------------

CYCLE_SRC = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def ab():
        with A:
            with B:
                pass

    def ba():
        with B:
            with A:
                pass
"""


def test_two_lock_cycle_is_reported_with_both_stacks(tmp_path):
    db = _db(tmp_path, {"twist.py": CYCLE_SRC})
    found = _run(LockOrderCyclePass(), db)
    assert len(found) == 1, found
    f = found[0]
    assert f.rule == "lock-order-cycle"
    assert f.path == GRAPH_PATH
    # both acquisition chains are named so the report is actionable
    assert "twist.A" in f.message and "twist.B" in f.message
    assert "ab" in f.message and "ba" in f.message


def test_consistent_order_is_clean(tmp_path):
    db = _db(
        tmp_path,
        {
            "calm.py": """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A:
                    with B:
                        pass

            def two():
                with A:
                    with B:
                        pass
            """
        },
    )
    assert _run(LockOrderCyclePass(), db) == []


def test_interprocedural_cycle_through_helper(tmp_path):
    """A cycle whose A→B edge only exists through a helper call — the
    point of summary propagation: no single function shows both orders."""
    db = _db(
        tmp_path,
        {
            "twist.py": """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def _grab_b():
                with B:
                    pass

            def outer():
                with A:
                    _grab_b()

            def reverse():
                with B:
                    with A:
                        pass
            """
        },
    )
    found = _run(LockOrderCyclePass(), db)
    assert len(found) == 1, found
    assert "twist.A" in found[0].message and "twist.B" in found[0].message
    assert "_grab_b" in found[0].message  # the chain names the helper


# ---------------------------------------------------------------------------
# lock-order-new-edge + golden workflow
# ---------------------------------------------------------------------------


def test_new_edge_vs_golden_fails_with_site(tmp_path):
    db = _db(
        tmp_path,
        {
            "fresh.py": """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def pair():
                with A:
                    with B:
                        pass
            """
        },
    )
    found = _run(LockOrderNewEdgePass(), db, golden=set())
    assert len(found) == 1
    f = found[0]
    assert f.rule == "lock-order-new-edge"
    assert f.severity == "error"
    assert f.path == "fresh.py"  # anchored at the real acquisition site
    assert "fresh.A -> fresh.B" in f.message


def test_stale_golden_edge_warns_and_blessed_edge_is_silent(tmp_path):
    db = _db(
        tmp_path,
        {
            "fresh.py": """
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def pair():
                with A:
                    with B:
                        pass
            """
        },
    )
    golden = {"fresh.A -> fresh.B", "fresh.GONE -> fresh.B"}
    found = _run(LockOrderNewEdgePass(), db, golden=golden)
    assert len(found) == 1
    f = found[0]
    assert f.severity == "warning" and "fresh.GONE" in f.message
    assert f.path == GRAPH_PATH


def test_no_golden_skips_the_edge_diff(tmp_path):
    db = _db(tmp_path, {"fresh.py": CYCLE_SRC})
    assert _run(LockOrderNewEdgePass(), db, golden=None) == []


def test_golden_round_trip(tmp_path):
    path = str(tmp_path / "lock_order.json")
    edges = ["m.B -> m.C", "m.A -> m.B", "m.A -> m.B"]  # dupes collapse
    save_lock_order(edges, path)
    assert load_lock_order(path) == {"m.A -> m.B", "m.B -> m.C"}
    # the file is reviewable: sorted, commented, newline-terminated
    raw = open(path).read()
    assert raw.endswith("\n")
    data = json.loads(raw)
    assert data["edges"] == sorted(set(edges))
    assert "--update-lock-order" in data["comment"]


def test_load_lock_order_missing_file_is_none(tmp_path):
    assert load_lock_order(str(tmp_path / "absent.json")) is None


def test_update_lock_order_scoping(tmp_path):
    """--update-lock-order over a SUBTREE writes only that subtree's
    edges — a scoped re-bless must not silently drop the rest of the
    repo's constraints from a golden it then overwrites."""
    path = str(tmp_path / "lock_order.json")
    sub = os.path.join(REPO_ROOT, "sentinel_tpu", "cluster")
    n = update_lock_order(path=path, roots=[sub])
    scoped = load_lock_order(path)
    assert n == len(scoped) > 0
    full = set(current_edges())
    # every scoped edge exists in the full graph under the same ids
    # (canonicalization must not depend on which roots were scanned)
    assert scoped <= full
    assert scoped < full  # and scoping genuinely narrowed the set


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


def test_blocking_call_through_helper_is_found(tmp_path):
    """The trigger an intra-procedural lint cannot see: the lock is in
    one function, the socket connect two calls away."""
    db = _db(
        tmp_path,
        {
            "svc.py": """
            import socket
            import threading

            L = threading.Lock()

            def _dial(host):
                return socket.create_connection((host, 80))

            def _fetch(host):
                return _dial(host)

            def serve(host):
                with L:
                    return _fetch(host)
            """
        },
    )
    found = _run(BlockingUnderLockPass(), db)
    assert len(found) == 1, found
    f = found[0]
    assert f.rule == "blocking-under-lock"
    assert f.path == "svc.py"
    assert "svc.L" in f.message
    # the call chain to the blocking op is reconstructed for the report
    assert "_fetch" in f.message and "_dial" in f.message


def test_blocking_without_lock_is_clean(tmp_path):
    db = _db(
        tmp_path,
        {
            "svc.py": """
            import socket
            import threading

            L = threading.Lock()

            def _dial(host):
                return socket.create_connection((host, 80))

            def serve(host):
                with L:
                    pass
                return _dial(host)
            """
        },
    )
    assert _run(BlockingUnderLockPass(), db) == []


def test_source_site_suppression_kills_transitive_findings(tmp_path):
    """A rationale ON the blocking call removes it from the summary —
    callers holding locks stop reporting it too."""
    db = _db(
        tmp_path,
        {
            "svc.py": """
            import socket
            import threading

            L = threading.Lock()

            def _dial(host):
                return socket.create_connection((host, 80))  # stlint: disable=blocking-under-lock — fixture rationale

            def serve(host):
                with L:
                    return _dial(host)
            """
        },
    )
    assert _run(BlockingUnderLockPass(), db) == []


def test_timeoutless_future_result_under_lock(tmp_path):
    db = _db(
        tmp_path,
        {
            "pool.py": """
            import threading

            L = threading.Lock()

            def wait_all(futs):
                with L:
                    return [f.result() for f in futs]
            """
        },
    )
    found = _run(BlockingUnderLockPass(), db)
    assert len(found) == 1
    assert "future-result" in found[0].message


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------


def test_unjoined_non_daemon_thread_is_reported(tmp_path):
    db = _db(
        tmp_path,
        {
            "svc.py": """
            import threading

            class Svc:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass
            """
        },
    )
    found = _run(ThreadLifecyclePass(), db)
    assert len(found) == 1, found
    assert found[0].rule == "thread-lifecycle"
    assert found[0].path == "svc.py"


def test_daemon_or_joined_threads_are_clean(tmp_path):
    db = _db(
        tmp_path,
        {
            "svc.py": """
            import threading

            class Daemonic:
                def start(self):
                    self._t = threading.Thread(target=self._run, daemon=True)
                    self._t.start()

                def _run(self):
                    pass

            class Joined:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass

                def stop(self):
                    self._t.join()
            """
        },
    )
    assert _run(ThreadLifecyclePass(), db) == []


def test_timeoutless_wait_under_lock_is_reported(tmp_path):
    db = _db(
        tmp_path,
        {
            "svc.py": """
            import threading

            class W:
                def __init__(self):
                    self._cv = threading.Condition()

                def pump(self):
                    with self._cv:
                        self._cv.wait()
            """
        },
    )
    found = _run(ThreadLifecyclePass(), db)
    assert len(found) == 1
    assert "timeout" in found[0].message

    db2 = _db(
        tmp_path / "b",
        {
            "svc.py": """
            import threading

            class W:
                def __init__(self):
                    self._cv = threading.Condition()

                def pump(self):
                    with self._cv:
                        self._cv.wait(timeout=1.0)
            """
        },
    )
    assert _run(ThreadLifecyclePass(), db2) == []


# ---------------------------------------------------------------------------
# CLI / reporting integration
# ---------------------------------------------------------------------------


def test_rule_catalog_spans_three_tiers():
    cat = rule_catalog()
    for p in ALL_CONCURRENCY_PASSES:
        assert p.name in cat and cat[p.name]


def test_sarif_carries_tier3_findings(tmp_path):
    from sentinel_tpu.analysis.framework import format_sarif

    db = _db(tmp_path, {"twist.py": CYCLE_SRC})
    findings = []
    for p in ALL_CONCURRENCY_PASSES:
        findings.extend(p.run(db, None))
    assert findings
    doc = json.loads(format_sarif(findings, findings, rule_catalog()))
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "lock-order-cycle" in rule_ids
    locs = [
        r["locations"][0]["physicalLocation"]["artifactLocation"]
        for r in run["results"]
    ]
    # the concurrency:// pseudo-path must not claim the repo uriBaseId
    pseudo = [l for l in locs if l["uri"].startswith("concurrency://")]
    assert pseudo and all("uriBaseId" not in l for l in pseudo)


def test_cli_tier_concurrency_gate():
    env = {**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "sentinel_tpu.analysis", "--tier", "concurrency"],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stdout


# ---------------------------------------------------------------------------
# THE repo gate
# ---------------------------------------------------------------------------


def test_repo_gate_zero_findings_and_acyclic_blessed_graph():
    """The CI contract for this tier: the committed golden exists, is
    exactly the current tree's edge set (any new edge fails until
    reviewed and re-blessed), the graph is acyclic, and every finding
    across all four passes is fixed or carries a written rationale."""
    golden = load_lock_order(LOCK_ORDER_PATH)
    assert golden, "lock_order.json missing or empty — re-bless and commit"
    assert set(current_edges()) == golden

    succ = {}
    for e in golden:
        a, _, b = e.partition(" -> ")
        succ.setdefault(a, set()).add(b)
        succ.setdefault(b, set())
    assert _sccs(set(succ), succ) == []

    findings = run_concurrency_analysis()
    assert findings == [], "\n".join(
        f"{f.path}:{f.line} [{f.rule}] {f.message}" for f in findings
    )


def test_tier3_baseline_is_empty():
    """Tier 3 launched with ZERO accepted debt: no concurrency rule may
    appear in baseline.json — new hazards get fixed or a written
    rationale, never a baseline bump."""
    from sentinel_tpu.analysis import DEFAULT_BASELINE, load_baseline

    conc_rules = {p.name for p in ALL_CONCURRENCY_PASSES}
    offenders = [
        k for k in load_baseline(DEFAULT_BASELINE) if k.split(":")[0] in conc_rules
    ]
    assert offenders == []


def test_canonical_runtime_order_tick_mutex_outer():
    """The ordering fix this tier landed: ``_tick_mutex`` is the OUTER
    runtime lock, ``_cluster_lock`` inner.  The reverse edge coming back
    (recompile warming inside the cluster lock again) re-creates the
    mode-partitioned deadlock hazard PR 16 removed."""
    edges = set(current_edges())
    tm = "runtime.client.SentinelClient._tick_mutex"
    cl = "runtime.client.SentinelClient._cluster_lock"
    assert f"{tm} -> {cl}" in edges
    assert f"{cl} -> {tm}" not in edges


# ---------------------------------------------------------------------------
# regression tests for the concurrency fixes this tier motivated
# ---------------------------------------------------------------------------


def test_ensure_connected_does_not_queue_behind_a_connect():
    """While one thread owns the connect lock, other admission threads
    must get an instant False (degraded fallback), not block for the
    2 s connect window."""
    from sentinel_tpu.cluster.client import ClusterTokenClient

    c = ClusterTokenClient("127.0.0.1", 1, reconnect_interval_s=0.0)
    assert c._lock.acquire(blocking=False)
    try:
        t0 = time.monotonic()
        assert c._ensure_connected() is False
        assert time.monotonic() - t0 < 0.5
    finally:
        c._lock.release()


def test_drain_resolves_abandons_wedged_ticks(monkeypatch):
    """A resolver future that never completes (wedged device readback)
    must not hang stop() under _tick_mutex forever: the drain shares one
    deadline and abandons what is still running."""
    from concurrent.futures import Future

    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime import client as RC

    c = RC.SentinelClient(cfg=small_engine_config(), mode="sync")
    wedged = Future()  # never resolved
    done = Future()
    done.set_result(None)
    c._pending_ticks = []
    c._resolve_futs = [done, wedged]

    # virtual clock: the first mono_s() sets the deadline, every later
    # read is past it — the drain must take the timeout path instantly
    ticks = iter([100.0] + [1000.0] * 10)
    monkeypatch.setattr(RC, "mono_s", lambda: next(ticks))
    c._drain_resolves()
    assert c._resolve_futs == []
    assert not wedged.done()  # abandoned, not cancelled into a fake result


def test_worker_waits_carry_timeouts():
    """The lost-notify fix: every Condition.wait on the lease-refresher
    and token-batcher worker loops must carry a timeout (a missed
    notify degrades to a bounded poll instead of a parked-forever
    thread).  Source-level so a revert cannot hide behind scheduling."""
    for rel in ("sentinel_tpu/cluster/shard.py", "sentinel_tpu/cluster/token_service.py"):
        tree = ast.parse(open(os.path.join(REPO_ROOT, rel)).read())
        bare = [
            node.lineno
            for node in ast.walk(tree)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "wait"
            and not node.args
            and not node.keywords
        ]
        assert bare == [], f"{rel}: timeout-less wait() at lines {bare}"


# ---------------------------------------------------------------------------
# runtime lock witness
# ---------------------------------------------------------------------------


@pytest.fixture()
def witness():
    from sentinel_tpu.analysis.concurrency import witness as W

    W.install()
    W.reset()
    yield W
    W.uninstall()
    W.reset()


def test_witness_records_and_inverts(witness):
    W = witness
    a = W.WitnessLock(W._REAL_LOCK(), "fix.A._lock", reentrant=False)
    b = W.WitnessLock(W._REAL_LOCK(), "fix.B._lock", reentrant=False)
    with a:
        with b:
            pass
    assert ("fix.A._lock", "fix.B._lock") in W.dynamic_edges()
    assert W.violations() == []
    with b:
        with a:
            pass
    assert any("order inversion" in v for v in W.violations())
    ok, detail = W.verdict()
    assert not ok and "violation" in detail


def test_witness_same_instance_reacquire_raises(witness):
    W = witness
    a = W.WitnessLock(W._REAL_LOCK(), "fix.A._lock", reentrant=False)
    with a:
        with pytest.raises(RuntimeError, match="self-deadlock"):
            a.acquire()
    assert any("re-acquire" in v for v in W.violations())


def test_witness_rlock_reentry_and_condition_are_clean(witness):
    W = witness
    r = W.WitnessRLock(W._REAL_RLOCK(), "fix.C._rlock")
    with r:
        with r:
            pass
    cv = threading.Condition(r)

    def poke():
        with cv:
            cv.notify()

    with cv:
        t = threading.Thread(target=poke)
        t.start()
        cv.wait(timeout=2.0)
        t.join()
    assert W.violations() == []
    assert W._held_stack() == []


def test_witnessed_client_smoke_no_violations(witness):
    """The acceptance run: a real threaded SentinelClient under the
    witness — zero violations, zero dynamic edges the static graph
    missed."""
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.core.rules import FlowRule
    from sentinel_tpu.runtime.client import SentinelClient

    W = witness
    c = SentinelClient(
        cfg=small_engine_config(), mode="threaded", tick_interval_ms=2.0
    )
    c.flow_rules.load([FlowRule(resource="res-w", count=100.0)])
    c.start()
    try:
        for _ in range(3):
            with c.entry("res-w"):
                pass
            time.sleep(0.01)
    finally:
        c.stop()
    assert W.violations() == []
    assert W.edges_unknown_to_static() == []
    # the run actually exercised witnessed locks — this is not a vacuous
    # pass on an uninstrumented client
    assert any(
        "runtime.client.SentinelClient" in a or "runtime.client.SentinelClient" in b
        for a, b in W.dynamic_edges()
    )
    ok, detail = W.verdict()
    assert ok, detail


def test_chaos_invariant_is_universal_and_green_when_inactive():
    from sentinel_tpu.chaos.invariants import (
        CATALOG,
        MetricsDelta,
        ScenarioContext,
        evaluate,
    )

    assert "no-order-violations" in CATALOG
    out = evaluate(["verdict-accounting"], ScenarioContext(metrics=MetricsDelta()))
    names = [v.name for v in out]
    assert "no-order-violations" in names  # appended without being asked
    v = next(v for v in out if v.name == "no-order-violations")
    assert v.ok and "inactive" in v.detail
