"""Prioritized occupy-ahead (DefaultController.tryOccupyNext /
OccupiableBucketLeapArray): a prioritized request rejected by the QPS check
borrows from the next bucket's budget, waits for it, and enters — up to one
bucket's worth per rule; the borrowed tokens reduce the next bucket's
budget exactly."""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.ops import window as W


@pytest.fixture()
def c(client_factory):
    return client_factory()


def _fill(c, vt, res, n):
    """Admit n normal requests inside the current bucket."""
    ok = 0
    for _ in range(n):
        try:
            with c.entry(res):
                pass
            ok += 1
        except st.BlockException:
            pass
    return ok


def test_prioritized_borrows_next_bucket(c, vt):
    c.flow_rules.load([st.FlowRule(resource="occ", count=4)])
    assert _fill(c, vt, "occ", 4) == 4
    # normal request: rejected
    with pytest.raises(st.FlowException):
        c.entry("occ")
    # prioritized request: borrows from the next bucket and waits
    t0 = c.time.now_ms()
    e = c.entry("occ", prioritized=True)
    waited = c.time.now_ms() - t0
    assert 0 < waited <= c.cfg.second_window_ms  # slept to the bucket edge
    e.exit()
    s = c.stats.resource("occ")
    assert s["occupiedPassQps"] >= 1


def test_occupy_capped_at_one_bucket():
    """Within ONE tick, borrows against the next bucket stop at the rule's
    count (maxOccupyRatio = 1): 3 of 6 prioritized over-quota requests get
    SHOULD-WAIT verdicts, the rest block."""
    import jax.numpy as jnp

    from sentinel_tpu.core import errors as ERR
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.core.rules import FlowRule
    from sentinel_tpu.ops import engine as E
    from sentinel_tpu.runtime.registry import Registry

    cfg = small_engine_config()
    reg = Registry(cfg)
    reg.resource_id("cap")
    ruleset = E.compile_ruleset(cfg, reg, flow_rules=[FlowRule(resource="cap", count=3)])
    tick = E.make_tick(cfg, donate=False)
    state = E.init_state(cfg)
    b = cfg.batch_size
    rid = reg.peek_resource_id("cap")

    # one batch: 3 normal (fill the window) + 6 prioritized over-quota
    res = jnp.full((b,), cfg.trash_row, jnp.int32).at[:9].set(rid)
    prio = jnp.zeros((b,), jnp.int32).at[3:9].set(1)
    acq = E.empty_acquire(cfg)._replace(
        res=res, count=jnp.ones((b,), jnp.int32), prio=prio
    )
    state, out = tick(
        state, ruleset, acq, E.empty_complete(cfg), jnp.int32(100),
        jnp.float32(0), jnp.float32(0),
    )
    v = np.asarray(out.verdict)[:9]
    w = np.asarray(out.wait_ms)[:9]
    assert list(v[:3]) == [ERR.PASS] * 3
    assert list(v[3:6]) == [ERR.PASS_WAIT] * 3  # borrows up to count=3
    assert all(0 < x <= 500 for x in w[3:6])
    assert list(v[6:9]) == [ERR.BLOCK_FLOW] * 3  # next-bucket budget spent


def test_borrowed_tokens_reduce_next_bucket(c, vt):
    c.flow_rules.load([st.FlowRule(resource="debt", count=4)])
    _fill(c, vt, "debt", 4)
    e = c.entry("debt", prioritized=True)  # borrows 1; sleeps into next bucket
    e.exit()
    # we are now INSIDE the borrowed-against bucket: the sliding window
    # still holds the previous bucket's 4 passes + the folded borrow
    assert _fill(c, vt, "debt", 4) == 0
    # a full interval later the debt has rolled out of the window
    vt.advance(c.cfg.second_window_ms * c.cfg.second_sample_count)
    assert _fill(c, vt, "debt", 4) == 4


def test_occupy_revoked_by_open_breaker_books_nothing(c, vt):
    """A prioritized over-quota request that a later slot (open circuit
    breaker) blocks must not commit its borrow, must not count OCCUPIED,
    and must not leak concurrency."""
    import numpy as np

    c.flow_rules.load([st.FlowRule(resource="rv", count=1)])
    c.degrade_rules.load(
        [
            st.DegradeRule(
                resource="rv", grade=st.CB_STRATEGY_ERROR_COUNT, count=1,
                min_request_amount=1, stat_interval_ms=1000, time_window=5,
            )
        ]
    )
    # trip the breaker
    with c.entry("rv"):
        c.trace(ValueError("x"))
    vt.advance(50)
    # over-quota normal attempt: flow slot blocks first (reference order)
    with pytest.raises(st.FlowException):
        c.entry("rv")
    # over-quota prioritized attempt: flow GRANTS the occupy, then the open
    # breaker blocks — the grant must be revoked
    with pytest.raises(st.DegradeException):
        c.entry("rv", prioritized=True)
    s = c.stats.resource("rv")
    assert s["occupiedPassQps"] == 0
    assert s["curThreadNum"] == 0
    assert float(np.asarray(c._state.occ_tokens).sum()) == 0


def test_occupied_counts_once(c, vt):
    """occupiedPassQps counts once at grant; the fold adds only the
    deferred PASS (the reference's OCCUPIED_PASS-then-PASS split)."""
    c.flow_rules.load([st.FlowRule(resource="once", count=1)])
    with c.entry("once"):
        pass
    e = c.entry("once", prioritized=True)  # borrows; sleeps into next bucket
    e.exit()
    vt.advance(10)
    s = c.stats.resource("once")
    assert s["occupiedPassQps"] == 1  # not 2
    assert s["passQps"] == 2  # original + folded borrow


def test_cluster_prioritized_should_wait(c, vt):
    """Token-server parity: a prioritized requestToken over the global quota
    comes back STATUS_SHOULD_WAIT with the wait to the next bucket."""
    from sentinel_tpu.cluster import constants as CC
    from sentinel_tpu.cluster.token_service import DefaultTokenService

    svc = DefaultTokenService(c)
    svc.flow_rules.load(
        "ns",
        [
            st.FlowRule(
                resource="g", count=2, cluster_mode=True,
                cluster_flow_id=42, cluster_threshold_type=1,
            )
        ],
    )
    assert svc.request_token(42, 1).status == CC.STATUS_OK
    assert svc.request_token(42, 1).status == CC.STATUS_OK
    assert svc.request_token(42, 1).status == CC.STATUS_BLOCKED
    r = svc.request_token(42, 1, prioritized=True)
    assert r.status == CC.STATUS_SHOULD_WAIT
    assert 0 < r.wait_ms <= c.cfg.second_window_ms


def test_normal_requests_never_occupy(c, vt):
    c.flow_rules.load([st.FlowRule(resource="norm", count=2)])
    _fill(c, vt, "norm", 2)
    with pytest.raises(st.FlowException):
        c.entry("norm")
    s = c.stats.resource("norm")
    assert s["occupiedPassQps"] == 0


def test_relate_rule_occupies_ref_node(c, vt):
    """RELATE rules can borrow ahead (VERDICT r2 #9): the grant records the
    METERED node (the referenced resource's row), the deferred PASS folds
    there, and the next bucket's budget shrinks by the borrow."""
    c.flow_rules.load(
        [
            st.FlowRule(
                resource="write",
                count=2,
                strategy=st.STRATEGY_RELATE,
                ref_resource="read",
            )
        ]
    )
    # the metered node is "read": fill its budget with real read traffic
    assert _fill(c, vt, "read", 2) == 2
    with pytest.raises(st.FlowException):
        c.entry("write")  # read's window is full
    # prioritized borrow waits into the next bucket and enters
    t0 = c.time.now_ms()
    e = c.entry("write", prioritized=True)
    waited = c.time.now_ms() - t0
    assert 0 < waited <= c.cfg.second_window_ms
    e.exit()
    # slide past the original reads' bucket: the window then holds only the
    # bucket the borrow folded into
    vt.advance(c.cfg.second_window_ms + 10)
    # the deferred PASS folded onto the REF node's row ("read"), matching
    # where the rule meters — the borrow consumed the new bucket's budget
    s_read = c.stats.resource("read")
    assert s_read["passQps"] == 1
    with c.entry("read"):  # unruled: counts on read's node (now 2/2)
        pass
    with pytest.raises(st.FlowException):
        c.entry("write")


def test_chain_rule_occupies_ctx_node(c, vt):
    """CHAIN rules borrow against their (resource, context) node."""
    c.flow_rules.load(
        [
            st.FlowRule(
                resource="task",
                count=1,
                strategy=st.STRATEGY_CHAIN,
                ref_resource="ctx-a",
            )
        ]
    )
    with c.context("ctx-a"):
        with c.entry("task"):
            pass
        with pytest.raises(st.FlowException):
            c.entry("task")
        e = c.entry("task", prioritized=True)
        e.exit()
    s = c.stats.resource("task")
    assert s["occupiedPassQps"] == 1
