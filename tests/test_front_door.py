"""Native front door: C epoll ingestion end-to-end over real sockets.

Covers SURVEY §2.9's native host boundary — socket → frame parse →
acquire ring → engine tick → response ring → socket, with Python running
only per tick.  Skipped when the native toolchain is unavailable.
"""

import socket
import struct
import time

import pytest

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.core import rules as R
from sentinel_tpu.native.loader import load_native

pytestmark = pytest.mark.skipif(load_native() is None, reason="no native lib")


@pytest.fixture()
def door_setup():
    from sentinel_tpu.cluster.front_door import NativeFrontDoor
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime.client import SentinelClient

    # threaded + real clock: the front door is served by the tick loop
    decision = SentinelClient(
        cfg=small_engine_config(), mode="threaded", tick_interval_ms=2.0
    )
    decision.start()
    svc = DefaultTokenService(decision)
    svc.flow_rules.load(
        "default",
        [
            R.FlowRule(
                resource="res-101", count=3.0, cluster_mode=True, cluster_flow_id=101
            )
        ],
    )
    door = NativeFrontDoor(port=0)
    door.follow(svc)
    decision.attach_front_door(door)
    door.start()
    yield door, decision
    door.stop()
    decision.stop()
    door.close()


def _rpc(sock, req: P.ClusterRequest) -> P.ClusterResponse:
    sock.sendall(P.encode_request(req))
    head = sock.recv(2)
    (n,) = struct.unpack(">H", head)
    body = b""
    while len(body) < n:
        body += sock.recv(n - len(body))
    return P.decode_response(body)


def test_front_door_flow_roundtrip(door_setup):
    door, decision = door_setup
    s = socket.create_connection(("127.0.0.1", door.port), timeout=5)
    try:
        pong = _rpc(s, P.ClusterRequest(xid=1, type=C.MSG_TYPE_PING, namespace="default"))
        assert pong.status == C.STATUS_OK

        statuses = [
            _rpc(
                s, P.ClusterRequest(xid=10 + i, type=C.MSG_TYPE_FLOW, flow_id=101)
            ).status
            for i in range(5)
        ]
        assert statuses.count(C.STATUS_OK) == 3
        assert statuses.count(C.STATUS_BLOCKED) == 2

        norule = _rpc(s, P.ClusterRequest(xid=99, type=C.MSG_TYPE_FLOW, flow_id=777))
        assert norule.status == C.STATUS_NO_RULE

        # unknown type answered FAIL, not hung (raw frame — the client
        # encoder refuses to build one)
        raw = struct.pack(">iB", 100, 99)
        s.sendall(struct.pack(">H", len(raw)) + raw)
        head = s.recv(2)
        (n2,) = struct.unpack(">H", head)
        body = b""
        while len(body) < n2:
            body += s.recv(n2 - len(body))
        bad = P.decode_response(body)
        assert bad.xid == 100 and bad.status == C.STATUS_FAIL
    finally:
        s.close()


def test_front_door_param_flow(door_setup):
    """MSG_TYPE_PARAM_FLOW served natively: C-side value hashing must agree
    with hash_param, per-value budgets enforce, multi-value requests join
    (all values must pass)."""
    door, decision = door_setup
    svc = door._service
    svc.param_rules.load(
        "default",
        [
            R.ParamFlowRule(
                resource="res-55", param_idx=0, count=2.0,
                cluster_mode=True, cluster_flow_id=55,
            )
        ],
    )
    s = socket.create_connection(("127.0.0.1", door.port), timeout=5)
    try:
        def param(xid, values):
            return _rpc(
                s,
                P.ClusterRequest(
                    xid=xid, type=C.MSG_TYPE_PARAM_FLOW, flow_id=55,
                    count=1, params=list(values),
                ),
            ).status

        # per-value budget 2/s: strings hash in C with Python parity
        assert param(1, ["alice"]) == C.STATUS_OK
        assert param(2, ["alice"]) == C.STATUS_OK
        assert param(3, ["alice"]) == C.STATUS_BLOCKED
        assert param(4, ["bob"]) == C.STATUS_OK  # independent value
        assert param(5, [7]) == C.STATUS_OK  # int hashing parity
        assert param(6, [7]) == C.STATUS_OK
        assert param(7, [7]) == C.STATUS_BLOCKED
        # multi-value join: "carol" has budget, "alice" is exhausted -> all
        # must pass, so the request blocks
        assert param(8, ["carol", "alice"]) == C.STATUS_BLOCKED
        assert param(9, ["carol"]) == C.STATUS_OK
        # doubles can't hash natively (str() parity) -> explicit FAIL
        assert param(10, [3.5]) == C.STATUS_FAIL
        norule = _rpc(
            s,
            P.ClusterRequest(
                xid=12, type=C.MSG_TYPE_PARAM_FLOW, flow_id=777,
                count=1, params=["x"],
            ),
        )
        assert norule.status == C.STATUS_NO_RULE
    finally:
        s.close()


def test_front_door_concurrent_tokens(door_setup):
    """CONCURRENT acquire/release on the same port: TTL token table served
    host-side, token ids round-trip through the native response path."""
    door, decision = door_setup
    s = socket.create_connection(("127.0.0.1", door.port), timeout=5)
    try:
        def acquire(xid):
            return _rpc(
                s,
                P.ClusterRequest(
                    xid=xid, type=C.MSG_TYPE_CONCURRENT_ACQUIRE,
                    flow_id=101, count=1,
                ),
            )

        def release(xid, tid):
            return _rpc(
                s,
                P.ClusterRequest(
                    xid=xid, type=C.MSG_TYPE_CONCURRENT_RELEASE, token_id=tid
                ),
            )

        # rule 101 count=3 (AVG_LOCAL x 0 connected... GLOBAL threshold):
        # acquire up to the limit, then blocked, then release frees a slot
        got = [acquire(200 + i) for i in range(4)]
        ok = [r for r in got if r.status == C.STATUS_OK]
        blocked = [r for r in got if r.status == C.STATUS_BLOCKED]
        assert len(ok) == 3 and len(blocked) == 1
        assert all(r.token_id > 0 for r in ok)
        assert len({r.token_id for r in ok}) == 3  # distinct tokens
        rel = release(300, ok[0].token_id)
        assert rel.status == C.STATUS_RELEASE_OK
        again = release(301, ok[0].token_id)
        assert again.status == C.STATUS_ALREADY_RELEASE
        assert acquire(302).status == C.STATUS_OK  # freed slot reusable
    finally:
        s.close()


def test_front_door_pipelined_burst(door_setup):
    """Many pipelined requests on one socket coalesce into engine batches
    and every one gets a correlated answer."""
    door, decision = door_setup
    s = socket.create_connection(("127.0.0.1", door.port), timeout=5)
    try:
        n = 500
        payload = b"".join(
            P.encode_request(
                P.ClusterRequest(xid=i, type=C.MSG_TYPE_FLOW, flow_id=101)
            )
            for i in range(n)
        )
        s.sendall(payload)
        got = {}
        buf = b""
        deadline = time.monotonic() + 10
        while len(got) < n and time.monotonic() < deadline:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
            while len(buf) >= 2:
                (ln,) = struct.unpack(">H", buf[:2])
                if len(buf) - 2 < ln:
                    break
                rsp = P.decode_response(buf[2 : 2 + ln])
                got[rsp.xid] = rsp.status
                buf = buf[2 + ln :]
        assert len(got) == n, f"only {len(got)}/{n} answered"
        oks = sum(1 for v in got.values() if v == C.STATUS_OK)
        # threshold 3/s — virtually everything blocks, but every xid answers
        assert oks >= 1
        assert all(v in (C.STATUS_OK, C.STATUS_BLOCKED) for v in got.values())
    finally:
        s.close()


def test_front_door_reuseport_shards():
    """SO_REUSEPORT sharding: N doors on ONE port, each with its own io
    thread; the kernel spreads connections and every shard's traffic is
    served by the same engine (the multi-core scaling architecture)."""
    from sentinel_tpu.cluster.front_door import NativeFrontDoor
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime.client import SentinelClient

    decision = SentinelClient(
        cfg=small_engine_config(), mode="threaded", tick_interval_ms=2.0
    )
    decision.start()
    svc = DefaultTokenService(decision)
    svc.flow_rules.load(
        "default",
        [R.FlowRule(resource="res-7", count=1000.0, cluster_mode=True, cluster_flow_id=7)],
    )
    doors = [NativeFrontDoor(port=0, reuseport=True)]
    port = doors[0].port
    doors.append(NativeFrontDoor(port=port, reuseport=True))
    try:
        for d in doors:
            d.follow(svc)
            decision.attach_front_door(d)
            d.start()
        # many short-lived connections: REUSEPORT hashes per 4-tuple, so
        # distinct source ports spread across the two shards
        ok = 0
        for i in range(24):
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            try:
                r = _rpc(s, P.ClusterRequest(xid=i, type=C.MSG_TYPE_FLOW, flow_id=7))
                if r.status == C.STATUS_OK:
                    ok += 1
            finally:
                s.close()
        assert ok == 24
    finally:
        for d in doors:
            d.stop()
        decision.stop()
        for d in doors:
            d.close()
