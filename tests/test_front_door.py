"""Native front door: C epoll ingestion end-to-end over real sockets.

Covers SURVEY §2.9's native host boundary — socket → frame parse →
acquire ring → engine tick → response ring → socket, with Python running
only per tick.  Skipped when the native toolchain is unavailable.
"""

import socket
import struct
import time

import pytest

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.core import rules as R
from sentinel_tpu.native.loader import load_native

pytestmark = pytest.mark.skipif(load_native() is None, reason="no native lib")


@pytest.fixture()
def door_setup():
    from sentinel_tpu.cluster.front_door import NativeFrontDoor
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime.client import SentinelClient

    # threaded + real clock: the front door is served by the tick loop
    decision = SentinelClient(
        cfg=small_engine_config(), mode="threaded", tick_interval_ms=2.0
    )
    decision.start()
    svc = DefaultTokenService(decision)
    svc.flow_rules.load(
        "default",
        [
            R.FlowRule(
                resource="res-101", count=3.0, cluster_mode=True, cluster_flow_id=101
            )
        ],
    )
    door = NativeFrontDoor(port=0)
    door.follow(svc)
    decision.attach_front_door(door)
    door.start()
    yield door, decision
    door.stop()
    decision.stop()
    door.close()


def _rpc(sock, req: P.ClusterRequest) -> P.ClusterResponse:
    sock.sendall(P.encode_request(req))
    head = sock.recv(2)
    (n,) = struct.unpack(">H", head)
    body = b""
    while len(body) < n:
        body += sock.recv(n - len(body))
    return P.decode_response(body)


def test_front_door_flow_roundtrip(door_setup):
    door, decision = door_setup
    s = socket.create_connection(("127.0.0.1", door.port), timeout=5)
    try:
        pong = _rpc(s, P.ClusterRequest(xid=1, type=C.MSG_TYPE_PING, namespace="default"))
        assert pong.status == C.STATUS_OK

        statuses = [
            _rpc(
                s, P.ClusterRequest(xid=10 + i, type=C.MSG_TYPE_FLOW, flow_id=101)
            ).status
            for i in range(5)
        ]
        assert statuses.count(C.STATUS_OK) == 3
        assert statuses.count(C.STATUS_BLOCKED) == 2

        norule = _rpc(s, P.ClusterRequest(xid=99, type=C.MSG_TYPE_FLOW, flow_id=777))
        assert norule.status == C.STATUS_NO_RULE

        # unsupported type answered, not hung
        bad = _rpc(
            s,
            P.ClusterRequest(
                xid=100, type=C.MSG_TYPE_CONCURRENT_ACQUIRE, flow_id=101
            ),
        )
        assert bad.status == C.STATUS_FAIL
    finally:
        s.close()


def test_front_door_pipelined_burst(door_setup):
    """Many pipelined requests on one socket coalesce into engine batches
    and every one gets a correlated answer."""
    door, decision = door_setup
    s = socket.create_connection(("127.0.0.1", door.port), timeout=5)
    try:
        n = 500
        payload = b"".join(
            P.encode_request(
                P.ClusterRequest(xid=i, type=C.MSG_TYPE_FLOW, flow_id=101)
            )
            for i in range(n)
        )
        s.sendall(payload)
        got = {}
        buf = b""
        deadline = time.monotonic() + 10
        while len(got) < n and time.monotonic() < deadline:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
            while len(buf) >= 2:
                (ln,) = struct.unpack(">H", buf[:2])
                if len(buf) - 2 < ln:
                    break
                rsp = P.decode_response(buf[2 : 2 + ln])
                got[rsp.xid] = rsp.status
                buf = buf[2 + ln :]
        assert len(got) == n, f"only {len(got)}/{n} answered"
        oks = sum(1 for v in got.values() if v == C.STATUS_OK)
        # threshold 3/s — virtually everything blocks, but every xid answers
        assert oks >= 1
        assert all(v in (C.STATUS_OK, C.STATUS_BLOCKED) for v in got.values())
    finally:
        s.close()
