"""Multi-host routing over a REAL wire: two shard processes, mixed-batch
fan-out, shard death mid-test, degrade + recovery.

Reference analog: DefaultClusterTokenClient.java:45 / NettyTransportClient
(reconnect, degrade) — here at the host-shard layer (SURVEY §2.9).
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from sentinel_tpu.core import errors as ERR
from sentinel_tpu.parallel.remote_shard import RemoteShard
from sentinel_tpu.parallel.router import ShardRouter, shard_of

HERE = os.path.dirname(os.path.abspath(__file__))


def _spawn_shard(rules_json: str):
    proc = subprocess.Popen(
        [sys.executable, os.path.join(HERE, "shard_host.py"), rules_json],
        stdout=subprocess.PIPE,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("PORT "), f"shard failed to start: {line!r}"
    return proc, int(line.split()[1])


@pytest.fixture(scope="module")
def two_shards():
    """Resources routed by crc32 across 2 shards; each shard enforces a
    rule on one resource it owns."""
    # find resource names landing on each shard deterministically
    a_res = next(f"svc-{i}" for i in range(100) if shard_of(f"svc-{i}", 2) == 0)
    b_res = next(f"svc-{i}" for i in range(100) if shard_of(f"svc-{i}", 2) == 1)
    pa, porta = _spawn_shard(f'[{{"resource": "{a_res}", "count": 3}}]')
    pb, portb = _spawn_shard(f'[{{"resource": "{b_res}", "count": 5}}]')
    yield (a_res, pa, porta), (b_res, pb, portb)
    for p in (pa, pb):
        if p.poll() is None:
            p.kill()
            p.wait(timeout=10)


def test_mixed_batch_two_processes(two_shards):
    (a_res, _pa, porta), (b_res, _pb, portb) = two_shards
    router = ShardRouter(
        [
            RemoteShard("127.0.0.1", porta, timeout_s=10),
            RemoteShard("127.0.0.1", portb, timeout_s=10),
        ]
    )
    # interleaved mixed batch: both shards consulted, results restored in
    # input order, each shard's rule enforced independently
    names = [a_res, b_res] * 8
    results = router.check_batch(names)
    a_pass = sum(1 for i in range(0, 16, 2) if results[i][0] == ERR.PASS)
    b_pass = sum(1 for i in range(1, 16, 2) if results[i][0] == ERR.PASS)
    assert a_pass == 3  # shard A's rule: 3
    assert b_pass == 5  # shard B's rule: 5
    for s in router.shards:
        s.close()


def test_shard_killed_mid_test_degrades_and_recovers(two_shards):
    (a_res, _pa, porta), (b_res, pb, portb) = two_shards
    import sentinel_tpu as st
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime.client import SentinelClient
    from sentinel_tpu.utils.time_source import VirtualTimeSource

    # local fallback with a TIGHTER rule so degraded enforcement is visible
    vt = VirtualTimeSource()
    fb = SentinelClient(cfg=small_engine_config(), time_source=vt)
    fb.start()
    fb.flow_rules.load([st.FlowRule(resource=b_res, count=1)])

    shard_b = RemoteShard(
        "127.0.0.1", portb, timeout_s=10, fallback=fb, retry_interval_s=1.0
    )
    router = ShardRouter([RemoteShard("127.0.0.1", porta, timeout_s=10), shard_b])

    # healthy: remote enforces count=5 — issue a couple through the wire
    healthy = router.check_batch([b_res, b_res])
    assert all(v in (ERR.PASS, ERR.BLOCK_FLOW) for v, _ in healthy)

    # kill shard B mid-test
    pb.send_signal(signal.SIGKILL)
    pb.wait(timeout=10)

    # traffic now degrades to the local fallback (count=1): exactly one
    # passes per window; shard A keeps serving remotely
    vt.advance(1500)
    got = [router.check_batch([b_res])[0][0] for _ in range(4)]
    assert got.count(ERR.PASS) == 1
    assert got.count(ERR.BLOCK_FLOW) == 3
    still_a = router.check_batch([a_res])
    assert still_a[0][0] in (ERR.PASS, ERR.BLOCK_FLOW)

    # a replacement shard process on a NEW port takes over after rewire
    # (membership change); reconnect logic also covers same-port restart
    pb2, portb2 = _spawn_shard(f'[{{"resource": "{b_res}", "count": 5}}]')
    try:
        shard_b.port = portb2
        shard_b._down_until = 0.0
        time.sleep(0.1)
        revived = router.check_batch([b_res] * 6)
        assert sum(1 for v, _ in revived if v == ERR.PASS) == 5
    finally:
        pb2.kill()
        pb2.wait(timeout=10)
        fb.stop()
        for s in router.shards:
            s.close()
