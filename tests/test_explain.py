"""Verdict provenance plane (obs/explain.py + the wire's explain section):
device-packed "explain" records for every blocked decision.

Covers the ISSUE-20 acceptance surface: the packed record round-trips
under jit at the 1M-resource (sketch) config; explain-section corruption
drops provenance but never touches a verdict (the main section still
fails CLOSED on its own checksum); a flash-crowd run stays >=99%
explainable; the block log's 5-field legacy and 7-field provenance line
formats both parse; and cluster v3 deny frames carry the same tuple.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from sentinel_tpu.chaos import FaultPlan, FaultSpec
from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.metrics.block_log import BlockLogger, parse_line
from sentinel_tpu.obs import REGISTRY
from sentinel_tpu.obs import explain as EX
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import wire as WIRE


class _Reg:
    def resource_id(self, n):
        return 1


def _metric(name, **labels):
    m = REGISTRY.get(name, labels or None)
    return float(m.value) if m is not None else 0.0


def _rec(resource, kind, rule=None, sketch=False, forced=False,
         observed=None, threshold=None):
    """Build one 4-word wire record (the _device_explain layout)."""
    w1 = (
        int(kind)
        | (0x8 if sketch else 0)
        | (0x10 if forced else 0)
        | (((rule + 1) if rule is not None else 0) << 16)
    )
    return [int(resource), w1, EX.fx_encode(observed), EX.fx_encode(threshold)]


def _section(records, n_blocked=None):
    """Raw uint32 explain words [n_blocked, sec_sum, K*4 ...] with a
    CORRECT checksum — the shape ops/wire.py hands to obs/explain.py."""
    recs = np.asarray(records, np.uint32).reshape(-1)
    n = len(records) if n_blocked is None else n_blocked
    sec = (
        WIRE.EXPLAIN_MAGIC + n + int(np.sum(recs, dtype=np.uint64))
    ) & 0xFFFFFFFF
    return np.concatenate([np.asarray([n, sec], np.uint32), recs])


# -- fixed-point codec + layout ----------------------------------------------


def test_fx_codec_round_trip():
    assert EX.fx_encode(None) == EX.FX_UNKNOWN
    assert EX.fx_decode(EX.FX_UNKNOWN) is None
    # 1/256 resolution values survive exactly
    for v in (0.0, 1.0, 12.5, 3.00390625, 1e6):
        assert EX.fx_decode(EX.fx_encode(v)) == v
    # clamps: negatives to 0, overflow to the uint32-safe ceiling
    assert EX.fx_decode(EX.fx_encode(-5.0)) == 0.0
    assert EX.fx_encode(1e12) == int(EX.FX_MAX)


def test_wire_layout_explain_section_and_gate():
    # the gate: provenance rides ONLY the packed wire
    assert E.explain_k(small_engine_config()) == 0  # packed_wire unset
    assert E.explain_k(small_engine_config(packed_wire=True)) == 32
    assert E.explain_k(small_engine_config(packed_wire=True, explain_k=0)) == 0
    # layout: the section trails the hot block, main checksum stops at it
    cfg = small_engine_config(packed_wire=True)
    lo = WIRE.layout_for(cfg, 64)
    assert lo.expl_k == 32
    assert lo.total == lo.off_expl + 2 + lo.expl_k * WIRE.EXPLAIN_WORDS
    assert (lo.total - lo.off_expl) * 4 == 520  # the BENCH_r20 wire cost
    # off: layout (and so the traced program) is unchanged
    lo_off = WIRE.layout_for(small_engine_config(packed_wire=True, explain_k=0), 64)
    assert lo_off.expl_k == 0 and lo_off.total == lo_off.off_expl


# -- device round-trip under jit at the 1M-resource config -------------------


@pytest.mark.jitted
def test_engine_packed_explain_round_trip_jit_1m_config():
    """A jitted packed tick at the sketch (1M+ resource id space) config:
    every blocked row's record decodes back with the right resource,
    kind, blamed rule slot, and fixed-point observed/threshold."""
    cfg = small_engine_config(
        packed_wire=True,
        explain_k=8,
        sketch_stats=True,
        sketch_width=256,
        sketch_capacity=1 << 20,  # 1M sketch-tier resources
    )
    rules = E._compile_ruleset(
        cfg, _Reg(), [FlowRule(resource="r", count=3.0)], [], [], [], [], None
    )
    b = 8
    wd = WIRE.acquire_wire_dtypes(cfg)
    acq = E.empty_acquire(cfg, b=b)._replace(
        res=jnp.ones((b,), jnp.int32),
        count=jnp.ones((b,), dtype=wd.get("count", np.int32)),
    )
    st = E.init_state(cfg)
    tick = E.make_tick(cfg, donate=False)
    z = jnp.float32(0.0)
    _st, out = tick(
        st, rules, acq, E.empty_complete(cfg, b=b), jnp.int32(1000), z, z
    )
    lo = WIRE.layout_for(cfg, b)
    frame = WIRE.unpack(np.asarray(out.wire).tobytes(), lo)
    verdict = np.asarray(frame.verdict)
    blocked_rows = np.flatnonzero(verdict == ERR.BLOCK_FLOW)
    assert len(blocked_rows) > 0  # count=3.0 over 8 requests must block
    assert frame.expl is not None
    n_blocked, rows = EX.decode_section(frame.expl)
    assert n_blocked == len(blocked_rows)
    recs = [EX.decode_record(r) for r in rows[:n_blocked]]
    assert all(r is not None for r in recs)
    for r in recs:
        assert r.resource == 1
        assert r.kind_name == "flow" and r.kind == ERR.BLOCK_FLOW
        assert r.rule == 0  # the single compiled flow slot
        assert not r.sketch_tier and not r.forced
        assert r.threshold == 3.0  # exact at 1/256 resolution
        assert r.observed is not None and r.observed >= 3.0
        assert r.margin is not None and r.margin >= 0.0
    # rows past n_blocked are zero padding
    assert not np.asarray(rows[n_blocked:]).any()


# -- decode integrity (fail-open contract) -----------------------------------


def test_decode_section_rejects_any_single_byte_corruption():
    words = _section(
        [_rec(7, ERR.BLOCK_FLOW, rule=2, observed=9.0, threshold=4.0),
         _rec(9, ERR.BLOCK_DEGRADE, rule=0, observed=1.0, threshold=0.5)]
    )
    n, rows = EX.decode_section(words)
    assert n == 2 and rows.shape == (2, WIRE.EXPLAIN_WORDS)
    good = words.tobytes()
    for pos in range(len(good)):
        bad = bytearray(good)
        bad[pos] ^= 0xFF
        with pytest.raises(EX.ExplainDecodeError):
            EX.decode_section(np.frombuffer(bytes(bad), np.uint32))


def test_decode_record_padding_unknown_kind_and_flags():
    # a zero padding row and an undecodable kind both drop, never raise
    assert EX.decode_record([0, 0, 0, 0]) is None
    assert EX.decode_record([5, 7, 0, 0]) is None  # kind 7 unknown
    r = EX.decode_record(
        _rec(3, ERR.BLOCK_FLOW, rule=None, sketch=True, forced=True,
             observed=None, threshold=2.0),
        ts_ms=123, origin="cluster",
    )
    assert r.rule is None and r.sketch_tier and r.forced
    assert r.observed is None and r.threshold == 2.0 and r.margin is None
    assert r.ts_ms == 123 and r.origin == "cluster"


def test_plane_counts_unexplained_beyond_capacity():
    plane = EX.ExplainPlane()
    # 5 blocked, section capacity carried only 2 records
    folded = plane.ingest_section(
        _section(
            [_rec(1, ERR.BLOCK_FLOW, rule=0, observed=5.0, threshold=2.0),
             _rec(2, ERR.BLOCK_PARAM, rule=1, threshold=3.0)],
            n_blocked=5,
        )
    )
    assert folded == 2
    cov = plane.coverage()
    assert cov == {"blocked": 5, "explained": 2, "frac": 0.4}
    # a pre-v3 remote deny has no provenance at all
    plane.count_unexplained(1)
    assert plane.coverage()["blocked"] == 6
    causes = plane.top_causes()
    assert sum(c["count"] for c in causes) == 2
    assert plane.latest_rule(2, ERR.BLOCK_PARAM) == 1
    assert plane.latest_rule(2, ERR.BLOCK_FLOW) is None


def test_plane_eps_annotation_flags_possibly_false_sketch_blocks():
    """A sketch-tier block whose margin is within the audit eps budget is
    the exact signature of a CMS-overestimate false block."""
    pf0 = _metric("sentinel_explain_possibly_false_total")
    plane = EX.ExplainPlane(eps_source=lambda: 10.0)
    within = plane.fold(EX.decode_record(
        _rec(4, ERR.BLOCK_FLOW, rule=0, sketch=True, observed=105.0,
             threshold=100.0)
    ))
    assert within.eps == 10.0 and within.possibly_false
    beyond = plane.fold(EX.decode_record(
        _rec(4, ERR.BLOCK_FLOW, rule=0, sketch=True, observed=150.0,
             threshold=100.0)
    ))
    assert beyond.possibly_false is False
    # exact-tier records carry no eps annotation at all
    exact = plane.fold(EX.decode_record(
        _rec(4, ERR.BLOCK_FLOW, rule=0, observed=101.0, threshold=100.0)
    ))
    assert exact.eps is None and not exact.possibly_false
    assert _metric("sentinel_explain_possibly_false_total") == pf0 + 1


# -- client path -------------------------------------------------------------


def test_client_explains_blocked_decisions(client_factory):
    c = client_factory()
    c.flow_rules.load([FlowRule(resource="expl/r", count=2.0)])
    got = [v for v, _ in c.check_batch(["expl/r"] * 5)]
    assert got.count(int(ERR.BLOCK_FLOW)) == 3
    recs = c.explain("expl/r")
    assert len(recs) == 3
    top = recs[0]
    assert top.kind_name == "flow" and top.rule is not None
    assert top.threshold == 2.0 and top.name == "expl/r"
    assert top.observed is not None and top.origin == "local"
    causes = c.explain_top_causes()
    assert causes and causes[0]["name"] == "expl/r"
    assert causes[0]["count"] == 3 and causes[0]["kind"] == "flow"
    cov = c.explain_coverage()
    assert cov["blocked"] == 3 and cov["frac"] == 1.0
    # unknown resource / plane-off answers stay shaped
    assert c.explain("never-seen") == []


@pytest.mark.parametrize("action", ["corrupt", "short_read", "drop", "raise"])
def test_explain_fault_drops_provenance_never_verdicts(client_factory, action):
    """obs.explain.decode faults: the tick's explanations are lost and
    counted; the verdicts are bit-identical to the unfaulted ticks."""
    c = client_factory()
    c.flow_rules.load([FlowRule(resource="ef/r", count=2.0)])
    c.check_batch(["ef/r"] * 4)  # fill the window
    base = [v for v, _ in c.check_batch(["ef/r"] * 4)]
    assert base == [int(ERR.BLOCK_FLOW)] * 4
    dec0 = _metric("sentinel_explain_decode_failures_total")
    pkd0 = _metric("sentinel_packed_decode_failures_total")
    exp0 = c.explain_coverage()["explained"]
    plan = FaultPlan(
        name=f"expl-{action}", seed=5,
        faults=[FaultSpec("obs.explain.decode", action, max_fires=1)],
    )
    with FP.armed(plan) as st:
        got = [v for v, _ in c.check_batch(["ef/r"] * 4)]
        assert st.injected().get(f"obs.explain.decode:{action}") == 1
    assert got == base  # verdicts untouched by the provenance fault
    assert _metric("sentinel_explain_decode_failures_total") == dec0 + 1
    assert _metric("sentinel_packed_decode_failures_total") == pkd0
    assert c.explain_coverage()["explained"] == exp0  # nothing folded
    # recovery: the next tick's provenance folds again
    c.check_batch(["ef/r"] * 2)
    assert c.explain_coverage()["explained"] == exp0 + 2


def test_main_section_still_fails_closed_with_explain_on(client_factory):
    """The split contract's other half: a mangled MAIN section fails the
    tick CLOSED exactly as before the explain section existed, and the
    failed tick contributes no provenance records."""
    c = client_factory()
    assert E.explain_k(c.cfg) > 0
    c.flow_rules.load([FlowRule(resource="mc/r", count=100.0)])
    c.check_batch(["mc/r"] * 2)
    dec0 = _metric("sentinel_explain_decode_failures_total")
    rec0 = _metric("sentinel_explain_records_total")
    plan = FaultPlan(
        name="main-corrupt", seed=11,
        faults=[FaultSpec("transport.packed.decode", "corrupt", max_fires=1)],
    )
    with FP.armed(plan):
        got = [v for v, _ in c.check_batch(["mc/r"] * 4)]
    assert got == [int(ERR.BLOCK_SYSTEM)] * 4
    assert _metric("sentinel_explain_decode_failures_total") == dec0
    assert _metric("sentinel_explain_records_total") == rec0


def test_flash_crowd_stays_explainable(client_factory):
    """The acceptance bar: >=99% of blocked decisions resolve through
    explain() in a flash-crowd run (explain_k sized to the batch — the
    operator knob for block-heavy workloads; BENCH_r20 measures 100%)."""
    cfg = small_engine_config(explain_k=64)
    c = client_factory(cfg=cfg)
    names = [f"crowd/r{i}" for i in range(8)]
    c.flow_rules.load([FlowRule(resource=n, count=4.0) for n in names])
    for _ in range(10):
        c.check_batch(names * 8)  # 64 decisions/tick, mostly blocked
        c.time.advance(40)
    cov = c.explain_coverage()
    assert cov["blocked"] > 300
    assert cov["frac"] >= 0.99
    # every resource can answer "why?", and the leaderboard adds up
    for n in names:
        recs = c.explain(n, limit=4)
        assert recs and all(r.kind_name == "flow" for r in recs)
    causes = c.explain_top_causes(len(names))
    assert sum(cz["count"] for cz in causes) == cov["explained"]


def test_sketch_tier_block_explains_with_sketch_flag(client_factory):
    """A block enforced from the SALSA/CMS estimate carries the
    sketch-tier flag — the hook the eps annotation keys off."""
    cfg = small_engine_config(
        max_resources=4, max_nodes=8, sketch_stats=True, sketch_width=256
    )
    c = client_factory(cfg=cfg)
    for i in range(5):  # exhaust the exact row space
        c.registry.resource_id(f"sk-{i}")
    c.flow_rules.load([FlowRule(resource="sk-tail", count=0)])
    rid = c.registry.peek_resource_id("sk-tail")
    if rid is not None and not c.registry.is_sketch_id(rid):
        pytest.skip("promotion found an exact row for the ruled resource")
    with pytest.raises(ERR.BlockException):
        with c.entry("sk-tail"):
            pass
    recs = c.explain("sk-tail")
    assert recs
    assert recs[0].kind_name == "flow" and recs[0].sketch_tier


def test_flight_bundle_carries_explain_section(client_factory):
    from sentinel_tpu.obs import flight as FL

    c = client_factory()
    c.flow_rules.load([FlowRule(resource="fb/r", count=1.0)])
    c.check_batch(["fb/r"] * 3)
    bundle = FL.FLIGHT.dump_bundle(reason="test")
    sec = bundle["providers"].get("explain")
    assert sec is not None
    assert sec["coverage"]["explained"] >= 2
    assert any(r["kind"] == "flow" for r in sec["recent"])
    assert sec["top_causes"][0]["count"] >= 2


# -- block log: 7-field provenance lines, legacy lines still parse -----------


def test_block_log_parses_both_line_formats(tmp_path):
    legacy = parse_line("5000|res1|FlowException|100|web")
    assert legacy == {
        "ts": 5000, "resource": "res1", "exception": "FlowException",
        "count": 100, "origin": "web", "kind": None, "rule": None,
    }
    expl = parse_line("5000|res1|FlowException|100|web|flow|3")
    assert expl["kind"] == "flow" and expl["rule"] == 3
    unattr = parse_line("5000|res1|FlowException|1|||")
    assert unattr["kind"] is None and unattr["rule"] is None
    assert parse_line("garbage") is None
    assert parse_line("a|b|c|d|e|f") is None  # 6 fields: neither format
    assert parse_line("x|res|E|nan|o") is None
    assert parse_line("5000|r|E|1|o|flow|notanint") is None
    # the writer emits legacy lines without provenance, 7-field with
    bl = BlockLogger(str(tmp_path))
    bl.log(5000, "r1", "FlowException", "web")
    bl.log(5000, "r2", "FlowException", "web", kind="flow", rule=2)
    bl.flush()
    lines = open(bl.path).read().strip().split("\n")
    assert "5000|r1|FlowException|1|web" in lines
    assert "5000|r2|FlowException|1|web|flow|2" in lines
    assert all(parse_line(ln) is not None for ln in lines)


def test_client_block_log_line_carries_provenance_key(
    client_factory, tmp_path, monkeypatch
):
    import sentinel_tpu.metrics.block_log as BL

    monkeypatch.setattr(BL, "_default", None)
    monkeypatch.setenv("CSP_SENTINEL_LOG_DIR", str(tmp_path))
    c = client_factory(block_log=True)
    c.flow_rules.load([FlowRule(resource="blk2", count=0)])
    with pytest.raises(ERR.BlockException):
        c.entry("blk2")
    c.block_log.flush()
    rows = [parse_line(ln) for ln in open(c.block_log.path)]
    row = next(r for r in rows if r and r["resource"] == "blk2")
    assert row["exception"] == "FlowException"
    assert row["kind"] == "flow" and row["rule"] == 0
    monkeypatch.setattr(BL, "_default", None)


# -- cluster v3 deny provenance ----------------------------------------------


def test_cluster_deny_provenance_round_trips():
    from sentinel_tpu.cluster import protocol as CP

    rsp = CP.ClusterBatchResponse(
        xid=7, status=0,
        statuses=np.asarray([0, 2, 0], np.int8),
        remainings=np.asarray([1, 0, 1], np.int32),
        waits=np.zeros(3, np.int32),
        token_ids=np.zeros(3, np.int64),
        prov=[None, (ERR.BLOCK_FLOW, 3, 12.5, 10.0), None],
    )
    frame = CP.encode_batch_response(rsp)
    out = CP.decode_batch_response(frame[2:])
    assert out.prov == [None, (ERR.BLOCK_FLOW, 3, 12.5, 10.0), None]
    # unknown observed/limit survive as None (FX_UNKNOWN on the wire)
    rsp2 = dataclasses.replace(
        rsp, prov=[None, (ERR.BLOCK_PARAM, 0, None, None), None]
    )
    out2 = CP.decode_batch_response(CP.encode_batch_response(rsp2)[2:])
    assert out2.prov[1] == (ERR.BLOCK_PARAM, 0, None, None)
    # no provenance at all: the frame is byte-identical to v2
    plain = CP.encode_batch_response(dataclasses.replace(rsp, prov=None))
    empty = CP.encode_batch_response(
        dataclasses.replace(rsp, prov=[None, None, None])
    )
    assert plain == empty
    assert CP.decode_batch_response(plain[2:]).prov is None


def test_plane_folds_remote_deny_provenance():
    plane = EX.ExplainPlane()
    rec = plane.fold_remote(
        resource=42, kind=ERR.BLOCK_FLOW, rule=3, observed=12.5,
        threshold=10.0, ts_ms=999,
    )
    assert rec.origin == "cluster" and rec.kind_name == "flow"
    assert rec.rule == 3 and rec.margin == 2.5
    assert plane.coverage() == {"blocked": 1, "explained": 1, "frac": 1.0}
    assert plane.top_causes()[0]["origin"] == "cluster"
    # an unknown kind from a newer peer drops cleanly
    assert plane.fold_remote(1, kind=99, rule=0, observed=None,
                             threshold=None) is None
