"""Store datasource bindings (datasource/stores.py, zookeeper.py) against
fake servers speaking each store's real wire protocol subset.

Engine-free (no jax): these exercise the transport + SPI wiring; the
datasource→RuleManager→engine plumbing is covered by test_datasource.py /
test_redis_datasource.py.
"""

from __future__ import annotations

import base64
import json
import socket
import struct
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from sentinel_tpu.datasource.property import SimplePropertyListener
from sentinel_tpu.datasource import stores as ST
from sentinel_tpu.datasource.zookeeper import ZookeeperDataSource


def _serve(handler_cls):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


def _collect(ds):
    got = []
    evt = threading.Event()

    def on(v):
        got.append(v)
        evt.set()

    ds.get_property().add_listener(SimplePropertyListener(on))
    return got, evt


def _wait(evt, got, pred, timeout=8.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if got and pred(got[-1]):
            return True
        evt.clear()
        evt.wait(0.25)
    return False


# --------------------------- nacos ---------------------------------------


def test_nacos_long_poll_push():
    state = {"value": "v1", "changed": threading.Event()}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            assert self.path.startswith("/nacos/v1/cs/configs?")
            q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
            assert q["dataId"] == ["rules"] and q["group"] == ["G"]
            body = state["value"].encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            assert self.path == "/nacos/v1/cs/configs/listener"
            n = int(self.headers["Content-Length"])
            raw = urllib.parse.parse_qs(self.rfile.read(n).decode())
            listening = raw["Listening-Configs"][0]
            data_id, group, _md5 = listening.rstrip("\x01").split("\x02")[:3]
            # hold until a change or a short timeout (fake long poll)
            changed = state["changed"].wait(timeout=2.0)
            self.send_response(200)
            self.end_headers()
            if changed:
                state["changed"].clear()
                self.wfile.write(
                    urllib.parse.quote(f"{data_id}\x02{group}\x01").encode()
                )

    srv, port = _serve(H)
    ds = ST.NacosDataSource(
        f"127.0.0.1:{port}", "G", "rules", parser=lambda s: s.upper(),
        poll_timeout_ms=2000,
    )
    try:
        got, evt = _collect(ds)
        assert ds.get_property().value == "V1"
        state["value"] = "v2"
        state["changed"].set()
        assert _wait(evt, got, lambda v: v == "V2")
    finally:
        ds.close()
        srv.shutdown()


# --------------------------- consul --------------------------------------


def test_consul_blocking_query():
    state = {"value": "c1", "index": 7, "changed": threading.Event()}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            u = urllib.parse.urlparse(self.path)
            assert u.path == "/v1/kv/sentinel/rules"
            q = urllib.parse.parse_qs(u.query)
            if "index" in q and int(q["index"][0]) >= state["index"]:
                state["changed"].wait(timeout=2.0)
                state["changed"].clear()
            body = json.dumps(
                [{"Value": base64.b64encode(state["value"].encode()).decode()}]
            ).encode()
            self.send_response(200)
            self.send_header("X-Consul-Index", str(state["index"]))
            self.end_headers()
            self.wfile.write(body)

    srv, port = _serve(H)
    ds = ST.ConsulDataSource(
        "127.0.0.1", port, "sentinel/rules", parser=lambda s: s + "!",
        watch_timeout_s=2,
    )
    try:
        got, evt = _collect(ds)
        assert ds.get_property().value == "c1!"
        state["value"] = "c2"
        state["index"] = 8
        state["changed"].set()
        assert _wait(evt, got, lambda v: v == "c2!")
    finally:
        ds.close()
        srv.shutdown()


# --------------------------- apollo --------------------------------------


def test_apollo_notifications():
    state = {"value": "a1", "nid": 3, "changed": threading.Event()}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            u = urllib.parse.urlparse(self.path)
            if u.path == "/configfiles/json/my-app/default/application":
                body = json.dumps({"flowRules": state["value"]}).encode()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(body)
                return
            assert u.path == "/notifications/v2"
            ns = json.loads(
                urllib.parse.parse_qs(u.query)["notifications"][0]
            )
            if ns[0]["notificationId"] >= state["nid"]:
                if not state["changed"].wait(timeout=2.0):
                    self.send_response(304)
                    self.end_headers()
                    return
                state["changed"].clear()
            body = json.dumps(
                [{"namespaceName": "application", "notificationId": state["nid"]}]
            ).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

    srv, port = _serve(H)
    ds = ST.ApolloDataSource(
        f"127.0.0.1:{port}", "my-app", "default", "application",
        "flowRules", "[]", parser=lambda s: ("parsed", s),
    )
    try:
        got, evt = _collect(ds)
        assert ds.get_property().value == ("parsed", "a1")
        state["value"] = "a2"
        state["nid"] = 4
        state["changed"].set()
        assert _wait(evt, got, lambda v: v == ("parsed", "a2"))
    finally:
        ds.close()
        srv.shutdown()


# --------------------------- eureka --------------------------------------


def test_eureka_metadata_poll_and_replica_fallthrough():
    state = {"value": "e1"}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            assert self.path == "/eureka/apps/APP/inst-1"
            assert self.headers["Accept"] == "application/json"
            body = json.dumps(
                {"instance": {"metadata": {"flowRules": state["value"]}}}
            ).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

    srv, port = _serve(H)
    # first URL is dead: the binding must fall through to the live replica
    ds = ST.EurekaDataSource(
        "APP", "inst-1",
        ["http://127.0.0.1:1/eureka", f"http://127.0.0.1:{port}/eureka"],
        "flowRules", parser=json.loads if False else (lambda s: s),
        refresh_ms=60_000,
    )
    try:
        assert ds.get_property().value == "e1"
        state["value"] = "e2"
        assert ds.refresh() is True  # deterministic poll step
        assert ds.get_property().value == "e2"
    finally:
        ds.close()
        srv.shutdown()


# --------------------------- etcd ----------------------------------------


def test_etcd_range_and_watch_stream():
    state = {"value": "t1", "changed": threading.Event()}

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers["Content-Length"])
            req = json.loads(self.rfile.read(n).decode())
            if self.path == "/v3/kv/range":
                key = base64.b64decode(req["key"]).decode()
                assert key == "sentinel.rules"
                body = json.dumps(
                    {
                        "kvs": [
                            {
                                "value": base64.b64encode(
                                    state["value"].encode()
                                ).decode()
                            }
                        ]
                    }
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            assert self.path == "/v3/watch"
            assert "create_request" in req
            self.send_response(200)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(obj):
                b = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(b):x}\r\n".encode() + b + b"\r\n")
                self.wfile.flush()

            chunk({"result": {"created": True}})
            if state["changed"].wait(timeout=4.0):
                state["changed"].clear()
                chunk({"result": {"events": [{"type": "PUT"}]}})
            self.wfile.write(b"0\r\n\r\n")

    srv, port = _serve(H)
    ds = ST.EtcdDataSource("127.0.0.1", port, "sentinel.rules", parser=str.title)
    try:
        got, evt = _collect(ds)
        assert ds.get_property().value == "T1"
        state["value"] = "t2 new"
        state["changed"].set()
        assert _wait(evt, got, lambda v: v == "T2 New")
    finally:
        ds.close()
        srv.shutdown()


# --------------------------- spring cloud config --------------------------


def test_spring_cloud_config_poll():
    state = {"value": "s1"}

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            assert self.path == "/my-app/prod"
            body = json.dumps(
                {
                    "propertySources": [
                        {"source": {"other": "x"}},
                        {"source": {"sentinel.rules": state["value"]}},
                    ]
                }
            ).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

    srv, port = _serve(H)
    ds = ST.SpringCloudConfigDataSource(
        f"127.0.0.1:{port}", "my-app", "prod", "sentinel.rules",
        parser=lambda s: s, refresh_ms=60_000,
    )
    try:
        assert ds.get_property().value == "s1"
        state["value"] = "s2"
        assert ds.refresh() is True
        assert ds.get_property().value == "s2"
    finally:
        ds.close()
        srv.shutdown()


# --------------------------- zookeeper ------------------------------------


class FakeZkServer:
    """Speaks the jute subset ZkClient uses: connect, getData, exists,
    ping; set_data() fires one-shot data watches like a real ensemble."""

    def __init__(self):
        self.nodes = {}
        self.watches = {}  # path -> [conn]
        self._lock = threading.Lock()
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(4)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    @staticmethod
    def _recv_frame(conn):
        hdr = b""
        while len(hdr) < 4:
            c = conn.recv(4 - len(hdr))
            if not c:
                raise ConnectionError
            hdr += c
        (n,) = struct.unpack(">i", hdr)
        out = b""
        while len(out) < n:
            c = conn.recv(n - len(out))
            if not c:
                raise ConnectionError
            out += c
        return out

    @staticmethod
    def _send_frame(conn, payload):
        conn.sendall(struct.pack(">i", len(payload)) + payload)

    @staticmethod
    def _stat() -> bytes:
        return struct.pack(">qqqqiiiqiiq", 0, 0, 0, 0, 1, 0, 0, 0, 0, 0, 0)

    def _serve_conn(self, conn):
        try:
            frame = self._recv_frame(conn)  # ConnectRequest
            _proto, _zxid, timeout, _sid = struct.unpack_from(">iqiq", frame, 0)
            self._send_frame(
                conn,
                struct.pack(">iiq", 0, timeout, 0x1234)
                + struct.pack(">i", 16)
                + b"\x00" * 16,
            )
            while True:
                frame = self._recv_frame(conn)
                xid, op = struct.unpack_from(">ii", frame, 0)
                if xid == -2:  # ping
                    self._send_frame(conn, struct.pack(">iqi", -2, 0, 0))
                    continue
                (plen,) = struct.unpack_from(">i", frame, 8)
                path = frame[12 : 12 + plen].decode()
                watch = frame[12 + plen] == 1
                with self._lock:
                    data = self.nodes.get(path)
                    if watch:
                        self.watches.setdefault(path, []).append(conn)
                if op == 4:  # getData
                    if data is None:
                        self._send_frame(conn, struct.pack(">iqi", xid, 0, -101))
                    else:
                        self._send_frame(
                            conn,
                            struct.pack(">iqi", xid, 0, 0)
                            + struct.pack(">i", len(data))
                            + data
                            + self._stat(),
                        )
                elif op == 3:  # exists
                    if data is None:
                        self._send_frame(conn, struct.pack(">iqi", xid, 0, -101))
                    else:
                        self._send_frame(
                            conn, struct.pack(">iqi", xid, 0, 0) + self._stat()
                        )
        except (ConnectionError, OSError):
            pass

    def set_data(self, path: str, data: bytes):
        with self._lock:
            created = path not in self.nodes
            self.nodes[path] = data
            conns = self.watches.pop(path, [])
        evt_type = 1 if created else 3  # NodeCreated / NodeDataChanged
        b = path.encode()
        for conn in conns:
            try:
                self._send_frame(
                    conn,
                    struct.pack(">iqi", -1, 0, 0)
                    + struct.pack(">ii", evt_type, 3)
                    + struct.pack(">i", len(b))
                    + b,
                )
            except OSError:
                pass

    def close(self):
        self._stop.set()
        self._srv.close()


def test_zookeeper_watch_push():
    srv = FakeZkServer()
    srv.nodes["/sentinel/rules"] = b"z1"
    ds = ZookeeperDataSource(
        f"127.0.0.1:{srv.port}", "/sentinel/rules", parser=lambda s: s * 2
    )
    try:
        got, evt = _collect(ds)
        assert ds.get_property().value == "z1z1"
        srv.set_data("/sentinel/rules", b"z2")
        assert _wait(evt, got, lambda v: v == "z2z2")
        # watches are one-shot and re-armed: a second change must land too
        srv.set_data("/sentinel/rules", b"z3")
        assert _wait(evt, got, lambda v: v == "z3z3")
    finally:
        ds.close()
        srv.close()


def test_zookeeper_absent_node_publishes_on_creation():
    srv = FakeZkServer()
    ds = ZookeeperDataSource(
        f"127.0.0.1:{srv.port}", "/sentinel/late", parser=lambda s: s
    )
    try:
        got, evt = _collect(ds)
        assert ds.get_property().value is None
        srv.set_data("/sentinel/late", b"born")
        assert _wait(evt, got, lambda v: v == "born")
    finally:
        ds.close()
        srv.close()
