"""Flow-rule strategy semantics: RELATE (meter another resource's node),
CHAIN (context-scoped metering), and the warm-up controller's cold-start
ramp (FlowRuleChecker.selectNodeByRequesterAndStrategy:115,
WarmUpController.java:65-112)."""

import pytest

import sentinel_tpu as st


def test_strategy_relate_meters_reference_resource(client, vt):
    """Writes are limited by READ traffic: the rule on 'write' watches
    'read''s node (the classic read/write contention example)."""
    client.flow_rules.load(
        [
            st.FlowRule(
                resource="write",
                count=5,
                strategy=st.STRATEGY_RELATE,
                ref_resource="read",
            )
        ]
    )
    # no read traffic → writes sail through
    for _ in range(10):
        with client.entry("write"):
            pass
    # heavy read traffic fills the REFERENCE node's window over the limit
    vt.advance(1100)
    for _ in range(6):
        with client.entry("read"):
            pass
    with pytest.raises(st.FlowException):
        client.entry("write")
    # reads themselves are not limited by the rule on 'write'
    with client.entry("read"):
        pass


def test_strategy_chain_scopes_to_context(client, vt):
    """CHAIN: the rule applies only to entries made under the named
    context, metering that context's DefaultNode."""
    client.flow_rules.load(
        [
            st.FlowRule(
                resource="svc",
                count=2,
                strategy=st.STRATEGY_CHAIN,
                ref_resource="ctx-a",
            )
        ]
    )
    # other contexts: unlimited by this rule
    with client.context("ctx-b"):
        for _ in range(5):
            with client.entry("svc"):
                pass
    # the named context: capped at 2
    with client.context("ctx-a"):
        ok = blocked = 0
        for _ in range(5):
            try:
                with client.entry("svc"):
                    pass
                ok += 1
            except st.FlowException:
                blocked += 1
    assert ok == 2 and blocked == 3


def test_warm_up_cold_start_ramp(client, vt):
    """Cold system: admission starts near count/coldFactor and reaches the
    full count as traffic sustains (Guava warm-up token bucket)."""
    count = 30
    client.flow_rules.load(
        [
            st.FlowRule(
                resource="warm",
                count=count,
                control_behavior=st.CONTROL_WARM_UP,
                warm_up_period_sec=4,
                cold_factor=3,
            )
        ]
    )

    def offered_second():
        ok = 0
        for _ in range(count * 2):
            vt.advance(1000 // (count * 2))
            try:
                with client.entry("warm"):
                    pass
                ok += 1
            except st.FlowException:
                pass
        return ok

    first = offered_second()
    # cold: roughly count/coldFactor (10), certainly well under full rate
    assert first <= count * 0.6, first
    rates = [offered_second() for _ in range(6)]
    # warmed: the last seconds admit (close to) the full count
    assert rates[-1] >= count * 0.9, rates
    assert rates[-1] > first
