"""RT quantile histogram (ops/rtq.py): log-bucket accuracy vs numpy
percentiles, window expiry, and the client/command read path."""

import numpy as np
import pytest

import jax.numpy as jnp

import sentinel_tpu as st
from sentinel_tpu.ops import rtq as RQ


def test_bins_monotone_and_bounded():
    cfg = RQ.RtqConfig(2, 500, 5000.0)
    rts = jnp.asarray([0.0, 1.0, 10.0, 100.0, 1000.0, 5000.0, 99999.0])
    bins = np.asarray(RQ.bin_of(rts, cfg))
    assert list(bins) == sorted(bins)
    assert bins[-1] == RQ.BINS - 1
    # every bin's upper edge exceeds its lower edge by <= ~12%+1ms
    for b in range(RQ.BINS - 1):
        lo, hi = RQ.bin_upper_edge(b - 1, cfg), RQ.bin_upper_edge(b, cfg)
        assert hi > lo


def test_quantiles_close_to_numpy():
    cfg = RQ.RtqConfig(2, 500, 5000.0)
    s = RQ.init_rtq(cfg)
    rng = np.random.default_rng(0)
    rts = rng.lognormal(mean=3.0, sigma=1.0, size=4000).astype(np.float32)
    s = RQ.add(s, jnp.int32(100), jnp.asarray(rts), jnp.ones(4000, bool), cfg)
    counts = np.asarray(RQ.windowed_counts(s, jnp.int32(200), cfg))
    assert counts.sum() == 4000
    est = RQ.quantiles(counts, (0.5, 0.9, 0.99), cfg)
    for q in (0.5, 0.9, 0.99):
        true = float(np.percentile(rts, q * 100))
        assert true * 0.85 <= est[q] <= true * 1.3, (q, est[q], true)


def test_window_expiry():
    cfg = RQ.RtqConfig(2, 500, 5000.0)
    s = RQ.init_rtq(cfg)
    s = RQ.add(s, jnp.int32(0), jnp.asarray([50.0]), jnp.asarray([True]), cfg)
    assert np.asarray(RQ.windowed_counts(s, jnp.int32(400), cfg)).sum() == 1
    assert np.asarray(RQ.windowed_counts(s, jnp.int32(2000), cfg)).sum() == 0


def test_client_rt_quantiles_and_command(client, vt):
    from sentinel_tpu.transport import build_default_handlers
    from sentinel_tpu.transport.command import CommandRequest

    client.flow_rules.load([st.FlowRule(resource="svc", count=1000)])
    for rt in (5, 10, 20, 40, 400):
        with client.entry("svc", inbound=True):
            vt.advance(rt)
    q = client.rt_quantiles((0.5, 0.99))
    assert 15 <= q[0.5] <= 30  # median around 20ms
    assert 300 <= q[0.99] <= 600
    reg = build_default_handlers(client)
    out = reg.handle("rtQuantiles", CommandRequest(parameters={"q": "0.5"}))
    assert out.success and "p50" in out.result
