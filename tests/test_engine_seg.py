"""Segment-compacted effects (ops/engine_seg.py) vs the per-item fused
path: full-tick bit-identity, with and without capacity fallback.

Runs on CPU with Pallas interpret kernels — semantics only; device speed
is bench.py's job.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sentinel_tpu.core.config import small_engine_config
from tests.test_fused import _tick_once

_BASELINE_CACHE: dict = {}


def _baseline(sketch: bool, base: dict):
    if sketch not in _BASELINE_CACHE:
        _BASELINE_CACHE[sketch] = _tick_once(small_engine_config(**base))
    return _BASELINE_CACHE[sketch]


def _assert_state_equal(st1, st2):
    l1 = jax.tree.leaves(st1)
    l2 = jax.tree.leaves(st2)
    paths = [str(p) for p, _ in jax.tree_util.tree_flatten_with_path(st1)[0]]
    for p, x, y in zip(paths, l1, l2):
        np.testing.assert_array_equal(x, y, err_msg=p)


@pytest.mark.parametrize(
    "sketch,seg_u", [(False, 0), (True, 0), (False, 16)]
)
def test_seg_tick_matches_fused_path(sketch, seg_u):
    """seg_u=0: auto capacity (compacted path taken).  seg_u=16: capacity
    too small for the unsorted 96-item batch -> every tick falls back to
    the per-item kernels.  Both must match the plain fused path exactly."""
    base = dict(
        batch_size=96,
        complete_batch_size=96,
        use_mxu_tables=True,
        sketch_stats=sketch,
        enable_minute_window=True,
        fused_effects=True,
    )
    cfg_seg = small_engine_config(**base, seg_effects=True, seg_u=seg_u)
    st1, out1 = _baseline(sketch, base)
    st2, out2 = _tick_once(cfg_seg)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)
    _assert_state_equal(st1, st2)


@pytest.mark.parametrize("sort_batches", [True, False])
def test_seg_flow_check_k1(sort_batches):
    """flow_rules_per_resource=1 activates the segment-level flow check
    (check_flow_seg).  sorted batches take the segmented-rank branch;
    unsorted ones overflow capacity / fail res_sorted and fall back —
    both must match the plain fused engine bit for bit."""
    base = dict(
        batch_size=96,
        complete_batch_size=96,
        use_mxu_tables=True,
        enable_minute_window=True,
        fused_effects=True,
        flow_rules_per_resource=1,
        degrade_rules_per_resource=1,
        param_rules_per_resource=1,
    )
    cfg_fused = small_engine_config(**base)
    cfg_seg = small_engine_config(**base, seg_effects=True)
    st1, out1 = _tick_once(cfg_fused, sort_batches=sort_batches)
    st2, out2 = _tick_once(cfg_seg, sort_batches=sort_batches)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)
    _assert_state_equal(st1, st2)


def test_seg_tick_sorted_batch_matches_unsorted_semantics():
    """A batch presorted by resource (stable) must produce the same
    per-item verdicts as the unsorted batch once un-permuted, and the same
    final integer state (f32 rt sums may differ in summation order, so
    they are compared with quantization tolerance)."""
    from sentinel_tpu.core.rules import DegradeRule, FlowRule
    from sentinel_tpu.ops import engine as E
    from sentinel_tpu.runtime.registry import Registry

    base = dict(
        batch_size=128,
        complete_batch_size=128,
        use_mxu_tables=True,
        fused_effects=True,
        enable_minute_window=True,
    )

    def run(sort: bool, seg: bool):
        cfg = small_engine_config(**base, seg_effects=seg)
        reg = Registry(cfg)
        flow, deg = [], []
        for i in range(10):
            name = f"r{i}"
            reg.resource_id(name)
            flow.append(FlowRule(resource=name, count=6.0))
            deg.append(DegradeRule(resource=name, grade=0, count=3.0, time_window=5))
        rules = E.compile_ruleset(cfg, reg, flow_rules=flow, degrade_rules=deg)
        state = E.init_state(cfg)
        rng = np.random.default_rng(11)
        B = cfg.batch_size
        verdicts = []
        for t in range(3):
            ids = rng.integers(1, 12, B).astype(np.int32)
            cnt = np.ones(B, np.int32)
            rt = rng.uniform(0.5, 9.0, B).astype(np.float32)
            order = np.lexsort((np.arange(B), ids)) if sort else np.arange(B)
            acq = E.empty_acquire(cfg)._replace(
                res=jnp.asarray(ids[order]), count=jnp.asarray(cnt[order]),
                inbound=jnp.ones((B,), jnp.int32),
            )
            comp = E.empty_complete(cfg)._replace(
                res=jnp.asarray(ids[order]),
                rt=jnp.asarray(rt[order]),
                success=jnp.ones((B,), jnp.int32),
            )
            state, out = E.tick(
                state, rules, acq, comp, jnp.int32(500 + 400 * t),
                jnp.float32(0.0), jnp.float32(0.0), cfg=cfg,
            )
            v = np.asarray(out.verdict)
            inv = np.empty(B, np.int64)
            inv[order] = np.arange(B)
            verdicts.append(v[inv])  # back to arrival order
        return jax.tree.map(np.asarray, state), verdicts

    st_u, v_u = run(sort=False, seg=False)
    st_s, v_s = run(sort=True, seg=True)
    for a, b in zip(v_u, v_s):
        np.testing.assert_array_equal(a, b)
    # integer state identical; f32 rt sums within summation-order noise
    flat_u = jax.tree_util.tree_flatten_with_path(st_u)[0]
    flat_s = jax.tree.leaves(st_s)
    for (p, x), y in zip(flat_u, flat_s):
        if x.dtype.kind in "iub":
            np.testing.assert_array_equal(x, y, err_msg=str(p))
        else:
            np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-3, err_msg=str(p))
