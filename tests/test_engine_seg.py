"""Segment-compacted effects (ops/engine_seg.py) vs the per-item fused
path: full-tick bit-identity, with and without capacity fallback.

Runs on CPU with Pallas interpret kernels — semantics only; device speed
is bench.py's job.
"""

from __future__ import annotations

import numpy as np
import jax
import pytest

from sentinel_tpu.core.config import small_engine_config
from tests.test_fused import _tick_once

# Full-tick multi-config equivalence: minutes per test on a 1-core host
# (eager pallas interpret kernels compile per distinct kernel plan, and
# the _respawned isolation pays a fresh interpreter + jax import each).
# Excluded from the tier-1 gate (-m 'not slow'); run explicitly before
# touching the seg engine:  pytest tests/test_engine_seg.py -m ''
pytestmark = pytest.mark.slow

_BASELINE_CACHE: dict = {}


def _respawned(test_id: str) -> bool:
    """Run ``test_id`` in a FRESH interpreter and return True in the
    parent (the caller then returns immediately; the child re-enters with
    SENTINEL_SUBTEST=1 and runs the real body).

    Why: this jaxlib's CPU backend segfaults inside
    backend_compile_and_load once a single process has accumulated enough
    large engine compiles (reproduced repeatedly at the suite's ~20th
    engine compile, independent of wall clock, stack size, or system
    load; any single test passes alone).  Isolating the heavy NEW seg
    configs keeps the per-process compile count at the level the rest of
    the suite was built for — same compiler fragility the conftest's
    compilation-cache note records."""
    import os
    import subprocess
    import sys

    if os.environ.get("SENTINEL_SUBTEST") == "1":
        return False
    env = dict(os.environ, SENTINEL_SUBTEST="1")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest", "-x", "-q",
            # -o addopts= strips pytest.ini's xdist options (-n 4): each
            # respawn must be ONE plain in-process session, not a 4-worker
            # xdist fleet of its own; no:cacheprovider keeps respawns from
            # racing on .pytest_cache
            "-p", "no:cacheprovider", "-o", "addopts=", test_id,
        ],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    assert r.returncode == 0, (
        f"subtest failed:\n{r.stdout[-3000:]}\n{r.stderr[-2000:]}"
    )
    return True


def _baseline(sketch: bool, base: dict):
    if sketch not in _BASELINE_CACHE:
        _BASELINE_CACHE[sketch] = _tick_once(small_engine_config(**base))
    return _BASELINE_CACHE[sketch]


def _assert_state_equal(st1, st2):
    l1 = jax.tree.leaves(st1)
    l2 = jax.tree.leaves(st2)
    paths = [str(p) for p, _ in jax.tree_util.tree_flatten_with_path(st1)[0]]
    for p, x, y in zip(paths, l1, l2):
        np.testing.assert_array_equal(x, y, err_msg=p)


@pytest.mark.parametrize(
    "sketch,seg_u", [(False, 0), (True, 0), (False, 16)]
)
def test_seg_tick_matches_fused_path(sketch, seg_u):
    """seg_u=0: auto capacity (compacted path taken).  seg_u=16: capacity
    too small for the unsorted 96-item batch -> every tick falls back to
    the per-item kernels.  Both must match the plain fused path exactly."""
    base = dict(
        batch_size=96,
        complete_batch_size=96,
        use_mxu_tables=True,
        sketch_stats=sketch,
        enable_minute_window=True,
        fused_effects=True,
    )
    cfg_seg = small_engine_config(**base, seg_effects=True, seg_u=seg_u)
    st1, out1 = _baseline(sketch, base)
    st2, out2 = _tick_once(cfg_seg)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)
    _assert_state_equal(st1, st2)


@pytest.mark.parametrize("sort_batches", [True, False])
def test_seg_no_fallback_matches_when_capacity_fits(sort_batches):
    """seg_fallback=False removes the check-phase lax.cond entirely; with
    capacity that fits (auto seg_u), verdicts and state must still be
    bit-identical to the always-exact seg_fallback=True engine.

    Fresh-interpreter isolated: see _respawned."""
    if _respawned(
        f"{__file__}::test_seg_no_fallback_matches_when_capacity_fits"
        f"[{sort_batches}]"
    ):
        return
    base = dict(
        batch_size=96,
        complete_batch_size=96,
        use_mxu_tables=True,
        enable_minute_window=True,
        fused_effects=True,
        flow_rules_per_resource=1,
        degrade_rules_per_resource=1,
        param_rules_per_resource=1,
    )
    # unsorted batches make ~B segments; cover them so nothing overflows
    cfg_a = small_engine_config(**base, seg_effects=True, seg_u=128)
    cfg_b = small_engine_config(
        **base, seg_effects=True, seg_u=128, seg_fallback=False
    )
    st1, out1 = _tick_once(cfg_a, sort_batches=sort_batches)
    st2, out2 = _tick_once(cfg_b, sort_batches=sort_batches)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)
    _assert_state_equal(st1, st2)
