"""Shard host worker process for the multi-host routing tests.

Runs a SentinelClient + token server answering RES_CHECK on an ephemeral
port; prints "PORT <n>" on stdout once listening, then serves until
killed.  Rules come in as JSON on argv: [{"resource": ..., "count": ...}].
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import sentinel_tpu as st  # noqa: E402
from sentinel_tpu.cluster.server import ClusterTokenServer  # noqa: E402
from sentinel_tpu.cluster.token_service import DefaultTokenService  # noqa: E402
from sentinel_tpu.core.config import small_engine_config  # noqa: E402
from sentinel_tpu.runtime.client import SentinelClient  # noqa: E402


def main() -> None:
    rules = json.loads(sys.argv[1]) if len(sys.argv) > 1 else []
    # optional second arg: EngineConfig overrides (the multihost benchmark
    # sizes capacity so every routed resource gets a real ruled row)
    cfg_kw = json.loads(sys.argv[2]) if len(sys.argv) > 2 else {}
    client = SentinelClient(
        cfg=small_engine_config(**cfg_kw), mode="threaded", tick_interval_ms=2.0
    )
    client.start()
    client.flow_rules.load(
        [st.FlowRule(resource=r["resource"], count=r["count"]) for r in rules]
    )
    svc = DefaultTokenService(client)
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0)
    server.start()
    print(f"PORT {server.port}", flush=True)
    import threading

    threading.Event().wait()  # serve until killed


if __name__ == "__main__":
    main()
