"""sentinel_tpu.sketch.hotset — promotion loop, demotion, hysteresis, and
the runtime.hotset.promote failure contract (stats fail OPEN, tail-rule
verdicts fail CLOSED)."""

import numpy as np
import pytest

import jax.numpy as jnp

import sentinel_tpu as st
from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.ops import engine as E
from sentinel_tpu.runtime.registry import Registry
from sentinel_tpu.sketch.hotset import (
    _C_PROMOTE_FAIL,
    _C_PROMOTIONS,
    guarded_promote,
)


def _hot_cfg(**kw):
    base = dict(
        max_resources=8,
        max_nodes=16,
        sketch_stats=True,
        sketch_width=256,
        hotset_k=8,
        hotset_promote_qps=3.0,
        hotset_demote_qps=1.0,
        hotset_cooldown_s=30.0,
    )
    base.update(kw)
    return small_engine_config(**base)


def _burn_exact(c):
    i = 0
    while not c.registry.is_sketch_id(c.registry.resource_id(f"burn-{i}")):
        i += 1


# -- device candidate emission ----------------------------------------------


def test_tick_emits_hot_candidates(client_factory, vt):
    c = client_factory(cfg=_hot_cfg())
    _burn_exact(c)
    rid = c.registry.resource_id("hot-svc")
    assert c.registry.is_sketch_id(rid)
    for _ in range(6):
        e = c.try_entry("hot-svc")
        if e is not None:
            e.exit()
        vt.advance(5)
    assert c.hotset is not None
    cand = dict(c.hotset._cand)
    assert cand.get(rid, 0.0) >= 3.0  # folded windowed pass estimate


def test_hot_output_off_when_disabled():
    cfg = _hot_cfg(hotset_k=0)
    assert E.hotset_k(cfg) == 0
    state = E.init_state(cfg)
    rules = E.compile_ruleset(cfg, Registry(cfg))
    z = jnp.float32(0.0)
    _, out = E.tick(
        state, rules, E.empty_acquire(cfg), E.empty_complete(cfg),
        jnp.int32(1_000), z, z, cfg=cfg,
    )
    assert out.hot is None


def test_fold_normalizes_windowed_counts_to_qps(client_factory, vt):
    """TickOutput.hot carries WINDOWED pass sums; the manager must fold
    them as QPS so a minute-window sketch (interval 60 s) is not 60x too
    eager against hotset_promote_qps (same unit as the demote side)."""
    cfg = _hot_cfg(sketch_sample_count=60, sketch_window_ms=1000)
    c = client_factory(cfg=cfg)
    rid = cfg.node_rows + 7
    c.hotset.fold(np.asarray([[float(rid), 120.0]], np.float32))
    assert abs(c.hotset._cand[rid] - 2.0) < 1e-6  # 120 events / 60 s


# -- promotion / demotion loop ----------------------------------------------


def test_manager_promotes_hot_tail_resource(client_factory, vt):
    c = client_factory(cfg=_hot_cfg())
    _burn_exact(c)
    rid = c.registry.resource_id("hot-svc")
    assert c.registry.is_sketch_id(rid)
    for _ in range(8):
        e = c.try_entry("hot-svc")
        if e is not None:
            e.exit()
        vt.advance(5)
    c.hotset.evaluate_now()
    new_rid = c.registry.peek_resource_id("hot-svc")
    assert not c.registry.is_sketch_id(new_rid)
    assert c.hotset.promoted["hot-svc"] == new_rid
    # exact tier serves it now: stats come from real windows
    e = c.try_entry("hot-svc")
    assert e is not None
    e.exit()


def test_cold_promoted_row_demotes_with_hysteresis(client_factory, vt):
    c = client_factory(cfg=_hot_cfg())
    _burn_exact(c)
    c.registry.resource_id("fades")
    for _ in range(8):
        e = c.try_entry("fades")
        if e is not None:
            e.exit()
        vt.advance(5)
    c.hotset.evaluate_now()
    assert not c.registry.is_sketch_id(c.registry.peek_resource_id("fades"))
    # traffic stops; the window slides past -> two cold evaluations demote
    vt.advance(2_000)
    c.tick_once()
    c.hotset.evaluate_now()
    assert "fades" in c.hotset.promoted  # one cold eval holds
    c.hotset.evaluate_now()
    rid = c.registry.peek_resource_id("fades")
    assert c.registry.is_sketch_id(rid)  # demoted back to the tail
    assert "fades" not in c.hotset.promoted
    # hysteresis: re-promotion is refused while the cooldown runs
    hys = c.hotset._cool["fades"]
    assert hys.cooling
    c.hotset._cand[rid] = 100.0
    c.hotset.evaluate_now()
    assert c.registry.is_sketch_id(c.registry.peek_resource_id("fades"))


def test_demoted_row_quarantines_then_recycles():
    cfg = _hot_cfg()
    reg = Registry(cfg)
    i = 0
    while not reg.is_sketch_id(reg.resource_id(f"b{i}")):
        i += 1
    assert reg.promote_resource(f"b{i}") is not None
    row = reg.peek_resource_id(f"b{i}")
    # demote with zero quarantine: the row must be reusable immediately
    new_id = reg.demote_resource(f"b{i}", quarantine_s=0.0)
    assert reg.is_sketch_id(new_id)
    assert reg.resource_name(new_id) == f"b{i}"
    reg.resource_id("next-hot")
    got = reg.promote_resource("next-hot")
    assert got == row  # recycled, not burned from the reserve
    # long quarantine keeps the row OUT of rotation
    reg.demote_resource("next-hot", quarantine_s=3600.0)
    reg.resource_id("later")
    got2 = reg.promote_resource("later")
    assert got2 != row


# -- failure contract --------------------------------------------------------


def test_promote_failures_fail_open_for_stats_closed_for_verdicts(
    client_factory, vt
):
    """Injected runtime.hotset.promote failures: the ruled tail resource
    stays sketched (stats keep flowing = OPEN) and its rule enforces via
    the tail tables (blocks still fire = CLOSED)."""
    c = client_factory(cfg=_hot_cfg())
    _burn_exact(c)
    rid = c.registry.resource_id("guarded")
    assert c.registry.is_sketch_id(rid)
    fails0 = _C_PROMOTE_FAIL.value
    plan = FaultPlan(
        name="hotset_promote_fail",
        seed=1,
        faults=[
            FaultSpec(
                "runtime.hotset.promote", "raise",
                burst_start=0, burst_len=1000, exc="RuntimeError",
            )
        ],
    )
    st_armed = FP.arm(plan)
    try:
        c.flow_rules.load([st.FlowRule(resource="guarded", count=2)])
    finally:
        FP.disarm()
    assert st_armed.injected().get("runtime.hotset.promote:raise", 0) >= 1
    assert _C_PROMOTE_FAIL.value > fails0
    # CLOSED for verdicts: the un-promoted rule still blocks from the tail
    assert c.registry.is_sketch_id(c.registry.peek_resource_id("guarded"))
    got = sum(1 for _ in range(8) if c.try_entry("guarded"))
    assert 1 <= got <= 2
    # OPEN for stats: the sketch keeps observing the resource
    snap = c.stats.resource("guarded")
    assert snap["passQps"] >= 1


def test_guarded_promote_counts_transitions():
    cfg = _hot_cfg()
    reg = Registry(cfg)
    i = 0
    while not reg.is_sketch_id(reg.resource_id(f"b{i}")):
        i += 1
    p0 = _C_PROMOTIONS.value
    assert guarded_promote(reg, f"b{i}") is not None
    assert _C_PROMOTIONS.value == p0 + 1
    # idempotent: promoting an already-exact resource is not a transition
    assert guarded_promote(reg, f"b{i}") is not None
    assert _C_PROMOTIONS.value == p0 + 1
