"""Continuous profiling plane (obs/profile.py): HBM memory ledger,
retrace observatory, bounded deep-profile capture, and the online
sketch-accuracy audit — plus the flight/postmortem rendering of the new
provider sections and the protocol-v2 wire byte-accounting regression."""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.obs import REGISTRY
from sentinel_tpu.obs import flight as FL
from sentinel_tpu.obs import profile as PROF
from sentinel_tpu.obs import slo as S
from sentinel_tpu.obs import trace as OT
from sentinel_tpu.obs.flight import FlightRecorder
from sentinel_tpu.obs.registry import MetricRegistry
from sentinel_tpu.ops import engine as E


def _metric(name, **labels):
    m = REGISTRY.get(name, labels or None)
    return float(m.value) if m is not None else 0.0


# -- memory ledger -----------------------------------------------------------


def test_ledger_set_track_drop_and_gauges():
    reg = MetricRegistry()
    led = PROF.MemoryLedger(registry=reg)
    with PROF.ledger_owner("unit-a"):
        led.set("rules", "tbl", 1024)
        n = led.track("windows", "gs", {"a": np.zeros((4, 8), np.float32)})
    assert n == 4 * 8 * 4
    assert led.pool_bytes("rules") == 1024
    assert led.pool_bytes("windows") == n
    assert led.total_bytes() == 1024 + n
    g = reg.get("sentinel_hbm_bytes", {"pool": "windows"})
    assert g is not None and float(g.value) == n
    # per-owner entries show up namespaced in the snapshot
    snap = led.snapshot()
    assert snap["entries"]["rules/unit-a:tbl"] == 1024
    assert snap["pools"]["windows"] == n
    with PROF.ledger_owner("unit-a"):
        led.drop("rules", "tbl")
    assert led.pool_bytes("rules") == 0
    assert float(reg.get("sentinel_hbm_bytes", {"pool": "rules"}).value) == 0


def test_ledger_drop_owner_scopes_by_owner_only():
    led = PROF.MemoryLedger(registry=MetricRegistry())
    with PROF.ledger_owner("owner-x"):
        led.set("sketch", "s", 100)
    with PROF.ledger_owner("owner-y"):
        led.set("sketch", "s", 200)
    assert led.pool_bytes("sketch") == 300
    led.drop_owner("owner-x")
    assert led.pool_bytes("sketch") == 200
    assert "sketch/owner-y:s" in led.snapshot()["entries"]


def test_ledger_capacity_checks_and_breaches():
    reg = MetricRegistry()
    led = PROF.MemoryLedger(registry=reg)

    def _c(name):
        m = reg.get(name)
        return float(m.value) if m is not None else 0.0

    # no capacity configured -> mutations don't count as checks
    led.set("wire", "a", 10)
    assert _c("sentinel_hbm_capacity_checks_total") == 0
    led.set_capacity(100)
    led.set("wire", "b", 20)  # 30 <= 100: check, no breach
    assert _c("sentinel_hbm_capacity_checks_total") == 1
    assert _c("sentinel_hbm_capacity_breaches_total") == 0
    led.set("tokens", "big", 500)  # 530 > 100: breach
    assert _c("sentinel_hbm_capacity_breaches_total") == 1
    snap = led.snapshot()
    assert snap["capacity_bytes"] == 100 and snap["in_breach"] is True


def test_ledger_reconcile_fails_open_and_has_fields():
    led = PROF.MemoryLedger(registry=MetricRegistry())
    led.set("rules", "r", 64)
    rec = led.reconcile()
    # must never raise on CPU-only processes; fields present even when
    # the backend offers no memory stats
    assert rec["total_bytes"] == 64
    assert "live_array_bytes" in rec and "unaccounted_bytes" in rec
    assert "device_memory_stats" in rec
    sect = led.flight_section()
    assert sect["pools"]["rules"] == 64


def test_tree_nbytes_counts_leaves():
    tree = {"a": np.zeros(10, np.int32), "b": (np.zeros(3, np.float64), 7)}
    assert PROF.tree_nbytes(tree) == 10 * 4 + 3 * 8


def test_client_ledger_pools_match_salsa_and_release_on_stop(client_factory):
    import sentinel_tpu.sketch.salsa as SA

    cfg = small_engine_config(
        max_resources=4, max_nodes=8, sketch_stats=True, sketch_width=256
    )
    c = client_factory(cfg=cfg, sketch_audit_k=4)
    snap = PROF.LEDGER.snapshot()
    mine = {
        k: v
        for k, v in snap["entries"].items()
        if f"/{c._ledger_name}:" in k
    }
    pools = {k.split("/", 1)[0] for k in mine}
    assert {"windows", "sketch"} <= pools
    # acceptance: the ledger's sketch pool agrees with the analytic
    # salsa footprint within 10%
    sketch_bytes = sum(v for k, v in mine.items() if k.startswith("sketch/"))
    want = SA.hbm_bytes(E.sketch_config(cfg))
    assert abs(sketch_bytes - want) <= 0.1 * want
    c.stop()
    snap2 = PROF.LEDGER.snapshot()
    assert not any(f"/{c._ledger_name}:" in k for k in snap2["entries"])


# -- retrace observatory -----------------------------------------------------


def test_retrace_names_the_changed_field():
    reg = MetricRegistry()
    ro = PROF.RetraceObservatory(registry=reg)
    rec = ro.observe("unit.fn", width=256, donate=True)
    assert rec["expected"] is True and rec["cause"] == "warmup"
    rec = ro.observe("unit.fn", width=512, donate=True)
    assert rec["expected"] is False
    assert "width" in rec["cause"] and "256" in rec["cause"]
    assert "512" in rec["cause"]
    assert ro.surprise_count() == 1
    m = reg.get(
        "sentinel_retraces_total", {"entry": "unit.fn", "expected": "false"}
    )
    assert m is not None and float(m.value) == 1


def test_retrace_diffs_frozen_dataclass_fields():
    ro = PROF.RetraceObservatory(registry=MetricRegistry())
    a = small_engine_config(sketch_stats=True, sketch_width=256)
    b = dataclasses.replace(a, sketch_width=512)
    ro.observe("unit.cfg", cfg=a)
    rec = ro.observe("unit.cfg", cfg=b)
    assert not rec["expected"]
    assert "sketch_width" in rec["cause"]


def test_retrace_expected_context_suppresses_surprise():
    ro = PROF.RetraceObservatory(registry=MetricRegistry())
    ro.observe("unit.ctx", n=1)
    with PROF.expected_retrace("test-resize"):
        rec = ro.observe("unit.ctx", n=2)
    assert rec["expected"] is True and rec["reason"] == "test-resize"
    assert ro.surprise_count() == 0


def test_retrace_compile_ms_histogram_and_flight_section():
    reg = MetricRegistry()
    ro = PROF.RetraceObservatory(registry=reg)
    ro.observe("unit.h", x=1)
    ro.observe_compile_ms("unit.h", 12.5)
    h = reg.get("sentinel_compile_ms", {"entry": "unit.h"})
    assert h is not None
    sect = ro.flight_section()
    assert sect["total_seen"] == 1 and sect["surprises"] == 0
    assert sect["recent"][-1]["entry"] == "unit.h"


def test_engine_tick_retrace_journal_steady_state_and_config_change(client):
    """Acceptance: a warmed client shows zero surprise retraces under
    steady-state ticks; an induced config change journals exactly one
    surprise whose cause names the changed field."""
    base = PROF.RETRACE.surprise_count()
    for i in range(8):
        with client.entry(f"rt-{i % 3}"):
            pass
    assert PROF.RETRACE.surprise_count() == base
    # induced: same entry key, one changed EngineConfig field.  Two
    # expected warmups (unique shapes), then the surprise.
    cfg_a = small_engine_config(max_resources=7, max_nodes=13)
    cfg_b = dataclasses.replace(
        cfg_a, second_window_ms=cfg_a.second_window_ms + 500
    )
    with PROF.expected_retrace("test-setup"):
        E.make_tick(cfg_a)
    E.make_tick(cfg_b)
    assert PROF.RETRACE.surprise_count() == base + 1
    last = [r for r in PROF.RETRACE.recent() if not r["expected"]][-1]
    assert last["entry"] == "engine.tick"
    assert "second_window_ms" in last["cause"]


# -- deep-profile capture ----------------------------------------------------


def _reset_capture_clock():
    PROF._LAST_CAPTURE[0] = 0.0


def test_capture_profile_ok_and_clamped():
    _reset_capture_clock()
    assert not OT.TRACER.enabled
    before = _metric("sentinel_profile_captures_total", result="ok")

    def _sleep(s):
        # the tracer must be live inside the window
        assert OT.TRACER.enabled
        with OT.TRACER.span("unit.captured"):
            time.sleep(0.001)

    cap = PROF.capture_profile(ms=0.0, min_interval_s=0.0, sleep=_sleep)
    assert cap["ms"] == PROF.MIN_CAPTURE_MS  # clamped up
    assert cap["span_count"] >= 1
    trace = json.loads(cap["chrome_trace"]) if isinstance(
        cap["chrome_trace"], str
    ) else cap["chrome_trace"]
    assert trace  # non-empty chrome payload
    assert not OT.TRACER.enabled  # prior state restored
    assert _metric("sentinel_profile_captures_total", result="ok") == before + 1


def test_capture_profile_rate_limited():
    _reset_capture_clock()
    before = _metric("sentinel_profile_captures_total", result="rate_limited")
    ok = PROF.capture_profile(ms=1.0, min_interval_s=0.0, sleep=lambda s: None)
    assert "chrome_trace" in ok
    cap = PROF.capture_profile(ms=1.0, min_interval_s=60.0, sleep=lambda s: None)
    assert cap["error"] == "rate_limited" and cap["retry_after_s"] > 0
    assert (
        _metric("sentinel_profile_captures_total", result="rate_limited")
        == before + 1
    )
    _reset_capture_clock()


def test_capture_profile_fails_open_and_restores_tracer():
    _reset_capture_clock()
    before = _metric("sentinel_profile_captures_total", result="error")
    assert not OT.TRACER.enabled
    plan = FaultPlan(
        name="capture-fail",
        seed=1,
        faults=[
            FaultSpec(
                "obs.profile.capture",
                "raise",
                burst_start=0,
                burst_len=1,
                exc="RuntimeError",
            )
        ],
    )
    with FP.armed(plan):
        cap = PROF.capture_profile(
            ms=1.0, min_interval_s=0.0, sleep=lambda s: None
        )
    assert "error" in cap and cap["error"] != "rate_limited"
    assert not OT.TRACER.enabled  # fail OPEN: prior state restored
    assert (
        _metric("sentinel_profile_captures_total", result="error") == before + 1
    )


def test_api_profile_and_memory_endpoints(client):
    from sentinel_tpu.transport import build_default_handlers
    from sentinel_tpu.transport.command import CommandRequest

    _reset_capture_clock()
    registry = build_default_handlers(client)
    rsp = registry.handle(
        "api/profile", CommandRequest(parameters={"ms": "1"})
    )
    assert rsp.success and "chrome_trace" in rsp.result
    rsp = registry.handle("api/memory", CommandRequest(parameters={}))
    assert rsp.success and "pools" in rsp.result
    _reset_capture_clock()


# -- online sketch-accuracy audit --------------------------------------------


def _audit(k=2, period=1, **kw):
    kw.setdefault("node_rows", 8)
    kw.setdefault("window_ms", 1000)
    kw.setdefault("sample_count", 2)
    kw.setdefault("slack_buckets", 1)
    kw.setdefault("width", 256)
    kw.setdefault("registry", MetricRegistry())
    return PROF.SketchAudit(k=k, period=period, **kw)


def _vals(a):
    return {
        "checks": int(a._c_checks.value),
        "under": int(a._c_under.value),
        "eps": int(a._c_eps.value),
        "fail": int(a._c_fail.value),
    }


def test_audit_tracks_sketch_ids_only_and_counts_checks():
    a = _audit(k=4)
    res = np.asarray([2, 9, 10, 9], np.int32)  # row 2 is exact-tier
    cnt = np.asarray([5, 3, 7, 1], np.int32)
    a.observe(1_000, res, cnt)  # fold only (nothing tracked at audit time)
    assert set(a._tracked) == {9, 10}
    a.observe(1_050, res, cnt, reader=lambda rids, t: [100, 100])
    v = _vals(a)
    assert v["checks"] == 2 and v["fail"] == 0
    # volume counts ALL valid rows, exact tier included
    assert a._vol[1] == 2 * (5 + 3 + 7 + 1)


def test_audit_underestimate_detected():
    a = _audit(k=1)
    res = np.asarray([9], np.int32)
    cnt = np.asarray([10], np.int32)
    a.observe(1_000, res, cnt)
    a.observe(1_100, res, cnt)
    # shadow has 20 in-window; a reader at 5 breaks overestimate-only
    a.observe(1_200, res, cnt, reader=lambda rids, t: [5])
    v = _vals(a)
    assert v["under"] == 1 and v["checks"] == 1 and v["eps"] == 0


def test_audit_slack_only_overestimate_is_not_eps_violation():
    """Regression (slack windows, PR 14): an estimate above the bare
    window but inside the slack-widened exact bound + eps budget is
    journaled as overestimate magnitude, NOT as an eps violation."""
    a = _audit(k=1)  # slack_buckets stored = 1 + 1 guard = 2
    res = np.asarray([9], np.int32)
    cnt = np.asarray([10], np.int32)
    for t in (1_000, 2_000, 3_000):  # buckets w=1,2,3 get 10 each
        a.observe(t, res, cnt)
    # audit at w=4: bare window (2,4] holds only w3 = 10; slack span
    # (0,4] holds w1+w2+w3 = 30.  A reader at 30 models a sketch that
    # hasn't expired the slack buckets yet: overestimate vs the bare
    # window, legal vs the slack bound.
    a.observe(4_500, res, cnt, reader=lambda rids, t: [30])
    v = _vals(a)
    assert v["eps"] == 0 and v["under"] == 0 and v["checks"] == 1
    assert a._last_audit["eps_violations"] == 0
    # the magnitude IS observed (30 - 10 = 20 lands in the histogram)
    h = a._h_err
    assert h.count >= 1


def test_audit_eps_violation_beyond_slack_and_budget():
    a = _audit(k=1)
    res = np.asarray([9], np.int32)
    cnt = np.asarray([10], np.int32)
    for t in (1_000, 2_000, 3_000):
        a.observe(t, res, cnt)
    # slack bound 30, eps budget = e/256 * 30 ~ 0.32 -> 500 violates
    a.observe(4_500, res, cnt, reader=lambda rids, t: [500])
    v = _vals(a)
    assert v["eps"] == 1 and v["under"] == 0
    assert a._last_audit["eps_violations"] == 1


def test_audit_uncovered_resource_skips_eps_check():
    # stale sketch state: shadow may be incomplete for ids seen before
    a = _audit(k=1, fresh_state=False)
    res = np.asarray([9], np.int32)
    cnt = np.asarray([10], np.int32)
    a.observe(1_000, res, cnt)
    # first fold at w=1 > hi_min -> not covered; a huge estimate could
    # be pre-tracking history, so no eps verdict (underestimates still
    # impossible to hit here: est >= 0 never < shadow when shadow small)
    a.observe(1_100, res, cnt, reader=lambda rids, t: [10_000])
    v = _vals(a)
    assert v["eps"] == 0 and v["checks"] == 1


def test_audit_trash_row_excluded_from_volume():
    a = _audit(k=2, trash_row=63)
    res = np.asarray([63, 2, 9], np.int32)
    cnt = np.asarray([5, 7, 11], np.int32)
    a.observe(1_000, res, cnt)
    assert a._vol[1] == 7 + 11  # trash row's 5 excluded, exact row kept
    assert set(a._tracked) == {9}


def test_audit_rotation_retires_oldest():
    a = _audit(k=1, period=4, rotate_every=4)
    res_a = np.asarray([9], np.int32)
    res_b = np.asarray([10], np.int32)
    one = np.asarray([1], np.int32)
    for i in range(3):
        a.observe(1_000 + i, res_a, one)
    assert set(a._tracked) == {9}
    # 4th tick: k is full, ticks % rotate_every == 0 -> 10 replaces 9
    a.observe(1_003, res_b, one)
    assert set(a._tracked) == {10}


def test_audit_fails_open_on_raising_reader():
    a = _audit(k=1)
    res = np.asarray([9], np.int32)
    cnt = np.asarray([1], np.int32)
    a.observe(1_000, res, cnt)

    def boom(rids, t):
        raise RuntimeError("reader exploded")

    a.observe(1_100, res, cnt, reader=boom)  # must not raise
    v = _vals(a)
    assert v["fail"] == 1 and v["checks"] == 0
    # and the audit keeps working afterwards
    a.observe(1_200, res, cnt, reader=lambda rids, t: [100])
    assert _vals(a)["checks"] == 1


def test_audit_shadow_failpoint_fails_open():
    a = _audit(k=1)
    res = np.asarray([9], np.int32)
    cnt = np.asarray([1], np.int32)
    plan = FaultPlan(
        name="audit-fail",
        seed=1,
        faults=[
            FaultSpec(
                "sketch.audit.shadow",
                "raise",
                burst_start=0,
                burst_len=2,
                exc="RuntimeError",
            )
        ],
    )
    with FP.armed(plan):
        a.observe(1_000, res, cnt)
        a.observe(1_100, res, cnt)
    assert _vals(a)["fail"] == 2
    assert not a._tracked  # folds were skipped, nothing admitted
    a.observe(1_200, res, cnt)  # heals once disarmed
    assert set(a._tracked) == {9}


def test_audit_disabled_mode_under_five_micros():
    a = _audit(k=0)
    res = np.asarray([9], np.int32)
    cnt = np.asarray([1], np.int32)
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        a.observe(1_000, res, cnt)
    elapsed = time.perf_counter() - t0
    assert elapsed / n < 5e-6, f"disarmed audit costs {elapsed / n * 1e6:.2f}us"
    assert a._ticks == 0  # truly disarmed: no state mutated


def test_client_online_audit_end_to_end(client_factory, vt):
    """The wired path: sketch-tier client with the audit on — checks
    accumulate, the overestimate-only and eps invariants hold, and the
    flight bundle carries the audit section."""
    cfg = small_engine_config(
        max_resources=4, max_nodes=8, sketch_stats=True, sketch_width=256
    )
    c = client_factory(cfg=cfg, sketch_audit_k=4, sketch_audit_period=2)
    before = {
        "checks": _metric("sentinel_sketch_audit_checks_total"),
        "under": _metric("sentinel_sketch_underestimates_total"),
        "eps": _metric("sentinel_sketch_eps_violations_total"),
        "fail": _metric("sentinel_sketch_audit_failures_total"),
    }
    for i in range(40):
        with c.entry(f"audit-res-{i % 12}"):
            vt.advance(5)
    assert _metric("sentinel_sketch_audit_checks_total") > before["checks"]
    assert _metric("sentinel_sketch_underestimates_total") == before["under"]
    assert _metric("sentinel_sketch_eps_violations_total") == before["eps"]
    assert _metric("sentinel_sketch_audit_failures_total") == before["fail"]
    b = FL.FLIGHT.dump_bundle(reason="unit-audit")
    sect = b["providers"]["audit"]
    assert sect["k"] == 4 and sect["tracked"] >= 1
    assert sect["checks"] >= 1 and sect["underestimates"] == 0


# -- flight bundles + postmortem rendering -----------------------------------


def test_flight_bundle_has_memory_and_retrace_sections(client):
    b = FL.FLIGHT.dump_bundle(reason="unit-profile")
    mem = b["providers"]["memory"]
    assert set(mem["pools"]) <= set(PROF.MemoryLedger.POOLS)
    assert {"rules", "windows"} <= set(mem["pools"])
    assert mem["total_bytes"] >= 0
    rt = b["providers"]["retrace"]
    assert "surprises" in rt and "recent" in rt


def test_postmortem_renders_profiling_provider_sections(tmp_path, capsys):
    from sentinel_tpu.obs.__main__ import main

    fr = FlightRecorder(capacity=8)
    fr.register_provider("memory", PROF.LEDGER.flight_section)
    fr.register_provider("retrace", PROF.RETRACE.flight_section)
    a = _audit(k=1)
    fr.register_provider("audit", a.flight_section)
    a.observe(1_000, np.asarray([9], np.int32), np.asarray([3], np.int32))
    b = fr.dump_bundle(reason="unit-postmortem")
    p = tmp_path / "bundle.json"
    p.write_text(json.dumps(b))
    assert main(["--postmortem", str(p)]) == 0
    out = capsys.readouterr().out
    assert "provider [memory]" in out
    assert "provider [retrace]" in out
    assert "provider [audit]" in out
    assert "unit-postmortem" in out


def test_eps_violation_slo_alert_bundles_with_profiling_sections():
    """Satellite: a firing sketch_eps SLO burn auto-bundles, and the
    bundle carries the memory/retrace sections alongside the slo one."""
    reg, greg = MetricRegistry(), MetricRegistry()
    fl = FlightRecorder()
    fl.register_provider("memory", PROF.LEDGER.flight_section)
    fl.register_provider("retrace", PROF.RETRACE.flight_section)
    checks = reg.counter("sentinel_sketch_audit_checks_total", "c")
    eps = reg.counter("sentinel_sketch_eps_violations_total", "e")
    spec = [s for s in S.default_slos() if s.name == "sketch_eps"][0]
    eng = S.SloEngine(
        specs=(spec,), registry=reg, flight=fl, gauge_registry=greg
    )
    checks.inc(100)
    st0 = eng.step(0)[0]
    assert not st0.alerting
    checks.inc(1000)
    eng.step(60_000)
    # 40% violation rate >> the 1% budget -> both windows burn
    checks.inc(1000)
    eps.inc(400)
    st1 = eng.step(120_000)[0]
    assert st1.fired and st1.alerting
    b = fl.last_bundle()
    assert b is not None and b["reason"] == "slo-burn-sketch_eps"
    assert b["providers"]["slo"]["sketch_eps"]["alerting"] is True
    assert "memory" in b["providers"] and "retrace" in b["providers"]
    eng.close()


# -- protocol-v2 wire byte accounting ----------------------------------------


def _wire(direction):
    return _metric(
        "sentinel_wire_bytes_total", path="cluster", direction=direction
    )


def _frames(direction):
    return _metric("sentinel_cluster_batch_frames_total", direction=direction)


def test_wire_bytes_account_every_v2_frame_kind_exactly():
    """Coverage audit (PR 13 protocol v2): every encode/decode on the
    cluster path moves sentinel_wire_bytes_total by exactly len(frame)
    — prefix included — for request, response, batch-request and
    batch-response frames, traced variants included."""
    from sentinel_tpu.cluster import constants as C
    from sentinel_tpu.cluster import protocol as P

    reqs = [
        P.ClusterRequest(xid=1, type=C.MSG_TYPE_PING),
        P.ClusterRequest(
            xid=2, type=C.MSG_TYPE_FLOW, flow_id=77, count=3, priority=True
        ),
        P.ClusterRequest(
            xid=3,
            type=C.MSG_TYPE_PARAM_FLOW,
            flow_id=9,
            count=1,
            params=["user", "42"],
        ),
        # traced variant: the 17-byte trace tail must be accounted too
        P.ClusterRequest(
            xid=4,
            type=C.MSG_TYPE_LEASE,
            flow_id=5,
            count=2,
            trace_id=0xDEADBEEF,
            span_id=0xFEED,
        ),
    ]
    for req in reqs:
        tx0, rx0 = _wire("tx"), _wire("rx")
        f = P.encode_request(req)
        assert _wire("tx") - tx0 == len(f)
        got = P.decode_request(f[2:])
        assert _wire("rx") - rx0 == len(f)
        assert (got.xid, got.type, got.flow_id, got.count) == (
            req.xid,
            req.type,
            req.flow_id,
            req.count,
        )
        assert got.params == req.params and got.trace_id == req.trace_id

    rsps = [
        P.ClusterResponse(xid=1, type=C.MSG_TYPE_FLOW, status=C.STATUS_OK),
        P.ClusterResponse(
            xid=2,
            type=C.MSG_TYPE_FLOW,
            status=C.STATUS_OK,
            remaining=41,
            wait_ms=7,
            trace_id=0xBEEF,
            span_id=0x17,
        ),
    ]
    for rsp in rsps:
        tx0, rx0 = _wire("tx"), _wire("rx")
        f = P.encode_response(rsp)
        assert _wire("tx") - tx0 == len(f)
        got = P.decode_response(f[2:])
        assert _wire("rx") - rx0 == len(f)
        assert (got.xid, got.status, got.remaining, got.wait_ms) == (
            rsp.xid,
            rsp.status,
            rsp.remaining,
            rsp.wait_ms,
        )


def test_wire_bytes_account_batch_frames_and_frame_counters():
    from sentinel_tpu.cluster import constants as C
    from sentinel_tpu.cluster import protocol as P

    n = 3
    breq = P.ClusterBatchRequest(
        xid=11,
        kinds=np.asarray(
            [C.BATCH_KIND_FLOW, C.BATCH_KIND_FLOW_BATCH, C.BATCH_KIND_LEASE],
            np.uint8,
        ),
        ids=np.asarray([101, 102, 103], np.int64),
        counts=np.asarray([1, 4, 2], np.int32),
        flags=np.asarray([0, 1, 0], np.uint8),
        trace_id=0xABCD,
        span_id=0x99,
    )
    tx0, rx0 = _wire("tx"), _wire("rx")
    ftx0, frx0 = _frames("tx"), _frames("rx")
    f = P.encode_batch_request(breq)
    assert _wire("tx") - tx0 == len(f)
    got = P.decode_batch_request(f[2:])
    assert _wire("rx") - rx0 == len(f)
    assert _frames("tx") - ftx0 == 1 and _frames("rx") - frx0 == 1
    assert got.xid == breq.xid and got.trace_id == breq.trace_id
    np.testing.assert_array_equal(got.kinds, breq.kinds)
    np.testing.assert_array_equal(got.ids, breq.ids)
    np.testing.assert_array_equal(got.counts, breq.counts)

    brsp = P.ClusterBatchResponse(
        xid=11,
        status=C.STATUS_OK,
        statuses=np.zeros(n, np.int8),
        remainings=np.asarray([9, 8, 7], np.int32),
        waits=np.zeros(n, np.int32),
        token_ids=np.asarray([0, 0, 555], np.int64),
    )
    tx0, rx0 = _wire("tx"), _wire("rx")
    ftx0, frx0 = _frames("tx"), _frames("rx")
    f = P.encode_batch_response(brsp)
    assert _wire("tx") - tx0 == len(f)
    got = P.decode_batch_response(f[2:])
    assert _wire("rx") - rx0 == len(f)
    assert _frames("tx") - ftx0 == 1 and _frames("rx") - frx0 == 1
    np.testing.assert_array_equal(got.remainings, brsp.remainings)
    np.testing.assert_array_equal(got.token_ids, brsp.token_ids)
