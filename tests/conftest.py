"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so sharding tests exercise real
SPMD partitioning without TPU hardware.

Note: this image's sitecustomize registers an `axon` TPU-tunnel backend and
forces ``jax_platforms=axon`` at interpreter start (before conftest runs),
so setting the env var here is not enough — we must override the live jax
config.  Backends are still uninitialized at conftest-import time, so the
override takes effect for every test.
"""

import importlib.util
import os
import sys

# The mesh width/axis and the env recipe come from ONE shared helper
# (sentinel_tpu/parallel/meshspec.py — also consumed by parallel/spmd.py,
# the __graft_entry__ dry-run, and the tier-4 SPMD analyzer subprocess).
# Loaded by FILE PATH: importing the sentinel_tpu package here would pull
# jax in before the env mutation below, defeating the whole point.
_ms_spec = importlib.util.spec_from_file_location(
    "_sentinel_meshspec",
    os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        os.pardir,
        "sentinel_tpu",
        "parallel",
        "meshspec.py",
    ),
)
_meshspec = importlib.util.module_from_spec(_ms_spec)
# registered so @dataclass can resolve the defining module at class
# creation (dataclasses looks the module up in sys.modules)
sys.modules[_ms_spec.name] = _meshspec
_ms_spec.loader.exec_module(_meshspec)
# keep_existing_count: a caller who pre-forced a topology keeps it
_meshspec.force_cpu_mesh_env(os.environ, keep_existing_count=True)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: a persistent jax compilation cache was tried here to cut the
# suite's re-jit cost (VERDICT r3 weak #9) but the CPU backend segfaults
# deserializing cached executables on the second run (jaxlib
# compilation_cache.get_executable_and_time) — do not re-enable without
# verifying a double run passes.

import pytest  # noqa: E402

# Heavy equivalence/engine tests run EAGERLY (jax.disable_jit): their cost
# is XLA-CPU compilation of interpret-mode engine programs, not execution —
# the seg-vs-fused equivalence test alone took 1080 s jitted vs 96 s eager
# (measured, identical assertions; integer/float ops are bit-identical
# either way).  Modules needing real jit semantics (pjit/mesh sharding,
# subprocess hosts) stay jitted.
_EAGER_MODULES = {
    "test_engine_seg",
    "test_fused",
    "test_engine_backends",
    "test_client_fastpath",
    "test_tpu_equivalence",
    "test_rank",
    "test_occupy",
    "test_segment",
    "test_sketch",
    "test_tail_rules",
    "test_adapters",
    "test_mxu_table",
    "test_workload",
    "test_workload_adapters",
}


@pytest.fixture(autouse=True)
def _eager_heavy(request):
    # @pytest.mark.jitted opts a test back into compiled execution —
    # tests that run MANY small ticks are execution-bound, and eager
    # dispatch costs more there than one compile does
    if request.node.get_closest_marker("jitted") is not None:
        yield
        return
    mod = getattr(request.node, "module", None)
    name = mod.__name__.rsplit(".", 1)[-1] if mod else ""
    if name in _EAGER_MODULES:
        with jax.disable_jit():
            yield
    else:
        yield


@pytest.fixture(autouse=True)
def _clean_context():
    """Entries deliberately held open by one test must not leak into the
    next test's (or its asyncio.run copy's) context stack — the
    ContextTestUtil.cleanUpContext analog."""
    yield
    from sentinel_tpu.runtime import context as CTX

    CTX.clear()


@pytest.fixture()
def vt():
    """Fresh virtual time source starting at a non-zero, non-aligned ms."""
    from sentinel_tpu.utils.time_source import VirtualTimeSource

    return VirtualTimeSource(start_ms=1_000)


@pytest.fixture()
def client_factory(vt):
    """Builds sync-mode clients on the small engine config + virtual time;
    stops them all at teardown."""
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.runtime.client import SentinelClient

    made = []

    def factory(**kw):
        kw.setdefault("cfg", small_engine_config())
        kw.setdefault("time_source", vt)
        kw.setdefault("mode", "sync")
        c = SentinelClient(**kw)
        c.start()
        made.append(c)
        return c

    yield factory
    for c in made:
        c.stop()


@pytest.fixture()
def client(client_factory):
    """Shared sync-mode client on virtual time (the common fixture)."""
    return client_factory()
