"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax is imported anywhere,
so sharding tests exercise real SPMD partitioning without TPU hardware
(the driver separately dry-run-compiles the multi-chip path).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def vt():
    """Fresh virtual time source starting at a non-zero, non-aligned ms."""
    from sentinel_tpu.utils.time_source import VirtualTimeSource

    return VirtualTimeSource(start_ms=1_000)
