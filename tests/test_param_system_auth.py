"""ParamFlowSlot / SystemSlot / AuthoritySlot integration tests.

Counterparts of the reference's ParamFlowCheckerTest,
SystemGuardIntegrationTest and AuthoritySlotTest (SURVEY.md §4.3),
exercised through the public API with virtual time.
"""


import sentinel_tpu as st
from sentinel_tpu.core.rules import ParamFlowItem


# ---------------- param flow ----------------


def test_param_flow_per_value_budget(client, vt):
    client.param_flow_rules.load(
        [st.ParamFlowRule(resource="api", count=2, duration_in_sec=1)]
    )
    # value "a": budget 2/s
    got_a = sum(1 for _ in range(5) if client.try_entry("api", args=["a"]))
    # value "b" has its own bucket
    got_b = sum(1 for _ in range(5) if client.try_entry("api", args=["b"]))
    assert got_a == 2
    assert got_b == 2
    # no param → param rule does not apply
    assert client.try_entry("api") is not None

    vt.advance(1100)
    assert client.try_entry("api", args=["a"]) is not None


def test_param_flow_item_exception(client, vt):
    client.param_flow_rules.load(
        [
            st.ParamFlowRule(
                resource="api2",
                count=1,
                duration_in_sec=1,
                param_flow_item_list=[ParamFlowItem(object="vip", count=5)],
            )
        ]
    )
    got_vip = sum(1 for _ in range(8) if client.try_entry("api2", args=["vip"]))
    got_x = sum(1 for _ in range(8) if client.try_entry("api2", args=["x"]))
    assert got_vip == 5
    assert got_x == 1


def test_param_flow_burst(client, vt):
    client.param_flow_rules.load(
        [st.ParamFlowRule(resource="api3", count=2, duration_in_sec=1, burst_count=3)]
    )
    got = sum(1 for _ in range(10) if client.try_entry("api3", args=[7]))
    assert got == 5  # count*duration + burst


def test_param_flow_thread_grade(client, vt):
    """GRADE_THREAD param rules bound per-VALUE concurrency and release on
    exit (ParamFlowChecker.passLocalCheck THREAD branch,
    ParamFlowSlot.exit decreaseThreadCount)."""
    client.param_flow_rules.load(
        [st.ParamFlowRule(resource="papi", count=2, grade=st.GRADE_THREAD)]
    )
    e1 = client.try_entry("papi", args=["k"])
    e2 = client.try_entry("papi", args=["k"])
    assert e1 and e2
    # third concurrent holder of value "k" is rejected...
    assert client.try_entry("papi", args=["k"]) is None
    # ...but another value has its own concurrency budget
    e3 = client.try_entry("papi", args=["other"])
    assert e3
    # releasing one "k" holder frees a slot
    e1.exit()
    e4 = client.try_entry("papi", args=["k"])
    assert e4
    for e in (e2, e3, e4):
        e.exit()


def test_param_flow_multi_index(client, vt):
    """Two rules with different paramIdx on one resource enforce their own
    argument lanes (ParamFlowChecker.java:78 paramIdx dispatch)."""
    client.param_flow_rules.load(
        [
            st.ParamFlowRule(resource="mapi", count=50, param_idx=0),
            st.ParamFlowRule(resource="mapi", count=2, param_idx=1),
        ]
    )
    # distinct idx-0 values keep rule 0 out of the way; idx-1 value "y" is
    # capped at 2 by the second rule
    got = sum(
        1 for i in range(6) if client.try_entry("mapi", args=[f"x{i}", "y"])
    )
    assert got == 2
    # a fresh idx-1 value has its own budget even under one idx-0 value
    got2 = sum(
        1 for i in range(6) if client.try_entry("mapi", args=["x0", f"z{0}"])
    )
    assert got2 == 2


def test_param_flow_four_distinct_indices(client_factory, vt):
    """Four rules with four DISTINCT paramIdx on one resource all enforce
    (ParamFlowChecker.java:78 dispatches on arbitrary paramIdx; the ring
    transport carries four release lanes — sx_event.aux0..aux3).  The
    r2/r3 "unenforced rule" warning path must be unreachable here."""
    from sentinel_tpu.core.config import small_engine_config

    client = client_factory(
        cfg=small_engine_config(param_dims=4, param_rules_per_resource=4)
    )
    client.param_flow_rules.load(
        [
            st.ParamFlowRule(resource="papi4", count=50, param_idx=0),
            st.ParamFlowRule(resource="papi4", count=2, param_idx=1),
            st.ParamFlowRule(resource="papi4", count=3, param_idx=2),
            st.ParamFlowRule(
                resource="papi4", count=2, param_idx=3, grade=st.GRADE_THREAD
            ),
        ]
    )
    # every index got a hash lane (nothing dropped to the warning path)
    assert sorted(
        client.param_lane("papi4", k) for k in range(4)
    ) == [0, 1, 2, 3]

    # idx-1 value "y" capped at 2 while idx 0/2/3 stay distinct
    got = sum(
        1
        for i in range(6)
        if client.try_entry("papi4", args=[f"a{i}", "y", f"c{i}", f"d{i}"])
    )
    assert got == 2
    # idx-2 value "w" capped at 3 under fresh values elsewhere
    got2 = sum(
        1
        for i in range(6)
        if client.try_entry("papi4", args=[f"e{i}", f"f{i}", "w", f"g{i}"])
    )
    assert got2 == 3
    vt.advance(1100)
    # idx-3 THREAD grade: per-value concurrency 2, released on exit
    # through the ring's third release lane
    e1 = client.try_entry("papi4", args=["p", "q", "r", "t"])
    e2 = client.try_entry("papi4", args=["p2", "q2", "r2", "t"])
    assert e1 and e2
    assert client.try_entry("papi4", args=["p3", "q3", "r3", "t"]) is None
    e1.exit()
    e4 = client.try_entry("papi4", args=["p4", "q4", "r4", "t"])
    assert e4
    for e in (e2, e4):
        e.exit()


# ---------------- system rules ----------------


def test_system_qps_gate(client, vt):
    client.system_rules.load([st.SystemRule(qps=5)])
    got = sum(1 for _ in range(10) if client.try_entry("in-svc", inbound=True))
    assert got == 5
    # outbound traffic unaffected (SystemSlot guards inbound only)
    assert client.try_entry("out-svc") is not None
    vt.advance(1100)
    assert client.try_entry("in-svc", inbound=True) is not None


def test_system_thread_gate(client, vt):
    client.system_rules.load([st.SystemRule(max_thread=2)])
    e1 = client.try_entry("s1", inbound=True)
    e2 = client.try_entry("s1", inbound=True)
    assert e1 and e2
    assert client.try_entry("s1", inbound=True) is None
    e1.exit()
    assert client.try_entry("s1", inbound=True) is not None


def test_system_avg_rt_gate(client, vt):
    client.system_rules.load([st.SystemRule(avg_rt=10)])
    # one slow completion drives the global average RT over the threshold
    e = client.entry("slow", inbound=True)
    vt.advance(100)
    e.exit()
    assert client.try_entry("anything", inbound=True) is None
    # the slow sample ages out of the second window → gate reopens
    vt.advance(1100)
    assert client.try_entry("anything", inbound=True) is not None


# ---------------- authority ----------------


def test_authority_white_list(client, vt):
    client.authority_rules.load(
        [st.AuthorityRule(resource="guarded", limit_app="appA,appB", strategy=st.AUTHORITY_WHITE)]
    )
    with client.context("ctx", "appA"):
        assert client.try_entry("guarded") is not None
    with client.context("ctx", "appC"):
        assert client.try_entry("guarded") is None
    # no origin: not on the white list → blocked? The reference requires a
    # matching origin for white-listed resources; empty origin doesn't match
    with client.context("ctx", ""):
        assert client.try_entry("guarded") is None


def test_authority_black_list(client, vt):
    client.authority_rules.load(
        [st.AuthorityRule(resource="g2", limit_app="evil", strategy=st.AUTHORITY_BLACK)]
    )
    with client.context("ctx", "evil"):
        assert client.try_entry("g2") is None
    with client.context("ctx", "good"):
        assert client.try_entry("g2") is not None


# ---------------- origin-scoped flow rules ----------------


def test_flow_rule_limit_app_specific_and_other(client, vt):
    client.flow_rules.load(
        [
            st.FlowRule(resource="mix", count=2, limit_app="appA"),
            st.FlowRule(resource="mix", count=5, limit_app="other"),
        ]
    )
    with client.context("c", "appA"):
        got_a = sum(1 for _ in range(8) if client.try_entry("mix"))
    with client.context("c", "appZ"):
        got_z = sum(1 for _ in range(8) if client.try_entry("mix"))
    assert got_a == 2  # specific rule
    assert got_z == 5  # "other" rule
