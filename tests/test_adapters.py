"""Adapter tests — decorator, WSGI, ASGI, gRPC interceptors, outbound HTTP
guard, and the gateway rule/param bridge (reference: the 96 adapter tests'
pattern — drive the framework hook, assert block vs pass + node counters)."""

import asyncio

import pytest

import sentinel_tpu as st
from sentinel_tpu.adapters import (
    ApiDefinition,
    ApiDefinitionManager,
    ApiPredicateItem,
    GatewayAdapter,
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayRuleManager,
    RequestAttributes,
    SentinelASGIMiddleware,
    SentinelHttpClient,
    SentinelWSGIMiddleware,
    sentinel_resource,
)
from sentinel_tpu.adapters import gateway as GW


# -- decorator --------------------------------------------------------------


def test_decorator_pass_block_fallback(client, vt):
    calls = []

    def on_block(x, block_exception=None):
        calls.append(("block", x, type(block_exception).__name__))
        return "blocked"

    def on_err(x, exception=None):
        calls.append(("fallback", x, type(exception).__name__))
        return "fell-back"

    @sentinel_resource("deco", block_handler=on_block, fallback=on_err, client=client)
    def fn(x):
        if x == "boom":
            raise ValueError("biz")
        return x * 2

    client.flow_rules.load([st.FlowRule(resource="deco", count=2)])
    assert fn("a") == "aa"
    assert fn("boom") == "fell-back"
    assert fn("c") == "blocked"  # third call in the window → flow-blocked
    assert calls == [("fallback", "boom", "ValueError"), ("block", "c", "FlowException")]
    s = client.stats.resource("deco")
    assert s["blockQps"] == 1
    assert s["exceptionQps"] == 1


def test_decorator_default_name_and_ignore(client, vt):
    @sentinel_resource(exceptions_to_ignore=(KeyError,), client=client)
    def named():
        raise KeyError("skip")

    assert named.__sentinel_resource__.endswith("named")
    with pytest.raises(KeyError):
        named()
    s = client.stats.resource(named.__sentinel_resource__)
    assert s["exceptionQps"] == 0  # ignored exceptions are not traced


# -- WSGI -------------------------------------------------------------------


def _wsgi_get(mw, path, **environ):
    status_headers = {}

    def start_response(status, headers):
        status_headers["status"] = status

    env = {"REQUEST_METHOD": "GET", "PATH_INFO": path, **environ}
    result = mw(env, start_response)
    try:
        body = b"".join(result)
    finally:
        close = getattr(result, "close", None)
        if close is not None:
            close()  # WSGI servers always call close()
    return status_headers["status"], body


def test_wsgi_block_and_pass(client, vt):
    def app(environ, start_response):
        start_response("200 OK", [("Content-Type", "text/plain")])
        return [b"hello"]

    mw = SentinelWSGIMiddleware(app, client=client)
    client.flow_rules.load([st.FlowRule(resource="GET:/api", count=2)])
    assert _wsgi_get(mw, "/api") == ("200 OK", b"hello")
    assert _wsgi_get(mw, "/api") == ("200 OK", b"hello")
    status, body = _wsgi_get(mw, "/api")
    assert status.startswith("429")
    assert b"Blocked" in body
    s = client.stats.resource("GET:/api")
    assert s["passQps"] == 2 and s["blockQps"] == 1
    assert s["curThreadNum"] == 0  # iterator close exits every entry


def test_wsgi_origin_and_exception(client, vt):
    def app(environ, start_response):
        raise RuntimeError("app broke")

    mw = SentinelWSGIMiddleware(app, client=client)
    client.authority_rules.load(
        [st.AuthorityRule(resource="GET:/sec", limit_app="evil", strategy=st.AUTHORITY_BLACK)]
    )
    status, body = _wsgi_get(mw, "/sec", HTTP_S_USER="evil")
    assert status.startswith("429")
    with pytest.raises(RuntimeError):
        _wsgi_get(mw, "/ok", HTTP_S_USER="good")
    s = client.stats.resource("GET:/ok")
    assert s["exceptionQps"] == 1 and s["curThreadNum"] == 0


# -- ASGI -------------------------------------------------------------------


def test_asgi_block_and_pass(client, vt):
    async def app(scope, receive, send):
        await send({"type": "http.response.start", "status": 200, "headers": []})
        await send({"type": "http.response.body", "body": b"ok"})

    mw = SentinelASGIMiddleware(app, client=client)
    client.flow_rules.load([st.FlowRule(resource="GET:/a", count=1)])

    async def run_one():
        sent = []

        async def send(msg):
            sent.append(msg)

        async def receive():
            return {"type": "http.request"}

        scope = {"type": "http", "method": "GET", "path": "/a", "headers": []}
        await mw(scope, receive, send)
        return sent

    first = asyncio.run(run_one())
    assert first[0]["status"] == 200
    second = asyncio.run(run_one())
    assert second[0]["status"] == 429
    s = client.stats.resource("GET:/a")
    assert s["passQps"] == 1 and s["blockQps"] == 1


# -- outbound HTTP guard ----------------------------------------------------


def test_http_client_guard(client, vt):
    sent = []

    def send(method, url, **kw):
        sent.append((method, url))
        return "rsp"

    hc = SentinelHttpClient(send, client=client)
    client.flow_rules.load(
        [st.FlowRule(resource="GET:http://svc/api", count=1)]
    )
    assert hc.request("GET", "http://svc/api?q=1") == "rsp"
    with pytest.raises(st.BlockException):
        hc.request("GET", "http://svc/api?q=2")  # query stripped → same resource
    assert len(sent) == 1


# -- gRPC interceptors ------------------------------------------------------


def test_grpc_server_interceptor(client, vt):
    import grpc
    from sentinel_tpu.adapters.grpc_adapter import SentinelServerInterceptor

    inner_calls = []

    def inner(request, context):
        inner_calls.append(request)
        return "reply"

    base_handler = grpc.unary_unary_rpc_method_handler(inner)

    class Details:
        method = "/pkg.Svc/Do"
        invocation_metadata = (("s-user", "caller-x"),)

    class FakeContext:
        def __init__(self):
            self.aborted = None

        def abort(self, code, details):
            self.aborted = code
            raise RuntimeError("aborted")

    interceptor = SentinelServerInterceptor(client=client)
    handler = interceptor.intercept_service(lambda d: base_handler, Details())
    client.flow_rules.load([st.FlowRule(resource="/pkg.Svc/Do", count=1)])
    ctx = FakeContext()
    assert handler.unary_unary("req", ctx) == "reply"
    ctx2 = FakeContext()
    with pytest.raises(RuntimeError):
        handler.unary_unary("req2", ctx2)
    assert ctx2.aborted == grpc.StatusCode.RESOURCE_EXHAUSTED
    assert inner_calls == ["req"]


def test_grpc_client_interceptor(client, vt):
    import grpc
    from sentinel_tpu.adapters.grpc_adapter import SentinelClientInterceptor

    class FakeCall:
        def __init__(self):
            self.cbs = []

        def add_done_callback(self, cb):
            self.cbs.append(cb)

        def code(self):
            return grpc.StatusCode.OK

    class Details:
        method = "/pkg.Svc/Out"

    interceptor = SentinelClientInterceptor(client=client)
    client.flow_rules.load([st.FlowRule(resource="/pkg.Svc/Out", count=1)])
    call = interceptor.intercept_unary_unary(lambda d, r: FakeCall(), Details(), "req")
    for cb in call.cbs:
        cb(call)  # RPC completes → entry exits
    with pytest.raises(st.BlockException):
        interceptor.intercept_unary_unary(lambda d, r: FakeCall(), Details(), "req")
    s = client.stats.resource("/pkg.Svc/Out")
    assert s["curThreadNum"] == 0


# -- gateway ----------------------------------------------------------------


def test_gateway_param_parser_strategies():
    p = GW.GatewayParamParser()
    req = RequestAttributes(
        path="/x",
        client_ip="10.0.0.9",
        host="svc.example",
        headers={"X-Tenant": "acme"},
        url_params={"user": "u1"},
        cookies={"session": "s1"},
    )
    assert p.parse_value(GatewayParamFlowItem(GW.PARAM_PARSE_STRATEGY_CLIENT_IP), req) == "10.0.0.9"
    assert p.parse_value(GatewayParamFlowItem(GW.PARAM_PARSE_STRATEGY_HOST), req) == "svc.example"
    assert (
        p.parse_value(
            GatewayParamFlowItem(GW.PARAM_PARSE_STRATEGY_HEADER, field_name="X-Tenant"), req
        )
        == "acme"
    )
    assert (
        p.parse_value(
            GatewayParamFlowItem(GW.PARAM_PARSE_STRATEGY_URL_PARAM, field_name="user"), req
        )
        == "u1"
    )
    assert (
        p.parse_value(
            GatewayParamFlowItem(GW.PARAM_PARSE_STRATEGY_COOKIE, field_name="session"), req
        )
        == "s1"
    )
    # pattern mismatch → NOT_MATCH sentinel
    item = GatewayParamFlowItem(
        GW.PARAM_PARSE_STRATEGY_HEADER,
        field_name="X-Tenant",
        pattern="globex",
        match_strategy=GW.PARAM_MATCH_STRATEGY_EXACT,
    )
    assert p.parse_value(item, req) == GW.NOT_MATCH_PARAM
    item.match_strategy = GW.PARAM_MATCH_STRATEGY_CONTAINS
    item.pattern = "cm"
    assert p.parse_value(item, req) == "acme"


def test_api_definition_matching():
    apis = ApiDefinitionManager()
    apis.load(
        [
            ApiDefinition("user-api", [ApiPredicateItem("/users", GW.URL_MATCH_STRATEGY_PREFIX)]),
            ApiDefinition("exact-api", [ApiPredicateItem("/ping", GW.URL_MATCH_STRATEGY_EXACT)]),
            ApiDefinition("re-api", [ApiPredicateItem(r"/v\d+/items", GW.URL_MATCH_STRATEGY_REGEX)]),
        ]
    )
    assert apis.match("/users/42") == ["user-api"]
    assert apis.match("/ping") == ["exact-api"]
    assert apis.match("/v2/items") == ["re-api"]
    assert apis.match("/other") == []


def test_gateway_end_to_end_per_param_limit(client, vt):
    gw = GatewayAdapter(client)
    gw.rules.load_rules(
        [
            GatewayFlowRule(
                resource="route-a",
                count=2,
                param_item=GatewayParamFlowItem(
                    GW.PARAM_PARSE_STRATEGY_HEADER, field_name="X-Tenant"
                ),
            )
        ]
    )

    def hit(tenant):
        req = RequestAttributes(path="/svc", client_ip="1.1.1.1", headers={"X-Tenant": tenant})
        try:
            entries = gw.entries_for("route-a", req)
        except st.BlockException:
            return False
        for e in entries:
            e.exit()
        return True

    assert hit("acme") and hit("acme")
    assert not hit("acme")  # tenant acme exhausted its 2 QPS
    assert hit("globex")  # other tenant unaffected
    vt.advance(1100)
    assert hit("acme")


def test_gateway_api_group_entry(client, vt):
    gw = GatewayAdapter(client)
    gw.apis.load(
        [ApiDefinition("grp", [ApiPredicateItem("/g", GW.URL_MATCH_STRATEGY_PREFIX)])]
    )
    gw.rules.load_rules([GatewayFlowRule(resource="grp", count=1)])
    req = RequestAttributes(path="/g/1", client_ip="2.2.2.2")
    entries = gw.entries_for("route-b", req)
    assert [e.resource for e in entries] == ["route-b", "grp"]
    for e in entries:
        e.exit()
    with pytest.raises(st.BlockException):
        gw.entries_for("route-b", req)  # grp limit 1/s exhausted
    # the failed acquisition exited the route entry it had taken
    assert client.stats.resource("route-b")["curThreadNum"] == 0
