"""Remote datasources (HTTP poll with conditional GET, push callback),
async entry, and hot-param top-K visibility."""

import asyncio
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource import CallbackDataSource, HttpDataSource, json_rule_converter


@pytest.fixture()
def rules_server():
    state = {"body": json.dumps([{"resource": "http-res", "count": 5}]), "etag": "v1", "hits": 0, "not_modified": 0}

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            state["hits"] += 1
            if self.headers.get("If-None-Match") == state["etag"]:
                state["not_modified"] += 1
                self.send_response(304)
                self.end_headers()
                return
            payload = state["body"].encode()
            self.send_response(200)
            self.send_header("ETag", state["etag"])
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv, state
    srv.shutdown()
    srv.server_close()


def test_http_datasource_polls_and_conditional_gets(rules_server, client):
    srv, state = rules_server
    url = f"http://127.0.0.1:{srv.server_address[1]}/rules"
    ds = HttpDataSource(url, json_rule_converter("flow"), refresh_ms=50)
    try:
        client.flow_rules.register_property(ds.get_property())
        assert client.flow_rules.get()[0].count == 5  # initial fetch

        assert not ds.refresh()  # unchanged → 304 → no push
        assert state["not_modified"] >= 1

        state["body"] = json.dumps([{"resource": "http-res", "count": 9}])
        state["etag"] = "v2"
        assert ds.refresh()
        assert client.flow_rules.get()[0].count == 9
    finally:
        ds.close()


def test_callback_datasource_push(client):
    ds = CallbackDataSource(json_rule_converter("degrade"))
    client.degrade_rules.register_property(ds.get_property())
    ds.update(json.dumps([{"resource": "cb-res", "count": 3, "grade": 2}]))
    rules = client.degrade_rules.get()
    assert rules[0].resource == "cb-res"
    ds.update("[]")
    assert client.degrade_rules.get() == []


def test_entry_async(client, vt):
    client.flow_rules.load([st.FlowRule(resource="aio", count=1)])

    async def run():
        e = await client.entry_async("aio")
        e.exit()
        with pytest.raises(st.BlockException):
            await client.entry_async("aio")

    asyncio.run(run())
    assert client.stats.resource("aio")["passQps"] == 1


def test_entry_async_trace_and_context(client, vt):
    """The Entry lands on the AWAITING task's context stack: st-style
    trace() after the await records the error, and exit() pops cleanly."""
    from sentinel_tpu.runtime import context as CTX

    client.flow_rules.load([st.FlowRule(resource="aio2", count=10)])

    async def run():
        e = await client.entry_async("aio2")
        assert CTX.current_entry() is e
        client.trace(ValueError("async biz error"))
        vt.advance(5)
        e.exit()
        assert CTX.current_entry() is None

    asyncio.run(run())
    s = client.stats.resource("aio2")
    assert s["exceptionQps"] == 1
    assert s["curThreadNum"] == 0


def test_hot_param_topk(client, vt):
    from sentinel_tpu.transport import build_default_handlers
    from sentinel_tpu.transport.command import CommandRequest

    client.param_flow_rules.load(
        [st.ParamFlowRule(resource="hp", count=100, param_idx=0)]
    )
    for u, n in (("alice", 5), ("bob", 2), ("carol", 1)):
        for _ in range(n):
            with client.entry("hp", args=[u]):
                pass
    assert client.top_params("hp", 2) == [("alice", 5), ("bob", 2)]
    reg = build_default_handlers(client)
    out = reg.handle("topParams", CommandRequest(parameters={"id": "hp"}))
    assert out.success
    assert out.result[0] == {"param": "'alice'", "sightings": 5}


def test_hot_param_cap_decimates(client, vt):
    client.param_flow_rules.load(
        [st.ParamFlowRule(resource="cap", count=10000, param_idx=0)]
    )
    cap = client._HOT_PARAM_CAP
    # one hot value plus a long unique tail
    for _ in range(10):
        with client.entry("cap", args=["hot"]):
            pass
    for i in range(cap + 100):
        with client.entry("cap", args=[f"cold-{i}"]):
            pass
    top = client.top_params("cap", 1)
    assert top[0][0] == "hot"  # survivors are the hottest
    assert len(client._hot_params["cap"]) <= cap


def test_custom_entry_hook_and_init_funcs(client, vt):
    """Custom-slot SPI analog: an entry hook can reject; InitFunc analog:
    registered callbacks run once at api.init in order."""
    calls = []

    def deny_vip(resource, origin, args):
        calls.append(resource)
        if resource == "forbidden":
            raise st.BlockException("custom: forbidden")

    client.entry_hooks.append(deny_vip)
    with client.entry("ok-res"):
        pass
    with pytest.raises(st.BlockException):
        client.entry("forbidden")
    assert calls == ["ok-res", "forbidden"]
    # hook-raised blocks flow through the engine's accounting (the custom
    # slot's exception still passes StatisticSlot in the reference)
    assert client.stats.resource("forbidden")["blockQps"] == 1

    import sentinel_tpu.core.api as api

    ran = []
    api.reset()
    api._init_funcs.clear()
    st.register_init_func(lambda c: ran.append("b"), order=2)
    st.register_init_func(lambda c: ran.append("a"), order=1)
    c = api.init(cfg=client.cfg, time_source=client.time, mode="sync")
    try:
        assert ran == ["a", "b"]
        api.init()  # second call: no re-run
        assert ran == ["a", "b"]
    finally:
        api.reset()
        api._init_funcs.clear()
