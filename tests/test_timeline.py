"""Per-resource metric timelines (PR 9): device top-K stat rows
(ops/engine._device_res_stats), the indexed binary MetricLog with
rotation/retention/crash recovery, the write-behind TimelineRecorder's
exact per-second fold, the GET /api/metric query surface, fleet merge
with per-shard provenance, and the fail-OPEN disk-write contract."""

from __future__ import annotations

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.obs import REGISTRY
from sentinel_tpu.obs import timeline as TL
from sentinel_tpu.obs.fleet import merge_timelines
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import window as W

BIG = 1 << 62


class _Reg:
    def resource_id(self, n):
        return 1


def _tick(cfg, res, rules=None, t=1000, state=None):
    rules = rules if rules is not None else E._compile_ruleset(
        cfg, _Reg(), [], [], [], [], [], None
    )
    st = state if state is not None else E.init_state(cfg)
    tick = E.make_tick(cfg, donate=False)
    b = len(res)
    acq = E.empty_acquire(cfg, b=b)._replace(
        res=jnp.asarray(res, jnp.int32),
        count=jnp.ones(b, jnp.int32),
        inbound=jnp.ones(b, jnp.int32),
    )
    comp = E.empty_complete(cfg, b=b)
    z = jnp.float32(0.0)
    return tick(st, rules, acq, comp, jnp.int32(t), z, z)


# ---------------------------------------------------------------------------
# engine emission
# ---------------------------------------------------------------------------


def test_res_stats_matches_host_window_read():
    """The device matrix's rows must equal a host read of the current
    window bucket for the top-K rows by windowed pass+block."""
    cfg = small_engine_config()
    rules = E._compile_ruleset(
        cfg, _Reg(), [FlowRule(resource="r", count=2.0)], [], [], [], [], None
    )
    st, out = _tick(cfg, [1, 1, 1, 2, 2, 3], rules=rules, t=1000)
    rs = np.asarray(out.res_stats)
    assert rs.shape == (E.timeline_k(cfg), E.TL_COLS)
    # host recompute: windowed pass+block per resource row, current bucket
    sec_cfg = W.WindowConfig(cfg.second_sample_count, cfg.second_window_ms)
    counts = np.asarray(
        W.window_counts(st.win_sec, jnp.int32(1000), sec_cfg)
    )
    by_rid = {int(r[E.TL_RID]): r for r in rs}
    # resource 1: rule count=2 -> 2 pass / 1 block; resources 2,3 pass
    for rid, want_pass, want_block in ((1, 2, 1), (2, 2, 0), (3, 1, 0)):
        row = by_rid[rid]
        assert row[E.TL_PASS] == want_pass
        assert row[E.TL_BLOCK] == want_block
        assert counts[rid, W.EV_PASS] == want_pass
    # top-K ordering: the busiest row (3 events) ranks first
    assert int(rs[0, E.TL_RID]) == 1
    # the matrix's byte cost is the documented K * TL_COLS * 4
    assert rs.nbytes == E.timeline_k(cfg) * E.TL_COLS * 4


def test_res_stats_off_mode_and_clamp():
    cfg_off = small_engine_config(timeline_k=0)
    _st, out = _tick(cfg_off, [1, 2])
    assert out.res_stats is None and out.stats is not None
    # telemetry off kills the matrix too
    assert E.timeline_k(small_engine_config(device_telemetry=False)) == 0
    # K clamps to the resource-row space
    assert E.timeline_k(small_engine_config()) == 63
    assert E.timeline_k(small_engine_config(timeline_k=7)) == 7


def test_res_stats_stale_bucket_reads_zero():
    """A row whose current bucket was never written this window must
    read zero, not a dead epoch's left-over counts."""
    cfg = small_engine_config()
    st, _out = _tick(cfg, [1, 1], t=1000)
    # much later tick, empty batch: row 1's old bucket is deprecated
    st2, out2 = _tick(
        cfg, [cfg.trash_row], t=100_000, state=st
    )
    rs = np.asarray(out2.res_stats)
    by_rid = {int(r[E.TL_RID]): r for r in rs}
    assert by_rid[1][E.TL_PASS] == 0
    assert by_rid[1][E.TL_BLOCK] == 0


# ---------------------------------------------------------------------------
# binary codec + log lifecycle
# ---------------------------------------------------------------------------

#: pinned golden: the on-disk record layout is a compatibility contract —
#: if this fails, the codec changed and RECORD_MAGIC must be bumped
_GOLDEN_ROW = TL.MetricRow(1700000000000, "res/a", 3, 2, 1, 0, 12.5, 1.25, 4)
_GOLDEN_HEX = (
    "4c5433000068e5cf8b0100000300000002000000010000000000000000004841"
    "0000a03f0400000005007265732f616dfde5a3"
)


def test_codec_golden_roundtrip():
    buf = TL.pack_record(_GOLDEN_ROW)
    assert buf.hex() == _GOLDEN_HEX
    row, nxt = TL.unpack_record(buf)
    assert nxt == len(buf)
    assert row == _GOLDEN_ROW
    # corruption anywhere inside the record is rejected by the CRC
    bad = bytearray(buf)
    bad[20] ^= 0xFF
    assert TL.unpack_record(bytes(bad)) is None
    # truncation (torn tail) is rejected, not misread
    assert TL.unpack_record(buf[:-3]) is None


def test_log_rotation_retention_and_cross_segment_query(tmp_path):
    log = TL.MetricLog(str(tmp_path), max_segment_bytes=120, max_segments=3)
    for sec in range(10):
        log.append([TL.MetricRow(1000 * (sec + 1), "r", sec + 1, 0, 0, 0)])
    segs = log.segments()
    assert len(segs) == 3  # rotated at the size cap, pruned to retention
    rows = log.find("r", 0, BIG)
    assert len(rows) >= 2  # retention pruned the oldest seconds...
    assert [r.pass_count for r in rows] == [
        r.sec_ms // 1000 for r in rows
    ]  # ...but surviving rows span segments and stay exact
    assert rows[-1].sec_ms == 10_000
    # range queries seek: an end before the newest segment excludes it
    assert all(r.sec_ms <= 9000 for r in log.find("r", 0, 9000))
    log.close()


def test_torn_tail_truncated_on_reopen(tmp_path):
    log = TL.MetricLog(str(tmp_path))
    log.append([TL.MetricRow(1000, "a", 1, 0, 0, 0)])
    log.append([TL.MetricRow(2000, "a", 2, 0, 0, 0)])
    log.close()
    seg = TL.MetricLog(str(tmp_path)).segments()[-1]
    with open(seg, "ab") as f:  # a crash mid-append leaves half a record
        f.write(TL.pack_record(TL.MetricRow(3000, "a", 3, 0, 0, 0))[:20])
    log2 = TL.MetricLog(str(tmp_path))
    rows = log2.find("a", 0, BIG)
    assert [r.pass_count for r in rows] == [1, 2]  # torn record gone
    # and the truncated segment accepts clean appends again
    log2.append([TL.MetricRow(3000, "a", 30, 0, 0, 0)])
    assert [r.pass_count for r in log2.find("a", 0, BIG)] == [1, 2, 30]
    log2.close()


def test_index_disagreement_rebuilt_on_reopen(tmp_path):
    log = TL.MetricLog(str(tmp_path))
    for sec in (1000, 2000, 3000):
        log.append([TL.MetricRow(sec, "a", sec // 1000, 0, 0, 0)])
    log.close()
    idx_path = log.segments()[-1].replace(".mlog", ".idx")
    with open(idx_path, "wb") as f:  # lie: offsets point mid-record
        f.write(TL._IDX.pack(2000, 7))
    log2 = TL.MetricLog(str(tmp_path))
    assert TL._read_idx(idx_path) != [(2000, 7)]  # rebuilt from records
    assert [r.pass_count for r in log2.find("a", 2000, 3000)] == [2, 3]
    log2.close()


def test_recorder_write_failure_fails_open(tmp_path):
    """An injected disk-write failure drops rows from DISK only: the
    failure is counted, and the memory ring still answers queries."""
    from sentinel_tpu.chaos import failpoints as FP
    from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec

    fail = REGISTRY.counter("sentinel_timeline_write_failures_total", "")
    f0 = fail.value
    log = TL.MetricLog(str(tmp_path))
    rec = TL.TimelineRecorder(lambda rid: f"res-{rid}", 500, 2, log=log)
    mat = np.zeros((1, E.TL_COLS), np.float32)
    mat[0] = [1, 4, 1, 0, 0, 0, 5000.0, 0]
    plan = FaultPlan(
        name="t", seed=1,
        faults=[FaultSpec("datasource.metriclog.write", "raise", max_fires=1)],
    )
    FP.arm(plan)
    try:
        rec.note_tick(mat, 1100, 0)
        rec.note_tick(mat, 2100, 0)  # flushes sec 1000 -> injected failure
    finally:
        FP.disarm()
    assert fail.value - f0 == 1
    assert log.find("res-1", 0, BIG) == []  # dropped from disk
    got = rec.find("res-1", 1000, 1000)  # ...but not from the recorder
    assert len(got) == 1 and got[0].pass_count == 4
    rec.close()


# ---------------------------------------------------------------------------
# acceptance: exact per-second rows through the full client + /api/metric
# ---------------------------------------------------------------------------


def _api_metric(client, **params):
    from sentinel_tpu.transport.command import CommandRequest
    from sentinel_tpu.transport.handlers import build_default_handlers

    rsp = build_default_handlers(client).handle(
        "api/metric", CommandRequest(parameters={k: str(v) for k, v in params.items()})
    )
    assert rsp.success
    return rsp.result


def test_api_metric_rows_exactly_match_injected_counts(tmp_path, vt, client_factory):
    """ISSUE 9 acceptance: known per-resource traffic through a
    SentinelClient; GET /api/metric returns per-second rows whose
    pass/block/rt sums EXACTLY match the injected counts — including
    across one log rotation."""
    log = TL.MetricLog(str(tmp_path), max_segment_bytes=150, max_segments=8)
    c = client_factory(timeline_log=log)
    c.flow_rules.load([FlowRule(resource="tl/r", count=3.0)])
    wall0 = vt.wall_epoch_ms + 1000
    # second 1: 5 attempts -> 3 pass / 2 block
    c.check_batch(["tl/r"] * 5, inbound=True)
    vt.advance(1100)
    # second 2: 4 attempts -> 3 pass / 1 block, plus completions with RT
    c.check_batch(["tl/r"] * 4, inbound=True)
    rid = c.registry.resource_id("tl/r")
    c.submit_completion_block(
        np.asarray([rid, rid], np.int32), np.asarray([2.0, 4.0], np.float32)
    )
    c.tick_once()
    vt.advance(1100)
    # second 3: traffic on another resource ticks the flush forward
    c.check_batch(["tl/other"] * 2, inbound=True)
    vt.advance(1100)
    c.check_batch(["tl/other"], inbound=True)

    rows = _api_metric(c, resource="tl/r", start=wall0, end=wall0 + 1999)
    assert [(r["ts"] - vt.wall_epoch_ms, r["pass"], r["block"]) for r in rows] == [
        (1000, 3, 2),
        (2000, 3, 1),
    ]
    sec2 = rows[1]
    assert sec2["success"] == 2 and sec2["rt_sum"] == pytest.approx(6.0)
    assert sec2["rt_min"] == pytest.approx(2.0)
    other = _api_metric(c, resource="tl/other", start=0, end=BIG)
    assert sum(r["pass"] for r in other) == 3
    # unfiltered query returns both resources; range filtering holds
    all_rows = _api_metric(c, start=wall0 + 1000, end=wall0 + 1000)
    assert {r["resource"] for r in all_rows} == {"tl/r"}
    c.stop()  # final flush; reopen the log COLD and re-verify across rotation
    assert len(TL.MetricLog(str(tmp_path)).segments()) > 1, "no rotation happened"
    cold = TL.MetricLog(str(tmp_path), max_segment_bytes=150)
    disk = cold.find("tl/r", 0, BIG)
    assert [(r.pass_count, r.block_count) for r in disk][:2] == [(3, 2), (3, 1)]
    cold.close()


def test_api_metric_max_rows_keeps_newest(vt, client_factory):
    c = client_factory()
    c.registry.resource_id("cap/r")
    for _ in range(4):
        c.check_batch(["cap/r"], inbound=True)
        vt.advance(1100)
    c.check_batch(["cap/r"], inbound=True)
    rows = _api_metric(c, resource="cap/r", start=0, end=BIG, maxRows=2)
    assert len(rows) == 2
    all_rows = _api_metric(c, resource="cap/r", start=0, end=BIG)
    assert rows == all_rows[-2:]  # the cap keeps the newest edge


def test_fleet_timeline_local_collisions_and_self_dedupe(vt, client_factory):
    """Two same-app recorders both contribute (suffixed, not replaced);
    a target serving a local recorder's own rows is dropped as a
    self-scrape duplicate."""
    from sentinel_tpu.obs.fleet import fleet_timeline

    a = client_factory(app_name="same")
    b = client_factory(app_name="same")
    a.flow_rules.load([FlowRule(resource="fl/r", count=100.0)])
    a.check_batch(["fl/r"] * 3, inbound=True)
    b.check_batch(["fl/r"] * 2, inbound=True)
    vt.advance(1100)
    a.check_batch(["fl/r"], inbound=True)
    b.check_batch(["fl/r"], inbound=True)
    import json

    self_rows = json.dumps(
        [r.to_dict() for r in a.timeline.find("fl/r", 0, BIG)]
    )
    merged = fleet_timeline(
        resource="fl/r", targets=["self:1"], fetch=lambda url: self_rows
    )
    by_sec = {m["ts"]: m for m in merged}
    first = by_sec[vt.wall_epoch_ms + 1000]
    # both local recorders merged (3 + 2), the self-scrape target dropped
    assert first["pass"] == 5
    assert set(first["sources"]) == {"local/same", "local/same#2"}


def test_wire_bytes_move_on_timeline_path(client_factory):
    rx = REGISTRY.get(
        "sentinel_wire_bytes_total", {"path": "timeline", "direction": "rx"}
    )
    rx0 = rx.value
    c = client_factory()
    c.registry.resource_id("tlw/r")
    c.check_batch(["tlw/r"] * 4)
    assert rx.value >= rx0 + E.timeline_k(c.cfg) * E.TL_COLS * 4


# ---------------------------------------------------------------------------
# fleet merge
# ---------------------------------------------------------------------------


def test_merge_timelines_aligns_sums_and_keeps_provenance():
    a = [
        {"ts": 1000, "resource": "r", "pass": 3, "block": 1, "success": 2,
         "exception": 0, "rt_sum": 4.0, "rt_min": 2.0, "concurrency": 1},
        {"ts": 2000, "resource": "r", "pass": 1, "block": 0, "success": 0,
         "exception": 0, "rt_sum": 0.0, "rt_min": 0.0, "concurrency": 0},
    ]
    b = [
        {"ts": 1000, "resource": "r", "pass": 2, "block": 2, "success": 1,
         "exception": 1, "rt_sum": 1.0, "rt_min": 0.5, "concurrency": 3},
        {"ts": 1000, "resource": "q", "pass": 7, "block": 0, "success": 0,
         "exception": 0, "rt_sum": 0.0, "rt_min": 0.0, "concurrency": 0},
    ]
    merged = merge_timelines({"shard-a": a, "shard-b": b})
    assert [(m["ts"], m["resource"]) for m in merged] == [
        (1000, "q"), (1000, "r"), (2000, "r"),
    ]
    r1 = merged[1]
    assert (r1["pass"], r1["block"], r1["success"], r1["exception"]) == (5, 3, 3, 1)
    assert r1["rt_sum"] == pytest.approx(5.0)
    assert r1["rt_min"] == pytest.approx(0.5)  # smallest NONZERO min
    assert r1["concurrency"] == 4
    assert r1["sources"] == {"shard-a": 4.0, "shard-b": 4.0}
    # a source with zero completions must not zero the fleet rt_min
    assert merged[2]["rt_min"] == 0.0
    assert merged[0]["sources"] == {"shard-b": 7.0}


def test_fleet_merged_timeline_over_live_4shard_fleet(vt, client_factory):
    """ISSUE 9 acceptance: a live 4-shard ShardFleet's per-shard
    timelines merge into one fleet timeline with per-shard provenance —
    each cluster flow's rows attribute to exactly its ring owner."""
    from sentinel_tpu.cluster.shard import ShardFleet

    f = ShardFleet(
        client_factory,
        n_shards=4,
        retry_interval_s=300.0,
        timeout_ms=5000,
        reconnect_interval_s=0.0,
        lease_slack=0.0,  # no standing leases: window counts == requests
    )
    fids = (101, 202, 303, 404)
    try:
        f.load_flow_rules(
            "default",
            [
                FlowRule(
                    resource=f"res-{fid}",
                    count=1000.0,
                    cluster_mode=True,
                    cluster_flow_id=fid,
                    cluster_threshold_type=1,
                )
                for fid in fids
            ],
        )
        from sentinel_tpu.cluster import constants as CC

        for fid in fids:  # second 1: 3 requests per flow
            for _ in range(3):
                assert f.client.request_token(fid).status == CC.STATUS_OK
        vt.advance(1100)
        for fid in fids:  # second 2: 2 requests per flow
            for _ in range(2):
                f.client.request_token(fid)
        vt.advance(1100)
        for fid in fids:  # second 3: tick each owner past the boundary
            f.client.request_token(fid)

        per_shard = {
            name: [r.to_dict() for r in svc.client.timeline.find(None, 0, BIG)]
            for name, svc in f.services.items()
        }
        merged = merge_timelines(per_shard)
        shard_names = set(f.services)
        for fid in fids:
            rows = [m for m in merged if m["resource"] == f"$cluster/flow/{fid}"]
            assert [r["pass"] for r in rows] == [3, 2, 1]
            # provenance: every row of one flow names exactly one live
            # shard — its consistent-hash ring owner
            owners = {src for r in rows for src in r["sources"]}
            assert len(owners) == 1 and owners <= shard_names
            for r in rows:
                assert sum(r["sources"].values()) == r["pass"] + r["block"]
    finally:
        f.stop()


def test_repository_and_fetcher_store_timelines_per_machine():
    from sentinel_tpu.dashboard.discovery import AppManagement, MachineInfo
    from sentinel_tpu.dashboard.metric_fetcher import MetricFetcher
    from sentinel_tpu.dashboard.repository import InMemoryMetricsRepository

    rowset = {
        "1.1.1.1:1": [{"ts": 1000, "resource": "r", "pass": 2, "block": 1,
                       "success": 0, "exception": 0, "rt_sum": 0.0,
                       "rt_min": 0.0, "concurrency": 0}],
        "2.2.2.2:1": [{"ts": 1000, "resource": "r", "pass": 5, "block": 0,
                       "success": 0, "exception": 0, "rt_sum": 0.0,
                       "rt_min": 0.0, "concurrency": 0}],
    }

    class _Api:
        def fetch_timeline(self, ip, port, resource=None, start_ms=0, end_ms=None):
            if ip == "3.3.3.3":
                raise OSError("down")
            return rowset[f"{ip}:{port}"]

    d = AppManagement()
    for ip in ("1.1.1.1", "2.2.2.2", "3.3.3.3"):
        d.register(MachineInfo(app="app", ip=ip, port=1))
    repo = InMemoryMetricsRepository()
    fetcher = MetricFetcher(d, repo, api=_Api())
    saved = fetcher.fetch_timelines(resource="r")
    assert saved == 2 and fetcher.fetch_fail == 1
    assert repo.timeline_machines("app") == ["1.1.1.1:1", "2.2.2.2:1"]
    merged = repo.query_timeline("app", "r", 0, BIG)
    assert len(merged) == 1
    assert merged[0]["pass"] == 7 and merged[0]["block"] == 1
    assert merged[0]["sources"] == {"1.1.1.1:1": 3.0, "2.2.2.2:1": 5.0}


# ---------------------------------------------------------------------------
# flight-recorder enrichment
# ---------------------------------------------------------------------------


def test_flight_bundle_timeline_section_and_postmortem_table(
    tmp_path, vt, client_factory, capsys
):
    import json

    from sentinel_tpu.obs.flight import FLIGHT
    from sentinel_tpu.obs.__main__ import _print_postmortem

    c = client_factory()
    c.flow_rules.load([FlowRule(resource="fb/r", count=2.0)])
    c.check_batch(["fb/r"] * 4, inbound=True)
    vt.advance(1100)
    c.check_batch(["fb/r"], inbound=True)
    bundle = FLIGHT.dump_bundle(reason="test")
    tl = bundle["providers"]["timeline"]
    assert tl["window_s"] == 30
    assert "fb/r" in tl["resources"]
    hot = [r for r in tl["rows"] if r["resource"] == "fb/r"]
    assert sum(r["pass"] for r in hot) == 3
    assert sum(r["block"] for r in hot) == 2
    # --postmortem renders the section as a per-second table
    path = tmp_path / "bundle.json"
    path.write_text(json.dumps(bundle))
    _print_postmortem(str(path))
    out = capsys.readouterr().out
    assert "provider [timeline]" in out
    assert "fb/r" in out and "resource" in out


def test_recorder_closes_and_deregisters(client_factory):
    from sentinel_tpu.obs.timeline import live_recorders

    c = client_factory()
    c.registry.resource_id("lr/r")
    rec = c.timeline
    assert rec in live_recorders()
    c.stop()
    assert rec not in live_recorders()
    assert c.timeline is None
