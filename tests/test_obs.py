"""sentinel_tpu.obs — span tracer ring, metrics registry, exposition, CLI.

Covers the ISSUE-3 contracts: ring wraparound and concurrent writers,
power-of-two histogram bucket boundaries + merge, Prometheus exposition
(golden text), the tracer-disabled overhead guard, the extension
error-counter satellite, and the ``python -m sentinel_tpu.obs --summary``
self-capture printing p50/p99 for all six tick stages.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from sentinel_tpu import obs
from sentinel_tpu.obs.registry import Counter, Gauge, Histogram, MetricRegistry
from sentinel_tpu.obs.trace import SpanTracer


@pytest.fixture(autouse=True)
def _tracer_state():
    """Never leak an enabled/poisoned global tracer into other tests."""
    was = obs.TRACER.enabled
    yield
    obs.TRACER.disable()
    obs.TRACER.reset()
    if was:  # pragma: no cover — the suite never leaves it on
        obs.TRACER.enable()


# ---------------------------------------------------------------------------
# span tracer ring
# ---------------------------------------------------------------------------


def test_ring_records_in_order_and_snapshot_is_sorted():
    tr = SpanTracer(capacity=64)
    tr.enable()
    for i in range(10):
        tr.record(f"s{i}", t0_ns=1000 + i, dur_ns=5, trace=7)
    snap = tr.snapshot()
    assert [s["name"] for s in snap] == [f"s{i}" for i in range(10)]
    assert all(s["trace"] == 7 for s in snap)
    assert tr.recorded_total == 10


def test_ring_wraparound_keeps_newest():
    tr = SpanTracer(capacity=8)  # already a power of two
    tr.enable()
    for i in range(20):
        tr.record("s", t0_ns=i, dur_ns=1)
    snap = tr.snapshot()
    assert len(snap) == 8
    # the survivors are exactly the last capacity records, oldest first
    assert [s["t0_ns"] for s in snap] == list(range(12, 20))
    assert tr.recorded_total == 20


def test_capacity_rounds_up_to_power_of_two():
    assert SpanTracer(capacity=100).capacity == 128
    assert SpanTracer(capacity=1).capacity == 2


def test_concurrent_writers_land_on_distinct_slots():
    tr = SpanTracer(capacity=4096)
    tr.enable()
    n_threads, per = 8, 200

    def work(k):
        for i in range(per):
            tr.record(f"t{k}", t0_ns=i, dur_ns=1)

    ts = [threading.Thread(target=work, args=(k,)) for k in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = tr.snapshot()
    assert len(snap) == n_threads * per  # nothing lost below capacity
    seqs = [s["seq"] for s in snap]
    assert len(set(seqs)) == len(seqs)  # no slot ever shared a sequence
    by_name = {}
    for s in snap:
        by_name.setdefault(s["name"], 0)
        by_name[s["name"]] += 1
    assert all(v == per for v in by_name.values())


def test_span_context_manager_and_disabled_noop():
    tr = SpanTracer(capacity=16)
    with tr.span("off"):  # disabled: shared no-op, nothing recorded
        pass
    assert tr.snapshot() == []
    tr.enable()
    with tr.span("on", trace=3, stage="x"):
        pass
    (s,) = tr.snapshot()
    assert s["name"] == "on" and s["trace"] == 3 and s["attrs"] == {"stage": "x"}
    assert s["dur_ns"] >= 0


def test_begin_end_crosses_threads():
    tr = SpanTracer(capacity=16)
    tr.enable()
    h = tr.begin("xthread", trace=9, chunk=1)
    done = threading.Event()

    def finisher():
        tr.end(h, ok=True)
        done.set()

    threading.Thread(target=finisher).start()
    assert done.wait(5.0)
    (s,) = tr.snapshot()
    assert s["name"] == "xthread" and s["trace"] == 9
    assert s["attrs"] == {"chunk": 1, "ok": True}
    # disabled begin returns None and end(None) is a no-op
    tr.disable()
    assert tr.begin("nope") is None
    tr.end(None)


def test_chrome_trace_export_shape(tmp_path):
    tr = SpanTracer(capacity=16)
    tr.enable()
    tr.record("a", t0_ns=2_000, dur_ns=1_000, trace=1, attrs={"k": "v"})
    doc = tr.chrome_trace()
    (ev,) = doc["traceEvents"]
    assert ev["ph"] == "X"
    assert ev["ts"] == 2.0 and ev["dur"] == 1.0  # microseconds
    assert ev["args"]["k"] == "v" and ev["args"]["trace"] == 1
    p = tmp_path / "trace.json"
    tr.dump(str(p))
    spans = obs.load_spans(str(p))
    assert spans[0]["name"] == "a" and spans[0]["dur_ns"] == 1_000.0


def test_summarize_percentiles():
    spans = [
        {"name": "tick.device", "dur_ns": d * 1e6, "t0_ns": 0, "tid": 0}
        for d in (1.0, 2.0, 3.0, 4.0, 100.0)
    ] + [{"name": "other", "dur_ns": 5e6, "t0_ns": 0, "tid": 0}]
    summ = obs.summarize(spans, prefix="tick.")
    assert list(summ) == ["tick.device"]
    s = summ["tick.device"]
    assert s["count"] == 5
    assert s["p50_ms"] == 3.0
    assert 4.0 < s["p99_ms"] <= 100.0


def test_disabled_overhead_guard():
    """The disabled fast path is a single flag check: 20k t0() probes must
    cost microseconds each at worst — no clock read, no allocation."""
    from sentinel_tpu.obs import trace as OT
    from sentinel_tpu.utils.time_source import mono_s

    assert not OT.TRACER.enabled
    n = 20_000
    t_start = mono_s()
    acc = 0
    for _ in range(n):
        t = OT.t0()
        if t:  # pragma: no cover — disabled: never taken
            acc += t
    elapsed = mono_s() - t_start
    assert acc == 0
    # ~100 ns/call in CPython; 5 µs/call is a 50x safety margin for CI
    assert elapsed / n < 5e-6, f"disabled-path cost {elapsed / n * 1e9:.0f} ns/call"


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


def test_histogram_power_of_two_bucket_boundaries():
    h = Histogram("h", start=1.0, buckets=4)  # bounds 1, 2, 4, 8, +Inf
    assert list(h.bounds) == [1.0, 2.0, 4.0, 8.0]
    for v, want in [
        (0.1, 0),  # below start -> first bucket
        (1.0, 0),  # boundary is INCLUSIVE (le semantics)
        (1.0001, 1),
        (2.0, 1),
        (2.0001, 2),
        (4.0, 2),
        (8.0, 3),
        (8.0001, 4),  # overflow -> +Inf slot
        (1e9, 4),
    ]:
        assert h._index(v) == want, (v, want, h._index(v))
    h.observe(1.5)
    h.observe(3.0)
    h.observe(100.0)
    assert h.count == 3 and h.sum == pytest.approx(104.5)


def test_histogram_merge_and_quantile():
    a = Histogram("h", start=1.0, buckets=8)
    b = Histogram("h", start=1.0, buckets=8)
    for v in (1.0, 1.0, 2.0, 4.0):
        a.observe(v)
    for v in (64.0, 128.0):
        b.observe(v)
    a.merge(b)
    assert a.count == 6
    assert a.quantile(0.5) == 2.0  # 3rd of 6 samples sits in the le=2 bucket
    assert a.quantile(1.0) == 128.0
    c = Histogram("h", start=2.0, buckets=8)
    with pytest.raises(ValueError):
        a.merge(c)


def test_histogram_quantile_empty_is_zero():
    assert Histogram("h").quantile(0.99) == 0.0


# ---------------------------------------------------------------------------
# registry + Prometheus exposition
# ---------------------------------------------------------------------------


def test_registry_get_or_create_identity_and_type_conflict():
    reg = MetricRegistry()
    c1 = reg.counter("x_total", "help one")
    c2 = reg.counter("x_total")
    assert c1 is c2
    assert reg.counter("x_total", labels={"a": "1"}) is not c1  # new series
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    assert isinstance(reg.gauge("g"), Gauge)
    assert reg.get("x_total") is c1
    assert reg.get("missing") is None


def test_prometheus_exposition_golden():
    reg = MetricRegistry()
    reg.counter("demo_requests_total", "requests served").inc(3)
    reg.counter("demo_requests_total", labels={"kind": "bulk"}).inc(2)
    reg.gauge("demo_depth", "queue depth").set(1.5)
    h = reg.histogram("demo_ms", "latency", start=1.0, buckets=3)
    h.observe(0.5)
    h.observe(3.0)
    h.observe(99.0)
    golden = "\n".join(
        [
            "# HELP demo_depth queue depth",
            "# TYPE demo_depth gauge",
            "demo_depth 1.5",
            "# HELP demo_ms latency",
            "# TYPE demo_ms histogram",
            'demo_ms_bucket{le="1"} 1',
            'demo_ms_bucket{le="2"} 1',
            'demo_ms_bucket{le="4"} 2',
            'demo_ms_bucket{le="+Inf"} 3',
            "demo_ms_sum 102.5",
            "demo_ms_count 3",
            "# HELP demo_requests_total requests served",
            "# TYPE demo_requests_total counter",
            "demo_requests_total 3",
            'demo_requests_total{kind="bulk"} 2',
            "",
        ]
    )
    assert reg.exposition() == golden


def test_exposition_lines_are_well_formed():
    """Every non-comment line of the GLOBAL registry (fully populated by
    the instrumented modules' imports) parses as `name{labels} value`."""
    import re

    import sentinel_tpu.runtime.client  # noqa: F401 — registers tick metrics

    text = obs.REGISTRY.exposition()
    assert "sentinel_tick_device_ms" in text
    assert "sentinel_pipeline_occupancy" in text
    pat = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9][0-9a-zA-Z+.e-]*$"
    )
    for line in text.strip().split("\n"):
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE "))
        else:
            assert pat.match(line), line


def test_label_values_are_escaped():
    reg = MetricRegistry()
    reg.counter("esc_total", labels={"k": 'a"b\\c\nd'}).inc()
    line = [
        l for l in reg.exposition().splitlines() if not l.startswith("#")
    ][0]
    assert line == 'esc_total{k="a\\"b\\\\c\\nd"} 1'


def test_gauges_zero_when_pipeline_drains(client_factory):
    """Occupancy/resolver-queue gauges must not stay stale after the loop
    goes idle (scrapes happen while idle)."""
    import sentinel_tpu as st
    from sentinel_tpu.runtime import client as RC

    obs.enable()
    try:
        c = client_factory()
        c.flow_rules.load([st.FlowRule(resource="g-res", count=100)])
        with c.entry("g-res"):
            pass
    finally:
        obs.disable()
    assert RC.OBS.get("sentinel_pipeline_occupancy").value == 0
    assert RC.OBS.get("sentinel_resolver_queue_depth").value == 0


def test_registry_snapshot_shape():
    reg = MetricRegistry()
    reg.counter("c_total").inc(4)
    h = reg.histogram("h_ms", start=1.0, buckets=4)
    h.observe(3.0)
    snap = reg.snapshot()
    assert snap["c_total"] == 4
    assert snap["h_ms"]["count"] == 1 and snap["h_ms"]["p50"] == 4.0


# ---------------------------------------------------------------------------
# extension error counting satellite
# ---------------------------------------------------------------------------


def test_safe_dispatch_counts_errors_and_rate_limits_log(monkeypatch):
    from sentinel_tpu.metrics import extension as MEXT

    class Boom(MEXT.MetricExtension):
        def on_pass(self, *a, **kw):
            raise RuntimeError("boom")

    logged = []

    class _FakeLog:
        def exception(self, msg, *args):
            logged.append(msg % args if args else msg)

    import sentinel_tpu.utils.record_log as RL

    monkeypatch.setattr(RL, "record_log", lambda: _FakeLog())
    clock = {"t": 100.0}
    monkeypatch.setattr(MEXT, "mono_s", lambda: clock["t"])
    MEXT._warn_state.clear()

    ext = Boom()
    MEXT.register_extension(ext)
    try:
        before = MEXT._C_EXT_ERRORS.value
        for _ in range(5):
            MEXT.safe_dispatch("on_pass", "res", 1, "")
        assert MEXT._C_EXT_ERRORS.value == before + 5  # every failure counted
        assert len(logged) == 1  # ...but only one log line inside the window
        clock["t"] += MEXT._WARN_INTERVAL_S + 1
        MEXT.safe_dispatch("on_pass", "res", 1, "")
        assert MEXT._C_EXT_ERRORS.value == before + 6
        assert len(logged) == 2
        assert "+4 more" in logged[1]  # the suppressed count surfaces
    finally:
        MEXT.unregister_extension(ext)


# ---------------------------------------------------------------------------
# end-to-end: instrumented client + CLI summary
# ---------------------------------------------------------------------------

#: the six pipelined tick stages the ISSUE-3 acceptance names
_SIX = (
    "tick.assemble",
    "tick.presort",
    "tick.dispatch",
    "tick.device",
    "tick.readback",
    "tick.resolve",
)


def test_cli_self_capture_prints_all_six_stages(capsys):
    """`python -m sentinel_tpu.obs --summary` (self-capture path): a
    SentinelClient run with pipeline_depth>0 yields p50/p99 for all six
    tick stages."""
    from sentinel_tpu.obs.__main__ import main

    obs.TRACER.reset()
    assert main(["--summary", "--blocks", "3"]) == 0
    out = capsys.readouterr().out
    for name in _SIX:
        assert name in out, f"{name} missing from CLI summary:\n{out}"
    assert "p50 ms" in out and "p99 ms" in out
    assert "absent from this trace" not in out


def test_client_run_populates_stage_histograms_and_gauges(client_factory):
    """Tick-stage histograms and the occupancy gauge fill from a traced
    sync-mode client run (the /metrics acceptance surface)."""
    import sentinel_tpu as st
    from sentinel_tpu.runtime import client as RC

    before = {n: RC.OBS.get(f"sentinel_tick_{n}_ms").count for n in
              ("assemble", "dispatch", "device", "readback", "resolve")}
    obs.enable()
    try:
        c = client_factory()
        c.flow_rules.load([st.FlowRule(resource="obs-res", count=100)])
        for _ in range(3):
            with c.entry("obs-res"):
                pass
    finally:
        obs.disable()
    for n, b in before.items():
        assert RC.OBS.get(f"sentinel_tick_{n}_ms").count > b, n
    # tick spans carry matching trace ids across stages
    spans = [s for s in obs.TRACER.snapshot() if s["name"].startswith("tick.")]
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace"], set()).add(s["name"])
    assert any(
        {"tick.assemble", "tick.dispatch", "tick.device", "tick.resolve"} <= v
        for v in by_trace.values()
    )


def test_chrome_roundtrip_through_summarize(tmp_path):
    obs.TRACER.reset()
    obs.enable()
    try:
        with obs.span("tick.device", trace=1):
            pass
    finally:
        obs.disable()
    p = tmp_path / "t.json"
    obs.TRACER.dump(str(p))
    spans = obs.load_spans(str(p))
    assert "tick.device" in obs.summarize(spans)
    # files that are neither format are rejected
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError):
        obs.load_spans(str(bad))
