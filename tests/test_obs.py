"""sentinel_tpu.obs — span tracer ring, metrics registry, exposition, CLI.

Covers the ISSUE-3 contracts: ring wraparound and concurrent writers,
power-of-two histogram bucket boundaries + merge, Prometheus exposition
(golden text), the tracer-disabled overhead guard, the extension
error-counter satellite, and the ``python -m sentinel_tpu.obs --summary``
self-capture printing p50/p99 for all six tick stages.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from sentinel_tpu import obs
from sentinel_tpu.obs.registry import Counter, Gauge, Histogram, MetricRegistry
from sentinel_tpu.obs.trace import SpanTracer


@pytest.fixture(autouse=True)
def _tracer_state():
    """Never leak an enabled/poisoned global tracer into other tests."""
    was = obs.TRACER.enabled
    yield
    obs.TRACER.disable()
    obs.TRACER.reset()
    if was:  # pragma: no cover — the suite never leaves it on
        obs.TRACER.enable()


# ---------------------------------------------------------------------------
# span tracer ring
# ---------------------------------------------------------------------------


def test_ring_records_in_order_and_snapshot_is_sorted():
    tr = SpanTracer(capacity=64)
    tr.enable()
    for i in range(10):
        tr.record(f"s{i}", t0_ns=1000 + i, dur_ns=5, trace=7)
    snap = tr.snapshot()
    assert [s["name"] for s in snap] == [f"s{i}" for i in range(10)]
    assert all(s["trace"] == 7 for s in snap)
    assert tr.recorded_total == 10


def test_ring_wraparound_keeps_newest():
    tr = SpanTracer(capacity=8)  # already a power of two
    tr.enable()
    for i in range(20):
        tr.record("s", t0_ns=i, dur_ns=1)
    snap = tr.snapshot()
    assert len(snap) == 8
    # the survivors are exactly the last capacity records, oldest first
    assert [s["t0_ns"] for s in snap] == list(range(12, 20))
    assert tr.recorded_total == 20


def test_capacity_rounds_up_to_power_of_two():
    assert SpanTracer(capacity=100).capacity == 128
    assert SpanTracer(capacity=1).capacity == 2


def test_concurrent_writers_land_on_distinct_slots():
    tr = SpanTracer(capacity=4096)
    tr.enable()
    n_threads, per = 8, 200

    def work(k):
        for i in range(per):
            tr.record(f"t{k}", t0_ns=i, dur_ns=1)

    ts = [threading.Thread(target=work, args=(k,)) for k in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = tr.snapshot()
    assert len(snap) == n_threads * per  # nothing lost below capacity
    seqs = [s["seq"] for s in snap]
    assert len(set(seqs)) == len(seqs)  # no slot ever shared a sequence
    by_name = {}
    for s in snap:
        by_name.setdefault(s["name"], 0)
        by_name[s["name"]] += 1
    assert all(v == per for v in by_name.values())


def test_span_context_manager_and_disabled_noop():
    tr = SpanTracer(capacity=16)
    with tr.span("off"):  # disabled: shared no-op, nothing recorded
        pass
    assert tr.snapshot() == []
    tr.enable()
    with tr.span("on", trace=3, stage="x"):
        pass
    (s,) = tr.snapshot()
    assert s["name"] == "on" and s["trace"] == 3 and s["attrs"] == {"stage": "x"}
    assert s["dur_ns"] >= 0


def test_begin_end_crosses_threads():
    tr = SpanTracer(capacity=16)
    tr.enable()
    h = tr.begin("xthread", trace=9, chunk=1)
    done = threading.Event()

    def finisher():
        tr.end(h, ok=True)
        done.set()

    threading.Thread(target=finisher).start()
    assert done.wait(5.0)
    (s,) = tr.snapshot()
    assert s["name"] == "xthread" and s["trace"] == 9
    assert s["attrs"] == {"chunk": 1, "ok": True}
    # disabled begin returns None and end(None) is a no-op
    tr.disable()
    assert tr.begin("nope") is None
    tr.end(None)


def test_chrome_trace_export_shape(tmp_path):
    tr = SpanTracer(capacity=16)
    tr.enable()
    tr.record("a", t0_ns=2_000, dur_ns=1_000, trace=1, attrs={"k": "v"})
    doc = tr.chrome_trace()
    (ev,) = doc["traceEvents"]
    assert ev["ph"] == "X"
    assert ev["ts"] == 2.0 and ev["dur"] == 1.0  # microseconds
    assert ev["args"]["k"] == "v" and ev["args"]["trace"] == 1
    p = tmp_path / "trace.json"
    tr.dump(str(p))
    spans = obs.load_spans(str(p))
    assert spans[0]["name"] == "a" and spans[0]["dur_ns"] == 1_000.0


def test_summarize_percentiles():
    spans = [
        {"name": "tick.device", "dur_ns": d * 1e6, "t0_ns": 0, "tid": 0}
        for d in (1.0, 2.0, 3.0, 4.0, 100.0)
    ] + [{"name": "other", "dur_ns": 5e6, "t0_ns": 0, "tid": 0}]
    summ = obs.summarize(spans, prefix="tick.")
    assert list(summ) == ["tick.device"]
    s = summ["tick.device"]
    assert s["count"] == 5
    assert s["p50_ms"] == 3.0
    assert 4.0 < s["p99_ms"] <= 100.0


def test_disabled_overhead_guard():
    """The disabled fast path is a single flag check: 20k t0() probes must
    cost microseconds each at worst — no clock read, no allocation."""
    from sentinel_tpu.obs import trace as OT
    from sentinel_tpu.utils.time_source import mono_s

    assert not OT.TRACER.enabled
    n = 20_000
    t_start = mono_s()
    acc = 0
    for _ in range(n):
        t = OT.t0()
        if t:  # pragma: no cover — disabled: never taken
            acc += t
    elapsed = mono_s() - t_start
    assert acc == 0
    # ~100 ns/call in CPython; 5 µs/call is a 50x safety margin for CI
    assert elapsed / n < 5e-6, f"disabled-path cost {elapsed / n * 1e9:.0f} ns/call"


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


def test_histogram_power_of_two_bucket_boundaries():
    h = Histogram("h", start=1.0, buckets=4)  # bounds 1, 2, 4, 8, +Inf
    assert list(h.bounds) == [1.0, 2.0, 4.0, 8.0]
    for v, want in [
        (0.1, 0),  # below start -> first bucket
        (1.0, 0),  # boundary is INCLUSIVE (le semantics)
        (1.0001, 1),
        (2.0, 1),
        (2.0001, 2),
        (4.0, 2),
        (8.0, 3),
        (8.0001, 4),  # overflow -> +Inf slot
        (1e9, 4),
    ]:
        assert h._index(v) == want, (v, want, h._index(v))
    h.observe(1.5)
    h.observe(3.0)
    h.observe(100.0)
    assert h.count == 3 and h.sum == pytest.approx(104.5)


def test_histogram_merge_and_quantile():
    a = Histogram("h", start=1.0, buckets=8)
    b = Histogram("h", start=1.0, buckets=8)
    for v in (1.0, 1.0, 2.0, 4.0):
        a.observe(v)
    for v in (64.0, 128.0):
        b.observe(v)
    a.merge(b)
    assert a.count == 6
    assert a.quantile(0.5) == 2.0  # 3rd of 6 samples sits in the le=2 bucket
    assert a.quantile(1.0) == 128.0
    c = Histogram("h", start=2.0, buckets=8)
    with pytest.raises(ValueError):
        a.merge(c)


def test_histogram_quantile_empty_is_zero():
    assert Histogram("h").quantile(0.99) == 0.0


# ---------------------------------------------------------------------------
# registry + Prometheus exposition
# ---------------------------------------------------------------------------


def test_registry_get_or_create_identity_and_type_conflict():
    reg = MetricRegistry()
    c1 = reg.counter("x_total", "help one")
    c2 = reg.counter("x_total")
    assert c1 is c2
    assert reg.counter("x_total", labels={"a": "1"}) is not c1  # new series
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    assert isinstance(reg.gauge("g"), Gauge)
    assert reg.get("x_total") is c1
    assert reg.get("missing") is None


def test_prometheus_exposition_golden():
    reg = MetricRegistry()
    reg.counter("demo_requests_total", "requests served").inc(3)
    reg.counter("demo_requests_total", labels={"kind": "bulk"}).inc(2)
    reg.gauge("demo_depth", "queue depth").set(1.5)
    h = reg.histogram("demo_ms", "latency", start=1.0, buckets=3)
    h.observe(0.5)
    h.observe(3.0)
    h.observe(99.0)
    golden = "\n".join(
        [
            "# HELP demo_depth queue depth",
            "# TYPE demo_depth gauge",
            "demo_depth 1.5",
            "# HELP demo_ms latency",
            "# TYPE demo_ms histogram",
            'demo_ms_bucket{le="1"} 1',
            'demo_ms_bucket{le="2"} 1',
            'demo_ms_bucket{le="4"} 2',
            'demo_ms_bucket{le="+Inf"} 3',
            "demo_ms_sum 102.5",
            "demo_ms_count 3",
            "# HELP demo_requests_total requests served",
            "# TYPE demo_requests_total counter",
            "demo_requests_total 3",
            'demo_requests_total{kind="bulk"} 2',
            "",
        ]
    )
    assert reg.exposition() == golden


def test_exposition_lines_are_well_formed():
    """Every non-comment line of the GLOBAL registry (fully populated by
    the instrumented modules' imports) parses as `name{labels} value`."""
    import re

    import sentinel_tpu.runtime.client  # noqa: F401 — registers tick metrics

    text = obs.REGISTRY.exposition()
    assert "sentinel_tick_device_ms" in text
    assert "sentinel_pipeline_occupancy" in text
    pat = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9][0-9a-zA-Z+.e-]*$"
    )
    for line in text.strip().split("\n"):
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ", "# EXEMPLAR "))
        else:
            assert pat.match(line), line


def test_label_values_are_escaped():
    reg = MetricRegistry()
    reg.counter("esc_total", labels={"k": 'a"b\\c\nd'}).inc()
    line = [
        l for l in reg.exposition().splitlines() if not l.startswith("#")
    ][0]
    assert line == 'esc_total{k="a\\"b\\\\c\\nd"} 1'


def test_gauges_zero_when_pipeline_drains(client_factory):
    """Occupancy/resolver-queue gauges must not stay stale after the loop
    goes idle (scrapes happen while idle)."""
    import sentinel_tpu as st
    from sentinel_tpu.runtime import client as RC

    obs.enable()
    try:
        c = client_factory()
        c.flow_rules.load([st.FlowRule(resource="g-res", count=100)])
        with c.entry("g-res"):
            pass
    finally:
        obs.disable()
    assert RC.OBS.get("sentinel_pipeline_occupancy").value == 0
    assert RC.OBS.get("sentinel_resolver_queue_depth").value == 0


def test_registry_snapshot_shape():
    reg = MetricRegistry()
    reg.counter("c_total").inc(4)
    h = reg.histogram("h_ms", start=1.0, buckets=4)
    h.observe(3.0)
    snap = reg.snapshot()
    assert snap["c_total"] == 4
    assert snap["h_ms"]["count"] == 1 and snap["h_ms"]["p50"] == 4.0


# ---------------------------------------------------------------------------
# extension error counting satellite
# ---------------------------------------------------------------------------


def test_safe_dispatch_counts_errors_and_rate_limits_log(monkeypatch):
    from sentinel_tpu.metrics import extension as MEXT

    class Boom(MEXT.MetricExtension):
        def on_pass(self, *a, **kw):
            raise RuntimeError("boom")

    logged = []

    class _FakeLog:
        def exception(self, msg, *args):
            logged.append(msg % args if args else msg)

    import sentinel_tpu.utils.record_log as RL

    monkeypatch.setattr(RL, "record_log", lambda: _FakeLog())
    clock = {"t": 100.0}
    monkeypatch.setattr(MEXT, "mono_s", lambda: clock["t"])
    MEXT._warn_state.clear()

    ext = Boom()
    MEXT.register_extension(ext)
    try:
        before = MEXT._C_EXT_ERRORS.value
        for _ in range(5):
            MEXT.safe_dispatch("on_pass", "res", 1, "")
        assert MEXT._C_EXT_ERRORS.value == before + 5  # every failure counted
        assert len(logged) == 1  # ...but only one log line inside the window
        clock["t"] += MEXT._WARN_INTERVAL_S + 1
        MEXT.safe_dispatch("on_pass", "res", 1, "")
        assert MEXT._C_EXT_ERRORS.value == before + 6
        assert len(logged) == 2
        assert "+4 more" in logged[1]  # the suppressed count surfaces
    finally:
        MEXT.unregister_extension(ext)


# ---------------------------------------------------------------------------
# end-to-end: instrumented client + CLI summary
# ---------------------------------------------------------------------------

#: the six pipelined tick stages the ISSUE-3 acceptance names
_SIX = (
    "tick.assemble",
    "tick.presort",
    "tick.dispatch",
    "tick.device",
    "tick.readback",
    "tick.resolve",
)


def test_cli_self_capture_prints_all_six_stages(capsys):
    """`python -m sentinel_tpu.obs --summary` (self-capture path): a
    SentinelClient run with pipeline_depth>0 yields p50/p99 for all six
    tick stages."""
    from sentinel_tpu.obs.__main__ import main

    obs.TRACER.reset()
    assert main(["--summary", "--blocks", "3"]) == 0
    out = capsys.readouterr().out
    for name in _SIX:
        assert name in out, f"{name} missing from CLI summary:\n{out}"
    assert "p50 ms" in out and "p99 ms" in out
    assert "absent from this trace" not in out


def test_client_run_populates_stage_histograms_and_gauges(client_factory):
    """Tick-stage histograms and the occupancy gauge fill from a traced
    sync-mode client run (the /metrics acceptance surface)."""
    import sentinel_tpu as st
    from sentinel_tpu.runtime import client as RC

    before = {n: RC.OBS.get(f"sentinel_tick_{n}_ms").count for n in
              ("assemble", "dispatch", "device", "readback", "resolve")}
    obs.enable()
    try:
        c = client_factory()
        c.flow_rules.load([st.FlowRule(resource="obs-res", count=100)])
        for _ in range(3):
            with c.entry("obs-res"):
                pass
    finally:
        obs.disable()
    for n, b in before.items():
        assert RC.OBS.get(f"sentinel_tick_{n}_ms").count > b, n
    # tick spans carry matching trace ids across stages
    spans = [s for s in obs.TRACER.snapshot() if s["name"].startswith("tick.")]
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s["trace"], set()).add(s["name"])
    assert any(
        {"tick.assemble", "tick.dispatch", "tick.device", "tick.resolve"} <= v
        for v in by_trace.values()
    )


def test_wraparound_loss_is_accounted_not_silent():
    """ISSUE-5 satellite: overwrite loss is exposed (spans_dropped_total
    + a registry counter synced on the read side), never silent."""
    from sentinel_tpu.obs.registry import MetricRegistry

    reg = MetricRegistry()
    c = reg.counter("sentinel_trace_spans_dropped_total")
    tr = SpanTracer(capacity=8, drop_counter=c)
    tr.enable()
    for i in range(20):
        tr.record("s", t0_ns=i, dur_ns=1)
    assert tr.spans_dropped_total() == 12  # 20 recorded, 8 retained
    snap = tr.snapshot()  # read side syncs the counter
    assert len(snap) == 8
    assert c.value == 12
    # further records keep the accounting monotonic, no double count
    for i in range(4):
        tr.record("s", t0_ns=100 + i, dur_ns=1)
    tr.snapshot()
    assert tr.spans_dropped_total() == 16 and c.value == 16
    tr.snapshot()
    assert c.value == 16
    # below-capacity tracers never report drops
    small = SpanTracer(capacity=64, drop_counter=reg.counter("other_total"))
    small.enable()
    small.record("x", 0, 1)
    small.snapshot()
    assert small.spans_dropped_total() == 0


def test_global_tracer_drop_counter_registered():
    assert obs.REGISTRY.get("sentinel_trace_spans_dropped_total") is not None


# ---------------------------------------------------------------------------
# distributed trace context
# ---------------------------------------------------------------------------


def test_trace_ctx_adoption_and_ids():
    from sentinel_tpu.obs import trace as OT

    t1, t2 = OT.new_trace_id(), OT.new_trace_id()
    assert t1 != t2 and t1 != 0 and t1 < 2**64
    tr = SpanTracer(capacity=16)
    tr.enable()
    with OT.trace_ctx(t1, 42):
        with tr.span("child"):
            pass
        h = tr.begin("xchild")
    tr.end(h)
    with tr.span("orphan"):
        pass
    by_name = {s["name"]: s for s in tr.snapshot()}
    assert by_name["child"]["trace"] == t1
    assert by_name["child"]["attrs"]["parent"] == 42
    assert by_name["xchild"]["trace"] == t1
    assert by_name["xchild"]["attrs"]["parent"] == 42
    assert by_name["orphan"]["trace"] == 0  # no ambient ctx -> unchanged
    # explicit trace beats ambient; ctx restores on exit
    with OT.trace_ctx(t1, 42):
        with tr.span("explicit", trace=7):
            pass
    assert OT.current_ctx() == (0, 0)
    assert {s["name"]: s for s in tr.snapshot()}["explicit"]["trace"] == 7


def test_maybe_ctx_noop_when_disabled():
    from sentinel_tpu.obs import trace as OT

    assert not OT.TRACER.enabled
    with OT.maybe_ctx(123, 456):
        assert OT.current_ctx() == (0, 0)  # disabled: nothing installed


def test_trace_context_plumbing_disabled_overhead_guard():
    """The wire-trace plumbing's disabled path (maybe_ctx on the server,
    the enabled-flag check before minting ids on the client) stays in
    the same <5 µs/call budget as every other disarmed obs site."""
    from sentinel_tpu.obs import trace as OT
    from sentinel_tpu.utils.time_source import mono_s

    assert not OT.TRACER.enabled
    n = 20_000
    t_start = mono_s()
    for _ in range(n):
        with OT.maybe_ctx(0, 0):
            pass
    elapsed = mono_s() - t_start
    assert elapsed / n < 5e-6, f"maybe_ctx cost {elapsed / n * 1e9:.0f} ns/call"


def test_golden_cross_process_merge_links_rpc_to_decision(tmp_path, capsys):
    """ISSUE-5 acceptance: client + server dumps --merge into ONE chrome
    trace where a cluster.rpc span and the server decision span share a
    trace id and are linked by flow events."""
    from sentinel_tpu.obs import trace as OT
    from sentinel_tpu.obs.__main__ import main, merge_traces

    tid, sid = OT.new_trace_id(), OT.new_span_id()
    # client process: the RPC span carrying its span id on the wire
    cl = SpanTracer(capacity=16)
    cl.enable()
    cl.record("cluster.rpc", t0_ns=1_000_000, dur_ns=900_000, trace=tid,
              attrs={"span_id": sid, "ok": True, "type": 1})
    client_doc = cl.chrome_trace()
    # server process: the decision span that adopted (tid, sid)
    sv = SpanTracer(capacity=16)
    sv.enable()
    with OT.trace_ctx(tid, sid):
        sv.record("token.decision", t0_ns=5_000_000, dur_ns=400_000, trace=tid,
                  attrs={"parent": sid, "flow_id": 101})
    server_doc = sv.chrome_trace()
    for e in server_doc["traceEvents"]:
        e["pid"] = e["pid"] + 1  # distinct process
    a, b = tmp_path / "client.json", tmp_path / "server.json"
    a.write_text(json.dumps(client_doc))
    b.write_text(json.dumps(server_doc))

    doc = merge_traces([str(a), str(b)])
    ev = doc["traceEvents"]
    rpc = [e for e in ev if e.get("name") == "cluster.rpc"]
    dec = [e for e in ev if e.get("name") == "token.decision"]
    assert rpc and dec
    assert rpc[0]["args"]["trace"] == dec[0]["args"]["trace"] == tid
    assert rpc[0]["pid"] != dec[0]["pid"]  # separate lanes survived
    starts = [e for e in ev if e.get("ph") == "s"]
    ends = [e for e in ev if e.get("ph") == "f"]
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["id"] == ends[0]["id"] == sid
    # flow endpoints bind inside their spans' (pid, ts) lanes
    assert starts[0]["pid"] == rpc[0]["pid"] and ends[0]["pid"] == dec[0]["pid"]
    # the CLI writes the same document
    out = tmp_path / "merged.json"
    assert main(["--merge", str(a), str(b), "-o", str(out)]) == 0
    written = json.loads(out.read_text())
    assert written["otherData"]["flow_links"] == 1
    assert "1 flow links" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_journal_ring_and_events():
    from sentinel_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(capacity=8)
    for i in range(12):
        fr.note("k", i=i)
    evs = fr.events()
    assert len(evs) == 8  # bounded: oldest overwritten
    assert [e["fields"]["i"] for e in evs] == list(range(4, 12))
    assert fr.recorded_total() == 12
    assert [e["fields"]["i"] for e in fr.events(last=3)] == [9, 10, 11]
    assert evs[0]["kind"] == "k" and evs[0]["t_ns"] > 0


def test_flight_bundle_contents_and_providers():
    from sentinel_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(capacity=32)
    fr.note("cluster.degrade.enter", cooldown_s=5.0)
    fr.register_provider("good", lambda: {"x": 1})
    fr.register_provider("bad", lambda: 1 / 0)
    b = fr.dump_bundle("unit")
    assert b["kind"] == "sentinel-flight-bundle" and b["reason"] == "unit"
    assert b["journal"][-1]["kind"] == "cluster.degrade.enter"
    assert isinstance(b["metrics"], dict) and "captured_wall_ms" in b
    assert b["providers"]["good"] == {"x": 1}
    assert "ZeroDivisionError" in b["providers"]["bad"]["error"]
    # unregister honors identity
    keeper = lambda: {}  # noqa: E731
    fr.register_provider("good", keeper)
    fr.unregister_provider("good", lambda: {})  # not the registered fn
    assert "good" in fr.dump_bundle("u2")["providers"]
    fr.unregister_provider("good", keeper)
    assert "good" not in fr.dump_bundle("u3")["providers"]


def test_flight_trigger_rate_limit_and_keep_k(tmp_path, monkeypatch):
    from sentinel_tpu.obs.flight import FlightRecorder

    monkeypatch.setenv("SENTINEL_FLIGHT_DIR", str(tmp_path))
    fr = FlightRecorder(capacity=8, keep=2, min_interval_s=3600.0)
    assert fr.trigger("breach") is not None
    assert fr.trigger("breach") is None  # inside the window
    fr.reset_rate_limit()
    assert fr.trigger("degrade") is not None
    fr.reset_rate_limit()
    assert fr.trigger("third") is not None
    reasons = [b["reason"] for b in fr.bundles()]
    assert reasons == ["degrade", "third"]  # keep=2, oldest evicted
    assert fr.last_bundle()["reason"] == "third"
    files = sorted(tmp_path.glob("flight_*.json"))
    assert len(files) == 3  # disk keeps everything the process triggered
    from sentinel_tpu.obs.flight import load_bundle

    assert load_bundle(str(files[0]))["kind"] == "sentinel-flight-bundle"
    rl = obs.REGISTRY.get("sentinel_flight_bundles_rate_limited_total")
    assert rl is None or rl.value >= 0  # registered lazily per instance


def test_flight_note_disarmed_overhead_guard():
    """The journal append is the black box's hot hook: it must stay in
    the same <5 µs/call budget as t0() and disarmed failpoints."""
    from sentinel_tpu.obs.flight import FlightRecorder
    from sentinel_tpu.utils.time_source import mono_s

    fr = FlightRecorder(capacity=1024)
    n = 20_000
    t_start = mono_s()
    for i in range(n):
        fr.note("overhead.guard.event")
    elapsed = mono_s() - t_start
    assert elapsed / n < 5e-6, f"note() cost {elapsed / n * 1e9:.0f} ns/call"


def test_postmortem_cli_prints_timeline(tmp_path, capsys):
    from sentinel_tpu.obs.__main__ import main
    from sentinel_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(capacity=16)
    fr.note("failpoint.fire", site="cluster.rpc.send", action="raise", hit=2)
    fr.note("cluster.degrade.enter", cooldown_s=5.0)
    b = fr.dump_bundle("unit-test")
    p = tmp_path / "bundle.json"
    p.write_text(json.dumps(b))
    assert main(["--postmortem", str(p)]) == 0
    out = capsys.readouterr().out
    assert "reason='unit-test'" in out
    assert "failpoint.fire" in out and "cluster.degrade.enter" in out
    # non-bundles are rejected loudly
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError):
        main(["--postmortem", str(bad)])


def test_build_info_gauge_in_exposition():
    text = obs.REGISTRY.exposition()
    line = [l for l in text.splitlines() if l.startswith("sentinel_build_info")]
    assert line, "sentinel_build_info missing from exposition"
    assert line[0].endswith(" 1")
    assert 'sentinel_version="' in line[0] and 'jax_version="' in line[0]
    assert 'backend="' in line[0]


def test_chrome_roundtrip_through_summarize(tmp_path):
    obs.TRACER.reset()
    obs.enable()
    try:
        with obs.span("tick.device", trace=1):
            pass
    finally:
        obs.disable()
    p = tmp_path / "t.json"
    obs.TRACER.dump(str(p))
    spans = obs.load_spans(str(p))
    assert "tick.device" in obs.summarize(spans)
    # files that are neither format are rejected
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError):
        obs.load_spans(str(bad))
