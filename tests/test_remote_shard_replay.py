"""RemoteShard._rpc_pipeline at-most-once semantics (ADVICE r5 low #3).

A chunk written to a socket that subsequently fails may already have been
admitted shard-side with its response lost; re-sending it on reconnect
would double-count admission (and WINDOW=8 pipelining widens the exposure
to 8 chunks per failure).  These tests drive the pipeline over scripted
in-memory sockets — no subprocesses, no real network (the wire-level
shard behavior lives in test_multihost.py).
"""

from __future__ import annotations

import struct
from typing import List

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.parallel.remote_shard import RemoteShard


class _ScriptedSocket:
    """In-memory 'server': every frame sent is decoded and (for the first
    ``answer_n`` requests) answered PASS; recv raises OSError once the
    scripted answers run out."""

    def __init__(self, answer_n: int):
        self.answer_n = answer_n
        self.requests: List[P.ClusterRequest] = []
        self._out = b""

    def sendall(self, raw: bytes) -> None:
        (n,) = struct.unpack(">H", raw[:2])
        req = P.decode_request(raw[2 : 2 + n])
        self.requests.append(req)
        if len(self.requests) <= self.answer_n:
            k = len(req.params) // 5  # RES_CHECK wire: 5-tuples per item
            self._out += P.encode_response(
                P.ClusterResponse(
                    req.xid,
                    C.MSG_TYPE_RES_CHECK,
                    C.STATUS_OK,
                    items=[(ERR.PASS, 0)] * k,
                )
            )

    def recv(self, n: int) -> bytes:
        if not self._out:
            raise OSError("scripted failure")
        chunk, self._out = self._out[:n], self._out[n:]
        return chunk

    def settimeout(self, t) -> None:
        pass

    def close(self) -> None:
        pass


class _RecordingFallback:
    def __init__(self):
        self.batches: List[List[str]] = []

    def check_batch(self, resources, **kw):
        self.batches.append(list(resources))
        return [(ERR.PASS, 0)] * len(resources)


def _shard(sockets, fallback=None) -> RemoteShard:
    s = RemoteShard("scripted", 0, fallback=fallback, retry_interval_s=60.0)
    s.CHUNK = 4  # small chunks -> several frames per batch
    it = iter(sockets)

    def connect():
        nxt = next(it)
        if isinstance(nxt, Exception):
            raise nxt
        return nxt

    s._connect = connect
    return s


def test_written_chunks_are_degraded_not_replayed():
    """3 chunks pipelined; the server answers one then dies.  The two
    written-but-unanswered chunks must degrade to the fallback and must
    NOT be re-sent anywhere — not to the dead socket, not to a fresh
    connection."""
    sock = _ScriptedSocket(answer_n=1)
    fb = _RecordingFallback()
    shard = _shard([sock], fallback=fb)
    names = [f"r{i}" for i in range(12)]

    out = shard.check_batch(names)

    assert len(out) == 12 and all(v == ERR.PASS for v, _ in out)
    # the server saw each chunk exactly once — no replay of the two
    # possibly-admitted chunks
    assert len(sock.requests) == 3
    # and exactly those two spans (r4..r7, r8..r11) degraded locally
    assert fb.batches == [names[4:8], names[8:12]]
    # a mid-exchange death that forfeits every remaining chunk arms the
    # cool-down like an unreachable shard: the next batch fast-degrades
    # instead of re-paying connect+write+fail and forfeiting again
    assert shard._down_until > 0.0
    out2 = shard.check_batch(names[:4])
    assert len(sock.requests) == 3  # cool-down: wire untouched
    assert fb.batches[-1] == names[:4]  # degraded locally


def test_unwritten_chunks_still_ride_the_reconnect():
    """A connect failure writes nothing, so every chunk is safe to retry:
    the single reconnect must serve the whole batch remotely."""
    good = _ScriptedSocket(answer_n=99)
    fb = _RecordingFallback()
    shard = _shard([OSError("connect refused"), good], fallback=fb)
    names = [f"r{i}" for i in range(8)]

    out = shard.check_batch(names)

    assert len(out) == 8 and all(v == ERR.PASS for v, _ in out)
    assert len(good.requests) == 2  # both chunks served remotely
    assert fb.batches == []  # nothing degraded


def test_mid_window_failure_without_fallback_fails_open_per_design():
    """fallback=None: forfeited spans take the documented pass-through
    degrade (the reference's fallbackToLocalOrPass default), while the
    answered span keeps its remote verdicts."""
    sock = _ScriptedSocket(answer_n=1)
    shard = _shard([sock], fallback=None)
    names = [f"r{i}" for i in range(8)]

    out = shard.check_batch(names)

    assert len(out) == 8 and all(v == ERR.PASS for v, _ in out)
    assert len(sock.requests) == 2  # chunk 0 answered, chunk 1 forfeited
