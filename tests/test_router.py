"""Multi-host shard routing: two 'hosts' (independent clients) own
disjoint resource shards; local rules enforce per shard, and a GLOBAL
budget is shared across hosts via the cluster token protocol — the
BASELINE #5 topology at miniature scale."""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.parallel.router import ShardRouter, shard_of


def test_shard_assignment_deterministic():
    names = [f"res-{i}" for i in range(200)]
    a = [shard_of(n, 4) for n in names]
    b = [shard_of(n, 4) for n in names]
    assert a == b
    assert set(a) == {0, 1, 2, 3}  # reasonably spread


def test_router_entry_and_batch(client_factory, vt):
    hosts = [client_factory(), client_factory()]
    router = ShardRouter(hosts)
    # find resources landing on each shard
    r0 = next(f"a{i}" for i in range(100) if shard_of(f"a{i}", 2) == 0)
    r1 = next(f"b{i}" for i in range(100) if shard_of(f"b{i}", 2) == 1)
    hosts[0].flow_rules.load([st.FlowRule(resource=r0, count=2)])
    hosts[1].flow_rules.load([st.FlowRule(resource=r1, count=3)])

    results = router.check_batch([r0, r1] * 5)
    ok_r0 = sum(1 for i in range(0, 10, 2) if results[i][0] == 0)
    ok_r1 = sum(1 for i in range(1, 10, 2) if results[i][0] == 0)
    assert ok_r0 == 2 and ok_r1 == 3

    # entries route to the owning host's stats
    with pytest.raises(st.BlockException):
        router.entry(r0)
    assert hosts[0].stats.resource(r0)["blockQps"] >= 1
    assert hosts[1].stats.resource(r0) is None  # other shard never saw it

    snap = router.snapshot()
    assert r0 in snap and r1 in snap


def test_router_slices_per_item_sequences(client_factory, vt):
    """origins/params/prioritized must be sliced with their shard group —
    forwarding them unsliced applies item 0's param to every shard."""
    hosts = [client_factory(), client_factory()]
    router = ShardRouter(hosts)
    r0 = next(f"p{i}" for i in range(100) if shard_of(f"p{i}", 2) == 0)
    r1 = next(f"q{i}" for i in range(100) if shard_of(f"q{i}", 2) == 1)
    for h, r in ((hosts[0], r0), (hosts[1], r1)):
        h.param_flow_rules.load([st.ParamFlowRule(resource=r, count=1, param_idx=0)])
    # one hot value per resource; the second hit of the SAME value blocks,
    # a different value passes — alignment errors cross these up
    res = [r0, r1, r0, r1]
    par = ["u1", "u2", "u1", "zz"]
    out = router.check_batch(res, params=par)
    assert [v for v, _ in out] == [0, 0, 3, 0]  # only the repeated (r0,u1) blocks


def test_router_snapshot_merges_shared_resources(client_factory, vt):
    hosts = [client_factory(), client_factory()]
    router = ShardRouter(hosts)
    for h in hosts:
        h.flow_rules.load([st.FlowRule(resource="both", count=100)])
        for _ in range(3):
            with h.entry("both"):
                vt.advance(2)
    snap = router.snapshot()
    assert snap["both"]["passQps"] == 6  # summed, not overwritten


class _ExplodingShard:
    """Shard double whose check_batch raises — the mid-batch fan-out
    failure the ISSUE-6 satellite pins down."""

    def __init__(self, exc):
        self.exc = exc
        self.calls = 0

    def check_batch(self, resources, **kw):
        self.calls += 1
        raise self.exc


def test_check_batch_shard_failure_degrades_spans_not_batch(client_factory, vt):
    """A raising shard must not lose its spans silently NOR discard the
    healthy shards' answers: its group fails CLOSED (BLOCK_SYSTEM) and
    the failure is counted by (shard, kind)."""
    from sentinel_tpu.core import errors as ERR
    from sentinel_tpu.obs import REGISTRY

    healthy = client_factory()
    router = ShardRouter([healthy, _ExplodingShard(TimeoutError("dcn"))])
    r0 = next(f"a{i}" for i in range(100) if shard_of(f"a{i}", 2) == 0)
    r1 = next(f"b{i}" for i in range(100) if shard_of(f"b{i}", 2) == 1)
    healthy.flow_rules.load([st.FlowRule(resource=r0, count=100)])

    before = REGISTRY.snapshot().get(
        'sentinel_shard_route_failures_total{kind="timeout",shard="1"}', 0
    )
    out = router.check_batch([r0, r1, r0, r1])
    assert [v for v, _ in out] == [0, ERR.BLOCK_SYSTEM, 0, ERR.BLOCK_SYSTEM]
    after = REGISTRY.snapshot()[
        'sentinel_shard_route_failures_total{kind="timeout",shard="1"}'
    ]
    assert after == before + 1


def test_check_batch_shard_failure_single_group_fails_closed(client_factory, vt):
    from sentinel_tpu.core import errors as ERR

    router = ShardRouter([client_factory(), _ExplodingShard(OSError("io"))])
    r1 = next(f"b{i}" for i in range(100) if shard_of(f"b{i}", 2) == 1)
    out = router.check_batch([r1, r1])  # one group, the failing shard
    assert [v for v, _ in out] == [ERR.BLOCK_SYSTEM, ERR.BLOCK_SYSTEM]


def test_check_batch_shard_failure_local_fallback(client_factory, vt):
    """on_shard_error='fallback': the failed group re-checks on the local
    fallback client — degraded enforcement, not a blanket block."""
    healthy, fallback = client_factory(), client_factory()
    router = ShardRouter(
        [healthy, _ExplodingShard(OSError("io"))],
        on_shard_error="fallback",
        fallback=fallback,
    )
    r1 = next(f"b{i}" for i in range(100) if shard_of(f"b{i}", 2) == 1)
    fallback.flow_rules.load([st.FlowRule(resource=r1, count=2)])
    out = router.check_batch([r1, r1, r1])
    assert [v for v, _ in out] == [0, 0, 1]  # fallback's local budget enforced


def test_check_batch_raise_mode_preserves_legacy_behavior(client_factory, vt):
    router = ShardRouter(
        [client_factory(), _ExplodingShard(OSError("io"))], on_shard_error="raise"
    )
    r1 = next(f"b{i}" for i in range(100) if shard_of(f"b{i}", 2) == 1)
    with pytest.raises(OSError):
        router.check_batch([r1])
    with pytest.raises(ValueError):
        ShardRouter([client_factory()], on_shard_error="fallback")  # no fallback client
    with pytest.raises(ValueError):
        ShardRouter([client_factory()], on_shard_error="sometimes")


def test_router_ring_agrees_with_shard_of(client_factory, vt):
    """The router's internal ring and the module-level shard_of are the
    same placement law — a split here would double-enforce budgets."""
    router = ShardRouter([client_factory(), client_factory()])
    for i in range(50):
        name = f"res-{i}"
        assert router.shards[shard_of(name, 2)] is router.shard_for(name)
        assert int(router.ring.owner(name)) == shard_of(name, 2)


def test_router_with_global_cluster_budget(client_factory, vt):
    """Both hosts defer a cluster-mode rule to ONE token service: the
    global cap holds across shards (cross-host budget via tokens, the
    DCN-level equivalent of the reference's token server)."""
    from sentinel_tpu.cluster.token_service import DefaultTokenService

    hosts = [client_factory(), client_factory()]
    svc_engine = client_factory()
    svc = DefaultTokenService(svc_engine)
    rule = st.FlowRule(
        resource="shared", count=4, cluster_mode=True,
        cluster_flow_id=99, cluster_threshold_type=1,
    )
    svc.flow_rules.load("ns", [rule])

    class Local:
        def __init__(self):
            self.mode = 0

        def token_service(self):
            return svc

        def is_available(self):
            return True

    for h in hosts:
        h.flow_rules.load([rule])
        h.set_cluster(Local())

    # the resource hashes to one shard, but in cluster mode EVERY host
    # could receive it (e.g. load-balanced ingress): both consult the
    # same global budget
    ok = 0
    for i in range(10):
        h = hosts[i % 2]
        try:
            with h.entry("shared"):
                pass
            ok += 1
        except st.BlockException:
            pass
    assert ok == 4  # global cap across both hosts
