"""Consistent-hash ring contracts (cluster/ring.py).

The golden test pins the EXACT assignment for a fixed member set so
placement is process- and version-independent: a refactor that changes
the hash, the vnode point construction, or the tie-break silently
reshuffles every deployed fleet's flow ownership — this test makes that
a loud diff instead.
"""

import math

import pytest

from sentinel_tpu.cluster.ring import DEFAULT_VNODES, HashRing, flow_key

MEMBERS = ["shard-0", "shard-1", "shard-2", "shard-3"]

#: pinned owner indices for keys k0..k63 on MEMBERS at vnodes=32
#: (regenerate ONLY for a deliberate placement-law change:
#:  [int(ring.owner(f"k{i}").split('-')[1]) for i in range(64)])
GOLDEN_V32 = [
    2, 2, 2, 2, 3, 3, 2, 1, 0, 3, 0, 0, 0, 0, 0, 0,
    0, 0, 2, 2, 2, 2, 0, 3, 2, 3, 3, 0, 2, 2, 1, 2,
    0, 0, 2, 1, 1, 0, 1, 2, 2, 2, 2, 2, 2, 2, 0, 2,
    3, 0, 1, 0, 0, 1, 0, 2, 1, 0, 1, 1, 1, 1, 0, 0,
]

#: pinned flow-id owners (the fleet/RLS placement surface)
GOLDEN_FLOWS_V32 = {101: "shard-3", 202: "shard-1", 303: "shard-1", 505: "shard-3"}


def test_golden_assignment_is_pinned():
    ring = HashRing(MEMBERS, vnodes=32)
    got = [int(ring.owner(f"k{i}").split("-")[1]) for i in range(64)]
    assert got == GOLDEN_V32
    for fid, owner in GOLDEN_FLOWS_V32.items():
        assert ring.owner_of_flow(fid) == owner


def test_assignment_deterministic_across_instances():
    a = HashRing(MEMBERS)
    b = HashRing(list(reversed(MEMBERS)))  # construction order is irrelevant
    keys = [f"key-{i}" for i in range(300)]
    assert a.assignment(keys) == b.assignment(keys)


@pytest.mark.parametrize("edit", ["remove", "add"])
def test_membership_change_moves_at_most_one_share(edit):
    """The consistent-hash law: a single-member edit moves ~K/N keys —
    bounded by ceil(K/N) + slack (vnode imbalance) — NOT the ~(N-1)/N a
    bare modulus reshuffles."""
    K = 512
    keys = [f"key-{i}" for i in range(K)]
    base = HashRing([f"s{i}" for i in range(4)])
    before = base.assignment(keys)
    if edit == "remove":
        other = HashRing([f"s{i}" for i in range(3)])
    else:
        other = HashRing([f"s{i}" for i in range(5)])
    after = other.assignment(keys)
    moved = sum(1 for k in keys if before[k] != after[k])
    bound = math.ceil(K / 4) + K // 8  # slack: vnode-level imbalance
    assert 0 < moved <= bound, f"{moved} keys moved, bound {bound}"
    if edit == "remove":
        # removal may only reassign the DEPARTING member's keys
        assert all(
            before[k] == "s3" for k in keys if before[k] != after[k]
        )
    else:
        # addition may only pull keys TO the arriving member
        assert all(
            after[k] == "s4" for k in keys if before[k] != after[k]
        )


def test_incremental_edits_match_fresh_construction():
    keys = [f"key-{i}" for i in range(256)]
    ring = HashRing(MEMBERS)
    ring.add("shard-4")
    ring.remove("shard-1")
    fresh = HashRing(["shard-0", "shard-2", "shard-3", "shard-4"])
    assert ring.assignment(keys) == fresh.assignment(keys)


def test_spread_covers_all_members():
    ring = HashRing(MEMBERS, vnodes=DEFAULT_VNODES)
    spread = ring.spread([f"key-{i}" for i in range(1000)])
    assert set(spread) == set(MEMBERS)
    assert all(v > 0 for v in spread.values())
    assert sum(spread.values()) == 1000


def test_flow_key_is_stable():
    assert flow_key(42) == "flow/42"  # the cross-layer placement key


def test_membership_validation():
    with pytest.raises(ValueError):
        HashRing([])
    with pytest.raises(ValueError):
        HashRing(["a", "a"])
    ring = HashRing(["a", "b"])
    with pytest.raises(ValueError):
        ring.add("a")
    with pytest.raises(ValueError):
        ring.remove("zz")
    ring.remove("b")
    with pytest.raises(ValueError):
        ring.remove("a")  # never empty
