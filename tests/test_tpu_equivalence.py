"""On-TPU three-path equivalence (VERDICT r2 #4): scatter vs MXU vs fused
paths must produce bit-identical verdicts and state ON THE REAL CHIP —
the only place the bf16 digit-plane tricks actually exercise the MXU.

The check runs in a subprocess WITHOUT the conftest CPU forcing (the suite
itself runs on a virtual CPU mesh); it is skipped when no TPU is
reachable, and green in the bench environment."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    # drop the virtual-device forcing the suite sets for CPU sharding tests
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def _tpu_available() -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True,
            text=True,
            timeout=120,
            env=_clean_env(),
        )
        return r.returncode == 0 and "cpu" not in r.stdout.lower()
    except Exception:
        return False


def test_three_path_equivalence_on_device():
    # probed lazily INSIDE the test — a skipif decorator would spawn the
    # jax-importing probe subprocess at collection time on every CPU run
    if not _tpu_available():
        pytest.skip("needs a real TPU")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "benchmarks", "tpu_equivalence.py")],
        env=_clean_env(),
        cwd=_REPO,
        timeout=1500,
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, f"on-device equivalence failed:\n{r.stdout[-4000:]}\n{r.stderr[-4000:]}"
