"""ops/segment.py vs a NumPy oracle: structure, sums, ranks, mins."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sentinel_tpu.ops import segment as SG


def _sorted_batch(rng, n, key_space, aux_space=1):
    k1 = np.sort(rng.integers(0, key_space, n).astype(np.int32))
    k2 = rng.integers(0, aux_space, n).astype(np.int32)
    # sort stably by (k1, k2) like the host presort
    order = np.lexsort((np.arange(n), k2, k1))
    return k1[order], k2[order]


def _oracle_segments(k1, k2):
    n = len(k1)
    head = np.zeros(n, bool)
    head[0] = True
    head[1:] = (k1[1:] != k1[:-1]) | (k2[1:] != k2[:-1])
    head |= np.arange(n) % SG.BLOCK == 0
    sid = np.cumsum(head) - 1
    return head, sid


@pytest.mark.parametrize("n,space", [(1024, 37), (2048, 500), (512, 2)])
def test_build_structure(n, space):
    rng = np.random.default_rng(0)
    k1, k2 = _sorted_batch(rng, n, space, aux_space=3)
    head_o, sid_o = _oracle_segments(k1, k2)
    U = int(sid_o[-1]) + 1 + 8
    ctx, _ = SG.build([jnp.asarray(k1), jnp.asarray(k2)], U)
    assert bool(ctx.ok)
    np.testing.assert_array_equal(np.asarray(ctx.head), head_o)
    np.testing.assert_array_equal(np.asarray(ctx.sid), sid_o)
    assert int(ctx.n_seg) == sid_o[-1] + 1
    seg_end = np.asarray(ctx.seg_end)
    live = np.asarray(ctx.live)
    for s in range(sid_o[-1] + 1):
        assert live[s]
        assert seg_end[s] == np.max(np.nonzero(sid_o == s))
    assert not live[sid_o[-1] + 1 :].any()


def test_build_overflow_flags_not_ok():
    rng = np.random.default_rng(1)
    k1, k2 = _sorted_batch(rng, 1024, 900)
    ctx, _ = SG.build([jnp.asarray(k1)], 16)
    assert not bool(ctx.ok)


def test_compact_and_expand():
    rng = np.random.default_rng(2)
    k1, k2 = _sorted_batch(rng, 1024, 100)
    head_o, sid_o = _oracle_segments(k1, k2)
    U = int(sid_o[-1]) + 1 + 4
    ctx, _ = SG.build([jnp.asarray(k1)], U)
    # k1 is constant per segment -> compaction then expansion round-trips
    c = SG.compact(ctx, jnp.asarray(k1), fill=-1)
    back = SG.expand(ctx, c)
    np.testing.assert_array_equal(np.asarray(back), k1)
    # 2-D variant
    arr2 = jnp.stack([jnp.asarray(k1), jnp.asarray(k1) * 7], axis=1)
    c2 = SG.compact(ctx, arr2, fill=0)
    np.testing.assert_array_equal(np.asarray(SG.expand(ctx, c2))[:, 1], k1 * 7)


@pytest.mark.parametrize("maxes", [(255,), (65535,), (255, 40000, 16_000_000)])
def test_seg_sums_exact(maxes):
    rng = np.random.default_rng(3)
    n = 2048
    k1, _ = _sorted_batch(rng, n, 61)
    head_o, sid_o = _oracle_segments(k1, k1 * 0)
    U = int(sid_o[-1]) + 1 + 4
    ctx, _ = SG.build([jnp.asarray(k1)], U)
    planes = [rng.integers(0, m + 1, n).astype(np.int32) for m in maxes]
    outs = SG.seg_sums(ctx, [jnp.asarray(p) for p in planes], list(maxes))
    for p, (plane, chunks) in enumerate(zip(planes, outs)):
        total = np.zeros(U, np.int64)
        for arr, w, digits in chunks:
            a = np.asarray(arr).astype(np.int64)
            assert a.max() < (1 << 24)
            assert a.max() < 256**digits
            total += a * w
        want = np.zeros(U, np.int64)
        np.add.at(want, sid_o, plane)
        np.testing.assert_array_equal(total, want)


def test_seg_excl_cumsum_matches_rank_oracle():
    rng = np.random.default_rng(4)
    n = 4096
    k1, _ = _sorted_batch(rng, n, 19)  # long runs spanning blocks
    # node-run heads WITHOUT block caps (the flow-rank use)
    head = np.zeros(n, bool)
    head[0] = True
    head[1:] = k1[1:] != k1[:-1]
    vals = rng.integers(0, 255, (3, n)).astype(np.int32)
    got = np.asarray(
        SG.seg_excl_cumsum(jnp.asarray(head), jnp.asarray(vals))
    )
    want = np.zeros_like(vals)
    for row in range(3):
        acc = {}
        for i in range(n):
            kk = k1[i]
            want[row, i] = acc.get(kk, 0)
            acc[kk] = acc.get(kk, 0) + vals[row, i]
    np.testing.assert_array_equal(got, want)
    # 1-D form
    got1 = np.asarray(SG.seg_excl_cumsum(jnp.asarray(head), jnp.asarray(vals[0])))
    np.testing.assert_array_equal(got1, want[0])


def test_seg_min_f32():
    rng = np.random.default_rng(5)
    n = 2048
    k1, _ = _sorted_batch(rng, n, 97)
    head_o, sid_o = _oracle_segments(k1, k1 * 0)
    U = int(sid_o[-1]) + 1 + 4
    ctx, _ = SG.build([jnp.asarray(k1)], U)
    v = rng.random(n).astype(np.float32) * 100
    got = np.asarray(SG.seg_min_f32(ctx, jnp.asarray(v), fill=1e30))
    want = np.full(U, 1e30, np.float32)
    np.minimum.at(want, sid_o, v)
    np.testing.assert_array_equal(got, want)


def test_sort_unsort_roundtrip():
    rng = np.random.default_rng(6)
    n = 1024
    keys = rng.integers(0, 50, n).astype(np.int32)
    payload = rng.integers(0, 1000, n).astype(np.int32)
    perm, (sp,) = SG.sort_batch([jnp.asarray(keys)], [jnp.asarray(payload)])
    perm_np = np.asarray(perm)
    np.testing.assert_array_equal(np.asarray(sp), payload[perm_np])
    # stability: equal keys keep arrival order
    assert all(
        perm_np[i] < perm_np[i + 1]
        for i in range(n - 1)
        if keys[perm_np[i]] == keys[perm_np[i + 1]]
    )
    (restored,) = SG.unsort(perm, [sp])
    np.testing.assert_array_equal(np.asarray(restored), payload)


def test_seg_sums_respects_block_cap():
    # one giant run: blocks force segment breaks every BLOCK items so no
    # digit-plane segment sum exceeds 255*BLOCK
    n = 4 * SG.BLOCK
    k1 = np.zeros(n, np.int32)
    ctx, _ = SG.build([jnp.asarray(k1)], 8)
    assert int(ctx.n_seg) == 4
    planes = [np.full(n, 255, np.int32)]
    outs = SG.seg_sums(ctx, [jnp.asarray(planes[0])], [255])
    (arr, w, digits) = outs[0][0]
    assert int(np.asarray(arr).max()) == 255 * SG.BLOCK
    assert digits == 3 or int(np.asarray(arr).max()) < 256**digits


def test_build_capacity_exceeds_batch():
    # U > N must still produce [U]-shaped outputs (short tail batches)
    k1 = np.sort(np.random.default_rng(7).integers(0, 5, 64)).astype(np.int32)
    ctx, _ = SG.build([jnp.asarray(k1)], 128)
    assert ctx.U == 128 and ctx.seg_end.shape == (128,)
    c = SG.compact(ctx, jnp.asarray(k1), fill=-1)
    assert c.shape == (128,)
    assert bool(ctx.ok)


def test_segscan_pallas_matches_xla_scans():
    """ops/segscan kernel vs segment.seg_excl_cumsum: exact equality over
    random segment shapes, values up to the int32 contract, runs spanning
    many 256-item tiles, and single/multi-row forms."""
    import jax.numpy as jnp

    from sentinel_tpu.ops import segment as SG
    from sentinel_tpu.ops import segscan as SC

    rng = np.random.default_rng(17)
    for n, vmax, V in ((96, 255, 2), (1024, 255, 1), (2048, (1 << 24) - 1, 2),
                       (700, 4095, 3)):
        head = rng.random(n) < 0.05
        head[0] = True
        v = rng.integers(0, min(vmax, 2**31 // n), (V, n)).astype(np.int32)
        got = np.asarray(SC.seg_excl_cumsum_pl(jnp.asarray(head), jnp.asarray(v)))
        want = np.asarray(SG.seg_excl_cumsum(jnp.asarray(head), jnp.asarray(v)))
        np.testing.assert_array_equal(got, want, err_msg=f"n={n} vmax={vmax}")
    # one giant run (carry renormalization across many tiles near 2^31)
    n = 4096
    head = np.zeros(n, bool)
    head[0] = True
    v = np.full((1, n), 500_000, np.int32)  # total ~2.05e9 < 2^31
    got = np.asarray(SC.seg_excl_cumsum_pl(jnp.asarray(head), jnp.asarray(v)))
    want = np.asarray(SG.seg_excl_cumsum(jnp.asarray(head), jnp.asarray(v)))
    np.testing.assert_array_equal(got, want)
    # 1-D squeeze form + wide variant
    head = rng.random(512) < 0.1
    head[0] = True
    v1 = rng.integers(0, 1 << 20, 512).astype(np.int32)
    np.testing.assert_array_equal(
        np.asarray(SC.seg_excl_cumsum_pl(jnp.asarray(head), jnp.asarray(v1))),
        np.asarray(SG.seg_excl_cumsum(jnp.asarray(head), jnp.asarray(v1))),
    )
    np.testing.assert_array_equal(
        np.asarray(SC.seg_excl_cumsum_wide_pl(jnp.asarray(head), jnp.asarray(v1))),
        np.asarray(SG.seg_excl_cumsum_wide(jnp.asarray(head), jnp.asarray(v1))),
    )


def test_segscan_min_matches_block_min():
    import jax.numpy as jnp

    from sentinel_tpu.ops import segment as SG
    from sentinel_tpu.ops import segscan as SC

    rng = np.random.default_rng(23)
    for n in (96, 512, 3000):
        # block-capped heads like heads_from_keys produces
        head = rng.random(n) < 0.07
        head[0] = True
        head[np.arange(n) % SG.BLOCK == 0] = True
        v = rng.random(n).astype(np.float32) * 100.0
        got = np.asarray(
            SC.seg_incl_min_pl(jnp.asarray(head), jnp.asarray(v), 3.0e38)
        )
        want = np.asarray(
            SG.block_min_inclusive(jnp.asarray(head), jnp.asarray(v), 3.0e38)
        )
        np.testing.assert_array_equal(got, want, err_msg=f"n={n}")


def test_segscan_wide_survives_int32_overflowing_totals():
    """The wide variant exists for batch totals beyond int32 (rate-limiter
    pacing costs); a first cut wrapped at 2^31 — pin the digit-lane path."""
    import jax.numpy as jnp

    from sentinel_tpu.ops import segment as SG
    from sentinel_tpu.ops import segscan as SC

    n = 4096
    head = np.zeros(n, bool)
    head[0] = True
    v = np.full(n, (1 << 24) - 1, np.int32)  # total ~6.9e10 >> 2^31
    got = np.asarray(SC.seg_excl_cumsum_wide_pl(jnp.asarray(head), jnp.asarray(v)))
    want = np.asarray(SG.seg_excl_cumsum_wide(jnp.asarray(head), jnp.asarray(v)))
    np.testing.assert_array_equal(got, want)
    assert got[-1] > 2**31  # genuinely past the int32 range
