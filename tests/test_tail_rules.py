"""Rule enforcement beyond the exact row space (sketch-tail resources).

The reference stops enforcing past its 6,000-chain cap (Constants.java:37);
here ruled tail resources either PROMOTE into exact rows or enforce
approximately from the observability sketch with documented (eps, delta)
bounds (rule_tensors.TailFlowTensors).
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.runtime.client import SentinelClient


@pytest.fixture()
def tiny_client(vt):
    """4 exact resource rows + sketch tail: tail paths trigger immediately."""
    cfg = small_engine_config(
        max_resources=4, max_nodes=16, sketch_stats=True, sketch_width=512,
        sketch_depth=2,
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    yield c
    c.stop()


def _fill_exact(client):
    """Consume every exact row so later resources get sketch ids."""
    for i in range(10):
        client.try_entry(f"filler-{i}")
    # rows 1..3 now taken (0 is ENTRY); anything new is a sketch id
    rid = client.registry.resource_id("overflow-probe")
    assert client.registry.is_sketch_id(rid)


def test_promotion_gives_exact_enforcement(tiny_client, vt):
    c = tiny_client
    # take rows 1,2 — leave one exact row free
    c.try_entry("a")
    c.try_entry("b")
    rid = c.registry.resource_id("c-sketch")  # takes row 3
    for i in range(10):
        c.registry.resource_id(f"spill-{i}")  # exhausts → sketch ids
    tail_rid = c.registry.resource_id("late")
    assert c.registry.is_sketch_id(tail_rid)
    # loading a rule for 'late' cannot promote (exact full) — wait: row
    # space is full, so this exercises the TAIL path below; promotion is
    # covered in test_promotion_with_room
    c.flow_rules.load([st.FlowRule(resource="late", count=2)])
    got = sum(1 for _ in range(6) if c.try_entry("late"))
    assert got <= 2  # CMS enforcement can only over-block, never under
    assert got >= 1


def test_promotion_with_room(vt):
    cfg = small_engine_config(
        max_resources=8, max_nodes=16, sketch_stats=True, sketch_width=512,
        sketch_depth=2,
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    try:
        # force 'hot' into the tail by filling rows first...
        for i in range(12):
            c.registry.resource_id(f"f{i}")
        rid = c.registry.resource_id("hot")
        assert c.registry.is_sketch_id(rid)
        # ...then free is impossible, but promote uses remaining space:
        # max_resources=8 means rows 1..7; f0..f6 took them → full.
        # Use a fresh registry state instead: direct promotion API.
        c2 = SentinelClient(
            cfg=cfg, time_source=vt
        )
        c2.start()
        try:
            for i in range(4):
                c2.registry.resource_id(f"g{i}")  # rows 1-4
            # simulate tail assignment by exhausting rows 5-7
            for i in range(3):
                c2.registry.resource_id(f"h{i}")
            t_rid = c2.registry.resource_id("tailres")
            assert c2.registry.is_sketch_id(t_rid)
            # free space cannot be reclaimed, so promotion fails here too;
            # promote_resource returns None and the rule goes to the tail
            assert c2.registry.promote_resource("tailres") is None
        finally:
            c2.stop()
    finally:
        c.stop()


def test_promotion_api_moves_to_exact(vt):
    cfg = small_engine_config(
        max_resources=8, max_nodes=16, sketch_stats=True, sketch_width=512
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    try:
        reg = c.registry
        # exhaust exact rows 1..7 ONLY via a pretend low cap: fill 7 rows
        for i in range(7):
            reg.resource_id(f"x{i}")
        sk = reg.resource_id("promoteme")
        assert reg.is_sketch_id(sk)
        # free a slot is impossible; instead verify the failure contract...
        assert reg.promote_resource("promoteme") is None
        # ...and the success contract with room available: new registry
        reg2 = SentinelClient(cfg=cfg, time_source=vt)
        reg2.start()
        try:
            r = reg2.registry
            for i in range(3):
                r.resource_id(f"y{i}")
            # manufacture a sketch id directly
            r._next_res = cfg.max_resources  # exhaust
            skid = r.resource_id("deep")
            assert r.is_sketch_id(skid)
            r._next_res = 5  # room appears (e.g. future eviction support)
            newid = r.promote_resource("deep")
            assert newid == 5
            assert r.resource_id("deep") == 5
            assert not r.is_sketch_id(newid)
            # rules loaded now bind to the exact row
            reg2.flow_rules.load([st.FlowRule(resource="deep", count=3)])
            got = sum(1 for _ in range(8) if reg2.try_entry("deep"))
            assert got == 3  # exact enforcement
        finally:
            reg2.stop()
    finally:
        c.stop()


def test_tail_rule_blocks_and_recovers(tiny_client, vt):
    c = tiny_client
    _fill_exact(c)
    rid = c.registry.resource_id("svc-tail")
    assert c.registry.is_sketch_id(rid)
    c.flow_rules.load([st.FlowRule(resource="svc-tail", count=3)])

    got = sum(1 for _ in range(10) if c.try_entry("svc-tail"))
    assert 1 <= got <= 3  # blocks: a tail rule actually enforces

    # the budget recovers when the window slides
    vt.advance(1500)
    assert c.try_entry("svc-tail") is not None


def test_unruled_tail_resources_pass(tiny_client, vt):
    c = tiny_client
    _fill_exact(c)
    c.flow_rules.load([st.FlowRule(resource="ruled-tail", count=1)])
    # unrelated tail resources stay pass-through (delta bound: for them to
    # block, EVERY depth cell must collide with a ruled cell)
    got = sum(1 for i in range(30) if c.try_entry(f"free-{i}"))
    assert got >= 29  # allow one unlucky full-depth collision at width 512
