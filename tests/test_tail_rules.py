"""Rule enforcement beyond the exact row space (sketch-tail resources).

The reference stops enforcing past its 6,000-chain cap (Constants.java:37);
here ruled tail resources either PROMOTE into exact rows or enforce
approximately from the observability sketch with documented (eps, delta)
bounds (rule_tensors.TailFlowTensors).
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.runtime.client import SentinelClient


@pytest.fixture()
def tiny_client(vt):
    """4 exact resource rows + sketch tail: tail paths trigger immediately."""
    cfg = small_engine_config(
        max_resources=4, max_nodes=16, sketch_stats=True, sketch_width=512,
        sketch_depth=2,
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    yield c
    c.stop()


def _fill_exact(client):
    """Consume every exact row so later resources get sketch ids."""
    for i in range(10):
        client.try_entry(f"filler-{i}")
    # rows 1..3 now taken (0 is ENTRY); anything new is a sketch id
    rid = client.registry.resource_id("overflow-probe")
    assert client.registry.is_sketch_id(rid)


def test_promotion_reserve_allows_exact_enforcement(vt):
    """Organic interning stops short of max_resources; a rule arriving for
    a tail resource claims a reserve row and enforces EXACTLY."""
    cfg = small_engine_config(
        max_resources=16, max_nodes=32, sketch_stats=True, sketch_width=512,
        sketch_depth=2,
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    try:
        reg = c.registry
        # fill the organic space (limit = max_resources - reserve)
        i = 0
        while not reg.is_sketch_id(reg.resource_id(f"f{i}")):
            i += 1
        tail_name = f"f{i}"  # landed in the sketch
        assert reg.is_sketch_id(reg.peek_resource_id(tail_name))
        # rule load promotes it into the reserve -> exact row + exact budget
        c.flow_rules.load([st.FlowRule(resource=tail_name, count=3)])
        assert not reg.is_sketch_id(reg.peek_resource_id(tail_name))
        got = sum(1 for _ in range(8) if c.try_entry(tail_name))
        assert got == 3  # exact enforcement
    finally:
        c.stop()


def test_promotion_exhausted_falls_back_to_tail_enforcement(vt):
    """Once the reserve is spent too, further tail rules enforce via the
    CMS tables (conservative, approximate)."""
    cfg = small_engine_config(
        max_resources=4, max_nodes=16, sketch_stats=True, sketch_width=512,
        sketch_depth=2,
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    try:
        reg = c.registry
        i = 0
        names = []
        while len(names) < 6:
            n = f"g{i}"
            if reg.is_sketch_id(reg.resource_id(n)):
                names.append(n)
            i += 1
        # load rules on several tail resources: the first may promote, the
        # rest exhaust the reserve and stay in the tail
        c.flow_rules.load([st.FlowRule(resource=n, count=2) for n in names])
        still_tail = [n for n in names if reg.is_sketch_id(reg.peek_resource_id(n))]
        assert still_tail, "reserve should not cover all six"
        tgt = still_tail[0]
        got = sum(1 for _ in range(6) if c.try_entry(tgt))
        assert 1 <= got <= 2  # approximate, conservative
    finally:
        c.stop()


def test_tail_rule_blocks_and_recovers(tiny_client, vt):
    c = tiny_client
    _fill_exact(c)
    rid = c.registry.resource_id("svc-tail")
    assert c.registry.is_sketch_id(rid)
    c.flow_rules.load([st.FlowRule(resource="svc-tail", count=3)])

    got = sum(1 for _ in range(10) if c.try_entry("svc-tail"))
    assert 1 <= got <= 3  # blocks: a tail rule actually enforces

    # the budget recovers when the window slides
    vt.advance(1500)
    assert c.try_entry("svc-tail") is not None


def test_unruled_tail_resources_pass(tiny_client, vt):
    c = tiny_client
    _fill_exact(c)
    c.flow_rules.load([st.FlowRule(resource="ruled-tail", count=1)])
    # unrelated tail resources stay pass-through (delta bound: for them to
    # block, EVERY depth cell must collide with a ruled cell)
    got = sum(1 for i in range(30) if c.try_entry(f"free-{i}"))
    assert got >= 29  # allow one unlucky full-depth collision at width 512


def test_nondefault_grades_on_tail_ids_promote_and_enforce(vt):
    """Grades beyond QPS/DEFAULT/DIRECT on tail ids (VERDICT r4 weak #5):
    the hot-set promotion path gives them exact rows, where every grade
    enforces exactly — rate-limiter pacing, THREAD concurrency, and a
    circuit breaker, each on a resource that started as a sketch id.

    max_resources=64 keeps the promotion reserve (max_resources // 16 = 4
    rows) big enough for all three promotions."""
    cfg = small_engine_config(
        max_resources=64, max_nodes=128, sketch_stats=True, sketch_width=512,
        sketch_depth=2,
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    try:
        # exhaust organic rows so the ruled resources start as tail ids
        i = 0
        while not c.registry.is_sketch_id(
            c.registry.resource_id(f"filler-{i}")
        ):
            i += 1
        for name in ("rl-tail", "thr-tail", "cb-tail"):
            assert c.registry.is_sketch_id(c.registry.resource_id(name))

        c.flow_rules.load([
            st.FlowRule(resource="rl-tail", count=10.0,
                        control_behavior=st.CONTROL_RATE_LIMITER,
                        max_queueing_time_ms=2000),
            st.FlowRule(resource="thr-tail", grade=st.GRADE_THREAD, count=1.0),
        ])
        c.degrade_rules.load([
            st.DegradeRule(resource="cb-tail", grade=2, count=1,
                           time_window=10, min_request_amount=1),
        ])
        for name in ("rl-tail", "thr-tail", "cb-tail"):
            assert not c.registry.is_sketch_id(
                c.registry.peek_resource_id(name)
            ), f"{name} should have promoted to an exact row"

        # rate limiter: 10/s pacing -> second entry waits ~100 ms
        e1 = c.try_entry("rl-tail")
        assert e1 is not None
        e2 = c.try_entry("rl-tail")
        assert e2 is not None
        assert e2.wait_ms >= 50  # paced, not plain-passed

        # THREAD grade: one in-flight entry holds the slot
        t1 = c.try_entry("thr-tail")
        assert t1 is not None
        assert c.try_entry("thr-tail") is None
        t1.exit()
        assert c.try_entry("thr-tail") is not None

        # circuit breaker: one traced error opens it
        e = c.try_entry("cb-tail")
        assert e is not None
        e.trace(RuntimeError("boom"))
        e.exit()
        vt.advance(5)
        assert c.try_entry("cb-tail") is None  # breaker open
    finally:
        c.stop()


def test_promotion_reserve_prioritizes_unservable_grades(vt):
    """When the reserve is too small for every ruled tail id, rules the
    tail CANNOT serve (rate-limiter here) win the exact rows; plain QPS
    rules keep their approximate tail fallback."""
    cfg = small_engine_config(
        max_resources=16, max_nodes=32, sketch_stats=True, sketch_width=512,
        sketch_depth=2,
    )
    c = SentinelClient(cfg=cfg, time_source=vt)
    c.start()
    try:
        i = 0
        while not c.registry.is_sketch_id(
            c.registry.resource_id(f"filler-{i}")
        ):
            i += 1
        reserve = cfg.max_resources - c.registry.num_resources
        # more QPS rules than reserve rows, then ONE rate-limiter rule
        # LAST in load order — priority, not order, must decide
        qps_names = [f"qps-{k}" for k in range(reserve + 2)]
        for n in qps_names + ["rl-prio"]:
            assert c.registry.is_sketch_id(c.registry.resource_id(n))
        c.flow_rules.load(
            [st.FlowRule(resource=n, count=5.0) for n in qps_names]
            + [st.FlowRule(resource="rl-prio", count=10.0,
                           control_behavior=st.CONTROL_RATE_LIMITER,
                           max_queueing_time_ms=2000)]
        )
        assert not c.registry.is_sketch_id(
            c.registry.peek_resource_id("rl-prio")
        ), "the unservable rule must win an exact row"
        # and it actually paces
        assert c.try_entry("rl-prio") is not None
        e2 = c.try_entry("rl-prio")
        assert e2 is not None and e2.wait_ms >= 50
        # unpromoted QPS rules still enforce approximately from the tail
        tail_qps = [
            n for n in qps_names
            if c.registry.is_sketch_id(c.registry.peek_resource_id(n))
        ]
        assert tail_qps, "some QPS rule should have stayed in the tail"
        got = sum(1 for _ in range(12) if c.try_entry(tail_qps[0]))
        assert got <= 5
    finally:
        c.stop()
