"""r14 window semantics: O(1) running-sum reads vs the exact masked
reads, uint32 wid continuity across the int32 wraparound, idle-gap
rollover bias (overestimate-only), and slack-window error bounds
(arXiv 1604.02450 running sums + arXiv 1703.01166 slack batching)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.ops import window as W

ROWS = 8


def _delta(event, n=1):
    d = np.zeros((1, W.NUM_EVENTS), np.int32)
    d[0, event] = n
    return jnp.asarray(d)


@pytest.mark.parametrize("slack", [0.0, 0.25])
def test_run_reads_vs_masked_reads(slack):
    """After every add (which refreshes at the add's now), the running
    sums must EQUAL the exact masked reads with slack off, and bound them
    from above (counts) / below (rt_min) with slack on — never the
    underestimating direction."""
    rng = np.random.default_rng(5)
    cfg = W.WindowConfig(sample_count=4, window_ms=250, slack_frac=slack)
    st = W.init_window(ROWS, cfg)
    add = jax.jit(functools.partial(W.add_batch, cfg=cfg))
    B = 8
    now = 0
    for _ in range(80):
        now += int(rng.integers(1, 700))
        rows = jnp.asarray(rng.integers(0, ROWS, B), jnp.int32)
        deltas = np.zeros((B, W.NUM_EVENTS), np.int32)
        deltas[np.arange(B), rng.integers(0, W.NUM_EVENTS, B)] = 1
        rt = rng.uniform(1.0, 50.0, B).astype(np.float32)
        st = add(st, jnp.int32(now), rows, jnp.asarray(deltas), jnp.asarray(rt))
        run = np.asarray(W.window_counts_run(st))
        exact = np.asarray(W.window_counts(st, jnp.int32(now), cfg))
        rt_run, min_run = (np.asarray(x) for x in W.gather_window_rt_run(
            st, jnp.arange(ROWS, dtype=jnp.int32)))
        rt_exact, min_exact = (np.asarray(x) for x in W.window_rt(
            st, jnp.int32(now), cfg))
        if slack == 0.0:
            np.testing.assert_array_equal(run, exact)
            np.testing.assert_allclose(rt_run, rt_exact, rtol=1e-5, atol=1e-3)
            np.testing.assert_allclose(min_run, min_exact, rtol=1e-6)
        else:
            # slack defers the purge up to g-1 buckets: counts/rt only
            # ever OVERESTIMATE, the rt floor only ever dips lower — all
            # three err in the fail-closed direction
            assert (run >= exact).all()
            assert (rt_run >= rt_exact - 1e-3).all()
            assert (min_run <= min_exact + 1e-6).all()


def test_wid_wraparound_boundary():
    """int32 now_ms wraps after ~24.8 days; the uint32 wid view is
    continuous across the 2^31 boundary, so counts written just before
    the wrap stay visible just after it and expire normally a full
    interval later (the pre-r14 floordiv on negative now_ms snapped every
    epoch stale at the boundary)."""
    cfg = W.WindowConfig(sample_count=2, window_ms=500)
    st = W.init_window(ROWS, cfg)
    one = jnp.asarray([2], jnp.int32)
    hi = np.int32(2**31 - 100)  # 48 ms into its bucket
    st = W.add_batch(st, jnp.int32(hi), one, _delta(W.EV_PASS), None, cfg)
    # 300 ms later the int32 clock is negative; same bucket, same count
    lo = np.int32(-(2**31) + 200)
    assert int(W.window_event(st, jnp.int32(lo), cfg, W.EV_PASS)[2]) == 1
    st2 = W.add_batch(st, jnp.int32(lo), one, _delta(W.EV_PASS), None, cfg)
    assert int(W.gather_window_event_run(st2, one, W.EV_PASS)[0]) == 2
    assert int(W.window_event(st2, jnp.int32(lo), cfg, W.EV_PASS)[2]) == 2
    # a full interval past the wrap the bucket has expired — masked read
    # drops it at any now, the run read after one refresh
    far = np.int32(-(2**31) + 1400)
    assert int(W.window_event(st2, jnp.int32(far), cfg, W.EV_PASS)[2]) == 0
    st3 = W.refresh(st2, jnp.int32(far), cfg)
    assert int(W.gather_window_event_run(st3, one, W.EV_PASS)[0]) == 0


def test_idle_gap_run_reads_overestimate_only():
    """Lazy expiry: with NO refresh after an idle gap the running sums
    lag reality — they may only OVERESTIMATE (fail-closed); the first
    refresh at the new now snaps them back to exact."""
    cfg = W.WindowConfig(sample_count=2, window_ms=500)
    st = W.init_window(ROWS, cfg)
    one = jnp.asarray([1], jnp.int32)
    st = W.add_batch(st, jnp.int32(100), one, _delta(W.EV_PASS, 7), None, cfg)
    far = jnp.int32(400_000)
    assert int(W.window_event(st, far, cfg, W.EV_PASS)[1]) == 0  # exact: gone
    assert int(W.window_event_run(st, W.EV_PASS)[1]) == 7  # stale: over, not under
    st = W.refresh(st, far, cfg)
    assert int(W.window_event_run(st, W.EV_PASS)[1]) == 0
    st = W.add_batch(st, far, one, _delta(W.EV_PASS, 3), None, cfg)
    assert int(W.window_event_run(st, W.EV_PASS)[1]) == 3
    assert int(W.window_event(st, far, cfg, W.EV_PASS)[1]) == 3


def test_slack_overestimate_bounded():
    """slack_frac=0.5 over 4 buckets → g=2 (one extra physical column):
    a constant-rate stream advancing one bucket per step must see the run
    read bounded by [exact, exact + (g-1) * per_bucket] at every step —
    the measured slack stays inside the configured bound."""
    cfg = W.WindowConfig(sample_count=4, window_ms=250, slack_frac=0.5)
    assert cfg.slack_buckets == 2
    assert cfg.phys_buckets == 5
    st = W.init_window(ROWS, cfg)
    one = jnp.asarray([0], jnp.int32)
    per_bucket = 5
    worst = 0
    for step in range(24):
        now = jnp.int32(step * 250 + 10)
        st = W.add_batch(st, now, one, _delta(W.EV_PASS, per_bucket), None, cfg)
        run = int(W.window_event_run(st, W.EV_PASS)[0])
        exact = int(W.window_event(st, now, cfg, W.EV_PASS)[0])
        assert run >= exact, step
        assert run - exact <= (cfg.slack_buckets - 1) * per_bucket, step
        worst = max(worst, run - exact)
    # the deferral is actually exercised (not vacuously exact throughout)
    assert worst > 0


def test_slack_zero_is_shape_identical():
    """slack_frac=0 must not change the physical layout — g=1, no extra
    columns, so EXACT windows pay nothing for the slack machinery."""
    cfg = W.WindowConfig(sample_count=4, window_ms=250, slack_frac=0.0)
    assert cfg.slack_buckets == 1
    assert cfg.phys_buckets == cfg.sample_count
    st = W.init_window(ROWS, cfg)
    assert st.counts.shape == (ROWS, 4, W.NUM_EVENTS)
