"""Packed host↔device wire format (ops/wire.py) and the client's fused
readback / delta-upload path: codec round-trips bit-exactly for every
verdict code, padding rows and the PASS_WAIT sidecar (incl. overflow);
the packed engine tick and the packed client are bit-identical to the
unpacked reference on the same traffic; delta uploads never change
verdicts; and a mangled fused readback fails the tick CLOSED."""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from sentinel_tpu.chaos import FaultPlan, FaultSpec
from sentinel_tpu.chaos import failpoints as FP
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.obs import REGISTRY
from sentinel_tpu.ops import engine as E
from sentinel_tpu.ops import wire as WIRE


class _Reg:
    def resource_id(self, n):
        return 1


def _metric(name, **labels):
    m = REGISTRY.get(name, labels or None)
    return float(m.value) if m is not None else 0.0


# -- codec goldens -----------------------------------------------------------


def _pack_unpack(cfg, verdict, wait, dropped=0):
    """Round-trip synthetic outputs through the device packer."""
    b = len(verdict)
    lo = WIRE.layout_for(cfg, b)
    rng = np.random.default_rng(42)
    stats = (
        rng.standard_normal(lo.n_stats).astype(np.float32)
        if lo.n_stats
        else None
    )
    res_stats = (
        rng.standard_normal((lo.tl_rows, lo.tl_cols)).astype(np.float32)
        if lo.tl_rows
        else None
    )
    hot = (
        rng.standard_normal((lo.hot_rows, 2)).astype(np.float32)
        if lo.hot_rows
        else None
    )
    buf = WIRE.pack_tick_output(
        cfg,
        jnp.asarray(verdict, jnp.int8),
        jnp.asarray(wait, jnp.int32),
        jnp.int32(dropped),
        None if stats is None else jnp.asarray(stats),
        None if res_stats is None else jnp.asarray(res_stats),
        None if hot is None else jnp.asarray(hot),
    )
    raw = np.asarray(buf)
    assert raw.dtype == np.uint32 and raw.shape == (lo.total,)
    frame = WIRE.unpack(raw.tobytes(), lo)
    return lo, raw, frame, stats, res_stats, hot


def test_codec_round_trip_all_verdict_codes():
    """Every verdict code 0..6 survives the 3-bit bitmap, including at
    word boundaries and with non-multiple-of-10 padding."""
    cfg = small_engine_config()
    codes = [
        ERR.PASS, ERR.BLOCK_FLOW, ERR.BLOCK_DEGRADE, ERR.BLOCK_PARAM,
        ERR.BLOCK_SYSTEM, ERR.BLOCK_AUTHORITY, ERR.PASS_WAIT,
    ]
    for b in (1, 7, 10, 11, 64, 257):
        verdict = np.array([codes[i % len(codes)] for i in range(b)], np.int8)
        wait = np.where(verdict == ERR.PASS_WAIT, 25, 0).astype(np.int32)
        lo, _raw, frame, stats, res_stats, hot = _pack_unpack(
            cfg, verdict, wait, dropped=3
        )
        assert np.array_equal(frame.verdict, verdict)
        assert frame.seg_dropped == 3
        if frame.n_wait <= lo.exc_k:
            assert np.array_equal(frame.wait, wait)
        if stats is not None:
            assert frame.stats.tobytes() == stats.tobytes()
        if res_stats is not None:
            assert frame.res_stats.tobytes() == res_stats.tobytes()
        if hot is not None:
            assert frame.hot.tobytes() == hot.tobytes()


def test_codec_wait_sidecar_exact_and_overflow():
    cfg = small_engine_config()
    b = 256
    assert WIRE.EXC_K < b
    # exactly EXC_K scattered wait rows: the sidecar covers them all
    verdict = np.zeros(b, np.int8)
    wait = np.zeros(b, np.int32)
    idx = np.arange(0, b, b // WIRE.EXC_K)[: WIRE.EXC_K]
    verdict[idx] = ERR.PASS_WAIT
    wait[idx] = 10 + np.arange(len(idx))
    _lo, _raw, frame, *_ = _pack_unpack(cfg, verdict, wait)
    assert frame.n_wait == WIRE.EXC_K
    assert np.array_equal(frame.wait, wait)
    # EXC_K + 1 rows: overflow — wait is None, the client falls back to
    # the full TickOutput.wait_ms column
    verdict[:] = ERR.PASS_WAIT
    wait[:] = 9
    _lo, _raw, frame, *_ = _pack_unpack(cfg, verdict, wait)
    assert frame.n_wait == b
    assert frame.wait is None
    # zero wait rows: no sidecar decode at all
    _lo, _raw, frame, *_ = _pack_unpack(
        cfg, np.zeros(b, np.int8), np.zeros(b, np.int32)
    )
    assert frame.n_wait == 0 and not frame.wait.any()


def test_codec_rejects_corruption_truncation_and_bad_magic():
    cfg = small_engine_config()
    verdict = np.array([0, 1, 6, 2, 0, 5, 3, 4], np.int8)
    wait = np.where(verdict == 6, 7, 0).astype(np.int32)
    lo, raw, _frame, *_ = _pack_unpack(cfg, verdict, wait)
    good = raw.tobytes()
    # any single flipped byte is caught (the chaos `corrupt` fault model)
    for pos in (0, 5, 17, len(good) // 2, len(good) - 1):
        bad = bytearray(good)
        bad[pos] ^= 0xFF
        with pytest.raises(WIRE.WireDecodeError):
            WIRE.unpack(bytes(bad), lo)
    # truncation / drop
    with pytest.raises(WIRE.WireDecodeError):
        WIRE.unpack(good[:-4], lo)
    with pytest.raises(WIRE.WireDecodeError):
        WIRE.unpack(b"", lo)
    # checksum fixed up but magic wrong is still rejected
    words = np.frombuffer(good, np.uint32).copy()
    words[0] ^= 1
    words[3] = (
        int(words[0]) + int(words[1]) + int(words[2])
        + int(np.sum(words[4:], dtype=np.uint64))
    ) & 0xFFFFFFFF
    with pytest.raises(WIRE.WireDecodeError):
        WIRE.unpack(words.tobytes(), lo)
    # the untouched buffer still decodes (guards the fixtures above)
    WIRE.unpack(good, lo)


def test_engine_packed_tick_bit_identical_to_unpacked():
    """The same inputs through a packed_wire tick and a classic tick must
    decode to bit-identical verdict/wait/stats/timeline outputs."""
    base = small_engine_config()
    packed = dataclasses.replace(base, packed_wire=True)
    rules_b = E._compile_ruleset(
        base, _Reg(), [FlowRule(resource="r", count=3.0)], [], [], [], [], None
    )
    res = np.array([1, 1, 1, 1, 1, base.trash_row, 1, 1], np.int32)
    outs = {}
    for cfg, rules in ((base, rules_b), (packed, None)):
        if rules is None:
            rules = E._compile_ruleset(
                cfg, _Reg(), [FlowRule(resource="r", count=3.0)],
                [], [], [], [], None,
            )
        st = E.init_state(cfg)
        tick = E.make_tick(cfg, donate=False)
        acq = E.empty_acquire(cfg, b=len(res))._replace(
            res=jnp.asarray(res, jnp.int32)
        )
        z = jnp.float32(0.0)
        _st, out = tick(
            st, rules, acq, E.empty_complete(cfg, b=len(res)),
            jnp.int32(1000), z, z,
        )
        outs[bool(cfg.packed_wire)] = out
    ref, pk = outs[False], outs[True]
    assert pk.verdict is None and pk.stats is None and pk.wire is not None
    lo = WIRE.layout_for(packed, len(res))
    frame = WIRE.unpack(np.asarray(pk.wire).tobytes(), lo)
    assert np.array_equal(frame.verdict, np.asarray(ref.verdict))
    assert frame.n_wait <= lo.exc_k
    assert np.array_equal(frame.wait, np.asarray(ref.wait_ms))
    assert frame.stats.tobytes() == np.asarray(ref.stats).tobytes()
    if ref.res_stats is not None:
        assert frame.res_stats.tobytes() == np.asarray(ref.res_stats).tobytes()


def test_empty_batch_dtypes_match_wire_uploads():
    """empty_acquire/empty_complete must carry the same narrow dtypes the
    client uploads, or warmup compiles a signature no real tick uses."""
    cfg = dataclasses.replace(small_engine_config(), packed_wire=True)
    acq = E.empty_acquire(cfg, b=8)
    wd = WIRE.acquire_wire_dtypes(cfg)
    for f in ("prio", "inbound", "pre_verdict", "count"):
        want = np.dtype(wd.get(f, np.int32))
        assert np.dtype(getattr(acq, f).dtype) == want, f
    comp = E.empty_complete(cfg, b=8)
    wdc = WIRE.complete_wire_dtypes(cfg)
    for f in ("inbound", "success", "error"):
        want = np.dtype(wdc.get(f, np.int32))
        assert np.dtype(getattr(comp, f).dtype) == want, f


# -- client path: packed vs reference, delta uploads, fail-closed ------------


def _drive(c, rules, rounds=6):
    """Deterministic mixed traffic; returns the flat verdict/wait lists."""
    c.flow_rules.load(rules)
    got = []
    for i in range(rounds):
        names = [f"wiretest/r{j % 3}" for j in range(4 + (i % 3))]
        got.extend(c.check_batch(names, inbound=True))
        # completions exercise the c.* upload columns too
        rids = np.array(
            [c.registry.resource_id(n) for n in names[:3]], np.int32
        )
        c.submit_completion_block(
            rids, rt=np.full(3, 1.0 + i, np.float32),
            inbound=np.ones(3, np.int32),
        )
        c.time.advance(50)
        c.tick_once()
    return got


def test_packed_client_bit_identical_to_reference_client(client_factory, vt):
    """The packed client (fused readback + narrow/delta uploads) must
    produce bit-identical verdicts and waits to a packed_wire=False
    reference client over identical traffic."""
    from sentinel_tpu.utils.time_source import VirtualTimeSource

    rules = [
        FlowRule(resource="wiretest/r0", count=3.0),
        FlowRule(
            resource="wiretest/r1", count=2.0,
            control_behavior=2, max_queueing_time_ms=400,
        ),  # RATE_LIMITER: produces PASS_WAIT rows through the sidecar
    ]
    ref_c = client_factory(
        cfg=small_engine_config(packed_wire=False),
        time_source=VirtualTimeSource(start_ms=1_000),
    )
    pk_c = client_factory(
        cfg=small_engine_config(packed_wire=True),
        time_source=VirtualTimeSource(start_ms=1_000),
    )
    assert pk_c.cfg.packed_wire is True
    ref = _drive(ref_c, rules)
    got = _drive(pk_c, rules)
    assert got == ref
    assert any(v == ERR.PASS_WAIT and w > 0 for v, w in ref)


def test_client_defaults_to_packed_and_delta_skips_clean_columns(client):
    """Tri-state default: the client resolves packed_wire=None to True.
    Repeating identical traffic must skip re-uploading unchanged columns
    (delta path) without changing verdicts; changed traffic must not be
    served from the stale cache."""
    assert client.cfg.packed_wire is True
    client.flow_rules.load([FlowRule(resource="delta/r", count=4.0)])
    names = ["delta/r"] * 6
    first = client.check_batch(names, inbound=True)
    skip0 = _metric("sentinel_wire_cols_skipped_total")
    tx0 = _metric(
        "sentinel_wire_bytes_total", path="device", direction="tx"
    )
    second = client.check_batch(names, inbound=True)
    assert _metric("sentinel_wire_cols_skipped_total") > skip0
    # identical traffic, fewer uploaded bytes than a full-column tick
    assert [v for v, _ in second].count(int(ERR.PASS)) == 0  # window used up
    assert len(first) == len(second) == 6
    # now CHANGE one column the delta path previously skipped — the
    # verdicts must track the new traffic, proving no stale device reuse
    client.time.advance(client.cfg.second_window_ms * client.cfg.second_sample_count + 10)
    third = client.check_batch(["delta/r"] * 2 + ["delta/other"] * 4)
    assert len(third) == 6
    assert [v for v, _ in third][:2] == [int(ERR.PASS)] * 2
    assert _metric(
        "sentinel_wire_bytes_total", path="device", direction="tx"
    ) > tx0


def test_corrupt_fused_readback_fails_tick_closed(client_factory):
    """chaos transport.packed.decode corrupt: the decoder must DETECT the
    mangled buffer (checksum), count it, and the tick must fail CLOSED —
    every caller gets BLOCK_SYSTEM, nothing hangs or passes.  The site
    pipes only the fail-CLOSED main section (the trailing explain block
    fails OPEN via its own obs.explain.decode site — test_explain.py),
    so this holds with the explain section present."""
    c = client_factory()
    c.flow_rules.load([FlowRule(resource="fc/r", count=100.0)])
    assert [v for v, _ in c.check_batch(["fc/r"] * 4)] == [int(ERR.PASS)] * 4
    dec0 = _metric("sentinel_packed_decode_failures_total")
    plan = FaultPlan(
        name="wire-corrupt", seed=11,
        faults=[FaultSpec("transport.packed.decode", "corrupt", max_fires=1)],
    )
    with FP.armed(plan) as st:
        got = c.check_batch(["fc/r"] * 4)
        assert st.injected().get("transport.packed.decode:corrupt") == 1
    assert [v for v, _ in got] == [int(ERR.BLOCK_SYSTEM)] * 4
    assert _metric("sentinel_packed_decode_failures_total") == dec0 + 1
    # recovery: the next tick decodes clean again
    assert [v for v, _ in c.check_batch(["fc/r"] * 2)] == [int(ERR.PASS)] * 2


def test_short_read_fused_readback_fails_tick_closed(client_factory):
    """A dropped/truncated fused buffer trips the length check."""
    c = client_factory()
    c.flow_rules.load([FlowRule(resource="fs/r", count=100.0)])
    c.check_batch(["fs/r"] * 2)
    dec0 = _metric("sentinel_packed_decode_failures_total")
    plan = FaultPlan(
        name="wire-short", seed=3,
        faults=[FaultSpec("transport.packed.decode", "short_read", max_fires=1)],
    )
    with FP.armed(plan):
        got = c.check_batch(["fs/r"] * 3)
    assert [v for v, _ in got] == [int(ERR.BLOCK_SYSTEM)] * 3
    assert _metric("sentinel_packed_decode_failures_total") == dec0 + 1


def test_single_fused_readback_accounting(client_factory):
    """Packed rx accounting: one tick moves exactly the layout's bytes
    (minus timeline, accounted on its own path) — not four transfers."""
    c = client_factory()
    c.registry.resource_id("acct/r")
    c.check_batch(["acct/r"] * 4)  # warm both shapes / const cols
    rx0 = _metric("sentinel_wire_bytes_total", path="device", direction="rx")
    tl0 = _metric("sentinel_wire_bytes_total", path="timeline", direction="rx")
    c.check_batch(["acct/r"] * 4)
    lo = c._wire_layout(c.cfg, min(256, c.cfg.batch_size))
    d_rx = _metric(
        "sentinel_wire_bytes_total", path="device", direction="rx"
    ) - rx0
    d_tl = _metric(
        "sentinel_wire_bytes_total", path="timeline", direction="rx"
    ) - tl0
    tl_bytes = lo.tl_rows * lo.tl_cols * 4
    assert d_rx == lo.total * 4 - tl_bytes
    assert d_tl == tl_bytes
