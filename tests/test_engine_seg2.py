"""Second half of the segment-engine equivalence suite.

Split from test_engine_seg.py so xdist's per-FILE distribution can run
the two halves on different workers — the family was the suite's
critical path serialized on one worker (~20 min of the 39-min wall).
Same helpers, same fresh-interpreter isolation.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sentinel_tpu.core.config import small_engine_config
from tests.test_fused import _tick_once
from tests.test_engine_seg import (  # noqa: F401 — shared harness
    _respawned,
    _assert_state_equal,
)

# Same tier-1 exclusion (and reason) as test_engine_seg.py.
pytestmark = pytest.mark.slow

def test_seg_static_ranks_matches_when_contract_holds():
    """seg_static_ranks=True compiles only the segmented-scan ranks; with
    the contract honored (sorted batches, DIRECT/default-limitApp rules)
    it must be bit-identical to the cond-based engine.

    Fresh-interpreter isolated: see _respawned."""
    if _respawned(
        f"{__file__}::test_seg_static_ranks_matches_when_contract_holds"
    ):
        return
    base = dict(
        batch_size=96,
        complete_batch_size=96,
        use_mxu_tables=True,
        enable_minute_window=True,
        fused_effects=True,
        flow_rules_per_resource=1,
        degrade_rules_per_resource=1,
        param_rules_per_resource=1,
    )
    cfg_a = small_engine_config(
        **base, seg_effects=True, seg_u=128, seg_fallback=False
    )
    cfg_b = small_engine_config(
        **base, seg_effects=True, seg_u=128, seg_fallback=False,
        seg_static_ranks=True,
    )
    st1, out1 = _tick_once(cfg_a, sort_batches=True)
    st2, out2 = _tick_once(cfg_b, sort_batches=True)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)
    _assert_state_equal(st1, st2)


def test_seg_static_ranks_unsorted_fails_closed():
    """Breaking the static-rank contract (unsorted batch) must over-block
    loudly — every flow-ruled item rejected — never misrank silently.

    Fresh-interpreter isolated: see _respawned."""
    if _respawned(f"{__file__}::test_seg_static_ranks_unsorted_fails_closed"):
        return
    from sentinel_tpu.core.errors import PASS, PASS_WAIT
    from sentinel_tpu.core.rules import FlowRule
    from sentinel_tpu.ops import engine as E
    from sentinel_tpu.runtime.registry import Registry

    cfg = small_engine_config(
        batch_size=64, complete_batch_size=64, use_mxu_tables=True,
        fused_effects=True, flow_rules_per_resource=1,
        degrade_rules_per_resource=1, param_rules_per_resource=1,
        seg_effects=True, seg_u=128, seg_fallback=False,
        seg_static_ranks=True,
    )
    reg = Registry(cfg)
    for i in range(8):
        reg.resource_id(f"r{i}")
    rules = E.compile_ruleset(
        cfg, reg,
        flow_rules=[FlowRule(resource=f"r{i}", count=1000.0) for i in range(8)],
    )
    state = E.init_state(cfg)
    rng = np.random.default_rng(3)
    res = rng.integers(1, 9, cfg.batch_size).astype(np.int32)  # UNSORTED
    acq = E.empty_acquire(cfg)._replace(
        res=jnp.asarray(res), count=jnp.ones((cfg.batch_size,), jnp.int32)
    )
    state, out = E.tick(
        state, rules, acq, E.empty_complete(cfg), jnp.int32(900),
        jnp.float32(0.0), jnp.float32(0.0), cfg=cfg,
    )
    v = np.asarray(out.verdict)
    assert not np.isin(v, [PASS, PASS_WAIT]).any()  # all fail closed


def test_seg_no_fallback_overflow_fails_closed():
    """seg_fallback=False with too-small capacity: overflow items must
    BLOCK (system rejection — never pass unchecked), kept items keep
    exact verdicts, and seg_dropped counts only real (non-trash) items.

    Fresh-interpreter isolated: see _respawned."""
    if _respawned(f"{__file__}::test_seg_no_fallback_overflow_fails_closed"):
        return
    from sentinel_tpu.core.errors import BLOCK_SYSTEM
    from sentinel_tpu.core.rules import FlowRule
    from sentinel_tpu.ops import engine as E
    from sentinel_tpu.runtime.registry import Registry

    base = dict(
        batch_size=64,
        complete_batch_size=64,
        use_mxu_tables=True,
        fused_effects=True,
        flow_rules_per_resource=1,
        degrade_rules_per_resource=1,
        param_rules_per_resource=1,
    )
    U = 8

    def run(seg_u, seg_fallback):
        cfg = small_engine_config(
            **base, seg_effects=True, seg_u=seg_u, seg_fallback=seg_fallback
        )
        reg = Registry(cfg)
        for i in range(16):
            reg.resource_id(f"r{i}")
        rules = E.compile_ruleset(
            cfg, reg,
            flow_rules=[FlowRule(resource=f"r{i}", count=50.0) for i in range(16)],
        )
        state = E.init_state(cfg)
        B = cfg.batch_size
        # sorted batch touching 16 resources -> 16 segments; pad the last
        # quarter with trash rows (must never count as dropped)
        ids = np.sort(np.arange(48) % 16 + 1).astype(np.int32)
        res = np.concatenate([ids, np.full(B - 48, cfg.trash_row, np.int32)])
        acq = E.empty_acquire(cfg)._replace(
            res=jnp.asarray(res), count=jnp.ones((B,), jnp.int32)
        )
        state, out = E.tick(
            state, rules, acq, E.empty_complete(cfg), jnp.int32(700),
            jnp.float32(0.0), jnp.float32(0.0), cfg=cfg,
        )
        return np.asarray(out.verdict), int(out.seg_dropped), res

    v_exact, dropped_exact, _ = run(seg_u=32, seg_fallback=True)
    v_over, dropped_over, res = run(seg_u=U, seg_fallback=False)
    assert dropped_exact == 0
    assert dropped_over > 0
    valid = res != small_engine_config(**base).trash_row
    # kept items (the first U segments) keep their exact verdicts
    kept = valid & (np.cumsum(np.concatenate([[True], res[1:] != res[:-1]])) <= U)
    np.testing.assert_array_equal(v_over[kept], v_exact[kept])
    # every overflow item fails closed as a system rejection
    over = valid & ~kept
    assert over.sum() == dropped_over
    assert (v_over[over] == BLOCK_SYSTEM).all()
    # trash padding is neither blocked-counted nor dropped-counted
    assert (v_over[~valid] == v_exact[~valid]).all()


@pytest.mark.parametrize("sort_batches", [True, False])
def test_seg_flow_check_k1(sort_batches):
    """flow_rules_per_resource=1 activates the segment-level flow check
    (check_flow_seg).  sorted batches take the segmented-rank branch;
    unsorted ones overflow capacity / fail res_sorted and fall back —
    both must match the plain fused engine bit for bit.

    Fresh-interpreter isolated: see _respawned."""
    if _respawned(f"{__file__}::test_seg_flow_check_k1[{sort_batches}]"):
        return
    base = dict(
        batch_size=96,
        complete_batch_size=96,
        use_mxu_tables=True,
        enable_minute_window=True,
        fused_effects=True,
        flow_rules_per_resource=1,
        degrade_rules_per_resource=1,
        param_rules_per_resource=1,
    )
    cfg_fused = small_engine_config(**base)
    cfg_seg = small_engine_config(**base, seg_effects=True)
    st1, out1 = _tick_once(cfg_fused, sort_batches=sort_batches)
    st2, out2 = _tick_once(cfg_seg, sort_batches=sort_batches)
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)
    _assert_state_equal(st1, st2)


def test_seg_tick_sorted_batch_matches_unsorted_semantics():
    """A batch presorted by resource (stable) must produce the same
    per-item verdicts as the unsorted batch once un-permuted, and the same
    final integer state (f32 rt sums may differ in summation order, so
    they are compared with quantization tolerance).

    Fresh-interpreter isolated: see _respawned."""
    if _respawned(
        f"{__file__}::test_seg_tick_sorted_batch_matches_unsorted_semantics"
    ):
        return
    from sentinel_tpu.core.rules import DegradeRule, FlowRule
    from sentinel_tpu.ops import engine as E
    from sentinel_tpu.runtime.registry import Registry

    base = dict(
        batch_size=128,
        complete_batch_size=128,
        use_mxu_tables=True,
        fused_effects=True,
        enable_minute_window=True,
    )

    def run(sort: bool, seg: bool):
        cfg = small_engine_config(**base, seg_effects=seg)
        reg = Registry(cfg)
        flow, deg = [], []
        for i in range(10):
            name = f"r{i}"
            reg.resource_id(name)
            flow.append(FlowRule(resource=name, count=6.0))
            deg.append(DegradeRule(resource=name, grade=0, count=3.0, time_window=5))
        rules = E.compile_ruleset(cfg, reg, flow_rules=flow, degrade_rules=deg)
        state = E.init_state(cfg)
        rng = np.random.default_rng(11)
        B = cfg.batch_size
        verdicts = []
        for t in range(3):
            ids = rng.integers(1, 12, B).astype(np.int32)
            cnt = np.ones(B, np.int32)
            rt = rng.uniform(0.5, 9.0, B).astype(np.float32)
            order = np.lexsort((np.arange(B), ids)) if sort else np.arange(B)
            acq = E.empty_acquire(cfg)._replace(
                res=jnp.asarray(ids[order]), count=jnp.asarray(cnt[order]),
                inbound=jnp.ones((B,), jnp.int32),
            )
            comp = E.empty_complete(cfg)._replace(
                res=jnp.asarray(ids[order]),
                rt=jnp.asarray(rt[order]),
                success=jnp.ones((B,), jnp.int32),
            )
            state, out = E.tick(
                state, rules, acq, comp, jnp.int32(500 + 400 * t),
                jnp.float32(0.0), jnp.float32(0.0), cfg=cfg,
            )
            v = np.asarray(out.verdict)
            inv = np.empty(B, np.int64)
            inv[order] = np.arange(B)
            verdicts.append(v[inv])  # back to arrival order
        return jax.tree.map(np.asarray, state), verdicts

    st_u, v_u = run(sort=False, seg=False)
    st_s, v_s = run(sort=True, seg=True)
    for a, b in zip(v_u, v_s):
        np.testing.assert_array_equal(a, b)
    # integer state identical; f32 rt sums within summation-order noise
    flat_u = jax.tree_util.tree_flatten_with_path(st_u)[0]
    flat_s = jax.tree.leaves(st_s)
    for (p, x), y in zip(flat_u, flat_s):
        if x.dtype.kind in "iub":
            np.testing.assert_array_equal(x, y, err_msg=str(p))
        else:
            np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-3, err_msg=str(p))
