"""sentinel_tpu.analysis.jaxpr — the tier-2 semantic analyzer.

Three jobs:

1. unit-test every jaxpr pass on tiny traced fixtures, one triggering
   and one non-triggering per rule — including THE demonstration the
   tier exists for: a module-level ``jnp`` const (the documented
   rowmin/rank/segment hazard class) is caught here and invisible to
   the AST tier;
2. golden-file mechanics: fingerprint mismatch/missing, budget breach,
   and the update round-trip;
3. THE CI GATE: trace the real engine/ops entry points and require both
   tiers clean vs the checked-in goldens — this is what keeps hoisted
   consts, timestamp wraps, smuggled callbacks, silent program drift,
   and cost regressions off the admission path.

Runs under JAX_PLATFORMS=cpu (tests/conftest.py); pallas kernels trace
via abstract eval — nothing here executes a tick.
"""

from __future__ import annotations

import ast
import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.analysis import ALL_PASSES, REPO_ROOT
from sentinel_tpu.analysis.framework import ParsedModule, parse_suppressions
from sentinel_tpu.analysis.jaxpr import (
    entry_signature,
    load_golden,
    run_jaxpr_analysis,
    save_golden,
)
from sentinel_tpu.analysis.jaxpr.framework import TracedEntry, walk_eqns
from sentinel_tpu.analysis.jaxpr.passes import (
    ConstHoistPass,
    CostBudgetPass,
    DtypeOverflowPass,
    FingerprintPass,
    TransferGuardPass,
)

# module-level jnp const — the EXACT hazard the ops comments guard by
# hand (rowmin.py:36 "numpy scalar, NOT jnp"); hoisted into the jaxpr of
# any function closing over it
_BAD_DEVICE_CONST = jnp.float32(-3.0e38)
_GOOD_NP_CONST = np.float32(-3.0e38)


def _entry(fn, *args, name="fixture", time_invars=(), **kw) -> TracedEntry:
    return TracedEntry(
        name=name,
        path="sentinel_tpu/ops/engine.py",
        closed_jaxpr=jax.make_jaxpr(fn)(*args),
        time_invars=time_invars,
        **kw,
    )


# ---------------------------------------------------------------------------
# const-hoist
# ---------------------------------------------------------------------------


def test_const_hoist_catches_module_jnp_const():
    """The rowmin/rank/segment hazard class: a module-level jnp scalar
    becomes a device-array const of the traced program."""
    e = _entry(lambda x: jnp.maximum(x, _BAD_DEVICE_CONST), jnp.zeros((4,)))
    got = list(ConstHoistPass().run(e))
    assert len(got) == 1
    assert got[0].rule == "const-hoist"
    assert "np.int32" in got[0].message  # the fix is named in the message


def test_const_hoist_np_scalar_is_clean():
    e = _entry(lambda x: jnp.maximum(x, _GOOD_NP_CONST), jnp.zeros((4,)))
    assert list(ConstHoistPass().run(e)) == []


def test_const_hoist_invisible_to_ast_tier():
    """The AST tier cannot distinguish the two spellings — both are
    module-level assignments feeding jnp.maximum; only the jaxpr shows
    the const's concrete type.  This is the gap the tier-2 analyzer
    closes."""
    source = textwrap.dedent(
        """
        import jax.numpy as jnp

        _NEG = jnp.float32(-3.0e38)

        def fill(x):
            return jnp.maximum(x, _NEG)
        """
    )
    line_disables, file_disables = parse_suppressions(source)
    mod = ParsedModule(
        path="sentinel_tpu/ops/rank.py",
        abspath="/sentinel_tpu/ops/rank.py",
        source=source,
        tree=ast.parse(source),
        line_disables=line_disables,
        file_disables=file_disables,
    )
    ast_findings = [f for p in ALL_PASSES for f in p.run(mod)]
    assert ast_findings == [], [f.message for f in ast_findings]


def test_const_hoist_warns_on_large_numpy_const():
    big = np.ones((1 << 15,), np.float32)  # 128 KiB > the 64 KiB bound
    e = _entry(lambda x: x + big, jnp.zeros((1 << 15,), jnp.float32))
    got = list(ConstHoistPass().run(e))
    assert len(got) == 1 and got[0].severity == "warning"


# ---------------------------------------------------------------------------
# transfer-guard
# ---------------------------------------------------------------------------


def test_transfer_guard_catches_pure_callback():
    def leaky(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct((4,), jnp.float32), x
        )
        return y + 1

    e = _entry(leaky, jnp.zeros((4,), jnp.float32))
    got = list(TransferGuardPass().run(e))
    assert len(got) == 1 and "callback" in got[0].message


def test_transfer_guard_clean_tensor_program():
    e = _entry(lambda x: jnp.cumsum(x) * 2, jnp.zeros((8,), jnp.float32))
    assert list(TransferGuardPass().run(e)) == []


def test_transfer_guard_flags_readbacks_outside_fused_wire():
    """A packed-wire tick whose TickOutput still carries a live stats (or
    verdict) array has silently un-fused the transport: _resolve_tick
    would sync that array separately from the single wire transfer."""
    e = _entry(
        lambda x: x + 1,
        jnp.zeros((4,), jnp.float32),
        packed_wire=True,
        readback_fields=("wait_ms", "seg_dropped", "stats", "wire"),
    )
    got = list(TransferGuardPass().run(e))
    assert len(got) == 1 and "'stats'" in got[0].message

    # ...and a packed entry that lost the wire buffer itself is flagged
    e = _entry(
        lambda x: x + 1,
        jnp.zeros((4,), jnp.float32),
        packed_wire=True,
        readback_fields=("verdict", "wait_ms"),
    )
    msgs = [f.message for f in TransferGuardPass().run(e)]
    assert any("no fused 'wire' buffer" in m for m in msgs)
    assert any("'verdict'" in m for m in msgs)


def test_transfer_guard_packed_allowance_is_clean():
    e = _entry(
        lambda x: x + 1,
        jnp.zeros((4,), jnp.float32),
        packed_wire=True,
        readback_fields=("wait_ms", "seg_dropped", "wire"),
    )
    assert list(TransferGuardPass().run(e)) == []


def test_packed_wire_entry_readback_surface_is_fused():
    """The REAL tick/packed-wire entry: eval_shape-observed live outputs
    must be exactly the fused buffer + the sidecar escape hatch — this is
    the acceptance invariant 'four readbacks fused to one' as a gate."""
    from sentinel_tpu.analysis.jaxpr.entrypoints import trace_entries

    ents = {e.name: e for e in trace_entries()}
    e = ents["tick/packed-wire"]
    assert e.packed_wire and e.readback_fields is not None
    assert "wire" in e.readback_fields
    assert set(e.readback_fields) <= {"wire", "wait_ms", "seg_dropped"}
    # the classic entries keep the multi-array form and are not gated
    assert ents["tick/plain"].packed_wire is False


# ---------------------------------------------------------------------------
# dtype-overflow
# ---------------------------------------------------------------------------


def test_dtype_overflow_flags_ms_scale_up():
    e = _entry(lambda t: t * 1000, jnp.int32(1_000), time_invars=(0,))
    got = list(DtypeOverflowPass().run(e))
    assert len(got) == 1
    assert "1000x" in got[0].message


def test_dtype_overflow_flags_narrowing_and_traced_mul():
    e1 = _entry(lambda t: t.astype(jnp.int16), jnp.int32(1_000), time_invars=(0,))
    assert any("narrowed" in f.message for f in DtypeOverflowPass().run(e1))
    e2 = _entry(
        lambda t, v: t * v, jnp.int32(1_000), jnp.int32(7), time_invars=(0,)
    )
    got = list(DtypeOverflowPass().run(e2))
    assert len(got) == 1 and "traced value" in got[0].message


def test_dtype_overflow_flags_pow_and_int_dot():
    """t**2 is the same wrap class as t*t (integer_pow must not slip
    through the unknown-primitive fallback), and an integer dot_general
    over tainted values is length-scaled accumulation."""
    e = _entry(lambda t: t**2, jnp.int32(1_000), time_invars=(0,))
    got = list(DtypeOverflowPass().run(e))
    assert len(got) == 1 and "power 2" in got[0].message
    e2 = _entry(
        lambda t: jnp.dot(jnp.full((4,), t), jnp.ones((4,), jnp.int32)),
        jnp.int32(1_000),
        time_invars=(0,),
    )
    got2 = list(DtypeOverflowPass().run(e2))
    assert len(got2) == 1 and "dot_general" in got2[0].message


def test_dtype_overflow_scans_while_loop_condition():
    """Deadline/spin conditions live in the while COND jaxpr — tainted
    arithmetic there must not escape the gate."""
    fn = lambda t: jax.lax.while_loop(  # noqa: E731
        lambda s: s * 1000 < 10_000_000, lambda s: s + 1, t
    )
    e = _entry(fn, jnp.int32(1), time_invars=(0,))
    got = list(DtypeOverflowPass().run(e))
    assert len(got) == 1 and "1000x" in got[0].message


def test_dtype_overflow_window_math_is_legal():
    """The operations the engine actually does with now_ms: bucket id,
    phase, round-trip to epoch start, deadline offsets, comparisons —
    none change the ms scale class."""

    def window_math(t):
        wid = t // 500
        idx = t % 500
        start = wid * 500
        deadline = t + 3_000
        fresh = (t - start) < 250
        return wid, idx, start, deadline, fresh

    e = _entry(window_math, jnp.int32(1_000), time_invars=(0,))
    assert list(DtypeOverflowPass().run(e)) == []


def test_dtype_overflow_untainted_counters_are_ignored():
    # length-scaled int accumulation of NON-timestamp values is the
    # engine's bread and butter (histograms); no taint, no finding
    e = _entry(lambda c: jnp.cumsum(c), jnp.ones((64,), jnp.int32))
    assert list(DtypeOverflowPass().run(e)) == []


# ---------------------------------------------------------------------------
# recompile-fingerprint
# ---------------------------------------------------------------------------


def test_fingerprint_roundtrip_and_drift(tmp_path):
    golden_path = str(tmp_path / "fingerprints.json")
    e = _entry(lambda x: x * 2 + 1, jnp.zeros((4,), jnp.float32), name="fp/probe")

    p = FingerprintPass(golden_path=golden_path)
    got = list(p.run(e))
    assert len(got) == 1 and "no golden fingerprint" in got[0].message

    save_golden(
        golden_path,
        {"jax_version": jax.__version__, "entries": {"fp/probe": entry_signature(e)}},
    )
    assert list(FingerprintPass(golden_path=golden_path).run(e)) == []

    # the same NAME tracing to a different program = drift
    e2 = _entry(
        lambda x: x * 2.0 + jnp.sum(x), jnp.zeros((4,), jnp.float32), name="fp/probe"
    )
    got = list(FingerprintPass(golden_path=golden_path).run(e2))
    assert len(got) == 1 and "traced program changed" in got[0].message


def test_fingerprint_is_weak_type_sensitive():
    """Weak-type drift on an entry INPUT is a real recompile hazard (one
    extra executable specialization per call site) — str(aval) hides
    weak_type, so the signature must encode it explicitly."""
    strong = _entry(lambda x, s: x * s, jnp.zeros((4,)), jnp.float32(2.0))
    weak = _entry(lambda x, s: x * s, jnp.zeros((4,)), 2.0)
    assert entry_signature(strong)["hash"] != entry_signature(weak)["hash"]


# ---------------------------------------------------------------------------
# flops-bytes-budget
# ---------------------------------------------------------------------------


def _budget_entry(flops, byts, name="bud/probe"):
    return _entry(
        lambda x: x * 2,
        jnp.zeros((4,), jnp.float32),
        name=name,
        cost_eligible=True,
        cost={"flops": flops, "bytes": byts},
    )


def test_budget_breach_missing_and_pass(tmp_path):
    path = str(tmp_path / "budgets.json")
    e = _budget_entry(2_000.0, 64_000.0)

    got = list(CostBudgetPass(budget_path=path).run(e))
    assert len(got) == 1 and "no cost budget" in got[0].message

    save_golden(
        path, {"entries": {"bud/probe": {"flops": 2_500, "bytes": 80_000}}}
    )
    assert list(CostBudgetPass(budget_path=path).run(e)) == []

    hot = _budget_entry(9_999.0, 64_000.0)
    got = list(CostBudgetPass(budget_path=path).run(hot))
    assert len(got) == 1 and "exceeds the checked-in ceiling" in got[0].message

    exempt = _entry(lambda x: x, jnp.zeros((4,)), name="bud/exempt")
    assert list(CostBudgetPass(budget_path=path).run(exempt)) == []


# ---------------------------------------------------------------------------
# the CI gate: real entry points vs checked-in goldens
# ---------------------------------------------------------------------------


def test_jaxpr_tier_clean_on_real_entry_points():
    """THE tier-2 gate: trace `ops.engine.tick` (plain/MXU/fused-seg and
    the cluster token-decision feature set), the segscan/fused/rank/
    window kernels, and run all five semantic passes.  A failure means a
    PR hoisted a device const, scaled a timestamp, smuggled a callback,
    changed a traced program without --update-fingerprints, or breached
    a cost ceiling."""
    findings = run_jaxpr_analysis()
    assert findings == [], "jaxpr-tier findings:\n" + "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in findings
    )


def test_goldens_cover_every_entry_point():
    """fingerprints.json tracks the live entry list — a new entry point
    without a golden (or a stale golden naming a removed entry) fails
    here rather than surfacing as a confusing missing-fingerprint
    finding in an unrelated PR."""
    from sentinel_tpu.analysis.jaxpr import FINGERPRINTS_PATH
    from sentinel_tpu.analysis.jaxpr.entrypoints import trace_entries

    live = {e.name for e in trace_entries()}
    golden = set(load_golden(FINGERPRINTS_PATH).get("entries", {}))
    assert golden == live


def test_tick_jaxpr_has_no_pallas_on_plain_config():
    """Sanity on the entry list itself: the plain-config tick must stay
    pallas-free (interpret-mode kernels on the scatter path would mean
    the config gating broke), while fused-seg must contain pallas_call."""
    from sentinel_tpu.analysis.jaxpr.entrypoints import trace_entries

    by_name = {e.name: e for e in trace_entries()}
    plain_prims = {eq.primitive.name for eq in walk_eqns(by_name["tick/plain"].closed_jaxpr)}
    seg_prims = {eq.primitive.name for eq in walk_eqns(by_name["tick/fused-seg"].closed_jaxpr)}
    assert "pallas_call" not in plain_prims
    assert "pallas_call" in seg_prims
