"""Observability plane tests: metric log writer/searcher round-trip, the
per-second aggregation timer, the rate-limited block log, and the metric
extension callback SPI (reference: MetricWriter/MetricSearcher tests,
MetricTimerListener.java:34-59, EagleEyeLogUtil.java:24-36,
metric/extension/MetricExtension.java)."""

import os

import pytest

import sentinel_tpu as st
from sentinel_tpu.metrics import (
    BlockLogger,
    MetricExtension,
    MetricNode,
    MetricSearcher,
    MetricTimerListener,
    MetricWriter,
    clear_extensions,
    list_metric_files,
    register_extension,
)


@pytest.fixture(autouse=True)
def _clean_extensions():
    clear_extensions()
    yield
    clear_extensions()


def test_metric_node_line_roundtrip():
    n = MetricNode(
        timestamp=1700000000000,
        resource="GET:/api/v1|weird name",
        pass_qps=20,
        block_qps=3,
        success_qps=19,
        exception_qps=1,
        rt=12.5,
        occupied_pass_qps=0,
        concurrency=4,
        classification=1,
    )
    back = MetricNode.from_line(n.to_line())
    assert back == n


def test_writer_searcher_roundtrip(tmp_path):
    w = MetricWriter(str(tmp_path), "app1", single_file_size=10_000)
    t0 = 1700000000000
    for sec in range(10):
        nodes = [
            MetricNode(resource="resA", pass_qps=sec + 1, success_qps=sec + 1),
            MetricNode(resource="resB", block_qps=2),
            MetricNode(resource="idle"),  # inactive → skipped
        ]
        w.write(t0 + sec * 1000, nodes)
    w.close()

    s = MetricSearcher(str(tmp_path), "app1")
    found = s.find(t0)
    assert len(found) == 20  # 2 active nodes × 10 s
    assert all(n.resource != "idle" for n in found)

    # seek into the middle of the range
    mid = s.find(t0 + 5000)
    assert len(mid) == 10
    assert min(n.timestamp for n in mid) == t0 + 5000

    by_res = s.find_by_time_and_resource(t0, t0 + 3000, "resA")
    assert [n.pass_qps for n in by_res] == [1, 2, 3, 4]

    # recommended_count never truncates mid-second
    few = s.find(t0, recommended_count=3)
    assert len(few) == 4
    assert len({n.timestamp for n in few}) == 2


def test_writer_rolls_and_trims(tmp_path):
    w = MetricWriter(str(tmp_path), "app2", single_file_size=500, total_file_count=3)
    t0 = 1700000000000
    for sec in range(40):
        w.write(t0 + sec * 1000, [MetricNode(resource="r", pass_qps=1)])
    w.close()
    files = list_metric_files(str(tmp_path), "app2")
    assert 1 <= len(files) <= 3
    # idx exists for every kept file
    for f in files:
        assert os.path.exists(f + ".idx")


def test_metric_timer_aggregates_from_engine(client, vt, tmp_path):
    client.flow_rules.load([st.FlowRule(resource="timed", count=100)])
    timer = MetricTimerListener(client, MetricWriter(str(tmp_path), "app3"))
    for _ in range(5):
        vt.advance(100)
        with client.entry("timed"):
            vt.advance(10)
    written = timer.run_once()
    assert written == 1
    timer.writer.close()
    found = MetricSearcher(str(tmp_path), "app3").find(0)
    assert len(found) == 1
    node = found[0]
    assert node.resource == "timed"
    assert node.pass_qps == 5
    assert node.success_qps == 5
    assert node.rt > 0


def test_block_logger_aggregates_per_second(tmp_path):
    bl = BlockLogger(str(tmp_path))
    for i in range(100):
        bl.log(5000, "res1", "FlowException", "web")
    bl.log(5000, "res2", "DegradeException")
    bl.log(6200, "res1", "FlowException", "web")  # second advances → flush
    bl.flush()
    lines = open(bl.path).read().strip().split("\n")
    assert "5000|res1|FlowException|100|web" in lines
    assert "5000|res2|DegradeException|1|" in lines
    assert "6000|res1|FlowException|1|web" in lines


class _Capture(MetricExtension):
    def __init__(self):
        self.events = []

    def on_pass(self, resource, count, origin, args=None):
        self.events.append(("pass", resource, count))

    def on_block(self, resource, count, origin, exc, args=None):
        self.events.append(("block", resource, type(exc).__name__))

    def on_complete(self, resource, rt_ms, success, origin):
        self.events.append(("complete", resource, success))

    def on_exception(self, resource, count, origin):
        self.events.append(("exception", resource, count))


def test_metric_extension_callbacks(client, vt):
    cap = _Capture()
    register_extension(cap)
    client.flow_rules.load([st.FlowRule(resource="ext", count=1)])
    with client.entry("ext"):
        client.trace(ValueError("biz"))
    with pytest.raises(st.BlockException):
        client.entry("ext")
    kinds = [e[0] for e in cap.events]
    assert kinds == ["pass", "complete", "exception", "block"]
    assert ("block", "ext", "FlowException") in cap.events


class _Thrower(MetricExtension):
    def on_pass(self, *a, **k):
        raise RuntimeError("ext boom")

    def on_complete(self, *a, **k):
        raise RuntimeError("ext boom")


def test_throwing_extension_does_not_corrupt_accounting(client, vt):
    register_extension(_Thrower())
    client.flow_rules.load([st.FlowRule(resource="boom", count=100)])
    with client.entry("boom"):
        vt.advance(5)
    s = client.stats.resource("boom")
    # success recorded and concurrency drained despite the throwing hooks
    assert s["successQps"] >= 1
    assert s["curThreadNum"] == 0


def test_client_block_log_wiring(client_factory, vt, tmp_path, monkeypatch):
    import sentinel_tpu.metrics.block_log as BL

    monkeypatch.setattr(BL, "_default", None)
    monkeypatch.setenv("CSP_SENTINEL_LOG_DIR", str(tmp_path))
    c = client_factory(block_log=True)
    c.flow_rules.load([st.FlowRule(resource="blk", count=0)])
    with pytest.raises(st.BlockException):
        c.entry("blk")
    c.block_log.flush()
    content = open(c.block_log.path).read()
    assert "blk|FlowException|1|" in content
    monkeypatch.setattr(BL, "_default", None)
