"""sentinel_tpu.analysis — the TPU-hazard linter.

Two jobs:

1. unit-test every pass on fixture snippets, one triggering and one
   non-triggering per rule (plus the suppression syntaxes);
2. THE CI GATE: run all five passes over the real ``sentinel_tpu/`` tree
   and require zero findings beyond the checked-in baseline — this is
   what keeps fail-open/host-sync/jit-recompile/time-source/unguarded-
   global hazards from riding in on future PRs.

Pure AST work — no jax, no engine compiles; this file is cheap.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from sentinel_tpu.analysis import (
    ALL_PASSES,
    DEFAULT_BASELINE,
    REPO_ROOT,
    load_baseline,
    new_findings,
    run_passes,
)
from sentinel_tpu.analysis.framework import (
    ParsedModule,
    parse_suppressions,
)
from sentinel_tpu.analysis.passes import (
    FailOpenPass,
    HostSyncPass,
    JitRecompilePass,
    TimeSourcePass,
    UnguardedGlobalPass,
)


def _mod(source: str, path: str = "sentinel_tpu/runtime/client.py") -> ParsedModule:
    """ParsedModule from an inline snippet; ``path`` controls which
    file-scoped rules engage."""
    source = textwrap.dedent(source)
    line_disables, file_disables = parse_suppressions(source)
    return ParsedModule(
        path=path,
        abspath="/" + path,
        source=source,
        tree=ast.parse(source),
        line_disables=line_disables,
        file_disables=file_disables,
    )


def _run(p, mod):
    # mirrors the runner's filter (framework.run_passes): the suppression
    # check covers the finding's whole anchor span, not just line 1 of it
    return [f for f in p.run(mod) if not mod.suppressed(f.rule, *f.span())]


# ---------------------------------------------------------------------------
# time-source
# ---------------------------------------------------------------------------


def test_time_source_triggers_on_raw_clock_and_aliases():
    mod = _mod(
        """
        import time as _time
        from time import monotonic as mono

        def deadline():
            return _time.time() + mono()
        """
    )
    got = _run(TimeSourcePass(), mod)
    assert len(got) == 2
    assert all(f.rule == "time-source" for f in got)


def test_time_source_allows_helpers_perf_counter_and_own_module():
    clean = _mod(
        """
        import time
        from sentinel_tpu.utils.time_source import mono_s

        def f():
            t0 = time.perf_counter()  # profiling-only: allowed
            time.sleep(0.01)          # not a clock READ
            return mono_s() - t0
        """
    )
    assert _run(TimeSourcePass(), clean) == []
    own = _mod(
        "import time\n\ndef now():\n    return time.time()\n",
        path="sentinel_tpu/utils/time_source.py",
    )
    assert _run(TimeSourcePass(), own) == []


def test_time_source_allowlists_tracer_read_point_only():
    """obs/trace.py holds the span tracer's single sanctioned monotonic
    read (ISSUE 3 satellite); every other obs module stays banned."""
    src = "import time\n\ndef now_ns():\n    return time.monotonic_ns()\n"
    assert _run(TimeSourcePass(), _mod(src, path="sentinel_tpu/obs/trace.py")) == []
    got = _run(TimeSourcePass(), _mod(src, path="sentinel_tpu/obs/registry.py"))
    assert len(got) == 1 and got[0].rule == "time-source"
    # the chaos failpoint registry is the fault-injection plane's single
    # sanctioned home for time manipulation (ISSUE 4 satellite): its
    # delay/clock_skew actions may touch the clock there, and NOWHERE
    # else in the chaos package
    assert (
        _run(TimeSourcePass(), _mod(src, path="sentinel_tpu/chaos/failpoints.py"))
        == []
    )
    got = _run(TimeSourcePass(), _mod(src, path="sentinel_tpu/chaos/runner.py"))
    assert len(got) == 1 and got[0].rule == "time-source"
    # the REAL tracer module keeps exactly ONE raw-clock call site
    real = os.path.join(REPO_ROOT, "sentinel_tpu", "obs", "trace.py")
    with open(real) as f:
        tree = ast.parse(f.read())
    from sentinel_tpu.analysis import astutil as A

    aliases = A.import_aliases(tree)
    raw_reads = [
        n
        for n in ast.walk(tree)
        if isinstance(n, ast.Call)
        and A.resolve_call(n, aliases)
        in ("time.monotonic_ns", "time.monotonic", "time.time", "time.time_ns")
    ]
    assert len(raw_reads) == 1, "obs/trace.py must keep ONE sanctioned clock read"


# ---------------------------------------------------------------------------
# fail-open
# ---------------------------------------------------------------------------


def test_fail_open_triggers_on_broad_swallow_in_admission_path():
    mod = _mod(
        """
        def check(item):
            try:
                return engine_verdict(item)
            except Exception:
                return PASS
        """
    )
    got = _run(FailOpenPass(), mod)
    assert len(got) == 1 and got[0].rule == "fail-open"


def test_fail_open_ignores_reraise_cleanup_and_out_of_scope_files():
    mod = _mod(
        """
        def check(item):
            try:
                return engine_verdict(item)
            except Exception:
                log()
                raise

        def teardown(sock):
            try:
                sock.close()
            except Exception:
                pass
        """
    )
    assert _run(FailOpenPass(), mod) == []
    # same swallow in a NON-admission file: out of scope
    other = _mod(
        """
        def render(x):
            try:
                return fmt(x)
            except Exception:
                return ""
        """,
        path="sentinel_tpu/dashboard/ui.py",
    )
    assert _run(FailOpenPass(), other) == []


def test_fail_open_suppression_with_rationale():
    mod = _mod(
        """
        def check(item):
            try:
                return consult_token_service(item)
            except Exception:  # stlint: disable=fail-open — degrades to local rules
                return degrade_to_local(item)
        """
    )
    assert _run(FailOpenPass(), mod) == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------


def test_host_sync_triggers_in_jit_zone_and_hot_path():
    mod = _mod(
        """
        import jax
        import numpy as np

        @jax.jit
        def kernel(state, x):
            bad = np.asarray(x)
            return state.sum() + float(x[0])

        def _run_tick(self, acq):
            v = self._tick(acq)
            return v.verdict.item()
        """
    )
    got = _run(HostSyncPass(), mod)
    rules = sorted(set(f.rule for f in got))
    assert rules == ["host-sync"]
    msgs = " | ".join(f.message for f in got)
    assert "numpy.asarray" in msgs  # np materialization inside jit
    assert "float()" in msgs  # traced coercion inside jit
    assert ".item()" in msgs  # sync in the client hot path


def test_host_sync_jit_zone_extends_to_callees_and_allows_static_cfg():
    mod = _mod(
        """
        import functools
        import jax
        import numpy as np

        def tick(state, acq, *, cfg):
            if cfg.seg_effects:          # static branch: fine
                state = _land(state, acq)
            return state

        def _land(state, acq):
            return state + np.asarray(acq)   # callee of a jitted root

        def make_tick(cfg):
            fn = functools.partial(tick, cfg=cfg)
            fn = jax.jit(fn, donate_argnums=(0,))
            return fn

        def host_prep(cols):
            return np.asarray(cols)      # not reachable from any root
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(HostSyncPass(), mod)
    assert len(got) == 1, [f.message for f in got]
    assert "_land" in got[0].message


def test_host_sync_clean_dispatch_is_clean():
    mod = _mod(
        """
        import numpy as np

        def _run_tick(self, acq):
            cols = np.zeros(len(acq), np.int32)   # host batch assembly: fine
            return self._tick(self._dev(cols))
        """
    )
    assert _run(HostSyncPass(), mod) == []


# ---------------------------------------------------------------------------
# jit-recompile
# ---------------------------------------------------------------------------


def test_jit_recompile_triggers_on_callsite_jit_loop_jit_and_traced_branch():
    mod = _mod(
        """
        import jax

        def per_call(x):
            return jax.jit(lambda y: y + 1)(x)

        def in_loop(xs):
            out = []
            for x in xs:
                out.append(jax.jit(step))
            return out

        @jax.jit
        def branchy(state, now_ms, *, cfg):
            if now_ms > 0:
                return state
            return state * 2
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(JitRecompilePass(), mod)
    msgs = " | ".join(f.message for f in got)
    assert "invoked at its own call site" in msgs
    assert "inside a loop" in msgs
    assert "traced parameter 'now_ms'" in msgs


def test_jit_recompile_flags_mutable_module_closure():
    mod = _mod(
        """
        import jax

        _REGISTRY = {}

        @jax.jit
        def kernel(x):
            return x * len(_REGISTRY)
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(JitRecompilePass(), mod)
    assert any("_REGISTRY" in f.message for f in got)


def test_jit_recompile_clean_cached_factory_is_clean():
    mod = _mod(
        """
        import functools
        import threading
        import jax

        _CACHE = {}
        _LOCK = threading.Lock()

        def tick(state, acq, *, cfg):
            return state if cfg.flag else state * 2

        def make_tick(cfg):
            with _LOCK:
                fn = _CACHE.get(cfg)
                if fn is None:
                    fn = functools.partial(tick, cfg=cfg)
                    fn = jax.jit(fn)
                    _CACHE[cfg] = fn
            return fn
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(JitRecompilePass(), mod)
    # `tick` is jitted via the two-step idiom; its cfg branch is static
    # and the cache write is lock-guarded -> nothing to report
    assert got == [], [f.message for f in got]


# ---------------------------------------------------------------------------
# unguarded-global
# ---------------------------------------------------------------------------


def test_unguarded_global_triggers_on_lockless_registry_write():
    mod = _mod(
        """
        _HANDLERS = {}
        _ORDER: list = []

        def register(name, fn):
            _HANDLERS[name] = fn
            _ORDER.append(name)
        """
    )
    got = _run(UnguardedGlobalPass(), mod)
    assert len(got) == 2
    assert all(f.rule == "unguarded-global" for f in got)


def test_unguarded_global_lock_guarded_and_local_shadows_are_clean():
    mod = _mod(
        """
        import threading

        _HANDLERS = {}
        _lock = threading.Lock()

        def register(name, fn):
            with _lock:
                _HANDLERS[name] = fn

        def local_work():
            tmp = {}
            tmp["k"] = 1      # local, not the module global
            return tmp
        """
    )
    assert _run(UnguardedGlobalPass(), mod) == []


def test_unguarded_global_catches_global_rebind():
    mod = _mod(
        """
        _EXTS: list = []

        def clear():
            global _EXTS
            _EXTS = []
        """
    )
    got = _run(UnguardedGlobalPass(), mod)
    assert len(got) == 1 and "rebound" in got[0].message


def test_unguarded_global_lockset_mismatch_reports_both_sites():
    """Lock PRESENCE is not enough: writes under _LOCK_A and _LOCK_B
    both 'hold a lock' but serialize against nothing.  Every site of the
    disjoint lockset is reported, each naming the other."""
    mod = _mod(
        """
        import threading

        _CACHE = {}
        _LOCK_A = threading.Lock()
        _LOCK_B = threading.Lock()

        def put(k, v):
            with _LOCK_A:
                _CACHE[k] = v

        def evict(k):
            with _LOCK_B:
                _CACHE.pop(k, None)
        """
    )
    got = _run(UnguardedGlobalPass(), mod)
    assert len(got) == 2
    assert all("disjoint locksets" in f.message for f in got)
    # each site names the other's lock
    assert "_LOCK_B" in got[0].message and "_LOCK_A" in got[1].message


def test_unguarded_global_consistent_lock_and_nesting_are_clean():
    mod = _mod(
        """
        import threading

        _CACHE = {}
        _LOCK = threading.Lock()
        _OTHER = threading.Lock()

        def put(k, v):
            with _LOCK:
                _CACHE[k] = v

        def evict(k):
            with _OTHER:
                with _LOCK:          # nested: _LOCK still held
                    _CACHE.pop(k, None)
        """
    )
    assert _run(UnguardedGlobalPass(), mod) == []


def test_unguarded_global_single_guarded_site_never_mismatches():
    """One guarded site has nothing to be inconsistent WITH — the
    lockset check needs two sites."""
    mod = _mod(
        """
        import threading

        _CACHE = {}
        _only_lock = threading.Lock()

        def put(k, v):
            with _only_lock:
                _CACHE[k] = v
        """
    )
    assert _run(UnguardedGlobalPass(), mod) == []


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------


def test_suppression_next_line_and_file_scope():
    mod = _mod(
        """
        # stlint: disable-file=time-source reason: fixture file
        import time

        def a():
            return time.time()

        def b():
            try:
                return check()
            # stlint: disable-next-line=fail-open
            except Exception:
                return 0
        """
    )
    assert _run(TimeSourcePass(), mod) == []
    assert _run(FailOpenPass(), mod) == []


def test_suppression_shares_comment_with_noqa():
    mod = _mod(
        """
        import time

        def f():
            return time.time()  # noqa: X100  # stlint: disable=time-source — fixture
        """
    )
    assert _run(TimeSourcePass(), mod) == []


def test_suppression_anchors_on_multiline_statement_tail():
    """A trailing directive naturally lands on the CLOSING line of a
    multi-line statement; the finding anchors on the first line.  The
    anchor span must cover the whole statement."""
    mod = _mod(
        """
        import time

        def f():
            return time.time(
            )  # stlint: disable=time-source — fixture: multi-line call
        """
    )
    assert _run(TimeSourcePass(), mod) == []
    # ... and a directive on a line BELOW the statement does nothing
    unrelated = _mod(
        """
        import time

        def f():
            t = time.time()
            # stlint: disable=time-source
            return t
        """
    )
    assert len(_run(TimeSourcePass(), unrelated)) == 1


def test_suppression_anchors_on_decorator_and_def_line():
    """For findings anchored at a decorated def, the directive works on
    the decorator line (where the @jax.jit that makes it hazardous
    lives) AND on the def line — both are the statement's header."""
    from sentinel_tpu.analysis.framework import Pass

    class DefPass(Pass):
        name = "def-probe"

        def run(self, mod):
            import ast as _ast

            for node in _ast.walk(mod.tree):
                if isinstance(node, _ast.FunctionDef):
                    yield self.finding(mod, node, "probe")

    on_decorator = _mod(
        """
        import functools

        @functools.cache  # stlint: disable=def-probe — fixture
        def f():
            return 1
        """
    )
    assert _run(DefPass(), on_decorator) == []

    on_def = _mod(
        """
        import functools

        @functools.cache
        def f():  # stlint: disable=def-probe — fixture
            return 1
        """
    )
    assert _run(DefPass(), on_def) == []

    in_body = _mod(
        """
        import functools

        @functools.cache
        def f():
            return 1  # stlint: disable=def-probe — body lines are NOT the header
        """
    )
    assert len(_run(DefPass(), in_body)) == 1


def test_suppression_span_does_not_leak_across_statements():
    """The span of statement N must not swallow a directive intended
    for statement N+1 sharing the same line region."""
    mod = _mod(
        """
        import time

        def f():
            a = time.time()
            # stlint: disable-next-line=time-source — only the SECOND read
            b = time.time()
            return a + b
        """
    )
    got = _run(TimeSourcePass(), mod)
    assert len(got) == 1 and got[0].line == 5


# ---------------------------------------------------------------------------
# the CI gate + CLI contract
# ---------------------------------------------------------------------------


def test_repo_is_clean_vs_baseline():
    """THE gate: all five passes over the real tree, zero findings beyond
    the checked-in baseline.  A failure here means a PR introduced a
    fail-open/host-sync/jit-recompile/time-source/unguarded-global hazard
    (fix it or suppress WITH a rationale; see sentinel_tpu/analysis/README.md)."""
    findings = run_passes(
        [os.path.join(REPO_ROOT, "sentinel_tpu")], ALL_PASSES, rel_to=REPO_ROOT
    )
    new = new_findings(findings, load_baseline(DEFAULT_BASELINE))
    assert new == [], "NEW lint findings:\n" + "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in new
    )


def test_cli_exit_codes(tmp_path):
    """Non-zero on a seeded violation, zero on the clean repo."""
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    bad = tmp_path / "sentinel_tpu" / "runtime"
    bad.mkdir(parents=True)
    snippet = bad / "client.py"
    snippet.write_text("import time\n\ndef f():\n    return time.time()\n")

    r = subprocess.run(
        [sys.executable, "-m", "sentinel_tpu.analysis", str(snippet), "--json"],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["new"] == 1
    assert report["findings"][0]["rule"] == "time-source"

    r2 = subprocess.run(
        [sys.executable, "-m", "sentinel_tpu.analysis"],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr


def test_cli_sarif_output(tmp_path):
    """--sarif: valid SARIF 2.1.0 with NEW findings as results (the
    GitHub code-scanning inline-annotation contract); exit code still 1."""
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    bad = tmp_path / "sentinel_tpu" / "runtime"
    bad.mkdir(parents=True)
    snippet = bad / "client.py"
    snippet.write_text("import time\n\ndef f():\n    return time.time()\n")

    r = subprocess.run(
        [sys.executable, "-m", "sentinel_tpu.analysis", str(snippet), "--sarif"],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    sarif = json.loads(r.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "stlint"
    results = run["results"]
    assert len(results) == 1
    assert results[0]["ruleId"] == "time-source"
    assert results[0]["level"] == "error"
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 4
    # the rule metadata block names every rule that fired
    assert [ru["id"] for ru in run["tool"]["driver"]["rules"]] == ["time-source"]

    # --sarif and --json are mutually exclusive (usage error)
    r2 = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(snippet),
            "--sarif", "--json",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r2.returncode == 2


def test_unguarded_global_call_rooted_lock_still_counts():
    """A lock reached through a call has no stable dotted name but must
    still count as a held lock (pre-lockset behavior) — not a false
    'without the owning lock' error."""
    mod = _mod(
        """
        _CACHE = {}

        def put(reg, k, v):
            with reg().lock:
                _CACHE[k] = v
        """
    )
    assert _run(UnguardedGlobalPass(), mod) == []


def test_cli_zero_pass_selection_is_usage_error(tmp_path):
    """--rules naming only the OTHER tier's passes must exit 2, not
    masquerade as a clean run with zero passes executed."""
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    snippet = tmp_path / "probe.py"
    snippet.write_text("import time\n\ndef f():\n    return time.time()\n")
    # explicit path pins tier=ast; const-hoist is jaxpr-tier only
    r = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis",
            str(snippet), "--rules", "const-hoist",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 2, r.stdout + r.stderr
    assert "no pass selected for tier(s) ast" in r.stderr


def test_scoped_update_baseline_preserves_out_of_scope_debt(tmp_path):
    """--update-baseline on a SCOPED run (explicit path) re-measures only
    that scope; accepted entries elsewhere must survive the rewrite or
    the next full run reports old debt as NEW."""
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    tree = tmp_path / "sentinel_tpu" / "runtime"
    tree.mkdir(parents=True)
    a = tree / "a.py"
    b = tree / "b.py"
    a.write_text("import time\n\ndef f():\n    return time.time()\n")
    b.write_text("import time\n\ndef g():\n    return time.time()\n")
    base = tmp_path / "baseline.json"

    # accept both files' debt
    r = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(a), str(b),
            "--baseline", str(base), "--update-baseline",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    accepted = json.loads(base.read_text())["accepted"]
    assert len(accepted) == 2

    # re-update scoped to a.py only: b.py's entry must be preserved
    r2 = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(a),
            "--baseline", str(base), "--update-baseline",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert json.loads(base.read_text())["accepted"] == accepted

    # the full (two-path) run still sees nothing new
    r3 = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(a), str(b),
            "--baseline", str(base),
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r3.returncode == 0, r3.stdout + r3.stderr


def test_rule_catalog_spans_both_tiers():
    """The CLI's SARIF rule metadata and the README catalog are driven
    by rule_catalog(); it must name the AST rules AND the jaxpr rules
    (importing the tier-2 pass classes must NOT trigger a trace)."""
    from sentinel_tpu.analysis import rule_catalog

    cat = rule_catalog()
    assert {
        "fail-open",
        "host-sync",
        "jit-recompile",
        "time-source",
        "unguarded-global",
        "transfer-guard",
        "dtype-overflow",
        "const-hoist",
        "recompile-fingerprint",
        "flops-bytes-budget",
    } <= set(cat)
    assert all(desc for desc in cat.values())


def test_cli_update_baseline_roundtrip(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    bad = tmp_path / "sentinel_tpu" / "runtime"
    bad.mkdir(parents=True)
    snippet = bad / "client.py"
    snippet.write_text("import time\n\ndef f():\n    return time.time()\n")
    base = tmp_path / "baseline.json"

    r = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(snippet),
            "--baseline", str(base), "--update-baseline",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # accepted into the baseline -> the same tree now exits 0...
    r2 = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(snippet),
            "--baseline", str(base),
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    # ...but --no-baseline still sees the debt
    r3 = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(snippet),
            "--baseline", str(base), "--no-baseline",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r3.returncode == 1
