"""sentinel_tpu.analysis — the TPU-hazard linter.

Two jobs:

1. unit-test every pass on fixture snippets, one triggering and one
   non-triggering per rule (plus the suppression syntaxes);
2. THE CI GATE: run all five passes over the real ``sentinel_tpu/`` tree
   and require zero findings beyond the checked-in baseline — this is
   what keeps fail-open/host-sync/jit-recompile/time-source/unguarded-
   global hazards from riding in on future PRs.

Pure AST work — no jax, no engine compiles; this file is cheap.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from sentinel_tpu.analysis import (
    ALL_PASSES,
    DEFAULT_BASELINE,
    REPO_ROOT,
    load_baseline,
    new_findings,
    run_passes,
)
from sentinel_tpu.analysis.framework import (
    ParsedModule,
    parse_suppressions,
)
from sentinel_tpu.analysis.passes import (
    FailOpenPass,
    HostSyncPass,
    JitRecompilePass,
    TimeSourcePass,
    UnguardedGlobalPass,
)


def _mod(source: str, path: str = "sentinel_tpu/runtime/client.py") -> ParsedModule:
    """ParsedModule from an inline snippet; ``path`` controls which
    file-scoped rules engage."""
    source = textwrap.dedent(source)
    line_disables, file_disables = parse_suppressions(source)
    return ParsedModule(
        path=path,
        abspath="/" + path,
        source=source,
        tree=ast.parse(source),
        line_disables=line_disables,
        file_disables=file_disables,
    )


def _run(p, mod):
    return [f for f in p.run(mod) if not mod.suppressed(f.rule, f.line)]


# ---------------------------------------------------------------------------
# time-source
# ---------------------------------------------------------------------------


def test_time_source_triggers_on_raw_clock_and_aliases():
    mod = _mod(
        """
        import time as _time
        from time import monotonic as mono

        def deadline():
            return _time.time() + mono()
        """
    )
    got = _run(TimeSourcePass(), mod)
    assert len(got) == 2
    assert all(f.rule == "time-source" for f in got)


def test_time_source_allows_helpers_perf_counter_and_own_module():
    clean = _mod(
        """
        import time
        from sentinel_tpu.utils.time_source import mono_s

        def f():
            t0 = time.perf_counter()  # profiling-only: allowed
            time.sleep(0.01)          # not a clock READ
            return mono_s() - t0
        """
    )
    assert _run(TimeSourcePass(), clean) == []
    own = _mod(
        "import time\n\ndef now():\n    return time.time()\n",
        path="sentinel_tpu/utils/time_source.py",
    )
    assert _run(TimeSourcePass(), own) == []


# ---------------------------------------------------------------------------
# fail-open
# ---------------------------------------------------------------------------


def test_fail_open_triggers_on_broad_swallow_in_admission_path():
    mod = _mod(
        """
        def check(item):
            try:
                return engine_verdict(item)
            except Exception:
                return PASS
        """
    )
    got = _run(FailOpenPass(), mod)
    assert len(got) == 1 and got[0].rule == "fail-open"


def test_fail_open_ignores_reraise_cleanup_and_out_of_scope_files():
    mod = _mod(
        """
        def check(item):
            try:
                return engine_verdict(item)
            except Exception:
                log()
                raise

        def teardown(sock):
            try:
                sock.close()
            except Exception:
                pass
        """
    )
    assert _run(FailOpenPass(), mod) == []
    # same swallow in a NON-admission file: out of scope
    other = _mod(
        """
        def render(x):
            try:
                return fmt(x)
            except Exception:
                return ""
        """,
        path="sentinel_tpu/dashboard/ui.py",
    )
    assert _run(FailOpenPass(), other) == []


def test_fail_open_suppression_with_rationale():
    mod = _mod(
        """
        def check(item):
            try:
                return consult_token_service(item)
            except Exception:  # stlint: disable=fail-open — degrades to local rules
                return degrade_to_local(item)
        """
    )
    assert _run(FailOpenPass(), mod) == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------


def test_host_sync_triggers_in_jit_zone_and_hot_path():
    mod = _mod(
        """
        import jax
        import numpy as np

        @jax.jit
        def kernel(state, x):
            bad = np.asarray(x)
            return state.sum() + float(x[0])

        def _run_tick(self, acq):
            v = self._tick(acq)
            return v.verdict.item()
        """
    )
    got = _run(HostSyncPass(), mod)
    rules = sorted(set(f.rule for f in got))
    assert rules == ["host-sync"]
    msgs = " | ".join(f.message for f in got)
    assert "numpy.asarray" in msgs  # np materialization inside jit
    assert "float()" in msgs  # traced coercion inside jit
    assert ".item()" in msgs  # sync in the client hot path


def test_host_sync_jit_zone_extends_to_callees_and_allows_static_cfg():
    mod = _mod(
        """
        import functools
        import jax
        import numpy as np

        def tick(state, acq, *, cfg):
            if cfg.seg_effects:          # static branch: fine
                state = _land(state, acq)
            return state

        def _land(state, acq):
            return state + np.asarray(acq)   # callee of a jitted root

        def make_tick(cfg):
            fn = functools.partial(tick, cfg=cfg)
            fn = jax.jit(fn, donate_argnums=(0,))
            return fn

        def host_prep(cols):
            return np.asarray(cols)      # not reachable from any root
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(HostSyncPass(), mod)
    assert len(got) == 1, [f.message for f in got]
    assert "_land" in got[0].message


def test_host_sync_clean_dispatch_is_clean():
    mod = _mod(
        """
        import numpy as np

        def _run_tick(self, acq):
            cols = np.zeros(len(acq), np.int32)   # host batch assembly: fine
            return self._tick(self._dev(cols))
        """
    )
    assert _run(HostSyncPass(), mod) == []


# ---------------------------------------------------------------------------
# jit-recompile
# ---------------------------------------------------------------------------


def test_jit_recompile_triggers_on_callsite_jit_loop_jit_and_traced_branch():
    mod = _mod(
        """
        import jax

        def per_call(x):
            return jax.jit(lambda y: y + 1)(x)

        def in_loop(xs):
            out = []
            for x in xs:
                out.append(jax.jit(step))
            return out

        @jax.jit
        def branchy(state, now_ms, *, cfg):
            if now_ms > 0:
                return state
            return state * 2
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(JitRecompilePass(), mod)
    msgs = " | ".join(f.message for f in got)
    assert "invoked at its own call site" in msgs
    assert "inside a loop" in msgs
    assert "traced parameter 'now_ms'" in msgs


def test_jit_recompile_flags_mutable_module_closure():
    mod = _mod(
        """
        import jax

        _REGISTRY = {}

        @jax.jit
        def kernel(x):
            return x * len(_REGISTRY)
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(JitRecompilePass(), mod)
    assert any("_REGISTRY" in f.message for f in got)


def test_jit_recompile_clean_cached_factory_is_clean():
    mod = _mod(
        """
        import functools
        import threading
        import jax

        _CACHE = {}
        _LOCK = threading.Lock()

        def tick(state, acq, *, cfg):
            return state if cfg.flag else state * 2

        def make_tick(cfg):
            with _LOCK:
                fn = _CACHE.get(cfg)
                if fn is None:
                    fn = functools.partial(tick, cfg=cfg)
                    fn = jax.jit(fn)
                    _CACHE[cfg] = fn
            return fn
        """,
        path="sentinel_tpu/ops/engine.py",
    )
    got = _run(JitRecompilePass(), mod)
    # `tick` is jitted via the two-step idiom; its cfg branch is static
    # and the cache write is lock-guarded -> nothing to report
    assert got == [], [f.message for f in got]


# ---------------------------------------------------------------------------
# unguarded-global
# ---------------------------------------------------------------------------


def test_unguarded_global_triggers_on_lockless_registry_write():
    mod = _mod(
        """
        _HANDLERS = {}
        _ORDER: list = []

        def register(name, fn):
            _HANDLERS[name] = fn
            _ORDER.append(name)
        """
    )
    got = _run(UnguardedGlobalPass(), mod)
    assert len(got) == 2
    assert all(f.rule == "unguarded-global" for f in got)


def test_unguarded_global_lock_guarded_and_local_shadows_are_clean():
    mod = _mod(
        """
        import threading

        _HANDLERS = {}
        _lock = threading.Lock()

        def register(name, fn):
            with _lock:
                _HANDLERS[name] = fn

        def local_work():
            tmp = {}
            tmp["k"] = 1      # local, not the module global
            return tmp
        """
    )
    assert _run(UnguardedGlobalPass(), mod) == []


def test_unguarded_global_catches_global_rebind():
    mod = _mod(
        """
        _EXTS: list = []

        def clear():
            global _EXTS
            _EXTS = []
        """
    )
    got = _run(UnguardedGlobalPass(), mod)
    assert len(got) == 1 and "rebound" in got[0].message


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------


def test_suppression_next_line_and_file_scope():
    mod = _mod(
        """
        # stlint: disable-file=time-source reason: fixture file
        import time

        def a():
            return time.time()

        def b():
            try:
                return check()
            # stlint: disable-next-line=fail-open
            except Exception:
                return 0
        """
    )
    assert _run(TimeSourcePass(), mod) == []
    assert _run(FailOpenPass(), mod) == []


def test_suppression_shares_comment_with_noqa():
    mod = _mod(
        """
        import time

        def f():
            return time.time()  # noqa: X100  # stlint: disable=time-source — fixture
        """
    )
    assert _run(TimeSourcePass(), mod) == []


# ---------------------------------------------------------------------------
# the CI gate + CLI contract
# ---------------------------------------------------------------------------


def test_repo_is_clean_vs_baseline():
    """THE gate: all five passes over the real tree, zero findings beyond
    the checked-in baseline.  A failure here means a PR introduced a
    fail-open/host-sync/jit-recompile/time-source/unguarded-global hazard
    (fix it or suppress WITH a rationale; see sentinel_tpu/analysis/README.md)."""
    findings = run_passes(
        [os.path.join(REPO_ROOT, "sentinel_tpu")], ALL_PASSES, rel_to=REPO_ROOT
    )
    new = new_findings(findings, load_baseline(DEFAULT_BASELINE))
    assert new == [], "NEW lint findings:\n" + "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in new
    )


def test_cli_exit_codes(tmp_path):
    """Non-zero on a seeded violation, zero on the clean repo."""
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    bad = tmp_path / "sentinel_tpu" / "runtime"
    bad.mkdir(parents=True)
    snippet = bad / "client.py"
    snippet.write_text("import time\n\ndef f():\n    return time.time()\n")

    r = subprocess.run(
        [sys.executable, "-m", "sentinel_tpu.analysis", str(snippet), "--json"],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["new"] == 1
    assert report["findings"][0]["rule"] == "time-source"

    r2 = subprocess.run(
        [sys.executable, "-m", "sentinel_tpu.analysis"],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr


def test_cli_update_baseline_roundtrip(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    bad = tmp_path / "sentinel_tpu" / "runtime"
    bad.mkdir(parents=True)
    snippet = bad / "client.py"
    snippet.write_text("import time\n\ndef f():\n    return time.time()\n")
    base = tmp_path / "baseline.json"

    r = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(snippet),
            "--baseline", str(base), "--update-baseline",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # accepted into the baseline -> the same tree now exits 0...
    r2 = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(snippet),
            "--baseline", str(base),
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    # ...but --no-baseline still sees the debt
    r3 = subprocess.run(
        [
            sys.executable, "-m", "sentinel_tpu.analysis", str(snippet),
            "--baseline", str(base), "--no-baseline",
        ],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r3.returncode == 1
