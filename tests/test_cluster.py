"""Cluster token backend tests.

Mirrors the reference's cluster test strategy (SURVEY.md §4.4): checker
logic against in-memory state, codec round-trips, connection bookkeeping —
plus a real localhost TCP server/client end-to-end loop the reference never
had.
"""

import threading
import time

import pytest

from sentinel_tpu.cluster import constants as C
from sentinel_tpu.cluster import protocol as P
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.rules import ClusterServerConfigManager, ServerFlowConfig
from sentinel_tpu.cluster.server import ClusterTokenServer
from sentinel_tpu.cluster.state import ClusterStateManager
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core import rules as R
from sentinel_tpu.utils.host_window import HostWindow


def cluster_flow_rule(flow_id=101, count=5.0, threshold_type=C.FLOW_THRESHOLD_GLOBAL):
    return R.FlowRule(
        resource=f"res-{flow_id}",
        count=count,
        cluster_mode=True,
        cluster_flow_id=flow_id,
        cluster_threshold_type=threshold_type,
    )


# ---------------------------------------------------------------------------
# codec round-trips (ParamFlowRequestDataWriterTest / FlowResponseDataDecoderTest)
# ---------------------------------------------------------------------------


def test_protocol_flow_roundtrip():
    req = P.ClusterRequest(xid=7, type=C.MSG_TYPE_FLOW, flow_id=12345678901, count=3, priority=True)
    frames = P.FrameReader().feed(P.encode_request(req))
    assert len(frames) == 1
    got = P.decode_request(frames[0])
    assert (got.xid, got.type, got.flow_id, got.count, got.priority) == (
        7, C.MSG_TYPE_FLOW, 12345678901, 3, True,
    )


def test_protocol_param_roundtrip():
    params = [42, 2**40, 3.5, "user-x", True]
    req = P.ClusterRequest(xid=9, type=C.MSG_TYPE_PARAM_FLOW, flow_id=5, count=1, params=params)
    got = P.decode_request(P.FrameReader().feed(P.encode_request(req))[0])
    assert got.params == params


def test_protocol_response_and_partial_frames():
    rsp = P.ClusterResponse(xid=3, type=C.MSG_TYPE_FLOW, status=C.STATUS_SHOULD_WAIT,
                            remaining=10, wait_ms=250)
    raw = P.encode_response(rsp)
    r = P.FrameReader()
    assert r.feed(raw[:3]) == []  # partial frame buffers
    frames = r.feed(raw[3:])
    got = P.decode_response(frames[0])
    assert (got.status, got.wait_ms, got.remaining) == (C.STATUS_SHOULD_WAIT, 250, 10)


def test_protocol_concurrent_roundtrip():
    req = P.ClusterRequest(xid=1, type=C.MSG_TYPE_CONCURRENT_RELEASE, token_id=99)
    assert P.decode_request(P.FrameReader().feed(P.encode_request(req))[0]).token_id == 99
    rsp = P.ClusterResponse(xid=1, type=C.MSG_TYPE_CONCURRENT_ACQUIRE,
                            status=C.STATUS_OK, token_id=77)
    assert P.decode_response(P.FrameReader().feed(P.encode_response(rsp))[0]).token_id == 77


# ---------------------------------------------------------------------------
# trace-context tail: version tolerance both ways
# ---------------------------------------------------------------------------

import struct  # noqa: E402 — the back-compat tests re-implement the legacy reader


def test_traced_frames_roundtrip_all_types():
    """(trace_id, span_id) survives encode→decode for every request type
    that carries it and for responses (echoed server-side)."""
    tid, sid = 0xABCDEF0123456789, 0x1122334455667788
    for req in (
        P.ClusterRequest(xid=1, type=C.MSG_TYPE_FLOW, flow_id=5, count=2,
                         priority=True, trace_id=tid, span_id=sid),
        P.ClusterRequest(xid=2, type=C.MSG_TYPE_FLOW_BATCH, flow_id=5, count=9,
                         trace_id=tid, span_id=sid),
        P.ClusterRequest(xid=3, type=C.MSG_TYPE_PARAM_FLOW, flow_id=5, count=1,
                         params=[42, "user-x", True], trace_id=tid, span_id=sid),
        P.ClusterRequest(xid=4, type=C.MSG_TYPE_CONCURRENT_ACQUIRE, flow_id=5,
                         trace_id=tid, span_id=sid),
        P.ClusterRequest(xid=5, type=C.MSG_TYPE_CONCURRENT_RELEASE, token_id=7,
                         trace_id=tid, span_id=sid),
        P.ClusterRequest(xid=6, type=C.MSG_TYPE_RES_CHECK,
                         params=["r", 1, False, "", ""], trace_id=tid, span_id=sid),
    ):
        got = P.decode_request(P.FrameReader().feed(P.encode_request(req))[0])
        assert (got.trace_id, got.span_id) == (tid, sid), req.type
        assert got.params == req.params and got.flow_id == req.flow_id
    rsp = P.ClusterResponse(xid=9, type=C.MSG_TYPE_FLOW, status=C.STATUS_OK,
                            remaining=3, wait_ms=10, trace_id=tid, span_id=sid)
    got = P.decode_response(P.FrameReader().feed(P.encode_response(rsp))[0])
    assert (got.trace_id, got.span_id) == (tid, sid)
    assert (got.remaining, got.wait_ms) == (3, 10)


def test_untraced_frames_are_byte_identical_to_legacy_format():
    """With no trace context the wire format is bit-exact the pre-trace
    encoding — a tracing-off deployment interoperates with ANY version."""
    req = P.ClusterRequest(xid=7, type=C.MSG_TYPE_FLOW, flow_id=12, count=3,
                           priority=True)
    legacy = struct.pack(">iB", 7, C.MSG_TYPE_FLOW) + struct.pack(">qiB", 12, 3, 1)
    assert P.encode_request(req) == struct.pack(">H", len(legacy)) + legacy
    rsp = P.ClusterResponse(xid=7, type=C.MSG_TYPE_FLOW, status=C.STATUS_OK,
                            remaining=2, wait_ms=0)
    legacy_r = struct.pack(">iBb", 7, C.MSG_TYPE_FLOW, C.STATUS_OK) + struct.pack(">ii", 2, 0)
    assert P.encode_response(rsp) == struct.pack(">H", len(legacy_r)) + legacy_r
    # and legacy frames (no tail) decode on the new reader with ctx == 0
    got = P.decode_request(P.FrameReader().feed(P.encode_request(req))[0])
    assert (got.trace_id, got.span_id) == (0, 0)
    got_r = P.decode_response(P.FrameReader().feed(P.encode_response(rsp))[0])
    assert (got_r.trace_id, got_r.span_id) == (0, 0)


def test_legacy_reader_skips_trace_tail_on_fixed_and_response_frames():
    """A pre-trace reader parsed fixed-size payloads by offset and
    count-bounded item lists — both skip the appended tail untouched.
    (Re-implemented here exactly as the legacy decoder read the wire.)"""
    tid, sid = 0x1234, 0x5678
    raw = P.encode_request(
        P.ClusterRequest(xid=3, type=C.MSG_TYPE_FLOW, flow_id=11, count=4,
                         priority=False, trace_id=tid, span_id=sid)
    )
    body = P.FrameReader().feed(raw)[0]
    xid, t = struct.unpack_from(">iB", body, 0)
    flow_id, count, prio = struct.unpack_from(">qiB", body[5:], 0)  # legacy parse
    assert (xid, t, flow_id, count, prio) == (3, C.MSG_TYPE_FLOW, 11, 4, 0)

    rsp = P.ClusterResponse(xid=4, type=C.MSG_TYPE_RES_CHECK, status=C.STATUS_OK,
                            items=[(0, 0), (4, 9)], trace_id=tid, span_id=sid)
    body = P.FrameReader().feed(P.encode_response(rsp))[0]
    xid, t, status = struct.unpack_from(">iBb", body, 0)
    p = body[6:]
    (n,) = struct.unpack_from(">i", p, 0)
    items, off = [], 4
    for _ in range(n):  # the legacy count-bounded item loop
        v, w = struct.unpack_from(">bi", p, off)
        off += 5
        items.append((v, w))
    assert items == [(0, 0), (4, 9)]


def test_tcp_roundtrip_carries_trace_context_end_to_end(tcp_cluster, tmp_path):
    """ISSUE-5 acceptance over the REAL wire: tracing on both ends of a
    SentinelClient↔ClusterTokenServer round-trip, the client's
    cluster.rpc span and the server's token.decision span share one wire
    trace id (parent = the RPC span id), and the per-endpoint dumps
    --merge into one Chrome trace with a flow event linking them."""
    import json as _json

    from sentinel_tpu import obs
    from sentinel_tpu.obs.__main__ import merge_traces

    server, tok, svc = tcp_cluster
    obs.TRACER.reset()
    obs.enable()
    try:
        assert tok.request_token(101).status in (C.STATUS_OK, C.STATUS_BLOCKED)
    finally:
        obs.disable()
    spans = obs.TRACER.snapshot()
    rpc = [s for s in spans if s["name"] == "cluster.rpc"]
    dec = [s for s in spans if s["name"] == "token.decision"]
    assert rpc and dec
    links = [
        (r, d)
        for r in rpc
        for d in dec
        if d["attrs"].get("parent") == r["attrs"].get("span_id")
    ]
    assert links, f"no parent link: rpc={rpc} dec={dec}"
    r, d = links[0]
    assert r["trace"] == d["trace"] != 0

    # the context crossed a real socket (client and server halves run in
    # one test process but share NOTHING except the wire frames) — dump
    # each endpoint's spans as its own process and merge
    client_doc = obs.TRACER.chrome_trace(rpc)
    server_doc = obs.TRACER.chrome_trace(dec)
    for e in server_doc["traceEvents"]:
        e["pid"] += 1  # the server's own dump would carry its own pid
    a, b = tmp_path / "client.json", tmp_path / "server.json"
    a.write_text(_json.dumps(client_doc))
    b.write_text(_json.dumps(server_doc))
    doc = merge_traces([str(a), str(b)])
    assert doc["otherData"]["flow_links"] >= 1
    flow_ids = {e["id"] for e in doc["traceEvents"] if e.get("ph") in ("s", "f")}
    assert r["attrs"]["span_id"] in flow_ids


# ---------------------------------------------------------------------------
# host window / namespace guard
# ---------------------------------------------------------------------------


def test_host_window_try_pass_and_expiry():
    w = HostWindow(10, 1000)
    t = 10_000
    for _ in range(5):
        assert w.try_pass(t, limit_qps=5.0)
    assert not w.try_pass(t, limit_qps=5.0)
    # window slides: 1.1 s later all buckets expired
    assert w.try_pass(t + 1100, limit_qps=5.0)


# ---------------------------------------------------------------------------
# token service decisions (ClusterFlowCheckerTest analog)
# ---------------------------------------------------------------------------


def test_request_token_blocks_over_global_threshold(client, vt):
    svc = DefaultTokenService(client)
    svc.flow_rules.load("default", [cluster_flow_rule(count=5.0)])
    got = [svc.request_token(101).status for _ in range(7)]
    assert got.count(C.STATUS_OK) == 5
    assert got.count(C.STATUS_BLOCKED) == 2
    vt.advance(1100)  # window rolls → tokens replenish
    assert svc.request_token(101).status == C.STATUS_OK


def test_request_token_no_rule(client):
    svc = DefaultTokenService(client)
    assert svc.request_token(999).status == C.STATUS_NO_RULE


def test_avg_local_threshold_scales_with_connections(client):
    svc = DefaultTokenService(client)
    svc.connected_count_fn = lambda ns: 3
    svc.flow_rules.load(
        "default", [cluster_flow_rule(count=2.0, threshold_type=C.FLOW_THRESHOLD_AVG_LOCAL)]
    )
    svc.refresh_connected_count()
    got = [svc.request_token(101).status for _ in range(8)]
    assert got.count(C.STATUS_OK) == 6  # 2 × 3 connections


def test_namespace_guard_too_many(client):
    cfgm = ClusterServerConfigManager()
    cfgm.set_flow_config("default", ServerFlowConfig(max_allowed_qps=3.0))
    svc = DefaultTokenService(client, config=cfgm)
    svc.flow_rules.load("default", [cluster_flow_rule(count=100.0)])
    got = [svc.request_token(101).status for _ in range(5)]
    assert got.count(C.STATUS_OK) == 3
    assert got.count(C.STATUS_TOO_MANY_REQUEST) == 2


def test_param_token(client, vt):
    svc = DefaultTokenService(client)
    rule = R.ParamFlowRule(
        resource="p", count=2.0, cluster_mode=True, cluster_flow_id=55, duration_in_sec=1
    )
    svc.param_rules.load("default", [rule])
    assert svc.request_param_token(55, 1, ["alice"]).status == C.STATUS_OK
    assert svc.request_param_token(55, 1, ["alice"]).status == C.STATUS_OK
    assert svc.request_param_token(55, 1, ["alice"]).status == C.STATUS_BLOCKED
    # different value has its own budget
    assert svc.request_param_token(55, 1, ["bob"]).status == C.STATUS_OK


def test_concurrent_tokens_and_expiry(client, vt):
    svc = DefaultTokenService(client, concurrent_ttl_ms=1000)
    svc.flow_rules.load("default", [cluster_flow_rule(count=2.0)])
    r1 = svc.request_concurrent_token(101)
    r2 = svc.request_concurrent_token(101)
    assert r1.ok and r2.ok and r1.token_id != r2.token_id
    assert svc.request_concurrent_token(101).blocked
    assert svc.release_concurrent_token(r1.token_id).status == C.STATUS_RELEASE_OK
    assert svc.release_concurrent_token(r1.token_id).status == C.STATUS_ALREADY_RELEASE
    assert svc.request_concurrent_token(101).ok
    # TTL sweep frees leaked tokens (RegularExpireStrategy)
    vt.advance(1500)
    svc.concurrent.expire(vt.now_ms())
    assert svc.concurrent.current(101) == 0
    assert svc.request_concurrent_token(101).ok


# ---------------------------------------------------------------------------
# TCP end-to-end (server + client over localhost)
# ---------------------------------------------------------------------------


@pytest.fixture()
def tcp_cluster(client_factory):
    decision = client_factory()
    svc = DefaultTokenService(decision)
    svc.flow_rules.load("default", [cluster_flow_rule(count=3.0)])
    server = ClusterTokenServer(svc, host="127.0.0.1", port=0)
    server.start()
    tok = ClusterTokenClient("127.0.0.1", server.port, namespace="default", timeout_ms=5000)
    tok.start()
    yield server, tok, svc
    tok.close()
    server.stop()


def test_tcp_token_roundtrip(tcp_cluster):
    server, tok, svc = tcp_cluster
    got = [tok.request_token(101).status for _ in range(5)]
    assert got.count(C.STATUS_OK) == 3
    assert got.count(C.STATUS_BLOCKED) == 2
    assert tok.request_token(31337).status == C.STATUS_NO_RULE


def test_tcp_connection_census(tcp_cluster):
    server, tok, svc = tcp_cluster
    deadline = time.monotonic() + 2
    while server.connections.connected_count("default") < 1:
        assert time.monotonic() < deadline, "PING registration not observed"
        time.sleep(0.01)


def test_tcp_token_batch_partial_grant(tcp_cluster):
    """FLOW_BATCH: one roundtrip, server grants k of n units (limit 3)."""
    server, tok, svc = tcp_cluster
    r = tok.request_token_batch(101, 5)
    assert r.status == C.STATUS_OK and r.remaining == 3
    r2 = tok.request_token_batch(101, 5)
    assert r2.status == C.STATUS_BLOCKED and r2.remaining == 0


def test_tcp_concurrent_roundtrip(tcp_cluster):
    server, tok, svc = tcp_cluster
    r = tok.request_concurrent_token(101)
    assert r.ok and r.token_id > 0
    assert tok.release_concurrent_token(r.token_id).status == C.STATUS_RELEASE_OK


def test_client_fail_fast_when_server_down():
    tok = ClusterTokenClient("127.0.0.1", 1, timeout_ms=100, reconnect_interval_s=0.0)
    assert tok.request_token(1).status == C.STATUS_FAIL


# ---------------------------------------------------------------------------
# runtime integration: embedded server + degrade-to-local
# ---------------------------------------------------------------------------


def test_embedded_cluster_entry_flow(client_factory):
    app = client_factory()
    decision = client_factory()
    svc = DefaultTokenService(decision)
    svc.flow_rules.load("default", [cluster_flow_rule(flow_id=101, count=2.0)])

    mgr = ClusterStateManager()
    mgr.set_to_server(svc, serve_network=False)
    app.set_cluster(mgr)
    rule = cluster_flow_rule(flow_id=101, count=2.0)
    app.flow_rules.load([rule])

    ok = blocked = 0
    for _ in range(5):
        try:
            e = app.entry("res-101")
            e.exit()
            ok += 1
        except ERR.FlowException:
            blocked += 1
    assert ok == 2 and blocked == 3
    # blocks were recorded into the app's own stat windows (pre_verdict path)
    s = app.stats.resource("res-101")
    assert s["blockQps"] > 0


def test_cluster_degrades_to_local_when_unavailable(client_factory):
    app = client_factory()
    mgr = ClusterStateManager()  # NOT_STARTED: no token service
    app.set_cluster(mgr)
    app.flow_rules.load([cluster_flow_rule(flow_id=7, count=2.0)])

    ok = blocked = 0
    for _ in range(5):
        try:
            app.entry("res-7").exit()
            ok += 1
        except ERR.FlowException:
            blocked += 1
    # degraded → the cluster rule enforces locally (fallbackToLocalOrPass)
    assert ok == 2 and blocked == 3


def test_check_batch_enforces_cluster_rules(client_factory):
    """The bulk API must consult the token service too (not just entry())."""
    app = client_factory()
    decision = client_factory()
    svc = DefaultTokenService(decision)
    svc.flow_rules.load("default", [cluster_flow_rule(flow_id=501, count=2.0)])
    mgr = ClusterStateManager()
    mgr.set_to_server(svc, serve_network=False)
    app.set_cluster(mgr)
    app.flow_rules.load([cluster_flow_rule(flow_id=501, count=2.0)])

    results = app.check_batch(["res-501"] * 5)
    verdicts = [v for v, _ in results]
    assert verdicts.count(ERR.PASS) == 2
    assert verdicts.count(ERR.BLOCK_FLOW) == 3


def test_too_many_request_degrades_to_local(client_factory):
    """Namespace-guard overload must fall back to local enforcement, not
    hard-block everything (applyTokenResult groups TOO_MANY with FAIL)."""
    from sentinel_tpu.cluster.token_service import TokenResult, TokenService

    class OverloadedService(TokenService):
        def request_token(self, flow_id, count=1, prioritized=False):
            return TokenResult(C.STATUS_TOO_MANY_REQUEST)

    class FakeMgr:
        def token_service(self):
            return OverloadedService()

    app = client_factory()
    app.set_cluster(FakeMgr())
    app.flow_rules.load([cluster_flow_rule(flow_id=9, count=2.0)])

    ok = blocked = 0
    for _ in range(5):
        try:
            app.entry("res-9").exit()
            ok += 1
        except ERR.FlowException:
            blocked += 1
    # local fallback enforces count=2, nothing hard-blocks on TOO_MANY itself
    assert ok == 2 and blocked == 3


def test_degraded_probe_recovers_without_unenforced_window(client_factory):
    """While degraded, fallback rules stay compiled through probes; a probe
    response flips back to remote enforcement."""
    from sentinel_tpu.cluster.token_service import TokenResult, TokenService

    class FlappingService(TokenService):
        def __init__(self):
            self.up = False
            self.calls = 0

        def request_token(self, flow_id, count=1, prioritized=False):
            self.calls += 1
            return TokenResult(C.STATUS_OK if self.up else C.STATUS_FAIL)

    svc = FlappingService()

    class Mgr:
        def token_service(self):
            return svc

    app = client_factory()
    app.set_cluster(Mgr())
    app.cluster_retry_interval_s = 0.0  # every entry re-probes
    app.flow_rules.load([cluster_flow_rule(flow_id=11, count=100.0)])

    app.entry("res-11").exit()  # FAIL → degraded
    assert app._cluster_degraded_active
    app.entry("res-11").exit()  # probe still failing → stays degraded
    assert app._cluster_degraded_active
    svc.up = True
    app.entry("res-11").exit()  # probe succeeds → back to remote
    assert not app._cluster_degraded_active


def test_cluster_no_fallback_passes_when_unavailable(client_factory):
    app = client_factory()
    app.set_cluster(ClusterStateManager())
    r = cluster_flow_rule(flow_id=8, count=1.0)
    r.cluster_fallback_to_local = False
    app.flow_rules.load([r])
    for _ in range(4):
        app.entry("res-8").exit()  # no fallback → pass-through


def test_authority_blocked_request_consumes_no_cluster_token(client_factory):
    """Slot-order parity with the reference (FlowRuleChecker.java:64-72 —
    cluster tokens are requested inside FlowSlot, AFTER AuthoritySlot): a
    blacklisted-origin request must be rejected WITHOUT consuming a
    cluster token (VERDICT r4 weak #6)."""
    from sentinel_tpu.cluster.token_service import TokenResult, TokenService

    class CountingService(TokenService):
        def __init__(self):
            self.calls = 0

        def request_token(self, flow_id, count=1, prioritized=False):
            self.calls += 1
            return TokenResult(C.STATUS_OK)

        def request_token_batch(self, flow_id, count=1):
            self.calls += 1
            r = TokenResult(C.STATUS_OK)
            r.remaining = count
            return r

    svc = CountingService()

    class Mgr:
        def token_service(self):
            return svc

    app = client_factory()
    app.set_cluster(Mgr())
    app.flow_rules.load([cluster_flow_rule(flow_id=77, count=100.0)])
    app.authority_rules.load(
        [R.AuthorityRule(resource="res-77", limit_app="badcaller",
                         strategy=R.AUTHORITY_BLACK)]
    )

    # blacklisted origin: engine rejects, token service never consulted
    with pytest.raises(ERR.AuthorityException):
        app.entry("res-77", origin="badcaller")
    assert svc.calls == 0

    # allowed origin: token consumed as usual
    app.entry("res-77", origin="goodcaller").exit()
    assert svc.calls == 1

    # bulk path: the doomed item is excluded from the group's token count
    out = app.check_batch(
        ["res-77", "res-77"], origins=["badcaller", "goodcaller"]
    )
    assert out[0][0] == ERR.BLOCK_AUTHORITY and out[1][0] == ERR.PASS
    assert svc.calls == 2

    # white-list form: an unlisted origin is equally doomed -> no token
    app.authority_rules.load(
        [R.AuthorityRule(resource="res-77", limit_app="goodcaller",
                         strategy=R.AUTHORITY_WHITE)]
    )
    with pytest.raises(ERR.AuthorityException):
        app.entry("res-77", origin="stranger")
    assert svc.calls == 2


def test_authority_mirror_two_rules_last_wins(client_factory):
    """ADVICE r5 medium, case (1): two authority rules on one resource —
    compile_authority_rules must apply TRUE last-wins (zero the origin
    slots before each write) so the device matches exactly the rule the
    host mirror keeps, not the union of both rules' origins."""
    from sentinel_tpu.core.rule_tensors import AUTH_EMPTY, compile_authority_rules

    app = client_factory()
    rules = [
        R.AuthorityRule(resource="res-au", limit_app="alpha,beta",
                        strategy=R.AUTHORITY_WHITE),
        R.AuthorityRule(resource="res-au", limit_app="gamma",
                        strategy=R.AUTHORITY_WHITE),
    ]
    rid = app.registry.resource_id("res-au")
    for o in ("alpha", "beta", "gamma"):
        app.registry.origin_id(o)
    t = compile_authority_rules(rules, app.cfg, app.registry)
    live = sorted(int(x) for x in t.origins[rid] if x != AUTH_EMPTY)
    assert live == [app.registry.origin_id("gamma")], (
        "first rule's origins must be cleared, not unioned"
    )

    # behavioral check through the engine: alpha (only in the OVERWRITTEN
    # rule) must now be rejected, gamma passes — and the mirror agrees,
    # so neither side opens a device-pass/mirror-block divergence
    app.authority_rules.load(rules)
    with pytest.raises(ERR.AuthorityException):
        app.entry("res-au", origin="alpha")
    app.entry("res-au", origin="gamma").exit()
    assert app._authority_pre_blocks("res-au", "alpha") is True
    assert app._authority_pre_blocks("res-au", "gamma") is False


def test_authority_mirror_unintered_origin_never_preblocks(client_factory):
    """ADVICE r5 medium, case (2): a rule origin past the intern cap is
    stored as -1 device-side, where it matches every un-interned request
    origin (device-LENIENT under WHITE).  The host mirror must therefore
    never pre-block for such a rule — otherwise a WHITE request the
    device passes would skip _cluster_check, opening an unenforced
    cluster-limit window."""
    app = client_factory()
    # exhaust the origin intern space so the NEXT origin fails to intern
    app.registry.MAX_ORIGINS = len(app.registry._origin_names) + 1
    app.registry.origin_id("filler-origin")
    assert app.registry.origin_id("vip-app") == -1  # past the cap

    app.authority_rules.load(
        [R.AuthorityRule(resource="res-au2", limit_app="vip-app",
                         strategy=R.AUTHORITY_WHITE)]
    )
    # device side: request origin "someone-else" is also un-interned (-1),
    # matches the rule's -1 slot -> device passes; the mirror must agree
    assert app._authority_pre_blocks("res-au2", "someone-else") is False
    assert app._authority_pre_blocks("res-au2", "vip-app") is False
    app.entry("res-au2", origin="someone-else").exit()

    # and the cluster token service still gets consulted for that traffic
    from sentinel_tpu.cluster.token_service import TokenResult, TokenService

    class CountingService(TokenService):
        def __init__(self):
            self.calls = 0

        def request_token(self, flow_id, count=1, prioritized=False):
            self.calls += 1
            return TokenResult(C.STATUS_OK)

    svc = CountingService()

    class Mgr:
        def token_service(self):
            return svc

    app.set_cluster(Mgr())
    app.flow_rules.load([R.FlowRule(resource="res-au2", count=100.0,
                                    cluster_mode=True, cluster_flow_id=909)])
    app.entry("res-au2", origin="someone-else").exit()
    assert svc.calls == 1, (
        "mirror pre-blocked device-passing traffic: cluster limit unenforced"
    )


# ---------------------------------------------------------------------------
# protocol v2: batched frames, HELLO negotiation, fail-closed framing
# ---------------------------------------------------------------------------

import socket  # noqa: E402

import numpy as np  # noqa: E402


def _batch_req(xid=11, tid=0, sid=0):
    return P.ClusterBatchRequest(
        xid=xid,
        kinds=np.array([C.BATCH_KIND_FLOW, C.BATCH_KIND_FLOW_BATCH, C.BATCH_KIND_LEASE], np.uint8),
        ids=np.array([101, 2**40, -7], np.int64),
        counts=np.array([1, 500, 32], np.int32),
        flags=np.array([C.BATCH_FLAG_PRIORITIZED, 0, 0], np.uint8),
        trace_id=tid,
        span_id=sid,
    )


def test_batch_frame_roundtrip_with_and_without_trace_tail():
    for tid, sid in ((0, 0), (0xABCDEF0123456789, 0x1122334455667788)):
        got = P.decode_batch_request(P.FrameReader().feed(
            P.encode_batch_request(_batch_req(tid=tid, sid=sid)))[0])
        want = _batch_req(tid=tid, sid=sid)
        assert got.xid == 11 and (got.trace_id, got.span_id) == (tid, sid)
        for f in ("kinds", "ids", "counts", "flags"):
            assert np.array_equal(getattr(got, f), getattr(want, f)), f
        rsp = P.ClusterBatchResponse(
            xid=11, status=C.STATUS_OK,
            statuses=np.array([C.STATUS_OK, C.STATUS_BLOCKED, C.STATUS_FAIL], np.int8),
            remainings=np.array([3, 0, -1], np.int32),
            waits=np.array([0, 250, 0], np.int32),
            token_ids=np.array([0, 0, 2**50], np.int64),
            trace_id=tid, span_id=sid,
        )
        got_r = P.decode_batch_response(P.FrameReader().feed(P.encode_batch_response(rsp))[0])
        assert (got_r.status, got_r.trace_id, got_r.span_id) == (C.STATUS_OK, tid, sid)
        for f in ("statuses", "remainings", "waits", "token_ids"):
            assert np.array_equal(getattr(got_r, f), getattr(rsp, f)), f


def test_batch_frame_golden_bytes():
    """Pin the v2 wire layout byte-for-byte: a future refactor that
    shifts a field breaks THIS test, not a live fleet mid-upgrade."""
    req = P.ClusterBatchRequest(
        xid=7,
        kinds=np.array([C.BATCH_KIND_FLOW], np.uint8),
        ids=np.array([12], np.int64),
        counts=np.array([3], np.int32),
        flags=np.array([1], np.uint8),
    )
    body = struct.pack(">iBH", 7, C.MSG_TYPE_BATCH, 1) + struct.pack(">BqiB", C.BATCH_KIND_FLOW, 12, 3, 1)
    assert P.encode_batch_request(req) == struct.pack(">H", len(body)) + body
    rsp = P.ClusterBatchResponse(
        xid=7, status=C.STATUS_OK,
        statuses=np.array([C.STATUS_BLOCKED], np.int8),
        remainings=np.array([2], np.int32),
        waits=np.array([9], np.int32),
        token_ids=np.array([5], np.int64),
    )
    body_r = struct.pack(">iBbH", 7, C.MSG_TYPE_BATCH, C.STATUS_OK, 1) + struct.pack(
        ">biiq", C.STATUS_BLOCKED, 2, 9, 5
    )
    assert P.encode_batch_response(rsp) == struct.pack(">H", len(body_r)) + body_r
    # and the type byte sits where peek_type reads it, on BOTH frames
    assert P.peek_type(body) == C.MSG_TYPE_BATCH == P.peek_type(body_r)


def test_batch_frame_strict_length_rejects_whole_frame():
    """_batch_payload: any length that is not exactly n entries (plus an
    optional well-formed trace block) rejects the WHOLE frame — a
    corrupted count byte or short read never yields partial entries."""
    raw = P.encode_batch_request(_batch_req())
    body = bytearray(P.FrameReader().feed(raw)[0])
    with pytest.raises(ValueError):
        P.decode_batch_request(bytes(body[:-1]))  # short read
    mangled = bytearray(body)
    mangled[6] ^= 0xFF  # count byte bit-flip -> slab length mismatch
    with pytest.raises(ValueError):
        P.decode_batch_request(bytes(mangled))
    with pytest.raises(ValueError):
        P.decode_batch_request(bytes(body) + b"x")  # trailing garbage


def test_hello_negotiation_flips_client_to_v2(tcp_cluster):
    server, tok, svc = tcp_cluster
    assert tok.request_token(101).status in (C.STATUS_OK, C.STATUS_BLOCKED)
    deadline = time.monotonic() + 2
    while tok.peer_version < 2:
        assert time.monotonic() < deadline, "HELLO response not observed"
        time.sleep(0.01)


def test_request_batch_v2_end_to_end(tcp_cluster):
    """One BATCH frame, many flows: per-entry verdicts match the
    sequential semantics of the device column batcher (limit 3)."""
    server, tok, svc = tcp_cluster
    assert tok.request_token(101).ok  # also completes HELLO negotiation
    results = tok.request_batch([
        (C.BATCH_KIND_FLOW, 101, 1),
        (C.BATCH_KIND_FLOW_BATCH, 101, 5),
        (C.BATCH_KIND_FLOW, 101, 1),
        (C.BATCH_KIND_FLOW, 31337, 1),
    ])
    assert tok.peer_version == C.PROTOCOL_VERSION
    assert results[0].status == C.STATUS_OK
    # partial grant: 1 unit already spent above, 1 by entry 0 -> 1 left
    assert results[1].status == C.STATUS_OK and results[1].remaining == 1
    assert results[2].status == C.STATUS_BLOCKED
    # v3: the deny explains itself (_T_PROV rode the response)
    assert results[2].prov_kind == ERR.BLOCK_FLOW
    assert results[2].prov_rule == 101
    assert results[2].prov_limit == 3.0
    assert results[2].prov_observed is not None
    assert results[3].status == C.STATUS_NO_RULE


def test_batch_frames_carry_trace_context(tcp_cluster):
    """The 17-byte trace tail rides batched frames end to end: the
    client's cluster.rpc span for a BATCH exchange carries the ambient
    trace id, and the server echoes the context on the response."""
    from sentinel_tpu import obs
    from sentinel_tpu.obs import trace as OT

    server, tok, svc = tcp_cluster
    assert tok.request_token(101).ok  # negotiate v2 first
    obs.TRACER.reset()
    obs.enable()
    try:
        tid, sid = OT.new_trace_id(), OT.new_span_id()
        with OT.trace_ctx(tid, sid):
            tok.request_batch([(C.BATCH_KIND_FLOW, 101, 1)])
    finally:
        obs.disable()
    rpc = [s for s in obs.TRACER.snapshot() if s["name"] == "cluster.rpc"]
    assert rpc and rpc[-1]["trace"] == tid
    assert rpc[-1]["attrs"].get("type") == C.MSG_TYPE_BATCH


class _V1Server(threading.Thread):
    """Hand-rolled LEGACY token server: answers PING and FLOW frames and
    silently drops anything it does not know — exactly how the v1
    decoder treats a type-15 HELLO (decode error -> frame dropped)."""

    def __init__(self):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self.seen_types = []
        self._halt = threading.Event()

    def run(self):
        self.sock.settimeout(2.0)
        try:
            conn, _ = self.sock.accept()
        except OSError:
            return
        reader = P.FrameReader()
        conn.settimeout(0.1)
        while not self._halt.is_set():
            try:
                data = conn.recv(4096)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            for body in reader.feed(data):
                xid, t = struct.unpack_from(">iB", body, 0)
                self.seen_types.append(t)
                if t == C.MSG_TYPE_PING:
                    rsp = P.ClusterResponse(xid, t, C.STATUS_OK)
                elif t == C.MSG_TYPE_FLOW:
                    rsp = P.ClusterResponse(xid, t, C.STATUS_OK, remaining=1)
                else:
                    continue  # v1: unknown frame type is dropped
                try:
                    conn.sendall(P.encode_response(rsp))
                except OSError:
                    return
        conn.close()

    def stop(self):
        self._halt.set()
        self.sock.close()
        self.join(timeout=3)


def test_v1_server_keeps_client_on_v1_and_batches_pipeline():
    """Negotiation back-compat, server side: a legacy peer drops the
    HELLO, the client's reaper resolves it to v1 after timeout_ms, and
    request_batch transparently degrades to PIPELINED legacy frames on
    the same multiplexed socket — correct answers, no v2 frames sent."""
    v1 = _V1Server()
    v1.start()
    tok = ClusterTokenClient("127.0.0.1", v1.port, timeout_ms=300,
                             reconnect_interval_s=0.0)
    try:
        assert tok.request_token(5).status == C.STATUS_OK
        time.sleep(0.5)  # past the HELLO reaper: negotiation settled
        assert tok.peer_version == 1
        results = tok.request_batch([
            (C.BATCH_KIND_FLOW, 5, 1),
            (C.BATCH_KIND_FLOW, 6, 1),
        ])
        assert [r.status for r in results] == [C.STATUS_OK, C.STATUS_OK]
        assert C.MSG_TYPE_BATCH not in v1.seen_types
        assert C.MSG_TYPE_HELLO in v1.seen_types  # offered, ignored
    finally:
        tok.close()
        v1.stop()


def test_corrupt_batch_frame_fails_closed(tcp_cluster):
    """cluster.batch.frame chaos site: a structurally corrupted or
    truncated BATCH frame fails the WHOLE exchange closed — every entry
    STATUS_FAIL, no partial answers applied — and the connection keeps
    working after.  (The wire carries no checksum, so a flip inside the
    entry slab just decodes as a different ask; the strict-length
    contract is about frame STRUCTURE: header, count, slab size.  The
    seed is picked so the deterministic fault lands structurally.)"""
    from sentinel_tpu.chaos import failpoints as FP
    from sentinel_tpu.chaos.plans import FaultPlan, FaultSpec

    server, tok, svc = tcp_cluster
    assert tok.request_token(101).ok  # negotiate v2 first
    body_len = 7 + 2 * 14  # [xid:4][type:1][n:2] + 2 entries, no trace tail

    def _plan(action, seed):
        return FaultPlan(
            name=f"batch-{action}", seed=seed,
            faults=[FaultSpec("cluster.batch.frame", action, max_fires=1)],
        )

    def _pick_seed(action):
        for s in range(500):
            rng = _plan(action, s).spec_rng(0)
            if action == "corrupt":
                # flip must land in the header (type/count bytes) to be
                # structurally detectable
                if rng.randrange(body_len) in (4, 5, 6):
                    return s
            else:
                # cut must keep the xid readable so the server can send
                # the frame-level FAIL instead of forcing a 5 s timeout
                if rng.randrange(1, body_len) >= 4:
                    return s
        raise AssertionError(f"no structural seed for {action}")

    for action in ("corrupt", "short_read"):
        plan = _plan(action, _pick_seed(action))
        with FP.armed(plan) as st:
            results = tok.request_batch([
                (C.BATCH_KIND_FLOW, 101, 1),
                (C.BATCH_KIND_FLOW, 101, 1),
            ])
        assert st.injected().get(f"cluster.batch.frame:{action}") == 1
        assert all(r.status == C.STATUS_FAIL for r in results), action
    # the frame-level reject did not poison the connection or the budget
    r = tok.request_batch([(C.BATCH_KIND_FLOW, 101, 1)])
    assert r[0].status in (C.STATUS_OK, C.STATUS_BLOCKED)
