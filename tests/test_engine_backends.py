"""Backend equivalence: the MXU one-hot-matmul table path must make the
same decisions as the XLA scatter/gather path — the engine logic is shared
and the two memory-access strategies are exact (ops/tables.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from sentinel_tpu.core import rules as R
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.core.rule_tensors import hash_param
from sentinel_tpu.ops import engine as E
from sentinel_tpu.runtime.registry import Registry


def _mk(cfg):
    reg = Registry(cfg)
    for i in range(1, 33):
        reg.resource_id(f"r{i}")
    rules = dict(
        flow_rules=[
            R.FlowRule(resource="r1", count=5),
            R.FlowRule(resource="r2", count=3, control_behavior=R.CONTROL_RATE_LIMITER),
            R.FlowRule(resource="r3", count=100, grade=R.GRADE_THREAD),
            R.FlowRule(resource="r4", count=8, control_behavior=R.CONTROL_WARM_UP),
        ],
        degrade_rules=[
            R.DegradeRule(resource="r5", grade=R.CB_STRATEGY_ERROR_COUNT, count=2, time_window=3),
            R.DegradeRule(resource="r6", grade=R.CB_STRATEGY_SLOW_REQUEST_RATIO, count=50, slow_ratio_threshold=0.5, time_window=2),
        ],
        param_rules=[R.ParamFlowRule(resource="r7", count=2, param_idx=0)],
        authority_rules=[
            R.AuthorityRule(resource="r8", limit_app="bad", strategy=R.AUTHORITY_BLACK)
        ],
        system_rules=[R.SystemRule(qps=1000)],
    )
    ruleset = E.compile_ruleset(cfg, reg, **rules)
    return reg, ruleset


def _workload(cfg, reg, seed):
    rng = np.random.default_rng(seed)
    b = cfg.batch_size
    res = rng.integers(1, 12, b).astype(np.int32)
    origin_bad = reg.origin_id("bad")
    acq = E.empty_acquire(cfg)._replace(
        res=jnp.asarray(res),
        count=jnp.ones((b,), jnp.int32),
        origin_id=jnp.asarray(
            np.where(rng.random(b) < 0.3, origin_bad, -1).astype(np.int32)
        ),
        inbound=jnp.asarray((rng.random(b) < 0.5).astype(np.int32)),
        param_hash=jnp.asarray(
            np.stack(
                [
                    np.array(
                        [
                            hash_param(f"v{i % 3}") if r == 7 else 0
                            for i, r in enumerate(res)
                        ],
                        dtype=np.int32,
                    )
                ]
                + [np.zeros(b, np.int32)] * (cfg.param_dims - 1),
                axis=1,
            )
        ),
    )
    comp_res = rng.integers(1, 12, b).astype(np.int32)
    comp = E.empty_complete(cfg)._replace(
        res=jnp.asarray(comp_res),
        rt=jnp.asarray(rng.uniform(1, 120, b).astype(np.float32)),
        success=jnp.ones((b,), jnp.int32),
        error=jnp.asarray((rng.random(b) < 0.3).astype(np.int32)),
        inbound=jnp.asarray((rng.random(b) < 0.5).astype(np.int32)),
    )
    return acq, comp


@pytest.mark.parametrize("features", [E.ALL_FEATURES, frozenset({"flow"})])
def test_backend_equivalence(features):
    cfgs = [
        small_engine_config(use_mxu_tables=False),
        small_engine_config(use_mxu_tables=True),
    ]
    outs = []
    for cfg in cfgs:
        reg, ruleset = _mk(cfg)
        tick = E.make_tick(cfg, donate=False, features=features)
        state = E.init_state(cfg)
        verdicts = []
        for step in range(8):
            acq, comp = _workload(cfg, reg, seed=step)
            state, out = tick(
                state,
                ruleset,
                acq,
                comp,
                jnp.int32(step * 300),
                jnp.float32(0.1),
                jnp.float32(0.1),
            )
            verdicts.append(np.asarray(out.verdict))
        outs.append(
            dict(
                verdicts=np.stack(verdicts),
                counts=np.asarray(state.win_sec.counts),
                conc=np.asarray(state.concurrency),
                cb=np.asarray(state.cb_state),
                latest=np.asarray(state.latest_passed_ms),
                rt_min=np.asarray(state.win_sec.rt_min),
                rt_min_minute=np.asarray(state.win_min.rt_min),
            )
        )
    a, b = outs
    np.testing.assert_array_equal(a["verdicts"], b["verdicts"])
    np.testing.assert_array_equal(a["counts"], b["counts"])
    np.testing.assert_array_equal(a["conc"], b["conc"])
    np.testing.assert_array_equal(a["cb"], b["cb"])
    np.testing.assert_allclose(a["latest"], b["latest"], rtol=1e-6, atol=1e-3)
    # per-row windowed minRt is maintained exactly on BOTH paths over RAW
    # rts (ops/rowmin.py) — bit-equal even though rt_sum quantizes on MXU
    np.testing.assert_array_equal(a["rt_min"], b["rt_min"])
    np.testing.assert_array_equal(a["rt_min_minute"], b["rt_min_minute"])
