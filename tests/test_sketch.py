"""Global stats sketch (ops/gsketch.py): windowed CMS observability for
resources beyond the exact row space — the north-star 'millions of
resources per chip' path (SURVEY §0)."""

import numpy as np
import pytest

import jax.numpy as jnp

import sentinel_tpu as st
from sentinel_tpu.core.config import small_engine_config
from sentinel_tpu.ops import gsketch as GS
from sentinel_tpu.ops import window as W


def test_sketch_add_estimate_roundtrip():
    cfg = GS.SketchConfig(sample_count=2, window_ms=500, depth=2, width=512)
    s = GS.init_sketch(cfg)
    res = jnp.asarray([100, 200, 100, 300], jnp.int32)
    vals = jnp.asarray([[1], [2], [3], [4]], jnp.int32)
    s = GS.add(
        s,
        jnp.int32(1000),
        res,
        vals,
        (W.EV_PASS,),
        jnp.asarray([True, True, True, False]),
        cfg,
    )
    est = np.asarray(GS.estimate(s, jnp.int32(1100), jnp.asarray([100, 200, 300], jnp.int32), cfg))
    assert est[0, W.EV_PASS] == 4  # 1 + 3 accumulated
    assert est[1, W.EV_PASS] == 2
    assert est[2, W.EV_PASS] == 0  # invalid item dropped


def test_sketch_window_expiry():
    cfg = GS.SketchConfig(sample_count=2, window_ms=500, depth=2, width=256)
    s = GS.init_sketch(cfg)
    vals = jnp.asarray([[5]], jnp.int32)
    one = jnp.asarray([42], jnp.int32)
    ok = jnp.asarray([True])
    s = GS.add(s, jnp.int32(0), one, vals, (W.EV_PASS,), ok, cfg)
    assert GS.estimate(s, jnp.int32(400), one, cfg)[0, W.EV_PASS] == 5
    # 1.2 s later the old bucket is out of window; its column resets on add
    s = GS.add(s, jnp.int32(1200), one, vals, (W.EV_PASS,), ok, cfg)
    assert GS.estimate(s, jnp.int32(1250), one, cfg)[0, W.EV_PASS] == 5


@pytest.fixture()
def sketch_client(client_factory):
    cfg = small_engine_config(
        max_resources=4, max_nodes=8, sketch_stats=True, sketch_width=256
    )
    return client_factory(cfg=cfg)


def test_client_overflows_into_sketch(sketch_client, vt):
    c = sketch_client
    # rows: entry(0) + 3 exact resources; the rest go to the sketch
    for i in range(10):
        with c.entry(f"res-{i}"):
            vt.advance(2)
    snap = c.stats.snapshot()
    assert len(snap) == 10
    assert snap["res-1"]["passQps"] == 1  # exact row
    assert snap["res-7"]["passQps"] >= 1  # sketch estimate (>= real count)
    assert c.registry.is_sketch_id(c.registry.peek_resource_id("res-7"))
    # per-resource read path
    s7 = c.stats.resource("res-7")
    assert s7["successQps"] >= 1
    assert s7["avgRt"] > 0


def test_sketch_resources_enforce_rules_via_tail_tables(sketch_client, vt):
    """Round-2 contract change: a rule on a sketch-id resource ENFORCES
    (approximately, via the tail threshold tables) instead of silently
    passing — tests/test_tail_rules.py covers the (eps, delta) behavior;
    here just the end-to-end block."""
    import pytest

    from sentinel_tpu.core import errors as ERR

    c = sketch_client
    # exhaust exact space
    for i in range(5):
        c.registry.resource_id(f"res-{i}")
    c.flow_rules.load([st.FlowRule(resource="res-9", count=0)])
    rid = c.registry.peek_resource_id("res-9")
    if rid is not None and not c.registry.is_sketch_id(rid):
        # promotion found room — the rule enforces exactly
        assert c.try_entry("res-9") is None
    else:
        with pytest.raises(ERR.BlockException):
            with c.entry("res-9"):
                pass
