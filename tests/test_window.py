"""Window kernel vs the NumPy LeapArray oracle.

TPU-native counterpart of the reference's LeapArrayTest /
BucketLeapArrayTest / ArrayMetricTest (SURVEY.md §4.2): randomized event
streams over virtual time, exact equality of windowed aggregates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.ops import window as W
from tests.oracle import OracleLeapArray

ROWS = 8
CFG = W.WindowConfig(sample_count=2, window_ms=500)  # second window default


def make_delta(event, n=1):
    d = np.zeros((W.NUM_EVENTS,), dtype=np.int32)
    d[event] = n
    return d


def test_single_bucket_accumulates():
    st = W.init_window(ROWS + 1, CFG)
    now = 250
    rows = jnp.array([3, 3, 5], dtype=jnp.int32)
    deltas = jnp.array([make_delta(W.EV_PASS), make_delta(W.EV_PASS), make_delta(W.EV_BLOCK)])
    st = W.add_batch(st, jnp.int32(now), rows, deltas, None, CFG)
    passed = W.window_event(st, jnp.int32(now), CFG, W.EV_PASS)
    blocked = W.window_event(st, jnp.int32(now), CFG, W.EV_BLOCK)
    assert int(passed[3]) == 2
    assert int(blocked[5]) == 1
    assert int(passed[5]) == 0


def test_window_slides_and_expires():
    st = W.init_window(ROWS + 1, CFG)
    one = jnp.array([0], dtype=jnp.int32)
    d = jnp.array([make_delta(W.EV_PASS)])
    st = W.add_batch(st, jnp.int32(100), one, d, None, CFG)
    # still visible at 999 (interval = 1000ms)
    assert int(W.window_event(st, jnp.int32(999), CFG, W.EV_PASS)[0]) == 1
    # bucket [0,500) expires once now >= 1000
    assert int(W.window_event(st, jnp.int32(1000), CFG, W.EV_PASS)[0]) == 0
    # a write at 1100 lands in the recycled column; old data must be gone
    st = W.add_batch(st, jnp.int32(1100), one, d, None, CFG)
    assert int(W.window_event(st, jnp.int32(1100), CFG, W.EV_PASS)[0]) == 1


def test_long_idle_gap_resets():
    st = W.init_window(ROWS + 1, CFG)
    one = jnp.array([2], dtype=jnp.int32)
    d = jnp.array([make_delta(W.EV_PASS)])
    st = W.add_batch(st, jnp.int32(0), one, d, None, CFG)
    st = W.add_batch(st, jnp.int32(10), one, d, None, CFG)
    # jump far into the future — everything stale
    assert int(W.window_event(st, jnp.int32(100_000), CFG, W.EV_PASS)[2]) == 0
    st = W.add_batch(st, jnp.int32(100_000), one, d, None, CFG)
    assert int(W.window_event(st, jnp.int32(100_000), CFG, W.EV_PASS)[2]) == 1


@pytest.mark.parametrize("sample_count,window_ms", [(2, 500), (4, 250), (10, 100)])
def test_randomized_vs_oracle(sample_count, window_ms):
    import functools

    rng = np.random.default_rng(42 + sample_count)
    cfg = W.WindowConfig(sample_count, window_ms)
    B = 16  # fixed batch shape — one compile, many steps
    trash = ROWS  # last row absorbs padding
    st = W.init_window(ROWS + 1, cfg)
    oracle = OracleLeapArray(ROWS + 1, sample_count, window_ms)

    add = jax.jit(functools.partial(W.add_batch, cfg=cfg))
    reads = jax.jit(
        lambda s, now: (
            W.window_event(s, now, cfg, W.EV_PASS),
            W.window_event(s, now, cfg, W.EV_BLOCK),
            W.window_event(s, now, cfg, W.EV_SUCCESS),
            *W.window_rt(s, now, cfg),
        )
    )

    now = 0
    for step in range(60):
        now += int(rng.integers(1, window_ms))
        b = int(rng.integers(1, B))
        rows = np.full((B,), trash, dtype=np.int32)
        rows[:b] = rng.integers(0, ROWS, size=b)
        events = rng.integers(0, W.NUM_EVENTS, size=B)
        rts = rng.uniform(1.0, 50.0, size=B).astype(np.float32)
        has_rt = (events == W.EV_SUCCESS) & (np.arange(B) < b)
        deltas = np.zeros((B, W.NUM_EVENTS), dtype=np.int32)
        deltas[np.arange(B), events] = 1
        deltas[b:] = 0
        st = add(
            st,
            jnp.int32(now),
            jnp.asarray(rows),
            jnp.asarray(deltas),
            jnp.asarray(np.where(has_rt, rts, 0.0), dtype=jnp.float32),
        )
        for i in range(b):
            oracle.add(now, rows[i], int(events[i]))
            if has_rt[i]:
                oracle.add_rt(now, rows[i], float(rts[i]))

        if step % 7 == 0:
            got_p, got_b, got_s, got_rt, got_min = reads(st, jnp.int32(now))
            # trash row excluded from comparison
            np.testing.assert_array_equal(
                np.asarray(got_p)[:ROWS], oracle.window_event(now, W.EV_PASS)[:ROWS]
            )
            np.testing.assert_array_equal(
                np.asarray(got_b)[:ROWS], oracle.window_event(now, W.EV_BLOCK)[:ROWS]
            )
            np.testing.assert_array_equal(
                np.asarray(got_s)[:ROWS], oracle.window_event(now, W.EV_SUCCESS)[:ROWS]
            )
            want_rt, want_min = oracle.window_rt(now)
            np.testing.assert_allclose(np.asarray(got_rt)[:ROWS], want_rt[:ROWS], rtol=1e-4)
            np.testing.assert_allclose(np.asarray(got_min)[:ROWS], want_min[:ROWS], rtol=1e-5)


def test_gather_matches_full_reduction():
    import functools

    rng = np.random.default_rng(7)
    st = W.init_window(ROWS + 1, CFG)
    add = jax.jit(functools.partial(W.add_batch, cfg=CFG))
    now = 0
    for _ in range(20):
        now += int(rng.integers(1, 400))
        b = 8
        rows = jnp.asarray(rng.integers(0, ROWS, size=b), dtype=jnp.int32)
        deltas = np.zeros((b, W.NUM_EVENTS), dtype=np.int32)
        deltas[:, W.EV_PASS] = 1
        st = add(st, jnp.int32(now), rows, jnp.asarray(deltas), None)
    full = np.asarray(W.window_event(st, jnp.int32(now), CFG, W.EV_PASS))
    sel = jnp.asarray([0, 3, 7, 2], dtype=jnp.int32)
    got = np.asarray(W.gather_window_event(st, jnp.int32(now), sel, CFG, W.EV_PASS))
    np.testing.assert_array_equal(got, full[np.asarray(sel)])
