"""MXU table ops vs numpy scatter/gather oracle — exactness, not approximation
(the one-hot contraction touches exactly one nonzero per selection)."""

import numpy as np
import pytest

import jax.numpy as jnp

from sentinel_tpu.ops import mxu_table as M


@pytest.mark.parametrize("n,b", [(1000, 257), (70_000, 4096), (131, 64)])
def test_scatter_add_matches_oracle(n, b):
    rng = np.random.default_rng(0)
    idx = rng.integers(-5, n + 5, b).astype(np.int32)  # include OOB → dropped
    vals = rng.integers(0, 100, (b, 3)).astype(np.int32)
    table = rng.integers(0, 1000, (n, 3)).astype(np.int32)

    oracle = table.copy()
    for i in range(b):
        if 0 <= idx[i] < n:
            oracle[idx[i]] += vals[i]

    plan = M.make_plan(n)
    Hi, Lo = M.onehots(jnp.asarray(idx), plan)
    out = np.asarray(M.scatter_add(jnp.asarray(table), plan, Hi, Lo, jnp.asarray(vals)))
    np.testing.assert_array_equal(out, oracle)


def test_scatter_add_float_plane():
    rng = np.random.default_rng(1)
    n, b = 5000, 1024
    idx = rng.integers(0, n, b).astype(np.int32)
    rt = rng.uniform(0, 5000, b).astype(np.float32)
    table = np.zeros((n,), np.float32)
    oracle = table.copy()
    for i in range(b):
        oracle[idx[i]] += rt[i]
    plan = M.make_plan(n)
    Hi, Lo = M.onehots(jnp.asarray(idx), plan)
    out = np.asarray(M.scatter_add(jnp.asarray(table), plan, Hi, Lo, jnp.asarray(rt)))
    np.testing.assert_allclose(out, oracle, rtol=1e-5, atol=1e-2)


@pytest.mark.parametrize("planes", [(), (5,), (2, 5)])
def test_gather_matches_oracle(planes):
    rng = np.random.default_rng(2)
    n, b = 33_000, 2048
    idx = rng.integers(-3, n + 3, b).astype(np.int32)
    table = rng.integers(0, 1 << 20, (n,) + planes).astype(np.int32)
    plan = M.make_plan(n)
    Hi, Lo = M.onehots(jnp.asarray(idx), plan)
    out = np.asarray(M.gather(jnp.asarray(table), plan, Hi, Lo))
    oracle = np.zeros((b,) + planes, np.int32)
    for i in range(b):
        if 0 <= idx[i] < n:
            oracle[i] = table[idx[i]]
    np.testing.assert_array_equal(out, oracle)


def test_gather_respects_valid_mask():
    n = 100
    idx = jnp.asarray([1, 2, 3], jnp.int32)
    table = jnp.arange(n, dtype=jnp.int32) * 10
    plan = M.make_plan(n)
    Hi, Lo = M.onehots(idx, plan, valid=jnp.asarray([True, False, True]))
    out = np.asarray(M.gather(table, plan, Hi, Lo))
    np.testing.assert_array_equal(out, [10, 0, 30])


def test_scatter_or():
    n, b = 4097, 512
    rng = np.random.default_rng(3)
    idx = rng.integers(0, n, b).astype(np.int32)
    flag = (rng.random(b) < 0.3)
    table = np.zeros((n,), np.int32)
    oracle = table.copy()
    for i in range(b):
        if flag[i]:
            oracle[idx[i]] = 1
    plan = M.make_plan(n)
    Hi, Lo = M.onehots(jnp.asarray(idx), plan)
    out = np.asarray(M.scatter_or(jnp.asarray(table), plan, Hi, Lo, jnp.asarray(flag)))
    np.testing.assert_array_equal(out, oracle)


def test_lane_gather_1col_matches_big_gather():
    """The lane-packed 1-column gather (pad to 8 lanes + data-dependent
    select) must match big_gather exactly — including out-of-range ids
    (zeros), n not a multiple of 8, and large f32 sentinels — on both the
    mxu and plain backends."""
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.ops import tables as T

    rng = np.random.default_rng(9)
    for n in (4093, 4096, 16384):
        idx = rng.integers(-3, n + 5, 777).astype(np.int32)  # incl. OOB
        for table in (
            rng.integers(0, (1 << 24) - 1, n).astype(np.int32),
            np.where(
                rng.random(n) < 0.5, 2.0e38, rng.random(n) * 100
            ).astype(np.float32),
        ):
            for mxu in (False, True):
                cfg = small_engine_config(use_mxu_tables=mxu)
                got = np.asarray(
                    T.lane_gather_1col(cfg, jnp.asarray(table), jnp.asarray(idx), n)
                )
                ok = (idx >= 0) & (idx < n)
                want = np.where(ok, table[np.clip(idx, 0, n - 1)], 0).astype(
                    np.float32
                )
                np.testing.assert_array_equal(got, want)
    # int variant restores exact small ints
    cfg = small_engine_config(use_mxu_tables=True)
    tab = rng.integers(0, 4096, 1000).astype(np.int32)
    ids = rng.integers(0, 1000, 256).astype(np.int32)
    got = np.asarray(T.lane_gather_1col_int(cfg, jnp.asarray(tab), jnp.asarray(ids), 1000))
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, tab[ids])


@pytest.mark.parametrize(
    "n,n_lo",
    [
        (1, 512), (7, 512), (127, 64), (128, 512), (129, 512), (131, 128),
        (255, 1000), (4093, 512), (1 << 14, 512), ((1 << 14) + 1, 512),
        (70_000, 384),
    ],
)
def test_make_plan_clamp_invariants(n, n_lo):
    """The clamp must hold for ANY (n, n_lo): the padded id space covers
    every logical id, the Lo axis is lane-friendly, and small tables never
    keep a caller's wide default (minimal padding, one Hi row)."""
    plan = M.make_plan(n, n_lo)
    assert plan.n_lo % 128 == 0
    assert plan.n_lo >= 128
    assert plan.padded >= n, (plan, n)
    # the Lo axis never exceeds the smallest lane multiple covering n
    assert plan.n_lo <= max(128, ((n + 127) // 128) * 128)
    assert plan.n_hi >= 1


def test_make_plan_small_n_full_coverage():
    """Scatter then gather across EVERY id of an awkward small size (the
    default n_lo=512 must clamp down, not truncate the id space)."""
    n = 131
    plan = M.make_plan(n)
    assert plan.padded >= n
    idx = jnp.arange(n, dtype=jnp.int32)
    Hi, Lo = M.onehots(idx, plan)
    vals = jnp.arange(1, n + 1, dtype=jnp.int32)
    tab = M.scatter_add(jnp.zeros((n,), jnp.int32), plan, Hi, Lo, vals)
    np.testing.assert_array_equal(np.asarray(tab), np.arange(1, n + 1))
    got = np.asarray(M.gather(tab, plan, Hi, Lo))
    np.testing.assert_array_equal(got, np.arange(1, n + 1))


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_depth_histogram_mxu_native_parity(depth):
    """tables.depth_histogram: the flat [depth*width] MXU contraction must
    be BIT-equal to the native scatter path and the per-event oracle —
    including invalid rows and out-of-range columns (dropped)."""
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.ops import tables as T

    rng = np.random.default_rng(17 + depth)
    width, N, P = 1 << 10, 513, 3
    cols = rng.integers(-2, width + 2, (N, depth)).astype(np.int32)
    vals = rng.integers(0, 50, (N, P)).astype(np.int32)
    valid = rng.random(N) < 0.8
    args = (jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(valid), depth, width)
    oracle = np.zeros((depth, width, P), np.int64)
    for i in range(N):
        if not valid[i]:
            continue
        for d in range(depth):
            c = cols[i, d]
            if 0 <= c < width:
                oracle[d, c] += vals[i]
    native = np.asarray(T.depth_histogram(None, *args))
    mxu = np.asarray(
        T.depth_histogram(small_engine_config(use_mxu_tables=True), *args)
    )
    np.testing.assert_array_equal(native, oracle)
    np.testing.assert_array_equal(mxu, oracle)


@pytest.mark.parametrize("depth", [1, 2, 3])
@pytest.mark.parametrize("kind", ["int", "float"])
def test_depth_gather_1col_mxu_native_parity(depth, kind):
    """tables.depth_gather_1col: one flat contraction (int digit planes) /
    one lane gather (float) per batch must match the native gather and the
    oracle exactly for both table dtypes, depths 1–3, out-of-range ids."""
    from sentinel_tpu.core.config import small_engine_config
    from sentinel_tpu.ops import tables as T

    rng = np.random.default_rng(23 + depth)
    width, N = 1 << 10, 777
    if kind == "int":
        tab = rng.integers(0, (1 << 24) - 1, (depth, width)).astype(np.int32)
        max_int = (1 << 24) - 1
    else:
        tab = (rng.random((depth, width)) * 5000.0).astype(np.float32)
        max_int = None
    cols = rng.integers(-2, width + 2, (N, depth)).astype(np.int32)
    oracle = np.zeros((depth, N), np.float32)
    for d in range(depth):
        ok = (cols[:, d] >= 0) & (cols[:, d] < width)
        oracle[d] = np.where(ok, tab[d, np.clip(cols[:, d], 0, width - 1)], 0)
    native = np.asarray(
        T.depth_gather_1col(None, jnp.asarray(tab), jnp.asarray(cols), width,
                            max_int=max_int)
    )
    mxu = np.asarray(
        T.depth_gather_1col(
            small_engine_config(use_mxu_tables=True),
            jnp.asarray(tab), jnp.asarray(cols), width, max_int=max_int,
        )
    )
    np.testing.assert_array_equal(native, oracle)
    np.testing.assert_array_equal(mxu, oracle)


def test_lane_gather_multi_matches_oracle():
    """tables.lane_gather_multi: k tables, one shared row gather — exact
    vs numpy for odd/even n, k=1..4, out-of-range ids."""
    import jax.numpy as jnp

    from sentinel_tpu.core.config import EngineConfig
    from sentinel_tpu.ops import tables as T

    rng = np.random.default_rng(31)
    cfg = EngineConfig(use_mxu_tables=True)
    for n in (7, 16, 333):
        for k in (1, 2, 3, 4):
            tabs = [
                rng.integers(0, 1 << 20, n).astype(np.int32) for _ in range(k)
            ]
            idx = rng.integers(-3, n + 3, 257).astype(np.int32)
            got = T.lane_gather_multi(
                cfg, [jnp.asarray(t) for t in tabs], jnp.asarray(idx), n
            )
            ok = (idx >= 0) & (idx < n)
            for c in range(k):
                want = np.where(ok, tabs[c][np.clip(idx, 0, n - 1)], 0)
                np.testing.assert_array_equal(
                    np.asarray(got[c]).astype(np.int64), want,
                    err_msg=f"n={n} k={k} col={c}",
                )
