"""Dashboard-lite tests: discovery, repository retention/top-N, fetcher
catch-up against a fake machine API, and the REST surface end-to-end with a
real client instance behind a real command center (reference:
sentinel-dashboard controller/repository tests)."""

import json
import time
import urllib.parse
import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.dashboard import (
    AppManagement,
    DashboardServer,
    InMemoryMetricsRepository,
    MachineInfo,
    MetricFetcher,
)
from sentinel_tpu.metrics.node import MetricNode


def _node(ts, resource, p=0, b=0):
    return MetricNode(timestamp=ts, resource=resource, pass_qps=p, block_qps=b)


def test_discovery_register_and_health():
    d = AppManagement(stale_after_s=0.2)
    d.register(MachineInfo(app="a", ip="1.2.3.4", port=8719))
    d.register(MachineInfo(app="a", ip="1.2.3.4", port=8719, pid=42))  # upsert
    d.register(MachineInfo(app="b", ip="5.6.7.8", port=8719))
    assert d.apps() == ["a", "b"]
    assert len(d.machines("a")) == 1
    assert d.machines("a")[0].pid == 42
    assert d.machines("a", only_healthy=True)
    time.sleep(0.25)
    assert not d.machines("a", only_healthy=True)
    assert d.remove_stale(older_than_s=0.1) == 2


def test_repository_query_merge_and_retention():
    repo = InMemoryMetricsRepository(retention_ms=10_000)
    t0 = 1_700_000_000_000
    repo.save_all("app", [_node(t0, "r1", p=10), _node(t0, "r2", p=1)])
    repo.save_all("app", [_node(t0, "r1", p=5)])  # second machine, same second
    assert repo.query("app", "r1", t0, t0)[0].pass_qps == 15
    # retention trim
    repo.save_all("app", [_node(t0 + 60_000, "r1", p=1)])
    assert repo.query("app", "r1", 0, 2**62)[0].timestamp == t0 + 60_000
    assert repo.resources_of("app") == ["r1", "r2"]


def test_repository_top_resources():
    repo = InMemoryMetricsRepository()
    t0 = 1_700_000_000_000
    repo.save_all("app", [_node(t0, "hot", p=100), _node(t0, "warm", p=10, b=5), _node(t0, "cold")])
    assert repo.top_resources("app", 0, 2**62) == ["hot", "warm"]
    assert repo.top_resources("app", 0, 2**62, limit=1) == ["hot"]


class _FakeApi:
    """Stand-in machine command plane serving canned metric lines."""

    def __init__(self):
        self.calls = []
        self.nodes = []
        self.prom_text = "sentinel_pipeline_occupancy 2\n"

    def fetch_metric(self, ip, port, start_ms, end_ms):
        self.calls.append((start_ms, end_ms))
        return [n for n in self.nodes if start_ms <= n.timestamp <= end_ms]

    def fetch_prometheus(self, ip, port):
        if port == 666:  # the designated down machine
            raise OSError("down")
        return self.prom_text


def test_fetcher_catchup_window():
    d = AppManagement()
    d.register(MachineInfo(app="app", ip="127.0.0.1", port=1))
    repo = InMemoryMetricsRepository()
    api = _FakeApi()
    f = MetricFetcher(d, repo, api=api, max_catchup_ms=15_000)
    now = 1_700_000_100_000
    api.nodes = [_node(now - 5000, "r", p=7)]
    saved = f.fetch_once(now)
    assert saved == 1
    assert repo.query("app", "r", 0, 2**62)[0].pass_qps == 7
    # catch-up start was clamped to 15 s before the end second
    start, end = api.calls[0]
    assert end == (now // 1000) * 1000 - 1000
    assert start >= end - 15_000
    # next sweep resumes after the last fetched second
    f.fetch_once(now + 1000)
    start2, _ = api.calls[1]
    assert start2 == (now - 5000) + 1000


def test_fetcher_scrapes_prometheus_per_machine():
    """MetricFetcher.scrape_prometheus sweeps healthy machines' /metrics
    (the obs-plane exposition) and skips unreachable ones."""
    d = AppManagement()
    d.register(MachineInfo(app="app", ip="127.0.0.1", port=1))
    d.register(MachineInfo(app="app", ip="127.0.0.1", port=666))
    f = MetricFetcher(d, InMemoryMetricsRepository(), api=_FakeApi())
    out = f.scrape_prometheus("app")
    assert list(out.values()) == ["sentinel_pipeline_occupancy 2\n"]
    assert f.fetch_ok == 1 and f.fetch_fail == 1


def test_fetcher_self_observability_counters():
    """ISSUE-5 satellite: the dashboard's OWN fetch loop is observable —
    sentinel_dashboard_fetch_total{result} moves per pull outcome and
    the last-success gauge is a fresh wall timestamp; before this, a
    silently failing loop just stopped filling the repository."""
    from sentinel_tpu.dashboard import metric_fetcher as MF

    ok0, err0 = MF._C_FETCH_OK.value, MF._C_FETCH_ERR.value
    d = AppManagement()
    d.register(MachineInfo(app="app", ip="127.0.0.1", port=1))
    d.register(MachineInfo(app="app", ip="127.0.0.1", port=666))
    f = MetricFetcher(d, InMemoryMetricsRepository(), api=_FakeApi())
    f.scrape_prometheus("app")
    assert MF._C_FETCH_OK.value == ok0 + 1
    assert MF._C_FETCH_ERR.value == err0 + 1
    last = MF._G_LAST_SUCCESS.value
    assert last > 0
    # the metric-log path counts too (fake api's fetch_metric never fails)
    api = _FakeApi()
    api.nodes = [_node(1_700_000_095_000, "r", p=1)]
    f2 = MetricFetcher(
        AppManagement(), InMemoryMetricsRepository(), api=api
    )
    f2.discovery.register(MachineInfo(app="app", ip="127.0.0.1", port=1))
    f2.fetch_once(1_700_000_100_000)
    assert MF._C_FETCH_OK.value == ok0 + 2


def test_dashboard_serves_ui_page():
    dash = DashboardServer(host="127.0.0.1", port=0, fetch_metrics=False,
                           auth_token="tok")
    dash.start()
    try:
        rsp = urllib.request.urlopen(f"http://127.0.0.1:{dash.port}/", timeout=3)
        body = rsp.read().decode()
        assert rsp.headers["Content-Type"].startswith("text/html")
        # the static page is reachable without the token; its data fetches
        # (e.g. /apps) still require it
        for frag in ("sentinel-tpu dashboard", 'id="chart"', "/metric/top"):
            assert frag in body
    finally:
        dash.stop()


def test_dashboard_auth_token():
    """With auth on, EVERY route — including /registry/machine — needs the
    token: an open registry would feed the proxy-target allowlist and the
    metric fetcher (SSRF via fake machine registration)."""
    import urllib.error

    dash = DashboardServer(host="127.0.0.1", port=0, fetch_metrics=False,
                           auth_token="s3cret")
    dash.start()
    try:
        base = f"http://127.0.0.1:{dash.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/apps", timeout=3)
        assert ei.value.code == 401
        req = urllib.request.Request(
            f"{base}/apps", headers={"Authorization": "Bearer s3cret"}
        )
        assert json.load(urllib.request.urlopen(req, timeout=3)) == {}
        hb_body = urllib.parse.urlencode(
            {"app": "a", "ip": "1.1.1.1", "port": "8719"}
        ).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/registry/machine", data=hb_body, method="POST"
                ),
                timeout=3,
            )
        assert ei.value.code == 401
        # a forged form-POST can't set the CSRF header even with the token
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/registry/machine",
                    data=hb_body,
                    method="POST",
                    headers={"Authorization": "Bearer s3cret"},
                ),
                timeout=3,
            )
        assert ei.value.code == 403
        # machines carry the token + heartbeat header (HeartbeatSender)
        hb = urllib.request.Request(
            f"{base}/registry/machine",
            data=hb_body,
            method="POST",
            headers={
                "Authorization": "Bearer s3cret",
                "X-Sentinel-Heartbeat": "1",
            },
        )
        assert urllib.request.urlopen(hb, timeout=3).status == 200
    finally:
        dash.stop()


@pytest.fixture()
def live_stack(client):
    """Real client + command center + dashboard server, wired by heartbeat."""
    from sentinel_tpu.transport import HeartbeatSender, start_command_center

    center = start_command_center(client, host="127.0.0.1", port=0)
    dash = DashboardServer(host="127.0.0.1", port=0, fetch_metrics=False)
    dash.start()
    # center= wiring derives port AND the loopback advertised ip — a
    # loopback-bound command center must never advertise the NIC ip
    hb = HeartbeatSender(
        client.app_name, dashboard_addresses=[f"127.0.0.1:{dash.port}"], center=center
    )
    assert hb.ip == "127.0.0.1" and hb.command_port == center.port
    assert hb.send_once()
    yield client, center, dash
    dash.stop()
    center.stop()


def _get(dash, path):
    return json.load(
        urllib.request.urlopen(f"http://127.0.0.1:{dash.port}{path}", timeout=3)
    )


def test_dashboard_rest_end_to_end(live_stack, vt):
    client, center, dash = live_stack
    apps = _get(dash, "/apps")
    assert client.app_name in apps
    machine = apps[client.app_name][0]
    assert machine["port"] == center.port and machine["healthy"]

    # push rules through the dashboard → machine command plane
    rules = json.dumps([{"resource": "dash-res", "count": 11}])
    body = urllib.parse.urlencode({"app": client.app_name, "type": "flow", "data": rules}).encode()
    rsp = json.load(
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{dash.port}/rules", data=body, method="POST"
            ),
            timeout=3,
        )
    )
    assert rsp["pushed"] == 1
    assert client.flow_rules.get()[0].count == 11

    # read rules back through the dashboard
    got = _get(
        dash,
        f"/rules?ip=127.0.0.1&port={center.port}&type=flow",
    )
    assert got[0]["resource"] == "dash-res"

    # live tree proxy
    with client.entry("dash-res"):
        vt.advance(3)
    tree = _get(dash, f"/tree?ip=127.0.0.1&port={center.port}")
    assert tree["resource"] == "machine-root"

    # metric flow: machine metric log → fetcher → repository → REST
    from sentinel_tpu.metrics import MetricSearcher, MetricTimerListener, MetricWriter
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        timer = MetricTimerListener(client, MetricWriter(td, client.app_name))
        timer.run_once()
        timer.writer.close()
        # rebuild the command registry with a searcher for the metric command
        from sentinel_tpu.transport import build_default_handlers

        center.registry._handlers.update(
            build_default_handlers(
                client, metric_searcher=MetricSearcher(td, client.app_name)
            )._handlers
        )
        wall = client.time.wall_ms()
        saved = dash.fetcher.fetch_once(wall + 2000)
        assert saved >= 1
    top = _get(dash, f"/metric/top?app={client.app_name}")
    assert "dash-res" in top
    series = _get(
        dash, f"/metric?app={client.app_name}&identity=dash-res"
    )
    assert series and series[0]["pass_qps"] >= 1


def _ui_save(dash, center, rtype, rules):
    """POST exactly the way the UI's save button does: type/ip/port in the
    query string, the full rule list as a raw JSON body."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{dash.port}/rules"
        f"?ip=127.0.0.1&port={center.port}&type={rtype}",
        data=json.dumps(rules).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    return json.load(urllib.request.urlopen(req, timeout=3))


def _ui_load(dash, center, rtype):
    """GET the way the UI's reload does."""
    return _get(dash, f"/rules?ip=127.0.0.1&port={center.port}&type={rtype}")


def test_rule_manager_crud_round_trip(live_stack, vt):
    """The UI rule manager's exact fetch paths: create, edit, delete for
    flow / degrade / paramFlow — each publish lands in the ENGINE (flips
    enforcement), not just a store (VERDICT r3 next #6)."""
    client, center, dash = live_stack

    # -- create: flow rule count=2 starts blocking the third entry --------
    flow = [{"resource": "ui-res", "count": 2, "grade": 1}]
    rsp = _ui_save(dash, center, "flow", flow)
    assert rsp["pushed"] == 1
    got = sum(1 for _ in range(5) if client.try_entry("ui-res"))
    assert got == 2
    vt.advance(1100)

    # -- edit: the UI mutates the fetched list and re-publishes -----------
    rules = _ui_load(dash, center, "flow")
    assert rules[0]["resource"] == "ui-res" and rules[0]["count"] == 2
    rules[0]["count"] = 3
    _ui_save(dash, center, "flow", rules)
    got = sum(1 for _ in range(5) if client.try_entry("ui-res"))
    assert got == 3
    vt.advance(1100)

    # -- degrade tab: error-count breaker opens after 2 errors ------------
    _ui_save(dash, center, "degrade", [{
        "resource": "ui-res", "grade": 2, "count": 2, "timeWindow": 10,
        "minRequestAmount": 1, "statIntervalMs": 1000,
    }])
    assert _ui_load(dash, center, "degrade")[0]["grade"] == 2
    for _ in range(2):
        e = client.try_entry("ui-res")
        assert e
        e.trace(RuntimeError("boom"))
        e.exit()
        vt.advance(3)
    vt.advance(3)
    assert client.try_entry("ui-res") is None  # breaker open

    # -- paramFlow tab: per-value budget enforced -------------------------
    _ui_save(dash, center, "paramFlow", [{
        "resource": "ui-papi", "count": 1, "paramIdx": 0, "grade": 1,
        "durationInSec": 1,
    }])
    assert _ui_load(dash, center, "paramFlow")[0]["resource"] == "ui-papi"
    got = sum(1 for _ in range(4) if client.try_entry("ui-papi", args=["v"]))
    assert got == 1

    # -- delete: the UI publishes the emptied list ------------------------
    _ui_save(dash, center, "flow", [])
    assert _ui_load(dash, center, "flow") == []
    vt.advance(1100)
    # breaker from the degrade tab still governs ui-res; use a fresh probe
    got = sum(1 for _ in range(6) if client.try_entry("ui-free"))
    assert got == 6  # no flow rule left

    # the page itself advertises the manager controls
    rsp = urllib.request.urlopen(
        f"http://127.0.0.1:{dash.port}/", timeout=3
    ).read().decode()
    for frag in ('id="rsave"', 'id="radd"', "tab-paramFlow", "tab-degrade",
                 "tab-system", "tab-authority"):
        assert frag in rsp


def test_rule_manager_system_authority_round_trip(live_stack, vt):
    """system + authority tabs (views/system.html / authority.html):
    CRUD through the UI's exact fetch paths flips ENGINE enforcement."""
    client, center, dash = live_stack

    # -- authority: BLACK-list an origin on one resource ------------------
    _ui_save(dash, center, "authority", [
        {"resource": "auth-res", "limitApp": "badcaller", "strategy": 1}
    ])
    got = _ui_load(dash, center, "authority")
    assert got[0]["limitApp"] == "badcaller" and got[0]["strategy"] == 1
    assert client.try_entry("auth-res", origin="goodcaller")
    assert client.try_entry("auth-res", origin="badcaller") is None

    # edit: flip to WHITE list — now ONLY badcaller may pass
    got[0]["strategy"] = 0
    _ui_save(dash, center, "authority", got)
    assert client.try_entry("auth-res", origin="badcaller")
    assert client.try_entry("auth-res", origin="goodcaller") is None

    # -- system: global inbound QPS cap -----------------------------------
    vt.advance(1100)
    _ui_save(dash, center, "system", [
        {"highestSystemLoad": -1, "highestCpuUsage": -1, "qps": 2,
         "avgRt": -1, "maxThread": -1}
    ])
    assert _ui_load(dash, center, "system")[0]["qps"] == 2
    passed = sum(1 for _ in range(5) if client.try_entry("sys-res", inbound=True))
    assert passed == 2  # global cap enforced on inbound traffic

    # -- delete both: enforcement lifts -----------------------------------
    _ui_save(dash, center, "system", [])
    _ui_save(dash, center, "authority", [])
    assert _ui_load(dash, center, "system") == []
    vt.advance(1100)
    assert client.try_entry("auth-res", origin="goodcaller")
    got = sum(1 for _ in range(4) if client.try_entry("sys-res", inbound=True))
    assert got == 4
