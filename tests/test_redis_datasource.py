"""Redis push datasource end-to-end over a real socket (VERDICT r2 #5).

A stub RESP2 server (GET/SET/AUTH/SELECT/SUBSCRIBE/PUBLISH subset) runs
in-process; the RedisDataSource client speaks the real wire protocol to
it.  The test pushes a rule change over PUBLISH and asserts the engine
recompiles and enforcement flips — the full datasource → property →
RuleManager → device path; plus the reconnect-heal path (kill the
subscriber socket, change the key, assert the re-GET picks it up)."""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time

import pytest

from sentinel_tpu.core import errors as ERR
from sentinel_tpu.core.rules import FlowRule
from sentinel_tpu.datasource.converters import json_rule_converter
from sentinel_tpu.datasource.redis import (
    RedisConnection,
    RedisDataSource,
    encode_command,
)


class StubRedis:
    """Minimal RESP2 server: enough of redis for the datasource binding."""

    def __init__(self):
        self.data = {}
        self.subscribers = {}  # channel -> list[socket]
        self.lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                buf = b""
                sock = self.request
                subscribed = []
                try:
                    while True:
                        try:
                            chunk = sock.recv(65536)
                        except OSError:
                            break
                        if not chunk:
                            break
                        buf += chunk
                        while True:
                            cmd, buf2 = outer._parse(buf)
                            if cmd is None:
                                break
                            buf = buf2
                            outer._dispatch(sock, cmd, subscribed)
                finally:
                    with outer.lock:
                        for ch in subscribed:
                            if sock in outer.subscribers.get(ch, []):
                                outer.subscribers[ch].remove(sock)

        self.server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    @staticmethod
    def _parse(buf):
        """One RESP array-of-bulk-strings request, or (None, buf)."""
        if not buf.startswith(b"*"):
            return None, buf
        try:
            head, rest = buf.split(b"\r\n", 1)
            n = int(head[1:])
            args = []
            for _ in range(n):
                if not rest.startswith(b"$"):
                    return None, buf
                lhead, rest = rest.split(b"\r\n", 1)
                ln = int(lhead[1:])
                if len(rest) < ln + 2:
                    return None, buf
                args.append(rest[:ln])
                rest = rest[ln + 2 :]
            return args, rest
        except ValueError:
            return None, buf

    def _dispatch(self, sock, cmd, subscribed):
        name = cmd[0].upper().decode()
        if name == "GET":
            v = self.data.get(cmd[1].decode())
            if v is None:
                sock.sendall(b"$-1\r\n")
            else:
                b = v.encode()
                sock.sendall(b"$%d\r\n%s\r\n" % (len(b), b))
        elif name == "SET":
            self.data[cmd[1].decode()] = cmd[2].decode()
            sock.sendall(b"+OK\r\n")
        elif name in ("AUTH", "SELECT"):
            sock.sendall(b"+OK\r\n")
        elif name == "SUBSCRIBE":
            ch = cmd[1].decode()
            with self.lock:
                self.subscribers.setdefault(ch, []).append(sock)
            subscribed.append(ch)
            sock.sendall(
                b"*3\r\n$9\r\nsubscribe\r\n$%d\r\n%s\r\n:1\r\n"
                % (len(cmd[1]), cmd[1])
            )
        elif name == "PUBLISH":
            ch = cmd[1].decode()
            payload = cmd[2]
            n = 0
            with self.lock:
                subs = list(self.subscribers.get(ch, []))
            for s in subs:
                try:
                    s.sendall(
                        b"*3\r\n$7\r\nmessage\r\n$%d\r\n%s\r\n$%d\r\n%s\r\n"
                        % (len(cmd[1]), cmd[1], len(payload), payload)
                    )
                    n += 1
                except OSError:
                    pass
            sock.sendall(b":%d\r\n" % n)
        else:
            sock.sendall(b"-ERR unknown command\r\n")

    def publish(self, channel: str, payload: str) -> None:
        """Publish from the 'operator' side via a real client connection."""
        c = RedisConnection("127.0.0.1", self.port)
        try:
            c.execute("SET", "sentinel:rules:flow", payload)
            c.execute("PUBLISH", channel, payload)
        finally:
            c.close()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def stub():
    s = StubRedis()
    yield s
    s.close()


def _rules_json(count: float) -> str:
    return json.dumps([FlowRule(resource="api", count=count).to_dict()])


def _passes(client, n=12) -> int:
    ok = 0
    for _ in range(n):
        try:
            with client.entry("api"):
                ok += 1
        except ERR.BlockException:
            pass
    return ok


def test_resp_roundtrip(stub):
    c = RedisConnection("127.0.0.1", stub.port)
    assert c.execute("SET", "k", "v") == "OK"
    assert c.execute("GET", "k") == b"v"
    assert c.execute("GET", "missing") is None
    c.close()


def test_push_flips_enforcement(stub, client):
    stub.data["sentinel:rules:flow"] = _rules_json(1000.0)
    ds = RedisDataSource(
        json_rule_converter("flow"),
        "127.0.0.1",
        stub.port,
        rule_key="sentinel:rules:flow",
        channel="sentinel:chan:flow",
    ).start()
    try:
        client.flow_rules.register_property(ds.get_property())
        # wait for the cold-start GET to land
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not client.flow_rules.get():
            time.sleep(0.02)
        assert [r.count for r in client.flow_rules.get()] == [1000.0]
        assert _passes(client) == 12  # permissive

        # operator publishes a restrictive rule set over the real wire
        stub.publish("sentinel:chan:flow", _rules_json(2.0))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (
            not client.flow_rules.get()
            or client.flow_rules.get()[0].count != 2.0
        ):
            time.sleep(0.02)
        assert [r.count for r in client.flow_rules.get()] == [2.0]
        client.time.advance(1100)  # fresh window (virtual time)
        assert _passes(client) == 2  # enforcement flipped

        # reconnect-heal: kill the subscriber's socket server-side, change
        # the KEY only (no publish) — the re-GET after reconnect heals it
        with stub.lock:
            socks = [s for subs in stub.subscribers.values() for s in subs]
        stub.data["sentinel:rules:flow"] = _rules_json(500.0)
        for s in socks:
            # shutdown (not close): the handler thread is blocked in recv on
            # this socket, and close() alone wouldn't send the FIN
            s.shutdown(socket.SHUT_RDWR)
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and (
            not client.flow_rules.get()
            or client.flow_rules.get()[0].count != 500.0
        ):
            time.sleep(0.05)
        assert [r.count for r in client.flow_rules.get()] == [500.0]
        client.time.advance(1100)
        assert _passes(client) == 12
    finally:
        ds.close()
