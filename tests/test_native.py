"""Native host runtime: build, ring round-trip, threaded stress, interner
semantics — and the pure-Python fallback path."""

import threading

import numpy as np
import pytest

from sentinel_tpu.native import EventRing, NativeInterner, native_available


def test_native_builds():
    # g++ is in the image; the native path must actually come up
    assert native_available()


@pytest.mark.parametrize("force_fallback", [False, True])
def test_ring_roundtrip(force_fallback, monkeypatch):
    if force_fallback:
        import sentinel_tpu.native.ring as RM

        monkeypatch.setattr(RM, "load_native", lambda: None)
    r = EventRing(1 << 8)
    assert r.native is not force_fallback
    for i in range(10):
        assert r.push(res=i, count=i + 1, rt_ms=float(i) / 2, user_tag=100 + i)
    assert len(r) == 10
    res, count, origin, ph, flags, rt, err, tag, aux0, aux1, aux2, aux3 = r.drain(64)
    assert list(res) == list(range(10))
    assert list(count) == [i + 1 for i in range(10)]
    np.testing.assert_allclose(rt, [i / 2 for i in range(10)])
    assert list(tag) == [100 + i for i in range(10)]
    assert len(r) == 0


def test_ring_full_and_wraparound():
    r = EventRing(1 << 4)
    for i in range(16):
        assert r.push(res=i)
    assert not r.push(res=99)  # full
    out = r.drain(8)
    assert list(out[0]) == list(range(8))
    for i in range(8):  # wrap
        assert r.push(res=100 + i)
    out = r.drain(32)
    assert list(out[0]) == list(range(8, 16)) + [100 + i for i in range(8)]


def test_ring_threaded_stress():
    r = EventRing(1 << 12)
    n_threads, per_thread = 8, 2000
    drained = []
    stop = threading.Event()

    def producer(t):
        pushed = 0
        while pushed < per_thread:
            if r.push(res=t * per_thread + pushed):
                pushed += 1

    def consumer():
        while not stop.is_set() or len(r):
            out = r.drain(512)
            if len(out[0]):
                drained.append(np.array(out[0]))

    ct = threading.Thread(target=consumer)
    ct.start()
    threads = [threading.Thread(target=producer, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    ct.join()
    got = np.concatenate(drained) if drained else np.array([])
    assert len(got) == n_threads * per_thread
    # every event delivered exactly once
    assert len(np.unique(got)) == len(got)


def test_completion_overflow_never_drops(client, vt):
    """A full ring spills to the overflow list; nothing is lost (losses
    would leak engine concurrency forever)."""
    import sentinel_tpu as st

    client.flow_rules.load([st.FlowRule(resource="ovf", count=1000)])
    client._comp_ring = EventRing(1 << 2)  # tiny ring: 4 slots
    entries = [client.entry("ovf") for _ in range(10)]  # sync: ticks run
    mode = client.mode
    client.mode = "threaded"  # hold ticks while we queue exits
    for e in entries:
        vt.advance(1)
        e.exit()
    assert len(client._comp_overflow) == 10 - (1 << 2)
    client.mode = mode
    client.tick_once()
    s = client.stats.resource("ovf")
    assert s["successQps"] == 10  # every completion landed
    assert s["curThreadNum"] == 0  # concurrency fully released
    assert not client._comp_overflow


def test_interner_dense_ids_and_capacity():
    t = NativeInterner(1 << 8, first_id=5, max_ids=5 + 3)
    assert t.native
    a = t.get("alpha")
    b = t.get("beta")
    assert (a, b) == (5, 6)
    assert t.get("alpha") == 5  # stable
    assert t.get("gamma") == 7
    assert t.get("delta") == -1  # id space exhausted
    assert t.count() == 3


def test_interner_threaded_consistency():
    t = NativeInterner(1 << 12, first_id=0, max_ids=10000)
    names = [f"res-{i % 50}" for i in range(2000)]
    results = {}
    lock = threading.Lock()

    def worker(offset):
        local = {}
        for n in names[offset::4]:
            local[n] = t.get(n)
        with lock:
            for k, v in local.items():
                assert results.setdefault(k, v) == v  # same id everywhere

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(results) == 50
    assert sorted(results.values()) == list(range(50))


def test_batch_sort_native_matches_numpy_fallback(monkeypatch):
    """The C stable argsort (sx_batch_sort5/3) must be byte-identical to
    the np.lexsort fallback — order AND inverse permutation, ties
    included (both sides are stable sorts over the same key order)."""
    import sentinel_tpu.native.ring as RM

    assert native_available()  # the native path must actually be on trial
    rng = np.random.default_rng(7)
    for n in (0, 1, 3, 257, 20000):
        # tiny key ranges force heavy ties — the stability trap
        k5 = [rng.integers(-2, 3, n).astype(np.int32) for _ in range(5)]
        k3 = [rng.integers(0, 4, n).astype(np.int32) for _ in range(3)]
        o5n, i5n = RM.batch_sort5(*k5)
        o3n, i3n = RM.batch_sort3(*k3, want_inv=True)
        with monkeypatch.context() as m:
            m.setattr(RM, "load_native", lambda: None)
            o5f, i5f = RM.batch_sort5(*k5)
            o3f, i3f = RM.batch_sort3(*k3, want_inv=True)
        assert np.array_equal(o5n, o5f) and np.array_equal(i5n, i5f)
        assert np.array_equal(o3n, o3f) and np.array_equal(i3n, i3f)
        # both agree with the reference np.lexsort key order
        assert np.array_equal(o5f, np.lexsort((k5[4], k5[3], k5[2], k5[1], k5[0])))
        if n:
            assert np.array_equal(i5n[o5n], np.arange(n))


def test_batch_framing_native_matches_numpy_fallback(monkeypatch):
    """Protocol-v2 frame pack/unpack (sx_frame_pack_entries & co) must be
    BYTE-identical to the numpy big-endian structured fallback — the two
    ends of one connection may be built differently."""
    import sentinel_tpu.native.ring as RM

    assert native_available()
    rng = np.random.default_rng(13)
    for n in (0, 1, 5, 2048):
        kinds = rng.integers(0, 255, n).astype(np.uint8)
        ids = rng.integers(-(2**62), 2**62, n).astype(np.int64)
        counts = rng.integers(-(2**31), 2**31 - 1, n).astype(np.int32)
        flags = rng.integers(0, 255, n).astype(np.uint8)
        statuses = rng.integers(-128, 127, n).astype(np.int8)
        waits = rng.integers(0, 2**31 - 1, n).astype(np.int32)
        wire_e_n = RM.pack_batch_entries(kinds, ids, counts, flags)
        wire_r_n = RM.pack_batch_results(statuses, counts, waits, ids)
        cols_e_n = RM.unpack_batch_entries(wire_e_n)
        cols_r_n = RM.unpack_batch_results(wire_r_n)
        with monkeypatch.context() as m:
            m.setattr(RM, "load_native", lambda: None)
            assert RM.pack_batch_entries(kinds, ids, counts, flags) == wire_e_n
            assert RM.pack_batch_results(statuses, counts, waits, ids) == wire_r_n
            cols_e_f = RM.unpack_batch_entries(wire_e_n)
            cols_r_f = RM.unpack_batch_results(wire_r_n)
        for a, b in zip(cols_e_n, cols_e_f):
            assert np.array_equal(a, b)
        for a, b in zip(cols_r_n, cols_r_f):
            assert np.array_equal(a, b)
        # round-trip restores the original columns exactly
        for a, b in zip(cols_e_n, (kinds, ids, counts, flags)):
            assert np.array_equal(a, b)
        for a, b in zip(cols_r_n, (statuses, counts, waits, ids)):
            assert np.array_equal(a, b)
    # a length that is not a whole number of entries is rejected on BOTH paths
    wire = RM.pack_batch_entries(*(np.zeros(2, dt) for dt in
                                   (np.uint8, np.int64, np.int32, np.uint8)))
    for use_fallback in (False, True):
        with monkeypatch.context() as m:
            if use_fallback:
                m.setattr(RM, "load_native", lambda: None)
            with pytest.raises(ValueError):
                RM.unpack_batch_entries(wire[:-1])
            with pytest.raises(ValueError):
                RM.unpack_batch_results(wire)  # 28 bytes is not k × 17
