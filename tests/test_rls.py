"""Envoy RLS gRPC service tests (SURVEY.md §4.5 analog — but over a real
in-process gRPC channel rather than mocked observers)."""

import pytest

grpc = pytest.importorskip("grpc")

from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.rls import rls_pb2 as pb
from sentinel_tpu.rls.rules import (
    EnvoyRlsRule,
    RlsKeyValue,
    RlsResourceDescriptor,
    descriptor_identifier,
    identifier_flow_id,
)
from sentinel_tpu.rls.server import SentinelEnvoyRlsService, SentinelRlsGrpcServer, make_channel_stub


def make_rule(domain="mesh", key="dest", value="svc-a", count=3.0):
    return EnvoyRlsRule(
        domain=domain,
        descriptors=[
            RlsResourceDescriptor(key_values=[RlsKeyValue(key, value)], count=count)
        ],
    )


def make_request(domain="mesh", entries=(("dest", "svc-a"),), hits=1):
    req = pb.RateLimitRequest(domain=domain, hits_addend=hits)
    d = req.descriptors.add()
    for k, v in entries:
        e = d.entries.add()
        e.key, e.value = k, v
    return req


def test_identifier_stability_and_order_independence():
    a = descriptor_identifier("d", [("k1", "v1"), ("k2", "v2")])
    b = descriptor_identifier("d", [("k2", "v2"), ("k1", "v1")])
    assert a == b
    assert identifier_flow_id(a) == identifier_flow_id(b) > 0


def test_should_rate_limit_inproc(client):
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load([make_rule(count=2.0)])

    codes = [rls.should_rate_limit(make_request()).overall_code for _ in range(4)]
    assert codes.count(pb.RateLimitResponse.OK) == 2
    assert codes.count(pb.RateLimitResponse.OVER_LIMIT) == 2

    # unmatched descriptor → OK (no rule)
    r = rls.should_rate_limit(make_request(entries=(("dest", "unknown"),)))
    assert r.overall_code == pb.RateLimitResponse.OK


def test_hits_addend_consumes_multiple_tokens(client):
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load([make_rule(count=5.0)])
    assert (
        rls.should_rate_limit(make_request(hits=5)).overall_code
        == pb.RateLimitResponse.OK
    )
    assert (
        rls.should_rate_limit(make_request(hits=1)).overall_code
        == pb.RateLimitResponse.OVER_LIMIT
    )


def test_grpc_server_end_to_end(client_factory):
    decision = client_factory()
    svc = DefaultTokenService(decision)
    server = SentinelRlsGrpcServer(svc, host="127.0.0.1", port=0)
    server.rules.load([make_rule(count=2.0)])
    server.start()
    try:
        channel, call = make_channel_stub(f"127.0.0.1:{server.port}")
        codes = [call(make_request()).overall_code for _ in range(4)]
        channel.close()
        assert codes.count(pb.RateLimitResponse.OK) == 2
        assert codes.count(pb.RateLimitResponse.OVER_LIMIT) == 2
    finally:
        server.stop()


def test_rule_dict_roundtrip():
    rule = make_rule()
    assert EnvoyRlsRule.from_dict(rule.to_dict()) == rule


# ---------------------------------------------------------------------------
# edge cases (ISSUE-6 satellite)
# ---------------------------------------------------------------------------


def test_empty_descriptor_list_is_ok(client):
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load([make_rule()])
    rsp = rls.should_rate_limit(pb.RateLimitRequest(domain="mesh"))
    assert rsp.overall_code == pb.RateLimitResponse.OK
    assert len(rsp.statuses) == 0  # one status per descriptor, none sent


def test_unknown_domain_is_ok_not_over_limit(client):
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load([make_rule(domain="mesh")])
    rsp = rls.should_rate_limit(make_request(domain="not-mesh"))
    assert rsp.overall_code == pb.RateLimitResponse.OK
    assert rsp.statuses[0].code == pb.RateLimitResponse.OK


def test_decision_exception_fails_closed(client):
    """An exception escaping the decision path must become OVER_LIMIT,
    not a gRPC UNKNOWN — Envoy's default failure_mode would admit an
    errored request unmetered."""
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load([make_rule(domain="mesh")])

    def boom(*a, **k):
        raise RuntimeError("decision backend down")

    rls.token_service = svc  # sanity: normal path first
    assert (
        rls.should_rate_limit(make_request(domain="mesh")).overall_code
        == pb.RateLimitResponse.OK
    )
    svc_broken = svc
    orig = svc_broken.request_token
    try:
        svc_broken.request_token = boom
        rsp = rls.should_rate_limit(make_request(domain="mesh"))
        assert rsp.overall_code == pb.RateLimitResponse.OVER_LIMIT
    finally:
        svc_broken.request_token = orig


def test_multi_descriptor_any_over_limit_semantics(client):
    """One over-limit descriptor flips the OVERALL verdict while the
    per-descriptor statuses stay individually truthful."""
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load(
        [
            EnvoyRlsRule(
                domain="mesh",
                descriptors=[
                    RlsResourceDescriptor(
                        key_values=[RlsKeyValue("dest", "svc-tight")], count=1.0
                    ),
                    RlsResourceDescriptor(
                        key_values=[RlsKeyValue("dest", "svc-wide")], count=100.0
                    ),
                ],
            )
        ]
    )

    def both():
        req = pb.RateLimitRequest(domain="mesh", hits_addend=1)
        for v in ("svc-tight", "svc-wide"):
            d = req.descriptors.add()
            e = d.entries.add()
            e.key, e.value = "dest", v
        return rls.should_rate_limit(req)

    first = both()
    assert first.overall_code == pb.RateLimitResponse.OK
    second = both()  # svc-tight exhausted (count=1), svc-wide still fine
    assert second.overall_code == pb.RateLimitResponse.OVER_LIMIT
    assert second.statuses[0].code == pb.RateLimitResponse.OVER_LIMIT
    assert second.statuses[1].code == pb.RateLimitResponse.OK


def test_grpc_roundtrip_multi_descriptor_and_empty(client_factory):
    """Real-gRPC (generic handler) round-trip of the edge shapes: the
    wire path must agree with the in-proc service on empty descriptor
    lists, unknown domains, and multi-descriptor verdicts."""
    decision = client_factory()
    svc = DefaultTokenService(decision)
    server = SentinelRlsGrpcServer(svc, host="127.0.0.1", port=0)
    server.rules.load([make_rule(count=1.0)])
    server.start()
    try:
        channel, call = make_channel_stub(f"127.0.0.1:{server.port}")
        assert (
            call(pb.RateLimitRequest(domain="mesh")).overall_code
            == pb.RateLimitResponse.OK
        )
        assert (
            call(make_request(domain="elsewhere")).overall_code
            == pb.RateLimitResponse.OK
        )
        req = make_request()  # matched descriptor...
        d = req.descriptors.add()  # ...plus an unmatched one
        e = d.entries.add()
        e.key, e.value = "dest", "unknown"
        first = call(req)
        assert first.overall_code == pb.RateLimitResponse.OK
        second = call(req)  # count=1 exhausted -> any-over-limit wins
        assert second.overall_code == pb.RateLimitResponse.OVER_LIMIT
        assert [s.code for s in second.statuses] == [
            pb.RateLimitResponse.OVER_LIMIT,
            pb.RateLimitResponse.OK,
        ]
        channel.close()
    finally:
        server.stop()
