"""Envoy RLS gRPC service tests (SURVEY.md §4.5 analog — but over a real
in-process gRPC channel rather than mocked observers)."""

import pytest

grpc = pytest.importorskip("grpc")

from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.rls import rls_pb2 as pb
from sentinel_tpu.rls.rules import (
    EnvoyRlsRule,
    RlsKeyValue,
    RlsResourceDescriptor,
    descriptor_identifier,
    identifier_flow_id,
)
from sentinel_tpu.rls.server import SentinelEnvoyRlsService, SentinelRlsGrpcServer, make_channel_stub


def make_rule(domain="mesh", key="dest", value="svc-a", count=3.0):
    return EnvoyRlsRule(
        domain=domain,
        descriptors=[
            RlsResourceDescriptor(key_values=[RlsKeyValue(key, value)], count=count)
        ],
    )


def make_request(domain="mesh", entries=(("dest", "svc-a"),), hits=1):
    req = pb.RateLimitRequest(domain=domain, hits_addend=hits)
    d = req.descriptors.add()
    for k, v in entries:
        e = d.entries.add()
        e.key, e.value = k, v
    return req


def test_identifier_stability_and_order_independence():
    a = descriptor_identifier("d", [("k1", "v1"), ("k2", "v2")])
    b = descriptor_identifier("d", [("k2", "v2"), ("k1", "v1")])
    assert a == b
    assert identifier_flow_id(a) == identifier_flow_id(b) > 0


def test_should_rate_limit_inproc(client):
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load([make_rule(count=2.0)])

    codes = [rls.should_rate_limit(make_request()).overall_code for _ in range(4)]
    assert codes.count(pb.RateLimitResponse.OK) == 2
    assert codes.count(pb.RateLimitResponse.OVER_LIMIT) == 2

    # unmatched descriptor → OK (no rule)
    r = rls.should_rate_limit(make_request(entries=(("dest", "unknown"),)))
    assert r.overall_code == pb.RateLimitResponse.OK


def test_hits_addend_consumes_multiple_tokens(client):
    svc = DefaultTokenService(client)
    rls = SentinelEnvoyRlsService(svc)
    rls.rules.load([make_rule(count=5.0)])
    assert (
        rls.should_rate_limit(make_request(hits=5)).overall_code
        == pb.RateLimitResponse.OK
    )
    assert (
        rls.should_rate_limit(make_request(hits=1)).overall_code
        == pb.RateLimitResponse.OVER_LIMIT
    )


def test_grpc_server_end_to_end(client_factory):
    decision = client_factory()
    svc = DefaultTokenService(decision)
    server = SentinelRlsGrpcServer(svc, host="127.0.0.1", port=0)
    server.rules.load([make_rule(count=2.0)])
    server.start()
    try:
        channel, call = make_channel_stub(f"127.0.0.1:{server.port}")
        codes = [call(make_request()).overall_code for _ in range(4)]
        channel.close()
        assert codes.count(pb.RateLimitResponse.OK) == 2
        assert codes.count(pb.RateLimitResponse.OVER_LIMIT) == 2
    finally:
        server.stop()


def test_rule_dict_roundtrip():
    rule = make_rule()
    assert EnvoyRlsRule.from_dict(rule.to_dict()) == rule
